"""Pure-NumPy Reed-Solomon codec over GF(2^8)/GF(2^16) — ground truth.

Capabilities mirrored from the reference's codec contract (SURVEY.md §2.3 D1,
call sites /root/reference/main.go:57-61, 73-77, 248-266):

- ``NewFEC(required, total)``-style construction with validation,
- systematic encode (shares 0..k-1 are the data split),
- decode from any >= k shares, with *error detection and correction* when
  extra shares are present (infectious performs Berlekamp-Welch; here the
  golden codec uses exhaustive consistent-subset search, which has the same
  unique-decoding guarantee floor((m - k)/2) for m received shares and is
  obviously correct — the property the ground truth is for),
- erasure reconstruction of any missing shard rows.

Everything is small-scale NumPy; the fast paths live in ``noise_ec_tpu.ops``.
"""

from __future__ import annotations

import itertools
from typing import Optional, Sequence

import numpy as np

from noise_ec_tpu.gf.field import GF, GF256, GF65536
from noise_ec_tpu.matrix.generators import generator_matrix
from noise_ec_tpu.matrix.linalg import gf_inv, reconstruction_matrix


class NotEnoughShardsError(ValueError):
    pass


class TooManyErrorsError(ValueError):
    pass


_FIELDS = {"gf256": GF256, "gf65536": GF65536}


class GoldenCodec:
    """Reference RS(k, n) codec.

    Parameters
    ----------
    k: minimum shards needed to reconstruct (``minimumNeededShards``,
       reference main.go:35 default 4).
    n: total shards (``totalShards``, main.go:34 default 6).
    field: "gf256" (default) or "gf65536".
    matrix: "cauchy" (default), "vandermonde", or "par1".
    """

    def __init__(self, k: int, n: int, field: str = "gf256", matrix: str = "cauchy"):
        if field not in _FIELDS:
            raise ValueError(f"unknown field {field!r}")
        self.gf: GF = _FIELDS[field]()
        self.k = int(k)
        self.n = int(n)
        self.field = field
        self.matrix_kind = matrix
        self.G = generator_matrix(self.gf, self.k, self.n, matrix)
        self.systematic = bool(
            np.array_equal(self.G[: self.k], np.eye(self.k, dtype=self.gf.dtype))
        )

    # -- array-level API ---------------------------------------------------

    def encode(self, data_shards: np.ndarray) -> np.ndarray:
        """(k, S) data -> (n-k, S) parity rows (systematic constructions)."""
        data_shards = self._check_data(data_shards)
        if not self.systematic:
            raise ValueError("encode() requires a systematic matrix; use encode_all()")
        return self.gf.matvec_stripes(self.G[self.k :], data_shards)

    def encode_all(self, data_shards: np.ndarray) -> np.ndarray:
        """(k, S) data -> full (n, S) codeword (works for any construction)."""
        data_shards = self._check_data(data_shards)
        return self.gf.matvec_stripes(self.G, data_shards)

    def verify(self, shards: np.ndarray) -> bool:
        """True iff the (n, S) codeword is consistent with its data rows."""
        shards = np.asarray(shards, dtype=self.gf.dtype)
        if shards.shape[0] != self.n:
            raise ValueError(f"verify needs all {self.n} rows, got {shards.shape[0]}")
        if not self.systematic:
            try:
                dec = self.decode_shares(list(enumerate(shards)), error_correction=False)
            except TooManyErrorsError:
                return False
            return bool(np.array_equal(self.encode_all(dec), shards))
        expect = self.encode(shards[: self.k])
        return bool(np.array_equal(expect, shards[self.k :]))

    def reconstruct(
        self,
        shards: Sequence[Optional[np.ndarray]],
        data_only: bool = False,
        max_subsets: int = 20000,
    ) -> list[np.ndarray]:
        """Fill in missing rows (None entries) from any k present rows.

        Mirrors klauspost ``Reconstruct``/``ReconstructData`` (the BASELINE
        metric's second config). Erasure-only: present rows are trusted.
        """
        shards = list(shards)
        if len(shards) != self.n:
            raise ValueError(f"expected {self.n} entries, got {len(shards)}")
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) < self.k:
            raise NotEnoughShardsError(
                f"have {len(present)} shards, need {self.k}"
            )
        limit = self.k if data_only else self.n
        missing = [i for i in range(limit) if shards[i] is None]
        if not missing:
            return shards
        # Prefer the first k present rows, but fall back to other k-subsets:
        # non-MDS constructions (par1) can have singular submatrices for
        # recoverable patterns.
        R = None
        for count, basis in enumerate(itertools.combinations(present, self.k)):
            if count >= max_subsets:
                break
            try:
                R = reconstruction_matrix(self.gf, self.G, list(basis), missing)
                break
            except np.linalg.LinAlgError:
                continue
        if R is None:
            raise TooManyErrorsError(
                "no invertible k-subset of present shards (non-MDS matrix?)"
            )
        stack = np.stack([np.asarray(shards[i], dtype=self.gf.dtype) for i in basis])
        filled = self.gf.matvec_stripes(R, stack)
        for row, i in enumerate(missing):
            shards[i] = filled[row]
        return shards

    def decode_shares_bw(
        self, shares: Sequence[tuple[int, np.ndarray]]
    ) -> np.ndarray:
        """(number, stripe) pairs -> (k, S) data via Berlekamp-Welch.

        The polynomial-time error-correcting decode (the algorithm
        infectious actually runs at the reference's main.go:77 call site):
        corrects up to floor((m - k)/2) wrong symbols *per byte column* —
        strictly stronger than the subset search, which models whole-share
        corruption only. MDS GRS constructions only (cauchy, vandermonde,
        vandermonde_raw); par1 has no GRS representation and must use
        :meth:`decode_shares`.
        """
        from noise_ec_tpu.matrix.bw import bw_decode_stripes

        nums, stripes = self._dedup_shares(shares)
        data = bw_decode_stripes(
            self.gf, self.matrix_kind, self.k, self.n, nums,
            np.stack([stripes[i] for i in nums]),
        )
        if data is None:
            m = len(nums)
            raise TooManyErrorsError(
                f"some column has more than {(m - self.k) // 2} errors "
                f"(m={m}, k={self.k})"
            )
        return data

    def _dedup_shares(
        self, shares: Sequence[tuple[int, np.ndarray]]
    ) -> tuple[list[int], dict[int, np.ndarray]]:
        dedup: dict[int, np.ndarray] = {}
        for num, data in shares:
            num = int(num)
            if not 0 <= num < self.n:
                raise ValueError(f"share number {num} out of range [0, {self.n})")
            arr = np.asarray(data, dtype=self.gf.dtype)
            if num in dedup:
                if not np.array_equal(dedup[num], arr):
                    raise ValueError(f"conflicting copies of share {num}")
                continue
            dedup[num] = arr
        if len(dedup) < self.k:
            raise NotEnoughShardsError(f"have {len(dedup)} shares, need {self.k}")
        return sorted(dedup), dedup

    def decode_shares(
        self,
        shares: Sequence[tuple[int, np.ndarray]],
        error_correction: bool = True,
        max_subsets: int = 20000,
    ) -> np.ndarray:
        """(number, stripe) pairs -> (k, S) original data rows.

        With more than k shares and ``error_correction=True``, performs
        consistent-subset search: finds a decoding that agrees with at least
        m - floor((m - k)/2) of the m distinct received shares — the same
        unique-decoding radius as Berlekamp-Welch (which infectious's
        ``Decode`` implements; SURVEY.md §2.3 D1). Raises TooManyErrorsError
        if no such decoding exists within ``max_subsets`` candidate subsets.
        """
        nums, stripes = self._dedup_shares(shares)
        m = len(nums)

        def try_basis(basis: tuple[int, ...]) -> tuple[Optional[np.ndarray], int]:
            # data = inv(G[basis]) @ survivors. (Not reconstruction_matrix:
            # for non-systematic G the data is a pre-image, not codeword rows.)
            try:
                inv = gf_inv(self.gf, self.G[list(basis)])
            except np.linalg.LinAlgError:
                return None, -1  # singular basis (non-MDS matrix): skip
            data = self.gf.matvec_stripes(
                inv, np.stack([stripes[i] for i in basis])
            )
            # Count agreement across all received shares.
            codeword = self.gf.matvec_stripes(self.G[nums], data)
            agree = sum(
                1 for row, i in enumerate(nums) if np.array_equal(codeword[row], stripes[i])
            )
            return data, agree

        # Unique-decoding acceptance threshold: agreement with at least
        # m - floor((m-k)/2) of the m received shares.
        needed = m - (m - self.k) // 2

        def judge(data, agree):
            """Returns data to accept, or None to keep searching."""
            if agree == m:
                return data
            if not error_correction:
                raise TooManyErrorsError("received shares are inconsistent")
            return data if agree >= needed else None

        first = tuple(nums[: self.k])
        data, agree = try_basis(first)
        if data is not None:
            accepted = judge(data, agree)
            if accepted is not None:
                return accepted
        # One bounded scan handles both jobs: find an invertible basis when
        # the first k-subset is singular (non-MDS matrices, e.g. par1), and
        # search for a decoding within the unique-decoding radius.
        for count, basis in enumerate(itertools.combinations(nums, self.k)):
            if count >= max_subsets:
                break
            if basis == first:  # already evaluated above
                continue
            d2, a2 = try_basis(basis)
            if d2 is None:
                continue
            accepted = judge(d2, a2)
            if accepted is not None:
                return accepted
            data = d2  # remember that an invertible basis exists
        if data is None:
            raise TooManyErrorsError("no invertible share subset (non-MDS matrix?)")
        raise TooManyErrorsError(
            f"no decoding agrees with >= {needed}/{m} shares"
        )

    # -- byte-level helpers ------------------------------------------------

    def split(self, data: bytes) -> np.ndarray:
        """Zero-pad bytes to a (k, S) symbol matrix (klauspost Split)."""
        buf = np.frombuffer(data, dtype=np.uint8)
        sym_bytes = self.gf.degree // 8
        row_bytes = -(-len(buf) // (self.k * sym_bytes)) * sym_bytes
        padded = np.zeros(self.k * row_bytes, dtype=np.uint8)
        padded[: len(buf)] = buf
        rows = padded.reshape(self.k, row_bytes)
        if sym_bytes == 1:
            return rows
        return rows.view("<u2")

    def join(self, data_shards: np.ndarray, out_len: int) -> bytes:
        """Inverse of split: concatenate data rows, trim padding."""
        arr = np.asarray(data_shards, dtype=self.gf.dtype)
        return arr.tobytes()[:out_len]

    def _check_data(self, data_shards: np.ndarray) -> np.ndarray:
        arr = np.atleast_2d(np.asarray(data_shards, dtype=self.gf.dtype))
        if arr.shape[0] != self.k:
            raise ValueError(f"expected {self.k} data rows, got {arr.shape[0]}")
        return arr
