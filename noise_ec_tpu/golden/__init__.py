"""Slow, obviously-correct NumPy reference codec — the ground-truth anchor.

The reference trusts ``vivint/infectious`` entirely (SURVEY.md §4 "codec
ground truth"); this framework generates its own: every faster path (jitted
JAX, Pallas kernels, the C++ shim) is tested bit-exactly against this codec.
"""

from noise_ec_tpu.golden.codec import GoldenCodec  # noqa: F401
