"""Reliable-UDP streams: the transport's ``kcp`` protocol option.

The reference exposes ``-protocol tcp|kcp`` (/root/reference/main.go:123),
where kcp is ARQ-over-UDP from the noise library's transport registry. This
module is an original, minimal ARQ in that family — selective-repeat with
cumulative acks, Jacobson RTO, fast retransmit — presenting the same duplex
byte-stream interface the TCP path uses, so ``Network(protocol="kcp")``
reuses the entire signed-frame / handshake / discovery stack unchanged
(the ARQ layer carries no identity; authentication stays in the signed
HELLO handshake above it).

Wire format (one UDP datagram = one segment, little-endian):

    u32 conv | u8 cmd | u32 sn | u32 una | u16 len | payload

- ``conv``: connection id, chosen randomly by the dialer; sessions demux
  by (remote addr, conv), so no SYN exchange is needed — the first PUSH
  from an unknown pair creates the acceptor-side session.
- cmd PUSH (1): stream payload segment ``sn``.
- cmd ACK (2): payload is ``len/4`` u32 sns being acked explicitly;
  ``una`` (all-received-below) rides in every segment either way.
- cmd FIN (3): graceful close after delivery of everything below ``sn``.

Sender: sliding window of in-flight segments; retransmit on per-segment
RTO expiry (backed off 1.5x per transmission) or when two acks for later
segments arrive first (fast resend). A segment transmitted DEAD_XMIT times
closes the session (dead link). Receiver: out-of-order segments buffer
until contiguous, then feed an ``asyncio.StreamReader`` — reassembly is
positional, so the stream needs no fragment field.
"""

from __future__ import annotations

import asyncio
import os
import struct
import time
from collections import deque
from typing import Callable, Optional

from noise_ec_tpu.obs.registry import default_registry

__all__ = ["open_kcp_connection", "start_kcp_server", "KcpServer"]


class _KcpMetrics:
    """Cached ARQ metric children (resolved once per process): retransmit
    counts by trigger, dead-link closes, sessions opened. Retransmit rate
    per peer is THE health signal for the UDP path — a rising rto share
    means loss, a rising fast share means reordering."""

    def __init__(self):
        reg = default_registry()
        fam = reg.counter("noise_ec_kcp_retransmits_total")
        self.rto = fam.labels(kind="rto")
        self.fast = fam.labels(kind="fast")
        self.dead = reg.counter("noise_ec_kcp_dead_links_total").labels()
        self.opened = reg.counter("noise_ec_kcp_sessions_opened_total").labels()


_metrics: Optional[_KcpMetrics] = None


def _kcp_metrics() -> _KcpMetrics:
    global _metrics
    if _metrics is None:
        _metrics = _KcpMetrics()
    return _metrics

_HDR = struct.Struct("<IBIIH")  # conv, cmd, sn, una, len
_CMD_PUSH = 1
_CMD_ACK = 2
_CMD_FIN = 3

MSS = 1200               # payload bytes per segment (under common MTUs)
SND_WND = 256            # max in-flight segments
UPDATE_INTERVAL = 0.01   # retransmission scan period (s)
RTO_MIN, RTO_MAX = 0.03, 3.0
DEAD_XMIT = 12           # transmissions of one segment before giving up
FAST_RESEND = 2          # later-acks before a skipped segment resends
HIGH_WATER = 1 << 20     # drain() blocks above this many buffered bytes
RCV_BUF_CAP = 4096       # out-of-order segments held before dropping


class _Seg:
    __slots__ = ("data", "sent_at", "rto", "xmit", "skips")

    def __init__(self, data: bytes):
        self.data = data
        self.sent_at = 0.0
        self.rto = 0.0
        self.xmit = 0
        self.skips = 0


class KcpSession:
    """One reliable stream over a shared UDP socket (see module doc)."""

    def __init__(self, conv: int, addr, sendto: Callable,
                 loop: asyncio.AbstractEventLoop,
                 on_close: Optional[Callable] = None):
        self.conv = conv
        self.addr = addr
        self._sendto = sendto
        self._loop = loop
        self._on_close = on_close
        self.reader = asyncio.StreamReader(loop=loop)
        # sender state
        self._snd_queue: deque[bytes] = deque()  # segmented, not yet in flight
        self._snd_buf: dict[int, _Seg] = {}      # sn -> in flight
        self._snd_nxt = 0
        self._queued_bytes = 0
        self._flight_bytes = 0
        self._partial = bytearray()              # < MSS tail awaiting more
        # receiver state
        self._rcv_nxt = 0
        self._rcv_buf: dict[int, bytes] = {}
        self._fin_at: Optional[int] = None
        self._read_eof = False  # peer FIN fully delivered (half-closed)
        self._half_close_deadline = 0.0  # write-idle close deadline after EOF
        # rtt estimation (Jacobson/Karels)
        self._srtt = 0.0
        self._rttvar = 0.0
        self._rto = 0.2
        self.closed = False
        # graceful-close state: FIN covers every byte written before
        # start_close(); the session lingers until it is acked (or the
        # linger deadline passes) so queued tail segments still deliver.
        self._fin_sn: Optional[int] = None
        self._fin_acked = False
        self._close_deadline: Optional[float] = None
        self._close_hard = 0.0  # set with _close_deadline in start_close
        self._drain_waiters: list[asyncio.Future] = []
        self._metrics = _kcp_metrics()
        self._metrics.opened.add(1)
        self._update_handle = loop.call_later(UPDATE_INTERVAL, self._update)

    # ------------------------------------------------------------- sending

    def write(self, data: bytes) -> None:
        if self.closed:
            raise ConnectionError("kcp session closed")
        if self._fin_sn is not None:
            # Writer already closed (start_close announced _fin_sn): the
            # peer's _push guard drops any segment with sn >= _fin_sn
            # unacked, so queued data would be silently lost and
            # retransmitted until the close deadline. TCP
            # shutdown(SHUT_WR) semantics: writing after closing the
            # write side is an error (round-3 ADVICE finding 3).
            raise ConnectionError("kcp write side already closed")
        if self._read_eof:
            # Half-closed: each write pushes the idle-close deadline out.
            self._half_close_deadline = time.monotonic() + self.LINGER
        buf = self._partial + data
        for i in range(0, len(buf) - MSS + 1, MSS):
            seg = bytes(buf[i : i + MSS])
            self._snd_queue.append(seg)
            self._queued_bytes += len(seg)
        tail = len(buf) % MSS if len(buf) >= MSS else len(buf)
        self._partial = bytearray(buf[len(buf) - tail :]) if tail else bytearray()
        self._fill_window()

    def flush_partial(self) -> None:
        """Push the sub-MSS tail out now (called before drain/idle)."""
        if self._partial:
            seg = bytes(self._partial)
            self._partial = bytearray()
            self._snd_queue.append(seg)
            self._queued_bytes += len(seg)
            self._fill_window()

    def buffered_bytes(self) -> int:
        return self._queued_bytes + self._flight_bytes + len(self._partial)

    async def drain(self) -> None:
        self.flush_partial()
        if self.buffered_bytes() <= HIGH_WATER or self.closed:
            return
        fut = self._loop.create_future()
        self._drain_waiters.append(fut)
        await fut

    def _fill_window(self) -> None:
        while self._snd_queue and len(self._snd_buf) < SND_WND:
            data = self._snd_queue.popleft()
            self._queued_bytes -= len(data)
            sn = self._snd_nxt
            self._snd_nxt += 1
            seg = _Seg(data)
            self._snd_buf[sn] = seg
            self._flight_bytes += len(data)
            self._transmit(sn, seg)

    def _transmit(self, sn: int, seg: _Seg) -> None:
        seg.xmit += 1
        seg.sent_at = time.monotonic()
        seg.rto = max(RTO_MIN, min(self._rto * (1.5 ** (seg.xmit - 1)), RTO_MAX))
        seg.skips = 0
        self._send_raw(_CMD_PUSH, sn, seg.data)

    def _send_raw(self, cmd: int, sn: int, payload: bytes = b"") -> None:
        hdr = _HDR.pack(self.conv, cmd, sn, self._rcv_nxt, len(payload))
        try:
            self._sendto(hdr + payload, self.addr)
        except OSError:
            pass  # transient socket error; retransmission covers the loss

    # ----------------------------------------------------------- receiving

    def input(self, data: bytes) -> None:
        """One datagram from the socket (header already conv-matched)."""
        if self.closed or len(data) < _HDR.size:
            return
        conv, cmd, sn, una, ln = _HDR.unpack_from(data)
        payload = data[_HDR.size : _HDR.size + ln]
        if len(payload) != ln:
            return  # truncated datagram
        freed = self._ack_upto(una)
        if cmd == _CMD_ACK:
            now = time.monotonic()
            for (ack_sn,) in struct.iter_unpack("<I", payload):
                self._ack_one(ack_sn, now)
            self._after_acks()
        elif freed:
            # una piggybacked on PUSH/FIN freed flight slots: refill the
            # window and wake drain() waiters now rather than waiting up to
            # UPDATE_INTERVAL for the next timer tick (bidirectional flows).
            self._fill_window()
            self._wake_drains()
        if cmd == _CMD_PUSH:
            self._push(sn, payload)
        elif cmd == _CMD_FIN:
            self._fin_at = sn
            self._send_raw(_CMD_ACK, 0, struct.pack("<I", sn))
            self._maybe_finish()

    def _push(self, sn: int, payload: bytes) -> None:
        if self._fin_at is not None and sn >= self._fin_at:
            # The peer's FIN covers exactly the segments below _fin_at: a
            # PUSH at or past it is corrupt/spoofed traffic. During the
            # half-closed linger it could otherwise reach feed_data after
            # feed_eof (StreamReader asserts); drop it unacked.
            return
        if sn > self._rcv_nxt + RCV_BUF_CAP:
            # Beyond the reorder window: drop WITHOUT acking, so the sender
            # retransmits once the window advances (acking here would pop it
            # from the peer's flight buffer and lose the bytes forever).
            return
        # Ack stored segments and duplicates alike — the prior ack may have
        # been lost.
        self._send_raw(_CMD_ACK, 0, struct.pack("<I", sn))
        if sn < self._rcv_nxt or sn in self._rcv_buf:
            return
        if self._fin_sn is not None and self._close_deadline is not None:
            # Inbound progress while we wait out our own close: keep the
            # session alive so the peer's response can finish delivering —
            # but never past the hard cap, or a peer that streams forever
            # without FINning holds the session (and its reader buffer)
            # open unboundedly.
            self._close_deadline = min(
                time.monotonic() + self.LINGER, self._close_hard
            )
        self._rcv_buf[sn] = payload
        while self._rcv_nxt in self._rcv_buf:
            self.reader.feed_data(self._rcv_buf.pop(self._rcv_nxt))
            self._rcv_nxt += 1
        self._maybe_finish()

    def _maybe_finish(self) -> None:
        # Half-close: the peer's FIN ends the READ side only. The write
        # side stays fully usable — locally queued and un-acked outbound
        # segments keep transmitting, and the app can still respond (the
        # TCP path would deliver both after a remote close). The session
        # fully closes when our own writer closes too (the FIN handshake in
        # _update), or after LINGER seconds of write-side idleness as leak
        # protection for handlers that never close their writer.
        if self._fin_at is not None and self._rcv_nxt >= self._fin_at:
            if not self._read_eof:
                self._read_eof = True
                self._half_close_deadline = time.monotonic() + self.LINGER
                self.reader.feed_eof()
            self._maybe_close_half_closed()

    def _maybe_close_half_closed(self) -> None:
        if not self._read_eof or self.closed:
            return
        if self._snd_buf or self._snd_queue or self._partial:
            return  # outbound data still delivering
        if self._fin_sn is not None:
            # Writer closed: the normal FIN completion in _update() owns
            # the close (fin acked, or its deadline); finish early here
            # when the ack already arrived.
            if self._fin_acked:
                self.close()
            return
        if time.monotonic() >= self._half_close_deadline:
            self.close()

    # -------------------------------------------------------------- acking

    def _ack_upto(self, una: int) -> int:
        """Drop in-flight segments below ``una``; returns how many freed."""
        acked = [s for s in self._snd_buf if s < una]
        for sn in acked:
            self._flight_bytes -= len(self._snd_buf.pop(sn).data)
        return len(acked)

    def _ack_one(self, sn: int, now: float) -> None:
        if self._fin_sn is not None and sn == self._fin_sn:
            self._fin_acked = True
        seg = self._snd_buf.pop(sn, None)
        if seg is None:
            return
        self._flight_bytes -= len(seg.data)
        if seg.xmit == 1:  # Karn: sample RTT only from unambiguous acks
            rtt = now - seg.sent_at
            if self._srtt == 0.0:
                self._srtt, self._rttvar = rtt, rtt / 2
            else:
                self._rttvar = 0.75 * self._rttvar + 0.25 * abs(self._srtt - rtt)
                self._srtt = 0.875 * self._srtt + 0.125 * rtt
            self._rto = max(RTO_MIN, min(self._srtt + 4 * self._rttvar, RTO_MAX))
        # Fast resend: anything older that keeps being skipped by newer acks.
        for older_sn, older in self._snd_buf.items():
            if older_sn < sn:
                older.skips += 1

    def _after_acks(self) -> None:
        for sn, seg in list(self._snd_buf.items()):
            if seg.skips >= FAST_RESEND:
                self._metrics.fast.add(1)
                self._transmit(sn, seg)
        self._fill_window()
        self._wake_drains()
        self._maybe_close_half_closed()

    # ------------------------------------------------------------ lifecycle

    def _update(self) -> None:
        if self.closed:
            return
        now = time.monotonic()
        for sn, seg in list(self._snd_buf.items()):
            if now - seg.sent_at >= seg.rto:
                if seg.xmit >= DEAD_XMIT:
                    self._metrics.dead.add(1)
                    self.close(ConnectionError("kcp dead link"))
                    return
                self._metrics.rto.add(1)
                self._transmit(sn, seg)
        # An idle tick flushes a lingering sub-MSS tail (write coalescing
        # above already batches; this bounds tail latency).
        if not self._snd_buf and not self._snd_queue and self._partial:
            self.flush_partial()
        if self._fin_sn is not None:
            done_sending = not self._snd_buf and not self._snd_queue
            # Both directions must finish before the full close: our FIN
            # acked AND the peer's FIN delivered (half-close: closing our
            # writer must not discard the peer's in-flight response). The
            # linger deadline bounds the wait for a peer that never FINs;
            # _push extends it while inbound data is still arriving.
            if (self._fin_acked and done_sending and self._read_eof) or (
                now >= self._close_deadline
            ):
                self.close()
                return
            if done_sending and not self._fin_acked:
                self._send_raw(_CMD_FIN, self._fin_sn)  # FIN retransmit
        self._maybe_close_half_closed()
        if self.closed:
            return
        self._wake_drains()
        self._update_handle = self._loop.call_later(UPDATE_INTERVAL, self._update)

    def _wake_drains(self) -> None:
        if self.buffered_bytes() <= HIGH_WATER or self.closed:
            for fut in self._drain_waiters:
                if not fut.done():
                    fut.set_result(None)
            self._drain_waiters.clear()

    LINGER = 5.0  # max seconds to keep delivering the tail after close()
    HALF_OPEN_MAX = 60.0  # hard cap on total post-close inbound lingering

    def start_close(self) -> None:
        """Graceful close (writer.close()): FIN covers ALL bytes written so
        far — including segments still waiting in the send queue, whose sns
        are preassigned by position — and the session lingers until the
        peer acks the FIN (everything delivered) or the deadline passes."""
        if self._fin_sn is not None or self.closed:
            return
        self.flush_partial()
        self._fin_sn = self._snd_nxt + len(self._snd_queue)
        now = time.monotonic()
        self._close_deadline = now + self.LINGER
        self._close_hard = now + self.HALF_OPEN_MAX
        self._send_raw(_CMD_FIN, self._fin_sn)

    def close(self, error: Optional[Exception] = None) -> None:
        if self.closed:
            return
        self.closed = True
        self._update_handle.cancel()
        if error is not None and not self._read_eof:
            self.reader.set_exception(error)
        else:
            # The inbound stream already finished cleanly (peer FIN fully
            # delivered): a send-side failure during the half-closed linger
            # (e.g. dead link) must not turn already-delivered data and its
            # clean EOF into a read error.
            self.reader.feed_eof()
        for fut in self._drain_waiters:
            if not fut.done():
                fut.set_result(None)
        self._drain_waiters.clear()
        if self._on_close is not None:
            self._on_close(self)


class KcpWriter:
    """StreamWriter-shaped facade over a session (the interface the signed
    framing layer consumes: write / drain / close /
    transport.get_write_buffer_size)."""

    def __init__(self, session: KcpSession):
        self._s = session
        self.transport = self  # .transport.get_write_buffer_size() duck type

    def get_write_buffer_size(self) -> int:
        return self._s.buffered_bytes()

    def write(self, data: bytes) -> None:
        self._s.write(data)
        self._s.flush_partial()

    async def drain(self) -> None:
        await self._s.drain()

    def close(self) -> None:
        if not self._s.closed:
            self._s.start_close()

    def is_closing(self) -> bool:
        return self._s.closed


class _Endpoint(asyncio.DatagramProtocol):
    """One UDP socket demuxing sessions by (remote addr, conv)."""

    TOMBSTONE_TTL = 30.0  # refuse re-accepting a closed (addr, conv) for this long

    def __init__(self, loop: asyncio.AbstractEventLoop,
                 on_accept: Optional[Callable] = None):
        self._loop = loop
        self._on_accept = on_accept  # server: cb(reader, writer)
        self.sessions: dict[tuple, KcpSession] = {}
        # Closed-session keys with expiry: a PUSH retransmission straggling
        # in after close must not resurrect a zombie session + handler.
        self._tombstones: dict[tuple, float] = {}
        self.transport: Optional[asyncio.DatagramTransport] = None

    def connection_made(self, transport) -> None:
        self.transport = transport

    def datagram_received(self, data: bytes, addr) -> None:
        if len(data) < _HDR.size:
            return
        conv, cmd, sn = _HDR.unpack_from(data)[:3]
        # Client endpoints are connected-UDP: one remote, sessions keyed by
        # conv alone (registered under addr=None before any reply arrives).
        key = (addr, conv) if self._on_accept is not None else (None, conv)
        sess = self.sessions.get(key)
        if sess is None:
            # Accept only a stream-INITIAL push (sn 0) from a non-tombstoned
            # key: mid-stream retransmissions for a dead session, and stray
            # ACK/FIN datagrams, must not create zombie sessions.
            if self._on_accept is None or cmd != _CMD_PUSH or sn != 0:
                return
            now = time.monotonic()
            dead_at = self._tombstones.get(key)
            if dead_at is not None:
                if now < dead_at + self.TOMBSTONE_TTL:
                    return
                del self._tombstones[key]
            if len(self._tombstones) > 4096:  # bounded; expire the stale
                self._tombstones = {
                    k: t for k, t in self._tombstones.items()
                    if now < t + self.TOMBSTONE_TTL
                }
            sess = self._make_session(conv, addr)
            reader, writer = sess.reader, KcpWriter(sess)
            self._loop.create_task(self._on_accept(reader, writer))
        sess.input(data)

    def _make_session(self, conv: int, addr) -> KcpSession:
        key = (addr, conv)

        def on_close(s, _key=key):
            self.sessions.pop(_key, None)
            self._tombstones[_key] = time.monotonic()

        sess = KcpSession(conv, addr, self._sendto, self._loop, on_close)
        self.sessions[key] = sess
        return sess

    def _sendto(self, data: bytes, addr) -> None:
        if self.transport is not None and not self.transport.is_closing():
            self.transport.sendto(data, addr)

    def close(self) -> None:
        for sess in list(self.sessions.values()):
            sess.close()
        if self.transport is not None:
            self.transport.close()


class KcpServer:
    """Server facade matching what the network layer uses from
    ``asyncio.AbstractServer``: ``.sockets[0].getsockname()``, ``.close()``."""

    def __init__(self, endpoint: _Endpoint):
        self._endpoint = endpoint

    @property
    def sockets(self):
        return [self._endpoint.transport.get_extra_info("socket")]

    def close(self) -> None:
        self._endpoint.close()


async def start_kcp_server(client_cb, host: str, port: int) -> KcpServer:
    """UDP-bind and dispatch each new (addr, conv) stream to ``client_cb``
    (same callback signature as ``asyncio.start_server``)."""
    loop = asyncio.get_running_loop()
    endpoint = _Endpoint(loop, on_accept=client_cb)
    await loop.create_datagram_endpoint(
        lambda: endpoint, local_addr=(host, port)
    )
    return KcpServer(endpoint)


async def open_kcp_connection(host: str, port: int):
    """Dial: returns (StreamReader, KcpWriter) like
    ``asyncio.open_connection``. The conv id is random; the session exists
    as soon as the first PUSH lands (no SYN round trip)."""
    loop = asyncio.get_running_loop()
    endpoint = _Endpoint(loop, on_accept=None)
    await loop.create_datagram_endpoint(
        lambda: endpoint, remote_addr=(host, port)
    )
    conv = struct.unpack("<I", os.urandom(4))[0]
    # connected-UDP transports pass addr=None to sendto
    sess = KcpSession(conv, None, lambda d, _a: endpoint._sendto(d, None), loop)
    endpoint.sessions[(None, conv)] = sess

    orig_on_close = sess._on_close

    def on_close(s):
        endpoint.sessions.pop((None, conv), None)
        endpoint.close()
        if orig_on_close:
            orig_on_close(s)

    sess._on_close = on_close
    return sess.reader, KcpWriter(sess)
