"""Wire format: the ``erasurecode.Shard`` proto3 message.

Byte-compatible with the reference's wire schema (field numbers and types
are the compatibility contract — SURVEY.md §2.3 D4):

    message Shard {                         // /root/reference/protobuf/shard.proto:21-27
      bytes  file_signature        = 1;
      bytes  shard_data            = 2;
      uint64 shard_number          = 3;
      uint64 total_shards          = 4;
      uint64 minimum_needed_shards = 5;
    }

The codec is hand-rolled (no protobuf runtime dependency), mirroring the
observable semantics of the reference's generated gogoproto code:

- marshal writes tags 0x0a/0x12/0x18/0x20/0x28 in field order and **omits**
  empty bytes / zero varints (proto3 default elision —
  /root/reference/protobuf/shard.pb.go:219-252);
- unmarshal is a varint-driven field loop with overflow and truncation
  checks (shard.pb.go:413-581); unknown fields are skipped, including
  nested group recursion (``skipShard``, shard.pb.go:582-680); a known
  field with the wrong wire type is an error;
- ``size()`` equals ``len(marshal())`` (shard.pb.go:355-376);
- ``populate(rng)`` is the randomized-instance generator the reference's
  fuzz tests build on (``NewPopulatedShard``, shard.pb.go:263-281).
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["Shard", "WireError", "marshal_shard", "unmarshal_shard"]

_MAX_VARINT_BYTES = 10  # 64-bit varints occupy at most 10 bytes


class WireError(ValueError):
    """Malformed wire bytes (truncation, varint overflow, bad wire type)."""


def _put_varint(out: bytearray, v: int) -> None:
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _varint_size(v: int) -> int:
    n = 1
    while v >= 0x80:
        v >>= 7
        n += 1
    return n


def _get_varint(buf: bytes, pos: int) -> tuple[int, int]:
    """Decode a varint at ``pos``; returns (value, new_pos)."""
    result = 0
    shift = 0
    start = pos
    while True:
        if pos >= len(buf):
            raise WireError("unexpected EOF in varint")
        if pos - start >= _MAX_VARINT_BYTES:
            raise WireError("varint overflow")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result & 0xFFFFFFFFFFFFFFFF, pos
        shift += 7


def _skip_field(buf: bytes, pos: int, wire_type: int, depth: int = 0) -> int:
    """Skip one unknown field's payload; mirrors skipShard
    (shard.pb.go:582-680) including group recursion."""
    if depth > 64:
        raise WireError("group nesting too deep")
    if wire_type == 0:  # varint
        _, pos = _get_varint(buf, pos)
        return pos
    if wire_type == 1:  # fixed64
        if pos + 8 > len(buf):
            raise WireError("unexpected EOF in fixed64")
        return pos + 8
    if wire_type == 2:  # length-delimited
        ln, pos = _get_varint(buf, pos)
        if ln < 0 or pos + ln > len(buf):
            raise WireError("unexpected EOF in bytes field")
        return pos + ln
    if wire_type == 3:  # start group: skip until matching end group
        while True:
            if pos >= len(buf):
                raise WireError("unexpected EOF in group")
            tag, pos = _get_varint(buf, pos)
            inner_type = tag & 0x7
            if inner_type == 4:  # end group
                return pos
            pos = _skip_field(buf, pos, inner_type, depth + 1)
    if wire_type == 5:  # fixed32
        if pos + 4 > len(buf):
            raise WireError("unexpected EOF in fixed32")
        return pos + 4
    raise WireError(f"illegal wire type {wire_type}")


_TEXT_ESCAPES = {0x07: "\\a", 0x08: "\\b", 0x0C: "\\f", 0x0A: "\\n",
                 0x0D: "\\r", 0x09: "\\t", 0x0B: "\\v",
                 0x22: '\\"', 0x27: "\\'", 0x5C: "\\\\"}
_TEXT_UNESCAPES = {"a": 7, "b": 8, "f": 12, "n": 10, "r": 13, "t": 9,
                   "v": 11, '"': 0x22, "'": 0x27, "\\": 0x5C, "?": 0x3F}


def _text_escape(b: bytes) -> str:
    """Proto text-format string escaping (C escapes + octal)."""
    out = []
    for c in b:
        esc = _TEXT_ESCAPES.get(c)
        if esc is not None:
            out.append(esc)
        elif 0x20 <= c < 0x7F:
            out.append(chr(c))
        else:
            out.append(f"\\{c:03o}")
    return "".join(out)


def _text_unescape(s: str, pos: int) -> tuple[bytes, int]:
    """Parse one quoted string starting at s[pos]; returns (bytes, end)."""
    quote = s[pos]
    pos += 1
    out = bytearray()
    n = len(s)
    while pos < n and s[pos] != quote:
        c = s[pos]
        if c != "\\":
            out += c.encode("utf-8")
            pos += 1
            continue
        pos += 1
        if pos >= n:
            raise WireError("dangling escape in text string")
        e = s[pos]
        if e in _TEXT_UNESCAPES:
            out.append(_TEXT_UNESCAPES[e])
            pos += 1
        elif e in "xX":
            pos += 1
            start = pos
            while pos < n and pos - start < 2 and s[pos] in "0123456789abcdefABCDEF":
                pos += 1
            if pos == start:
                raise WireError("bad hex escape in text string")
            out.append(int(s[start:pos], 16))
        elif e in "01234567":
            start = pos
            while pos < n and pos - start < 3 and s[pos] in "01234567":
                pos += 1
            val = int(s[start:pos], 8)
            if val > 255:
                raise WireError(f"octal escape \\{s[start:pos]} > 255")
            out.append(val)
        else:
            raise WireError(f"unknown escape \\{e} in text string")
    if pos >= n:
        raise WireError("unterminated text string")
    return bytes(out), pos + 1


@dataclass
class Shard:
    """One erasure-coded shard in flight (SURVEY.md C13).

    ``file_signature`` is the ed25519 signature of the *whole* original
    message (not this shard) — it identifies the reassembly pool and
    provides end-to-end integrity. ``total_shards``/``minimum_needed_shards``
    carry the RS geometry so the receiver never relies on its own defaults
    (main.go:73, §3.1 geometry note).
    """

    file_signature: bytes = b""
    shard_data: bytes = b""
    shard_number: int = 0
    total_shards: int = 0
    minimum_needed_shards: int = 0
    # --- streaming extension (fields 6-8; this framework only) ---------
    # Large objects stream as a sequence of independently erasure-coded
    # chunks sharing ONE object signature (file_signature signs the whole
    # object; per-chunk pools key on signature + chunk index). All three
    # fields zero-elide, so non-stream shards marshal byte-identically to
    # the reference schema, and reference decoders skip them as unknown
    # fields (shard.pb.go:582-680 skips; so does our _skip_field).
    # ``stream_chunk_count > 0`` marks a stream shard; every chunk carries
    # the same payload capacity (k * len(shard_data) bytes), and
    # ``stream_object_bytes`` trims the final chunk's zero padding.
    stream_chunk_index: int = 0
    stream_chunk_count: int = 0
    stream_object_bytes: int = 0

    def __str__(self) -> str:
        """Log-friendly one-liner (the gogoproto String(), SURVEY.md C20):
        byte fields as truncated hex, varints verbatim."""
        sig = self.file_signature.hex()
        data = self.shard_data
        body = data[:16].hex() + ("…" if len(data) > 16 else "")
        return (
            f"shard {self.shard_number}/{self.total_shards}"
            f"(min {self.minimum_needed_shards}) "
            f"sig={sig[:16]}… data[{len(data)}]={body}"
        )

    def gostring(self) -> str:
        """Evaluable constructor expression (the gogoproto GoString(),
        SURVEY.md C20) — ``eval(s.gostring())`` reproduces the shard."""
        return (
            f"Shard(file_signature={self.file_signature!r}, "
            f"shard_data={self.shard_data!r}, "
            f"shard_number={self.shard_number!r}, "
            f"total_shards={self.total_shards!r}, "
            f"minimum_needed_shards={self.minimum_needed_shards!r})"
        )

    # JSON / text-format field table: (attribute, jsonpb lowerCamelCase
    # name, kind). The reference's generated test suite round-trips both
    # representations (shardpb_test.go:84-137 — jsonpb, proto text,
    # compact text); these methods are the equivalents, cross-checked
    # against google.protobuf's json_format/text_format in
    # tests/test_wire_interop.py.
    _FIELDS = (
        ("file_signature", "fileSignature", "bytes"),
        ("shard_data", "shardData", "bytes"),
        ("shard_number", "shardNumber", "u64"),
        ("total_shards", "totalShards", "u64"),
        ("minimum_needed_shards", "minimumNeededShards", "u64"),
        ("stream_chunk_index", "streamChunkIndex", "u64"),
        ("stream_chunk_count", "streamChunkCount", "u64"),
        ("stream_object_bytes", "streamObjectBytes", "u64"),
    )

    def to_json_dict(self) -> dict:
        """proto3 JSON mapping (jsonpb): camelCase keys, bytes as
        standard base64, uint64 as decimal STRINGS, defaults omitted."""
        import base64

        out: dict = {}
        for attr, camel, kind in self._FIELDS:
            v = getattr(self, attr)
            if not v:
                continue
            if kind == "bytes":
                out[camel] = base64.b64encode(bytes(v)).decode("ascii")
            else:
                out[camel] = str(int(v))
        return out

    def to_json(self, indent=None) -> str:
        import json

        return json.dumps(self.to_json_dict(), indent=indent)

    @classmethod
    def from_json(cls, s) -> "Shard":
        """Parse jsonpb output; accepts both camelCase and original
        snake_case keys (required of proto3 JSON parsers), and u64 values
        as strings or numbers."""
        import base64
        import json

        obj = json.loads(s) if isinstance(s, (str, bytes)) else dict(s)
        if not isinstance(obj, dict):
            raise WireError("JSON shard must be an object")
        by_name = {}
        for attr, camel, kind in cls._FIELDS:
            by_name[camel] = (attr, kind)
            by_name[attr] = (attr, kind)
        kwargs: dict = {}
        for key, val in obj.items():
            hit = by_name.get(key)
            if hit is None:
                raise WireError(f"unknown JSON field {key!r}")
            attr, kind = hit
            if kind == "bytes":
                if not isinstance(val, str):
                    raise WireError(f"{key}: bytes field must be base64 string")
                # proto3 JSON parsers must accept the standard AND
                # URL-safe alphabets, padded or not (json_format does the
                # same normalize-then-decode); ONE strict decode after
                # normalization so foreign characters raise instead of
                # being silently dropped.
                b64 = val.replace("-", "+").replace("_", "/")
                b64 += "=" * (-len(b64) % 4)
                try:
                    kwargs[attr] = base64.b64decode(b64, validate=True)
                except Exception as exc:
                    raise WireError(f"{key}: invalid base64") from exc
            else:
                if isinstance(val, bool):
                    raise WireError(f"{key}: uint64 field got a bool")
                if isinstance(val, float):
                    if not val.is_integer():
                        raise WireError(f"{key}: uint64 got non-integer {val}")
                    val = int(val)
                try:
                    iv = int(val)
                except (TypeError, ValueError) as exc:
                    raise WireError(f"{key}: invalid uint64 {val!r}") from exc
                if not 0 <= iv < (1 << 64):
                    raise WireError(f"{key}: uint64 out of range")
                kwargs[attr] = iv
        return cls(**kwargs)

    def to_text(self) -> str:
        """proto text format, one ``name: value`` per line (gogo/golang
        text marshaling; shardpb_test.go:105-120)."""
        return "".join(
            f"{line}\n" for line in self._text_entries()
        )

    def to_compact_text(self) -> str:
        """Single-line text format (shardpb_test.go:122-137)."""
        return " ".join(self._text_entries())

    def _text_entries(self):
        for attr, _camel, kind in self._FIELDS:
            v = getattr(self, attr)
            if not v:
                continue
            if kind == "bytes":
                yield f'{attr}: "{_text_escape(bytes(v))}"'
            else:
                yield f"{attr}: {int(v)}"

    @classmethod
    def from_text(cls, s: str) -> "Shard":
        """Parse the text format (own output and google text_format's)."""
        by_name = {attr: kind for attr, _c, kind in cls._FIELDS}
        kwargs: dict = {}
        pos, n = 0, len(s)
        while True:
            while pos < n and s[pos] in " \t\r\n":
                pos += 1
            if pos >= n:
                break
            end = pos
            while end < n and (s[end].isalnum() or s[end] == "_"):
                end += 1
            name = s[pos:end]
            kind = by_name.get(name)
            if kind is None:
                raise WireError(f"unknown text field {name!r}")
            pos = end
            while pos < n and s[pos] in " \t":
                pos += 1
            if pos >= n or s[pos] != ":":
                raise WireError(f"expected ':' after {name}")
            pos += 1
            while pos < n and s[pos] in " \t":
                pos += 1
            if kind == "bytes":
                if pos >= n or s[pos] not in "\"'":
                    raise WireError(f"{name}: expected quoted string")
                chunks = []
                # Adjacent quoted strings concatenate (C/proto rule).
                while pos < n and s[pos] in "\"'":
                    part, pos = _text_unescape(s, pos)
                    chunks.append(part)
                    while pos < n and s[pos] in " \t":
                        pos += 1
                kwargs[name] = b"".join(chunks)
            else:
                end = pos
                while end < n and s[end] in "0123456789":
                    end += 1
                if end == pos:
                    raise WireError(f"{name}: expected integer")
                iv = int(s[pos:end])
                if iv >= (1 << 64):
                    raise WireError(f"{name}: uint64 out of range")
                kwargs[name] = iv
                pos = end
        return cls(**kwargs)

    def marshal(self) -> bytes:
        # shard_data dominates the message (often megabytes on the stream
        # path): join the three segments so its bytes are copied exactly
        # once, instead of bytearray-append + bytes() copying them twice.
        head = bytearray()
        if self.file_signature:
            head.append(0x0A)
            _put_varint(head, len(self.file_signature))
            head += self.file_signature
        if self.shard_data:
            head.append(0x12)
            _put_varint(head, len(self.shard_data))
        out = bytearray()
        if self.shard_number:
            out.append(0x18)
            _put_varint(out, self.shard_number)
        if self.total_shards:
            out.append(0x20)
            _put_varint(out, self.total_shards)
        if self.minimum_needed_shards:
            out.append(0x28)
            _put_varint(out, self.minimum_needed_shards)
        if self.stream_chunk_index:
            out.append(0x30)
            _put_varint(out, self.stream_chunk_index)
        if self.stream_chunk_count:
            out.append(0x38)
            _put_varint(out, self.stream_chunk_count)
        if self.stream_object_bytes:
            out.append(0x40)
            _put_varint(out, self.stream_object_bytes)
        if self.shard_data:
            return b"".join((bytes(head), self.shard_data, bytes(out)))
        return bytes(head + out)

    def marshal_parts(self) -> tuple:
        """``marshal()`` as (head, shard_data, tail) buffer parts whose
        concatenation is byte-identical to ``marshal()`` — the
        scatter-gather shape of the wire hot loop (docs/design.md §15):
        the transport signs the parts with a streaming hash and hands
        them to ``sendmsg`` as iovecs, so the dominant ``shard_data``
        buffer is never copied into a joined frame on the send path."""
        head = bytearray()
        if self.file_signature:
            head.append(0x0A)
            _put_varint(head, len(self.file_signature))
            head += self.file_signature
        data = self.shard_data
        if data:
            head.append(0x12)
            _put_varint(head, len(data))
        out = bytearray()
        if self.shard_number:
            out.append(0x18)
            _put_varint(out, self.shard_number)
        if self.total_shards:
            out.append(0x20)
            _put_varint(out, self.total_shards)
        if self.minimum_needed_shards:
            out.append(0x28)
            _put_varint(out, self.minimum_needed_shards)
        if self.stream_chunk_index:
            out.append(0x30)
            _put_varint(out, self.stream_chunk_index)
        if self.stream_chunk_count:
            out.append(0x38)
            _put_varint(out, self.stream_chunk_count)
        if self.stream_object_bytes:
            out.append(0x40)
            _put_varint(out, self.stream_object_bytes)
        return (bytes(head), data if data else b"", bytes(out))

    def size(self) -> int:
        n = 0
        if self.file_signature:
            ln = len(self.file_signature)
            n += 1 + _varint_size(ln) + ln
        if self.shard_data:
            ln = len(self.shard_data)
            n += 1 + _varint_size(ln) + ln
        if self.shard_number:
            n += 1 + _varint_size(self.shard_number)
        if self.total_shards:
            n += 1 + _varint_size(self.total_shards)
        if self.minimum_needed_shards:
            n += 1 + _varint_size(self.minimum_needed_shards)
        if self.stream_chunk_index:
            n += 1 + _varint_size(self.stream_chunk_index)
        if self.stream_chunk_count:
            n += 1 + _varint_size(self.stream_chunk_count)
        if self.stream_object_bytes:
            n += 1 + _varint_size(self.stream_object_bytes)
        return n

    @classmethod
    def unmarshal(cls, buf) -> "Shard":
        """Decode wire bytes into a Shard.

        ``buf`` may be ``bytes``, ``bytearray`` or a ``memoryview`` — the
        decoder walks it IN PLACE (the ring-buffer receive path hands in
        views of the recv ring, docs/design.md §15) and materializes each
        field as its own ``bytes`` exactly once, so the dominant
        ``shard_data`` payload is copied a single time end to end instead
        of whole-buffer-then-per-field."""
        if isinstance(buf, memoryview):
            buf = buf if buf.contiguous else bytes(buf)
        msg = cls()
        pos = 0
        while pos < len(buf):
            tag, pos = _get_varint(buf, pos)
            field_num = tag >> 3
            wire_type = tag & 0x7
            if field_num == 0:
                raise WireError("illegal field number 0")
            if field_num in (1, 2):
                if wire_type != 2:
                    raise WireError(
                        f"field {field_num}: expected wire type 2, got {wire_type}"
                    )
                ln, pos = _get_varint(buf, pos)
                if pos + ln > len(buf):
                    raise WireError("unexpected EOF in bytes field")
                # bytes() of a bytes slice is a no-op; of a memoryview
                # slice it is THE one copy this field ever pays.
                val = bytes(buf[pos : pos + ln])
                pos += ln
                if field_num == 1:
                    msg.file_signature = val
                else:
                    msg.shard_data = val
            elif field_num in (3, 4, 5, 6, 7, 8):
                if wire_type != 0:
                    raise WireError(
                        f"field {field_num}: expected wire type 0, got {wire_type}"
                    )
                val, pos = _get_varint(buf, pos)
                if field_num == 3:
                    msg.shard_number = val
                elif field_num == 4:
                    msg.total_shards = val
                elif field_num == 5:
                    msg.minimum_needed_shards = val
                elif field_num == 6:
                    msg.stream_chunk_index = val
                elif field_num == 7:
                    msg.stream_chunk_count = val
                else:
                    msg.stream_object_bytes = val
            else:
                pos = _skip_field(buf, pos, wire_type)
        return msg

    @classmethod
    def populate(cls, rng) -> "Shard":
        """Random instance for property/fuzz tests (mirrors
        NewPopulatedShard, shard.pb.go:263-281: 0-99-byte bytes fields,
        u32-range varints)."""
        return cls(
            file_signature=bytes(rng.integers(0, 256, size=int(rng.integers(0, 100)), dtype=int).tolist()),
            shard_data=bytes(rng.integers(0, 256, size=int(rng.integers(0, 100)), dtype=int).tolist()),
            shard_number=int(rng.integers(0, 1 << 32)),
            total_shards=int(rng.integers(0, 1 << 32)),
            minimum_needed_shards=int(rng.integers(0, 1 << 32)),
        )


def marshal_shard(s: Shard) -> bytes:
    return s.marshal()


def unmarshal_shard(buf: bytes) -> Shard:
    return Shard.unmarshal(buf)
