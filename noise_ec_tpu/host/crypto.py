"""Identity and signing: Ed25519 over BLAKE2b-256.

Reproduces the reference's L1 crypto contract (SURVEY.md §2.3 D3):

- ``KeyPair.random()`` — fresh identity per run (main.go:132), hex
  accessors for key logging (main.go:134-135);
- ``keys.sign(sig_policy, hash_policy, msg)`` — Ed25519 signature over
  ``blake2b_256(msg)`` (main.go:219-223);
- ``verify(sig_policy, hash_policy, pubkey, msg, sig)`` (main.go:82-89);
- ``serialize_message(peer_id, message)`` — the canonical signing preimage
  ``u32le(len(addr)) ‖ addr ‖ u32le(len(id)) ‖ id ‖ message``
  (main.go:276-302). Used for both signing and verification, so sender and
  receiver must agree on the peer's address string and node id.

Policies are small strategy objects so alternate algorithms can slot in,
matching the reference's SignaturePolicy/HashPolicy injection points
(main.go:38-41, 45-46).
"""

from __future__ import annotations

import functools
import hashlib
import logging
import os
import struct
import threading
from dataclasses import dataclass
from typing import Optional

try:  # OpenSSL-backed Ed25519: fast and constant-time. Preferred.
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    _HAVE_CRYPTOGRAPHY = True
except ImportError:  # hermetic images: the pure-Python RFC 8032 backend
    _HAVE_CRYPTOGRAPHY = False
    from noise_ec_tpu.host import _ed25519 as _pyed

    logging.getLogger("noise_ec_tpu.host").warning(
        "the 'cryptography' package is unavailable; Ed25519 falls back to "
        "the pure-Python backend (correct but slow and not constant-time "
        "— install cryptography for production use)"
    )

__all__ = [
    "Blake2bPolicy",
    "Ed25519Policy",
    "KeyPair",
    "PeerID",
    "serialize_message",
    "verify",
]


class Blake2bPolicy:
    """BLAKE2b-256 hash policy (noise/crypto/blake2b.New()).

    Prefers the native shim's streaming BLAKE2b (AVX512VL rotates in the
    compression function — bit-identical to hashlib by RFC 7693, cross-
    checked in tests/test_host_crypto.py) because whole-object sign and
    verify hashes dominate the host node's large-object stream path;
    hashlib is the always-available fallback.
    """

    digest_size = 32

    # Below this input size hashlib wins: the native path pays a ctypes
    # context allocate/marshal/free per hash, which beats the ~10% faster
    # compression function only when the payload amortizes it.
    NATIVE_MIN_BYTES = 1 << 18

    _native_factory = None  # resolved once; False = unavailable

    def _hasher(self, approx_size: Optional[int] = None):
        if approx_size is not None and approx_size < self.NATIVE_MIN_BYTES:
            return hashlib.blake2b(digest_size=self.digest_size)
        cls = type(self)
        if cls._native_factory is None:
            try:
                from noise_ec_tpu.shim import native_blake2b

                cls._native_factory = (
                    native_blake2b if native_blake2b(1) else False
                )
            except Exception:  # noqa: BLE001 — any shim failure -> hashlib
                cls._native_factory = False
        if cls._native_factory:
            try:
                return cls._native_factory(self.digest_size)
            except Exception:  # noqa: BLE001
                pass
        return hashlib.blake2b(digest_size=self.digest_size)

    def hash_bytes(self, data: bytes) -> bytes:
        h = self._hasher(len(data))
        h.update(data)
        return h.digest()

    def hash_parts(self, parts) -> bytes:
        """Hash the concatenation of ``parts`` without materializing it:
        bit-identical to ``hash_bytes(b"".join(parts))`` (BLAKE2b is a
        streaming hash), but skips the join copy — the signing preimage
        is header + full message (serialize_message), so on large objects
        the join is a whole-object memcpy."""
        approx = None
        if isinstance(parts, (tuple, list)):
            approx = sum(len(p) for p in parts)
        h = self._hasher(approx)
        for p in parts:
            h.update(p)
        return h.digest()


@functools.lru_cache(maxsize=1024)
def _parsed_public_key(public_key: bytes) -> "Ed25519PublicKey":
    """Parsed peer key, LRU-cached: reconstructing the object per verify
    cost ~35 us/message and a node talks to a small stable peer set."""
    return Ed25519PublicKey.from_public_bytes(public_key)


class Ed25519Policy:
    """Ed25519 signature policy (noise/crypto/ed25519.New())."""

    private_key_size = 32
    public_key_size = 32
    signature_size = 64

    def __init__(self) -> None:
        # Parsed signing keys cached PER POLICY INSTANCE, not in a module
        # global: a global cache keyed by the raw seed pins key material
        # beyond the owning KeyPair's lifetime and leaves it reachable via
        # cache introspection (r4 advisor). Discarding the policy (the
        # plugin holds it) releases the parsed keys with it. Tiny bound:
        # a node signs with its own few identities. Locked: one policy
        # instance signs from the transport's asyncio thread AND the
        # dispatch worker pool concurrently, and the LRU re-append
        # mutates the dict on every call.
        self._parsed_priv: dict[bytes, Ed25519PrivateKey] = {}
        self._priv_lock = threading.Lock()

    def sign(self, private_key: bytes, message: bytes) -> bytes:
        seed = bytes(private_key)
        with self._priv_lock:
            pk = self._parsed_priv.pop(seed, None)
            if pk is None:
                if len(self._parsed_priv) >= 8:
                    # Evict the LEAST-recently-used entry (insertion
                    # order + re-append-on-hit), so churning transient
                    # seeds cannot push out the node's hot identity.
                    self._parsed_priv.pop(next(iter(self._parsed_priv)))
                pk = (
                    Ed25519PrivateKey.from_private_bytes(seed)
                    if _HAVE_CRYPTOGRAPHY
                    else _pyed.SigningKey(seed)
                )
            self._parsed_priv[seed] = pk
        return pk.sign(message)

    def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        if len(public_key) != self.public_key_size:
            return False
        if not _HAVE_CRYPTOGRAPHY:
            return _pyed.verify(bytes(public_key), message, signature)
        try:
            _parsed_public_key(bytes(public_key)).verify(signature, message)
            return True
        except (InvalidSignature, ValueError):
            return False

    def verify_batch(self, items) -> list[bool]:
        """Per-item verdicts for ``[(public_key, message, signature),
        ...]``, amortizing whatever the backend can share — the wire hot
        loop's drain-quantum verify stage (docs/design.md §15).

        The verdict list is identical to ``[self.verify(*it) for it in
        items]``: the pure-Python backend runs true batch verification
        (one random-linear-combination equation with shared doublings)
        and fans back to per-item checks when the combined equation
        fails, so one bad signature never poisons its cohort; the
        OpenSSL backend has no batch entry point, so its amortization is
        the parsed-key LRU plus one call boundary per cohort."""
        items = list(items)
        if not _HAVE_CRYPTOGRAPHY:
            return _pyed.verify_batch(items)
        return [self.verify(pk, msg, sig) for pk, msg, sig in items]


@dataclass(frozen=True)
class KeyPair:
    """An Ed25519 identity (noise/crypto.KeyPair)."""

    private_key: bytes  # 32-byte seed
    public_key: bytes

    @classmethod
    def random(cls) -> "KeyPair":
        """Fresh identity, regenerated per run like the reference
        (ed25519.RandomKeyPair(), main.go:132)."""
        return cls.from_seed(os.urandom(32))

    @classmethod
    def from_seed(cls, seed: bytes) -> "KeyPair":
        if not _HAVE_CRYPTOGRAPHY:
            return cls(private_key=seed, public_key=_pyed.public_from_seed(seed))
        sk = Ed25519PrivateKey.from_private_bytes(seed)
        from cryptography.hazmat.primitives.serialization import (
            Encoding,
            PublicFormat,
        )

        return cls(
            private_key=seed,
            public_key=sk.public_key().public_bytes(Encoding.Raw, PublicFormat.Raw),
        )

    def private_key_hex(self) -> str:
        return self.private_key.hex()

    def public_key_hex(self) -> str:
        return self.public_key.hex()

    def sign(
        self, sig_policy: Ed25519Policy, hash_policy: Blake2bPolicy, message: bytes
    ) -> bytes:
        """Sign ``hash(message)`` — keys.Sign(sigPolicy, hashPolicy, msg),
        main.go:219-223."""
        return sig_policy.sign(self.private_key, hash_policy.hash_bytes(message))

    def sign_parts(
        self, sig_policy: Ed25519Policy, hash_policy: Blake2bPolicy, parts
    ) -> bytes:
        """``sign`` over the concatenation of ``parts`` (same signature
        bytes, no join copy)."""
        return sig_policy.sign(self.private_key, hash_policy.hash_parts(parts))


def verify(
    sig_policy: Ed25519Policy,
    hash_policy: Blake2bPolicy,
    public_key: bytes,
    message: bytes,
    signature: bytes,
) -> bool:
    """crypto.Verify(sigPolicy, hashPolicy, pubkey, msg, sig) — main.go:82-89."""
    return sig_policy.verify(public_key, hash_policy.hash_bytes(message), signature)


def verify_parts(
    sig_policy: Ed25519Policy,
    hash_policy: Blake2bPolicy,
    public_key: bytes,
    parts,
    signature: bytes,
) -> bool:
    """``verify`` over the concatenation of ``parts`` (no join copy)."""
    return sig_policy.verify(public_key, hash_policy.hash_parts(parts), signature)


@dataclass(frozen=True)
class PeerID:
    """Node identity on the wire (noise peer.ID: Address, Id, PublicKey).

    ``node_id`` is the BLAKE2b-256 hash of the public key, as in noise's
    peer.CreateID.
    """

    address: str
    node_id: bytes
    public_key: bytes

    @classmethod
    def create(cls, address: str, public_key: bytes) -> "PeerID":
        return cls(
            address=address,
            node_id=Blake2bPolicy().hash_bytes(public_key),
            public_key=public_key,
        )


def serialize_message(peer_id: PeerID, message: bytes) -> bytes:
    """Canonical signing preimage (main.go:276-302):
    ``u32le(len(addr)) ‖ addr ‖ u32le(len(id)) ‖ id ‖ message``.

    The reference panics if the assembled buffer length mismatches the
    precomputed size (main.go:297-299); here the construction makes that
    impossible by design.
    """
    return b"".join(serialize_message_parts(peer_id, message))


def serialize_message_parts(peer_id: PeerID, message: bytes) -> tuple:
    """``serialize_message`` as (header, message) parts — lets callers
    hash/sign the preimage without the whole-message join copy
    (``hash_parts``); the digest is identical by BLAKE2b streaming."""
    addr = peer_id.address.encode("utf-8")
    header = b"".join(
        [
            struct.pack("<I", len(addr)),
            addr,
            struct.pack("<I", len(peer_id.node_id)),
            peer_id.node_id,
        ]
    )
    return (header, message)
