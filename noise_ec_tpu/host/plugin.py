"""The shard plugin: encode/broadcast pipeline and receive state machine.

This is the reference's L4 (``ShardPlugin``, main.go:43-115, 201-241)
rebuilt on the TPU codec. The observable contract is preserved —

- every outgoing message is signed over the ``serialize_message`` preimage
  and the signature rides in each ``Shard.file_signature`` (main.go:219-223,
  228-239);
- the RS geometry (k, n) rides in every shard and the receiver always uses
  the arriving message's geometry, never its own defaults (main.go:73);
- when an input length is not divisible by k, the sender adjusts geometry
  instead of padding: k := largest prime factor of the length, n += k
  (main.go:185-191, reproduced bug-for-bug by the default policy);

— while the internal pool defects are fixed (see host.mempool).
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from collections import OrderedDict
from typing import Callable, Optional, Protocol

from noise_ec_tpu.codec.fec import FEC, Share
from noise_ec_tpu.host.crypto import (
    Blake2bPolicy,
    Ed25519Policy,
    KeyPair,
    PeerID,
    serialize_message,
    serialize_message_parts,
    verify,
    verify_parts,
)
from noise_ec_tpu.host.mempool import PoolLimitError, PoolTooLargeError, ShardPool
from noise_ec_tpu.host.wire import Shard
from noise_ec_tpu.obs.events import event
from noise_ec_tpu.obs.health import SLOEvaluator, record_e2e
from noise_ec_tpu.obs.metrics import Counters, Timer
from noise_ec_tpu.obs.registry import default_registry
from noise_ec_tpu.obs.trace import current_trace_id, span, trace_key


def _request_attrs(ctx=None) -> dict:
    """``{"request_trace": <id>}`` when the work runs inside a traced
    user request — on the send path read from the thread-local request
    scope, on the receive path from the delivery ``Ctx`` (the SHARD_BATCH
    frame's propagated trace block). The attr is what lets a collector
    merge signature-keyed pipeline spans into the originating request's
    fleet-wide trace; ``{}`` keeps untraced spans byte-identical."""
    rt = getattr(ctx, "trace", None) if ctx is not None else current_trace_id()
    return {"request_trace": rt} if rt else {}

__all__ = [
    "ShardPlugin",
    "PluginContext",
    "CorruptionError",
    "largest_prime_factor",
    "DEFAULT_MINIMUM_NEEDED_SHARDS",
    "DEFAULT_TOTAL_SHARDS",
]

log = logging.getLogger("noise_ec_tpu.host")

# Reference defaults: RS(k=4, n=6), two parity shards (main.go:34-35).
DEFAULT_MINIMUM_NEEDED_SHARDS = 4
DEFAULT_TOTAL_SHARDS = 6


class CorruptionError(RuntimeError):
    """All n shards arrived and the signature still does not verify — the
    message cannot be recovered (the reference's intended hard-failure
    branch, main.go:96-98; unreachable there, reachable here because the
    pool keeps accepting shares after a failed verify)."""


def largest_prime_factor(n: int) -> int:
    """Largest prime factor of ``n``; -1 for n <= 1.

    Mirrors ``largestPrimeFactors`` (main.go:303-335, trial division to
    sqrt) including its unguarded n <= 1 edge returning -1.
    """
    if n <= 1:
        return -1
    largest = -1
    while n % 2 == 0:
        largest = 2
        n //= 2
    p = 3
    while p * p <= n:
        while n % p == 0:
            largest = p
            n //= p
        p += 2
    if n > 1:
        largest = n
    return largest


class PluginContext(Protocol):
    """What the transport hands to ``receive`` — the slice of noise's
    ``network.PluginContext`` the reference uses (main.go:53-87)."""

    def message(self) -> object: ...
    def sender(self) -> PeerID: ...
    def client_public_key(self) -> bytes: ...


class ShardPlugin:
    """Erasure-shard broadcast/reassembly plugin.

    Construction mirrors ``NewShardPlugin`` (main.go:108-115): signature
    and hash policies plus the default RS geometry are injected; per-message
    geometry still rides the wire and wins on receive.
    """

    def __init__(
        self,
        signature_policy: Optional[Ed25519Policy] = None,
        hash_policy: Optional[Blake2bPolicy] = None,
        minimum_needed_shards: int = DEFAULT_MINIMUM_NEEDED_SHARDS,
        total_shards: int = DEFAULT_TOTAL_SHARDS,
        *,
        backend: str = "device",
        on_message: Optional[Callable[[bytes, PeerID], None]] = None,
        on_object: Optional[Callable[[bytearray, PeerID], None]] = None,
        pool_ttl_seconds: Optional[float] = ShardPool.DEFAULT_TTL_SECONDS,
        pool_max_pools: int = ShardPool.DEFAULT_MAX_POOLS,
        pool_max_total_bytes: int = ShardPool.DEFAULT_MAX_TOTAL_BYTES,
        adjust_geometry: bool = True,
        store=None,
        slo: Optional[SLOEvaluator] = None,
    ):
        self.signature_policy = signature_policy or Ed25519Policy()
        self.hash_policy = hash_policy or Blake2bPolicy()
        self.minimum_needed_shards = minimum_needed_shards
        self.total_shards = total_shards
        self.backend = backend
        self.on_message = on_message
        # Zero-copy delivery for STREAM objects: receives the verified
        # reassembly buffer itself (a bytearray whose ownership transfers
        # to the callee — the plugin drops every reference first). The
        # reference's Go plugin hands its decode output []byte to the
        # logger without a copy (main.go:92); on_message's immutable-bytes
        # contract forces a whole-object copy per delivery, which on
        # multi-hundred-MB/s streams is a measurable tax. When set it
        # takes precedence over on_message for stream objects; single
        # messages always use on_message.
        self.on_object = on_object
        self.adjust_geometry = adjust_geometry
        # Optional stripe store (store.StripeStore): verified receives
        # land in it as full stripes, and every arriving shard is offered
        # to it first — a shard for a stripe we already hold is absorbed
        # (or matched as a duplicate) there instead of re-walking the
        # pool/decode/verify path, which is what makes the repair
        # engine's anti-entropy exchange ride the plain SHARD opcode.
        self.store = store
        # Optional placement policy (placement.TargetedDelivery): when
        # wired, targeted sends consult the ring and the receive path
        # store-absorbs shards whose assigned failure domain is ours —
        # additively, never consuming, so broadcast semantics (chat,
        # manifests) are untouched. None = pure broadcast, the default.
        self.placement = None
        self.pool = ShardPool(
            ttl_seconds=pool_ttl_seconds,
            max_pools=pool_max_pools,
            max_total_bytes=pool_max_total_bytes,
        )
        self.counters = Counters()
        # End-to-end outcome events (obs/health.py): every completed or
        # failed object records into noise_ec_e2e_latency_seconds and an
        # SLO evaluator. None routes to the process default (the one the
        # CLI wires to /healthz); tests pass their own.
        self.slo = slo
        # Decode-path histograms (p50/p99 surfaces — the flat decode_s
        # sum stays for back-compat but cannot answer tail questions).
        # Children resolved once; observe is a lock + bisect + adds.
        reg = default_registry()
        self._decode_hist = reg.histogram("noise_ec_decode_seconds").labels()
        self._decode_bytes_hist = reg.histogram("noise_ec_decode_bytes").labels()
        # Geometry is runtime-dynamic (SURVEY.md §7.4); cache one codec per
        # (k, n) so repeated geometries reuse their jitted kernels. LRU-
        # bounded: geometry is attacker-influenced on the receive path, and
        # each FEC holds generator matrices + jitted kernels.
        self._fec_cache: OrderedDict[tuple[int, int], FEC] = OrderedDict()
        self._fec_lock = threading.Lock()
        self.fec_cache_size = 64
        # GF(2^8) bound: n distinct evaluation points cap total shards at
        # the field order (rs.py enforces the same on construction).
        self.max_total_shards = 256
        # Duplicate-delivery suppression: signatures of recently completed
        # objects with their completion time. Shards still in flight after
        # a decode+evict can re-accumulate to k distinct and deliver the
        # message again (the reference re-logs in that case). Suppression
        # is WINDOWED, not permanent: the signature is deterministic
        # (Ed25519 over a nonce-free preimage), so an identical message
        # legitimately re-broadcast later produces the same signature — a
        # permanent cache would swallow it. Within the window: exactly
        # once; beyond it: at-least-once, like the reference.
        # The window is a tradeoff, kept SHORT: a user legitimately
        # re-broadcasting the identical plaintext within the window loses
        # the repeat (indistinguishable on the wire from the first
        # broadcast's stragglers). 5s covers in-flight shard tails without
        # noticeably shadowing interactive repeats; 0 disables dedup.
        self._completed: OrderedDict[str, float] = OrderedDict()
        self._completed_lock = threading.Lock()
        self.completed_cache_size = 4096
        self.dedup_window_seconds = 5.0
        # Guards the (minimum_needed_shards, total_shards) read-modify-write
        # in _adjusted_geometry: concurrent prepare_shards calls must not
        # tear the geometry or skip the max_total_shards validation
        # (round-1 ADVICE finding 5).
        self._geometry_lock = threading.Lock()
        # Stream reassembly state (see _receive_stream) — initialized here,
        # not lazily: concurrent first stream shards must share one lock
        # and one table, and operator-configured caps must survive.
        self._streams: OrderedDict[str, dict] = OrderedDict()
        self._streams_lock = threading.Lock()
        self.max_stream_object_bytes = self.DEFAULT_MAX_STREAM_OBJECT_BYTES
        self.max_stream_objects = self.DEFAULT_MAX_STREAM_OBJECTS
        self.max_stream_total_bytes = self.DEFAULT_MAX_STREAM_TOTAL_BYTES
        self.max_stream_chunks = self.DEFAULT_MAX_STREAM_CHUNKS
        self._stream_buf_bytes = 0  # sum of active reassembly buffers
        self._shim_cache: dict[tuple[int, int], object] = {}
        # Novel-geometry rate limiter state (see _fec_receive) + the
        # host-only fallback codec cache for rate-limited senders.
        self._novel_geometry: OrderedDict[bytes, list] = OrderedDict()
        # Admission lifecycle: _fec_receive puts a granted novel geometry
        # into _novel_pending; the decode sites move it to _novel_inflight
        # for exactly the duration of the first decode (where the kernel
        # compile happens) and clear it in their finally. Only INFLIGHT
        # entries count against NOVEL_COMPILES_INFLIGHT_MAX, so stray
        # shards that never assemble to k cannot pin the admission budget
        # (r5 holistic review).
        self._novel_pending: dict[tuple, float] = {}
        self._novel_inflight: dict[tuple, float] = {}
        # Admission timestamps for the global window backstop.
        self._novel_global: list = []
        self._novel_lock = threading.Lock()
        self._fec_host_cache: OrderedDict[tuple[int, int], FEC] = OrderedDict()
        # NACK live shard repair (docs/resilience.md): a pool stuck with
        # 0 < have < k distinct shards past the grace timeout re-sends
        # its held shards — the PR-2 anti-entropy interest framing, over
        # the plain SHARD opcode — first directly to the original sender
        # (transport ``send_to``), then broadcast to peers; a peer (or
        # the sender) storing the stripe answers with its trusted
        # shards, which complete the pool through the ordinary receive
        # path. Retries back off exponentially (capped); exhausting the
        # budget records an ``outcome=incomplete`` e2e event.
        # ``nack_grace_seconds = 0`` disables. The sweeper thread starts
        # on the first stuck pool and exits when none remain.
        self.nack_grace_seconds = 1.0
        self.nack_max_retries = 4
        self.nack_backoff_base = 0.5
        self.nack_backoff_cap = 8.0
        self._nack_lock = threading.Lock()
        self._nack: OrderedDict[str, dict] = {}
        self._nack_thread: Optional[threading.Thread] = None
        self._network = lambda: None  # weakref to the attached transport
        self._nack_requests = reg.counter(
            "noise_ec_nack_requests_total"
        ).labels()
        self._nack_repaired = reg.counter(
            "noise_ec_nack_repaired_total"
        ).labels()
        self._nack_giveups = reg.counter(
            "noise_ec_nack_giveups_total"
        ).labels()

    def attach_network(self, network) -> None:
        """Give the receive path a transport handle for NACK repair
        (transports call this from ``add_plugin``; weakly held so a
        plugin can never pin a closed network)."""
        self._network = weakref.ref(network)

    # ---------------------------------------------------------------- codec

    def _fec(self, k: int, n: int) -> FEC:
        # Locked: receive() runs on the transport thread while
        # prepare_shards() runs on the caller's, and LRU mutation
        # (move_to_end / popitem) is not safe to interleave.
        with self._fec_lock:
            fec = self._fec_cache.get((k, n))
            if fec is not None:
                self._fec_cache.move_to_end((k, n))
                return fec
        fec = FEC(k, n, backend=self.backend)  # build outside the lock
        return self._cache_put_locked(self._fec_cache, (k, n), fec)

    # Per-sender novel-geometry budget on the RECEIVE path: geometry rides
    # in every message (main.go:73), and on the device backend the first
    # use of a fresh (k, n) compiles kernels — seconds. Without a cap one
    # hostile sender minting fresh geometries keeps a dispatch worker
    # perpetually compiling (round-3 VERDICT weak #5). Within the window a
    # sender gets this many novel geometries on the full backend; beyond
    # it, decodes fall back to a host-only codec (numpy/shim — correct,
    # no kernel compile) until a geometry recurs or the window rolls.
    NOVEL_GEOMETRY_WINDOW_SECONDS = 60.0
    NOVEL_GEOMETRY_PER_WINDOW = 8
    # Aggregate control across ALL senders (identities are cheap to mint,
    # so the per-sender budget alone is bypassed by key rotation) — TWO
    # mechanisms, primary + backstop. Primary: a cap on compiles IN
    # FLIGHT (admissions whose first full-backend decode has not
    # completed), so bystanders fall to the host codec only while the
    # compile pipeline is actually saturated; slots free as each first
    # decode lands, or after the grace timeout when one never does. This
    # replaced r4's TIGHT global window count (32), which let one
    # key-rotating flooder demote every bystander for a full window
    # (verdict weak #6).
    NOVEL_COMPILES_INFLIGHT_MAX = 2
    NOVEL_COMPILE_GRACE_SECONDS = 60.0
    # Backstop: a LOOSE window ceiling on total admissions. The in-flight
    # cap alone bounds concurrency, not total work — a flooder whose
    # geometries compile fast could keep both slots perpetually owned and
    # churn the codec LRU. This ceiling bounds compiles + cache insertions
    # per window. Deliberately HIGH (2x r4's 32): any window ceiling
    # demotes bystanders once exhausted — an inherent tension under
    # identity rotation (attacker and bystander are indistinguishable) —
    # so it should engage only under a genuinely heavy flood, with the
    # in-flight cap doing the everyday work.
    NOVEL_GEOMETRY_GLOBAL_PER_WINDOW = 64

    @staticmethod
    def _sender_key(ctx: PluginContext) -> bytes:
        try:
            return bytes(ctx.client_public_key())
        # noise-ec: allow(event-on-swallow) — identity-less test transports — empty identity is the contract
        except Exception:  # noqa: BLE001 — identity-less test transports
            return b""

    def _cache_put_locked(self, cache, key, fec: FEC) -> FEC:
        """LRU insert-or-get under self._fec_lock (shared by both codec
        caches so the eviction policy cannot diverge)."""
        with self._fec_lock:
            cache.setdefault(key, fec)
            cache.move_to_end(key)
            while len(cache) > self.fec_cache_size:
                cache.popitem(last=False)
            return cache[key]

    def _fec_receive(self, k: int, n: int, ctx: PluginContext) -> FEC:
        """Receive-path codec lookup with the novel-geometry rate caps
        (per sender AND global). Cached geometries (the steady state:
        senders reuse their geometry) bypass the limiter entirely."""
        with self._fec_lock:
            fec = self._fec_cache.get((k, n))
            if fec is not None:
                self._fec_cache.move_to_end((k, n))
                return fec
        if self.backend == "numpy":
            return self._fec(k, n)  # no compile cost to protect
        sender_key = self._sender_key(ctx)
        now = time.monotonic()
        cutoff = now - self.NOVEL_GEOMETRY_WINDOW_SECONDS
        with self._novel_lock:
            dq = self._novel_geometry.get(sender_key)
            if dq is None:
                dq = self._novel_geometry[sender_key] = []
                # Bound the tracking table itself.
                while len(self._novel_geometry) > 1024:
                    self._novel_geometry.pop(
                        next(iter(self._novel_geometry))
                    )
            else:
                self._novel_geometry.move_to_end(sender_key)
            while dq and dq[0] < cutoff:
                dq.pop(0)
            # Release in-flight slots whose first decode never completed
            # (connection died mid-object, decode raised): the compile is
            # over by the grace deadline either way.
            stale = now - self.NOVEL_COMPILE_GRACE_SECONDS
            for g in [g for g, t0 in self._novel_inflight.items() if t0 < stale]:
                del self._novel_inflight[g]
            for g in [g for g, t0 in self._novel_pending.items() if t0 < cutoff]:
                del self._novel_pending[g]
            while self._novel_global and self._novel_global[0] < cutoff:
                self._novel_global.pop(0)
            limited = (
                len(dq) >= self.NOVEL_GEOMETRY_PER_WINDOW
                or len(self._novel_inflight)
                >= self.NOVEL_COMPILES_INFLIGHT_MAX
                or len(self._novel_global)
                >= self.NOVEL_GEOMETRY_GLOBAL_PER_WINDOW
            )
            if not limited:
                dq.append(now)
                self._novel_pending[(k, n)] = now
                self._novel_global.append(now)
        if not limited:
            return self._fec(k, n)
        self.counters.add("geometry_rate_limited", 1)
        with self._fec_lock:
            fec = self._fec_host_cache.get((k, n))
            if fec is not None:
                self._fec_host_cache.move_to_end((k, n))
                return fec
        return self._cache_put_locked(
            self._fec_host_cache, (k, n), FEC(k, n, backend="numpy")
        )

    def _geometry_decode_begin(self, k: int, n: int) -> None:
        """Admitted geometry's first decode is starting: occupy an
        in-flight compile slot for its duration (see _novel_pending)."""
        with self._novel_lock:
            if self._novel_pending.pop((k, n), None) is not None:
                self._novel_inflight[(k, n)] = time.monotonic()

    def _geometry_ready(self, k: int, n: int) -> None:
        """Release the compile slot for (k, n): its first full-backend
        decode finished (either way — the compile is over), so the
        geometry no longer occupies the global admission budget."""
        with self._novel_lock:
            self._novel_inflight.pop((k, n), None)
            self._novel_pending.pop((k, n), None)

    def prewarm(self, geometries=None, stripe_len: int = 64,
                ladder: int = 0) -> None:
        """Build (and jit-warm) codecs for ``geometries`` before traffic.

        First use of a novel (k, n) constructs the FEC and, on the device
        backend, compiles its kernels — seconds of latency that would
        otherwise land on the dispatch path of whichever peer sends that
        geometry first (round-1 ADVICE finding 3). Call at startup with the
        geometries you expect; defaults to this plugin's own (k, n).

        ``ladder > 1`` additionally pre-warms the power-of-two batch
        ladder up to that size (the coalescer's quantized batch
        programs, ops/dispatch.prewarm_ladder) — paired with the
        persistent compile cache (-compile-cache-dir) so a restart
        replays the whole program set from disk instead of recompiling
        it under live traffic.
        """
        if geometries is None:  # explicit [] means: warm nothing
            geometries = [(self.minimum_needed_shards, self.total_shards)]
        for k, n in geometries:
            fec = self._fec(k, n)
            shares = fec.encode_shares(bytes(k * stripe_len))  # content is irrelevant
            fec.decode(shares[:k])
            if ladder > 1 and fec._rs._dev is not None:
                from noise_ec_tpu.ops.dispatch import prewarm_ladder

                prewarm_ladder(
                    fec._rs._dev, fec._rs.G[k:], max_batch=ladder
                )

    def _recently_completed(self, key: str) -> bool:
        """True iff ``key`` completed within the dedup window. Lazily drops
        expired entries."""
        with self._completed_lock:
            done_at = self._completed.get(key)
            if done_at is None:
                return False
            if time.monotonic() - done_at >= self.dedup_window_seconds:
                del self._completed[key]
                return False
            return True

    def _mark_completed(self, key: str) -> bool:
        """Record completion; returns False if another thread won the race
        (caller must not deliver again)."""
        with self._completed_lock:
            if key in self._completed:
                return False
            self._completed[key] = time.monotonic()
            while len(self._completed) > self.completed_cache_size:
                self._completed.popitem(last=False)
            return True

    # ----------------------------------------------------------- send path

    def shard_and_broadcast(
        self, network, input_bytes: bytes,
        *, geometry: Optional[tuple[int, int]] = None,
        targeted: bool = False,
    ) -> list[Shard]:
        """Encode ``input_bytes`` and broadcast one message per shard to all
        peers (main.go:201-210). Returns the shards for callers that want
        them (the reference discards them). ``geometry`` pins an explicit
        (k, n) instead of the plugin's mutable default — the object
        service's per-namespace geometry rides this.

        ``targeted`` opts the cohort into ring-directed placement
        (docs/placement.md): when a :class:`TargetedDelivery` policy is
        wired (``self.placement``), each shard goes ONLY to its assigned
        owner — one SHARD_BATCH cohort frame per destination peer,
        peers× wire fan-out cut to n×. Only the object service's data
        stripes pass ``targeted=True``; chat and manifest broadcasts
        stay full-fan-out so every node can index and the REPL is
        unchanged. With no placement policy (or a transport without the
        directed surface) the call is byte-identical to the broadcast
        path."""
        shards = self.prepare_shards(
            network.id, network.keys, input_bytes, geometry=geometry
        )
        # The origin keeps its own object too: anti-entropy repair
        # (store/repair.py) can then serve any peer that rots, and the
        # sender's stripe is the fleet's ground-truth copy.
        self._store_put_raw(
            shards[0].file_signature, input_bytes,
            int(shards[0].minimum_needed_shards),
            int(shards[0].total_shards),
            network.id.address, bytes(network.keys.public_key),
        )
        with span(
            "broadcast",
            key=trace_key(shards[0].file_signature),
            shards=len(shards),
            **_request_attrs(),
        ):
            placed = None
            if targeted and self.placement is not None:
                placed = self.placement.send(network, shards)
            if placed is None:
                # One cohort call: the TCP transport coalesces the whole
                # broadcast into a single SHARD_BATCH frame per peer
                # flush (one signature, one verify, one sendmsg —
                # design.md §15); transports without the hook keep
                # per-shard semantics.
                many = getattr(network, "broadcast_many", None)
                if many is not None:
                    many(shards)
                else:
                    for shard in shards:
                        network.broadcast(shard)
        self.counters.add("shards_out", len(shards))
        self.counters.add("bytes_out", sum(len(s.shard_data) for s in shards))
        return shards

    def prepare_shards(
        self, node_id: PeerID, keys: KeyPair, input_bytes: bytes,
        *, geometry: Optional[tuple[int, int]] = None,
    ) -> list[Shard]:
        """Sign the plaintext, split it into shares, wrap each in a wire
        ``Shard`` (main.go:211-241).

        The reference shadows and never checks the ``Sign`` error
        (main.go:219, noted in SURVEY.md C8); here a signing failure
        propagates. An explicit ``geometry`` bypasses the reference's
        mutable adjusted-geometry dance entirely: the caller promises a
        payload length divisible by k (the object service pads its
        stripes) and the plugin state is never touched.
        """
        if not input_bytes:
            raise ValueError("cannot prepare shards for empty input")  # main.go:215-217
        with span("prepare", nbytes=len(input_bytes),
                  **_request_attrs()) as psp:
            if geometry is not None:
                k, n = geometry
                if not 1 <= k <= n <= self.max_total_shards:
                    raise ValueError(
                        f"invalid explicit geometry k={k} n={n}"
                    )
                if len(input_bytes) % k:
                    raise ValueError(
                        f"input length {len(input_bytes)} is not a "
                        f"multiple of k={k} (explicit geometry does not "
                        "adjust; pad the payload)"
                    )
            else:
                k, n = self._adjusted_geometry(len(input_bytes))
            # The trace key IS the signature prefix, so the sign span
            # attaches it from inside (known only after signing) and the
            # enclosing prepare span adopts it before its own exit.
            with span("sign") as ssp:
                file_signature = keys.sign(
                    self.signature_policy,
                    self.hash_policy,
                    serialize_message(node_id, input_bytes),
                )
                ssp.set_key(trace_key(file_signature))
            psp.set_key(trace_key(file_signature))
            with span("encode", k=k, n=n):
                shares = self._fec(k, n).encode_shares(input_bytes)
        return [
            Shard(
                file_signature=file_signature,
                shard_data=s.data,
                shard_number=s.number,
                total_shards=n,
                minimum_needed_shards=k,
            )
            for s in shares
        ]

    def _adjusted_geometry(self, length: int) -> tuple[int, int]:
        """Dynamic geometry adjustment (main.go:185-191), reproduced
        bug-for-bug: when the length is not divisible by k, k becomes the
        largest prime factor of the length (so a prime-length message
        degenerates to k = length, 1-byte stripes) and n *accumulates* —
        ``n += k`` mutates plugin state, so n only ever grows over the
        process lifetime. Interop is unaffected either way because geometry
        rides in every shard; pass ``adjust_geometry=False`` to refuse
        (raise) instead."""
        with self._geometry_lock:
            k, n = self.minimum_needed_shards, self.total_shards
            if length % k == 0:
                return k, n
            if not self.adjust_geometry:
                raise ValueError(
                    f"input length {length} is not a multiple of k={k} "
                    "and geometry adjustment is disabled"
                )
            k = largest_prime_factor(length)
            if k < 1:
                raise ValueError(f"cannot shard {length}-byte input")
            # Validate BEFORE mutating plugin state: an over-field geometry
            # must not brick every subsequent send (the reference would panic
            # inside infectious here; we reject and keep the old geometry).
            if n + k > self.max_total_shards:
                raise ValueError(
                    f"adjusted geometry k={k} n={n + k} exceeds the GF(2^8) "
                    f"limit of {self.max_total_shards} total shards; message "
                    f"length {length} cannot be sharded with accumulated n={n}"
                )
            self.minimum_needed_shards = k
            self.total_shards = n + k
            log.info(
                "revised geometry: minimum_needed_shards=%d total_shards=%d",
                self.minimum_needed_shards,
                self.total_shards,
            )
            return self.minimum_needed_shards, self.total_shards

    # ---------------------------------------------------- streaming objects

    # Caps for the stream reassembly state (attacker-influenced sizes ride
    # in every stream shard, so all are validated before allocation):
    # per-object bytes, objects in flight, TOTAL reassembly-buffer bytes
    # across objects (a forged tiny shard pins a whole object's buffer,
    # so per-object x objects alone would multiply), and chunk count
    # (teardown/repair loops iterate it).
    DEFAULT_MAX_STREAM_OBJECT_BYTES = 1 << 30
    DEFAULT_MAX_STREAM_OBJECTS = 8
    DEFAULT_MAX_STREAM_TOTAL_BYTES = 1 << 30
    DEFAULT_MAX_STREAM_CHUNKS = 4096
    STREAM_TTL_SECONDS = 120.0

    def stream_and_broadcast(
        self,
        network,
        data: bytes,
        *,
        chunk_bytes: int = 4 << 20,
        geometry: Optional[tuple[int, int]] = None,
    ) -> int:
        """Broadcast a large object as a stream of erasure-coded chunks.

        The reference's node pushes whole stdin lines through one codec
        call (main.go:201-210); objects far beyond one codeword need the
        streaming shape instead (SURVEY.md §5 "long-context" row): the
        object is signed ONCE (same ``serialize_message`` preimage as a
        plain broadcast), split into fixed-capacity chunks, each chunk
        encoded as an independent RS(k, n) codeword — on the device
        backend through the pipelined ``StreamingEncoder``, so chunk i+1
        transfers while chunk i computes — and every share travels as a
        wire ``Shard`` carrying (chunk_index, chunk_count, object_bytes)
        in the streaming extension fields (wire.py fields 6-8).

        Chunk loss is repaired per chunk by the parity shares; corruption
        surfaces at the object-level signature verify on the receiver,
        exactly the reference's trust model (main.go:82-99). Returns the
        number of chunks sent.
        """
        if not data:
            raise ValueError("cannot stream an empty object")
        k, n, B, count = self._stream_plan(len(data), chunk_bytes, geometry)
        # Same preimage as a plain broadcast (serialize_message), hashed
        # in parts to skip a whole-object join copy.
        with span("sign", nbytes=len(data)) as ssp:
            file_signature = network.keys.sign_parts(
                self.signature_policy,
                self.hash_policy,
                serialize_message_parts(network.id, data),
            )
            ssp.set_key(trace_key(file_signature))
        # Whole object already in memory: keep the origin copy (one
        # stripe per object — the store's geometry, not the chunking).
        self._store_put_raw(
            file_signature, data, k, n,
            network.id.address, bytes(network.keys.public_key),
        )
        view = memoryview(data)
        chunks = (view[i * B : (i + 1) * B] for i in range(count))
        return self._emit_stream(
            network, file_signature, k, n, B, count, len(data), chunks
        )

    def stream_and_broadcast_file(
        self,
        network,
        path: str,
        *,
        chunk_bytes: int = 4 << 20,
        geometry: Optional[tuple[int, int]] = None,
    ) -> int:
        """Stream a FILE without loading it into memory.

        Sender memory stays O(chunk): pass 1 computes the object
        signature by streaming the file through the hash (same
        ``serialize_message`` preimage — bit-identical signature to
        ``stream_and_broadcast`` of the same bytes), pass 2 reads, encodes
        and broadcasts one chunk at a time.
        """
        import os

        stat0 = os.stat(path)
        size = stat0.st_size
        if size == 0:
            raise ValueError("cannot stream an empty file")
        k, n, B, count = self._stream_plan(size, chunk_bytes, geometry)
        header = serialize_message_parts(network.id, b"")[0]

        def sig_parts():
            yield header
            with open(path, "rb") as f:
                while True:
                    blk = f.read(4 << 20)
                    if not blk:
                        return
                    yield blk

        with span("sign", nbytes=size) as ssp:
            file_signature = network.keys.sign_parts(
                self.signature_policy, self.hash_policy, sig_parts()
            )
            ssp.set_key(trace_key(file_signature))

        def chunks():
            with open(path, "rb") as f:
                for _ in range(count):
                    yield f.read(B)

        sent = self._emit_stream(
            network, file_signature, k, n, B, count, size, chunks()
        )
        # Two-pass hazard: pass 1 signed the file, pass 2 re-read it. If
        # the file changed in between, every receiver reassembles bytes
        # that can never verify — the sender must report failure, not
        # success (round-3 ADVICE finding 2). size + mtime_ns catches
        # every ordinary rewrite; a same-size same-mtime splice is below
        # the filesystem's own change-detection granularity.
        stat1 = os.stat(path)
        if (stat1.st_size, stat1.st_mtime_ns) != (size, stat0.st_mtime_ns):
            raise RuntimeError(
                f"{path} changed while streaming (size {size} -> "
                f"{stat1.st_size}, mtime {stat0.st_mtime_ns} -> "
                f"{stat1.st_mtime_ns}): receivers got an unverifiable "
                "object; re-send"
            )
        return sent

    def _stream_plan(
        self, length: int, chunk_bytes: int, geometry
    ) -> tuple[int, int, int, int]:
        """Validate and size a stream: (k, n, chunk capacity B, count).

        Rejects up front what every receiver would reject anyway (chunk
        count / object size over the caps) — otherwise the sender reports
        success while receivers silently drop every shard.
        """
        k, n = geometry or (self.minimum_needed_shards, self.total_shards)
        if not 1 <= k <= n <= self.max_total_shards:
            raise ValueError(f"invalid stream geometry k={k} n={n}")
        # Chunk capacity: whole uint32 words per stripe so the padded
        # chunk equals the capacity on every backend (see wire.py field
        # docs — the receiver derives per-chunk payload from it).
        B = max(4 * k, chunk_bytes - chunk_bytes % (4 * k))
        count = -(-length // B)
        if length > self.max_stream_object_bytes:
            raise ValueError(
                f"object of {length} bytes exceeds the stream cap "
                f"{self.max_stream_object_bytes}; raise "
                "max_stream_object_bytes on both ends"
            )
        if count > self.max_stream_chunks:
            raise ValueError(
                f"{count} chunks exceed the stream cap "
                f"{self.max_stream_chunks}; use a larger chunk_bytes"
            )
        return k, n, B, count

    def _emit_stream(
        self, network, file_signature: bytes, k: int, n: int, B: int,
        count: int, length: int, chunks,
    ) -> int:
        shards_out = bytes_out = 0
        # Transport backpressure PER SHARE: without it a bulk stream
        # outruns TCP drain and the transport's anti-DoS write cap
        # disconnects the peers mid-object. Per-share (not per-chunk)
        # with the share's own size as headroom, so the guarantee holds
        # for any geometry/chunk combination — a whole chunk's burst can
        # exceed the cap's headroom on its own (e.g. k=1 fan-out).
        # Transports without the hook — the loopback fake — are
        # unbuffered. The non-busy check is one short lock + int reads.
        waiter = getattr(network, "wait_writable", None)
        many = getattr(network, "broadcast_many", None)
        with span("broadcast", key=trace_key(file_signature), chunks=count):
            for index, shares in self._encode_chunk_stream(chunks, k, n, B):
                chunk_shards = []
                chunk_bytes_ = 0
                for s in shares:
                    chunk_shards.append(Shard(
                        file_signature=file_signature,
                        shard_data=s.data,
                        shard_number=s.number,
                        total_shards=n,
                        minimum_needed_shards=k,
                        stream_chunk_index=index,
                        stream_chunk_count=count,
                        stream_object_bytes=length,
                    ))
                    chunk_bytes_ += len(s.data)
                if many is not None:
                    # Whole-chunk cohort: one SHARD_BATCH frame per peer
                    # flush. Backpressure waits once per chunk with the
                    # chunk's own burst as headroom — the same guarantee
                    # the per-share wait gave, at batch granularity.
                    if waiter is not None:
                        waiter(headroom=chunk_bytes_ + 4096 * len(shares))
                    many(chunk_shards)
                else:
                    for shard in chunk_shards:
                        if waiter is not None:
                            waiter(headroom=len(shard.shard_data) + 4096)
                        network.broadcast(shard)
                shards_out += len(chunk_shards)
                bytes_out += chunk_bytes_
        self.counters.add("stream_chunks_out", count)
        self.counters.add("shards_out", shards_out)
        self.counters.add("bytes_out", bytes_out)
        return count

    def _encode_chunk_stream(self, chunks, k: int, n: int, B: int):
        """Yield (chunk_index, shares) for an iterable of chunk payloads.

        Device backend: the pipelined StreamingEncoder (H2D of chunk i+1
        overlaps chunk i's kernels). Other backends: per-chunk encode on
        the native C++ shim, FEC fallback.
        """
        if self.backend == "device":
            from noise_ec_tpu.parallel.streaming import StreamingEncoder

            enc = StreamingEncoder(k, n - k, chunk_bytes=B)
            for sc in enc.encode_stream(chunks):
                # Row buffers, not .tobytes(): the wire marshal joins from
                # each buffer directly. rows() keeps the parity-only-fetch
                # split — data rows are zero-copy views of the caller's
                # payload, parity rows the (r, stride) D2H fetch — so no
                # (n, stride) codeword buffer is ever assembled.
                rows = sc.rows()
                yield sc.index, [Share(i, rows[i].data) for i in range(n)]
            return
        import numpy as np

        from noise_ec_tpu.shim import gf_matmul_rows

        shim = self._stream_shim(k, n)
        stride = B // k
        parity_matrix = None
        for index, chunk in enumerate(chunks):
            if shim is not None and len(chunk) == B:
                # Full chunk: the k data shards ARE consecutive slices of
                # the caller's payload — emit them as zero-copy views and
                # compute only the parity, straight from those slices via
                # the pointer-based shim matmul (no staging copy of the
                # data into a codeword buffer; a 64 MiB object used to
                # pay a full extra memcpy here). Parity rows get their
                # OWN buffer per chunk (never reused): callers may hold a
                # Shard past the broadcast call. NOTE the retention shape
                # of the zero-copy data shards: their memoryviews pin the
                # caller's WHOLE payload object, not one codeword buffer —
                # fine for the normal lifecycle (broadcast marshals before
                # the generator resumes, and the caller holds the payload
                # for the duration of the call anyway), but a consumer
                # that retains data Shards beyond the stream call keeps
                # the full object alive with them.
                if parity_matrix is None:
                    from noise_ec_tpu.gf.field import GF256
                    from noise_ec_tpu.matrix.generators import generator_matrix

                    # Same Cauchy construction the shim's encoder bakes in
                    # (byte-identical by tests/test_shim.py).
                    parity_matrix = generator_matrix(GF256(), k, n, "cauchy")[k:]
                view = memoryview(chunk)
                rows = [
                    np.frombuffer(view[j * stride : (j + 1) * stride],
                                  dtype=np.uint8)
                    for j in range(k)
                ]
                parity = gf_matmul_rows(parity_matrix, rows, stride)
                if parity is not None:
                    yield index, (
                        [Share(j, view[j * stride : (j + 1) * stride])
                         for j in range(k)]
                        + [Share(k + i, parity[i].data)
                           for i in range(n - k)]
                    )
                    continue
            if shim is not None:
                # Tail chunk (or pointer-matmul unavailable): stage into a
                # codeword buffer with explicit zero pad and use the
                # in-place encode. np.empty: data rows are fully written
                # below and parity rows are outputs.
                buf = np.empty((n, stride), dtype=np.uint8)
                flat = buf[:k].reshape(-1)
                m = len(chunk)
                flat[:m] = np.frombuffer(chunk, dtype=np.uint8)
                if m < B:
                    flat[m:] = 0
                shim.encode_into(buf)
                yield index, [Share(i, buf[i].data) for i in range(n)]
            else:
                padded = bytes(chunk)
                if len(padded) < B:
                    padded = padded + bytes(B - len(padded))
                yield index, self._fec(k, n).encode_shares(padded)

    def _stream_shim(self, k: int, n: int):
        """Native C++ codec for the host-only stream encode, or None.

        The numpy backend exists to serve hosts without a device; its
        stream hot loop still deserves the native path (SURVEY.md §2.2 —
        the shim IS the framework's native host codec)."""
        key = (k, n)
        if key not in self._shim_cache:
            try:
                from noise_ec_tpu.shim import CppReedSolomon

                self._shim_cache[key] = CppReedSolomon(k, n - k)
            except Exception as exc:  # noqa: BLE001 — any load/build failure -> FEC
                log.warning("shim load failed for %s (%s); using FEC",
                            key, exc)
                self._shim_cache[key] = None
        return self._shim_cache[key]

    def _receive_stream(self, ctx: PluginContext, msg: Shard):
        """Stream-shard arm of the receive state machine.

        Each chunk reassembles through the same ShardPool (pool key =
        object signature + chunk index, so chunk pools inherit the TTL /
        byte caps and dedup); decoded chunks land in a preallocated
        object buffer; completion of the last chunk triggers the one
        object-level signature verify and delivery.

        Repairability matches the non-stream path: chunk pools are kept
        (not evicted) until the OBJECT verifies, and a chunk re-decodes
        whenever its pool has gained shares since its last decode — so a
        corrupted share among the first k of a chunk (which decodes
        "successfully" at exactly k, with nothing to check against) is
        corrected by Berlekamp-Welch once a parity share arrives, and the
        object re-verifies. CorruptionError is raised only when every
        chunk already holds all n shares and the signature still fails —
        no future arrival can help.
        """
        # Stream state is keyed by (signature, SENDER): verify binds the
        # object to the transport sender's key (main.go:85 — the sender IS
        # the encoder; shards are never relayed), so shards from another
        # identity can never contribute to this object. Scoping the key
        # (rather than pinning a signature-keyed stream to its first
        # sender) means an interloper racing the first shard merely opens
        # their own doomed stream instead of hijacking the real one — and
        # it makes the reassembly buffer single-writer by construction
        # (per-sender serialized dispatch), which is what lets the
        # object-level verify hash the live buffer outside the lock.
        sender_pk = self._sender_key(ctx)
        key = f"{msg.file_signature.hex()}:{sender_pk.hex()}"
        if self._recently_completed(key):
            self.counters.add("late_shards", 1)
            return None
        k = int(msg.minimum_needed_shards)
        n = int(msg.total_shards)
        count = int(msg.stream_chunk_count)
        index = int(msg.stream_chunk_index)
        length = int(msg.stream_object_bytes)
        if not 1 <= k <= n <= self.max_total_shards:
            self.counters.add("rejected_shards", 1)
            raise ValueError(f"invalid geometry k={k} n={n} in stream shard")
        if not 0 <= msg.shard_number < n:
            self.counters.add("rejected_shards", 1)
            raise ValueError(
                f"shard number {msg.shard_number} out of range for n={n}"
            )
        streams = self._streams
        if not 0 <= index < count:
            self.counters.add("rejected_shards", 1)
            raise ValueError(f"stream chunk {index} out of range [0, {count})")
        if not 0 < length <= self.max_stream_object_bytes:
            self.counters.add("rejected_shards", 1)
            raise ValueError(
                f"stream object of {length} bytes outside (0, "
                f"{self.max_stream_object_bytes}]"
            )
        if count > self.max_stream_chunks:
            self.counters.add("rejected_shards", 1)
            raise ValueError(
                f"stream chunk count {count} exceeds the cap "
                f"{self.max_stream_chunks}"
            )
        B = k * len(msg.shard_data)
        if B <= 0 or (count - 1) * B >= length or count * B < length:
            self.counters.add("rejected_shards", 1)
            raise ValueError(
                f"stream chunk capacity {B} inconsistent with "
                f"{count} chunks / {length} bytes"
            )
        now = time.monotonic()
        with self._streams_lock:
            st = streams.get(key)
            if st is None:
                # Expire stale objects, then admit (bounded).
                for stale in [
                    sk for sk, sv in streams.items()
                    if now - sv["created"] > self.STREAM_TTL_SECONDS
                ]:
                    self._drop_stream_locked(stale)
                if len(streams) >= self.max_stream_objects:
                    self.counters.add("stream_rejections", 1)
                    raise PoolLimitError(
                        f"{len(streams)} stream objects in flight"
                    )
                if self._stream_buf_bytes + length > self.max_stream_total_bytes:
                    self.counters.add("stream_rejections", 1)
                    raise PoolLimitError(
                        f"stream reassembly budget exhausted "
                        f"({self._stream_buf_bytes} + {length} > "
                        f"{self.max_stream_total_bytes})"
                    )
                self._stream_buf_bytes += length
                st = {
                    "buf": bytearray(length),
                    # chunk index -> pool distinct count at last decode
                    "done": {},
                    "count": count,
                    "B": B,
                    "length": length,
                    "k": k,
                    "n": n,
                    "created": now,
                    "failed": False,  # a whole-object verify has failed
                }
                streams[key] = st
            if (st["count"], st["B"], st["length"], st["k"], st["n"]) != (
                count, B, length, k, n
            ):
                # Geometry is pinned too: a forged shard whose k *
                # len(shard_data) happens to match B must not steer the
                # repair/unrecoverability logic (or decode to a SHORTER
                # chunk — a step-1 bytearray slice assignment from a
                # shorter source silently RESIZES the buffer, corrupting
                # every later chunk's offsets).
                self.counters.add("rejected_shards", 1)
                raise ValueError(
                    "stream shard disagrees with the object's pinned "
                    f"shape (count {count} vs {st['count']}, capacity "
                    f"{B} vs {st['B']}, length {length} vs {st['length']}, "
                    f"geometry ({k},{n}) vs ({st['k']},{st['n']}))"
                )

        if self.store is not None:
            # Stream chunks never absorb into a stripe (the store holds
            # whole objects as single stripes), but a stream shard for an
            # object we already store IS peer interest — note_shard
            # surfaces it to the repair engine and returns False.
            self.store.note_shard(msg)
        share = Share(msg.shard_number, bytes(msg.shard_data))
        pool_key = f"{key}:{index}"
        try:
            with span("reassemble", key=trace_key(msg.file_signature),
                      chunk=index, **_request_attrs(ctx)):
                snapshot, distinct, was_new = self.pool.add(
                    pool_key, share, k, n
                )
        except PoolLimitError:
            self.counters.add("pool_limit_rejections", 1)
            raise
        except ValueError:
            self.counters.add("rejected_shards", 1)
            raise
        if distinct < k or not was_new:
            return None
        with self._streams_lock:
            st = streams.get(key)
            if st is None:
                return None
            prior = st["done"].get(index)
            if prior is not None and not (st["failed"] and distinct > prior):
                # Already decoded, and no verify failure demands a
                # re-decode: extra shares just accumulate in the pool
                # (repair evidence for later), the happy path pays one
                # decode per chunk.
                self.counters.add("late_shards", 1)
                return None
        if prior is None:
            # Happy-path direct assembly: with the k systematic data
            # shards present, the chunk's bytes ARE those shards — write
            # them straight into the object buffer, skipping the decode
            # join plus the buffer copy (two chunk-size memcpys; ~25% of
            # the non-hash receive cost on 4 MiB chunks). Consistency
            # against parity still happens: any later verify failure
            # re-decodes through the full error-correcting path
            # (_repair_stream), exactly as for a codec decode at k.
            stride = len(msg.shard_data)
            by_num: dict[int, bytes] = {}
            for s in snapshot:
                if s.number < k and s.number not in by_num:
                    if len(s.data) != stride:
                        by_num = {}
                        break
                    by_num[s.number] = s.data
            if len(by_num) == k:
                with self._streams_lock:
                    st = self._streams.get(key)
                    if st is None:
                        return None
                    data_len = min(st["B"], st["length"] - index * st["B"])
                    lo = index * st["B"]
                    for j in range(k):
                        seg_lo = j * stride
                        if seg_lo >= data_len:
                            break
                        seg = min(stride, data_len - seg_lo)
                        st["buf"][lo + seg_lo : lo + seg_lo + seg] = (
                            memoryview(by_num[j])[:seg]
                        )
                    # Record k, not distinct: direct assembly used only
                    # the k data shards and checked NO parity, so a later
                    # verify failure must re-decode this chunk whenever
                    # the pool holds ANY redundancy beyond k —
                    # _repair_stream's "pool grew" gate compares against
                    # this value (r4 advisor: recording distinct > k here
                    # made a repairable corrupt chunk permanently
                    # undeliverable).
                    st["done"][index] = k
                    self.counters.add("decodes", 1)
                    if len(st["done"]) < st["count"]:
                        return None
                    complete = st["buf"]
                delivered = self._verify_stream_object(ctx, msg, key, complete)
                if delivered is not None:
                    return delivered
                return self._repair_stream(ctx, msg, key, k, n, count)
        fec = self._fec_receive(k, n, ctx)
        self._geometry_decode_begin(k, n)
        decode_nbytes = sum(len(s.data) for s in snapshot)
        try:
            with span("decode", key=trace_key(msg.file_signature),
                      chunk=index, **_request_attrs(ctx)), \
                    Timer(self.counters, "decode_s", nbytes=decode_nbytes,
                          histogram=self._decode_hist):
                chunk = fec.decode(snapshot)
            self._decode_bytes_hist.observe(decode_nbytes)
        except Exception as exc:
            self.counters.add("decode_errors", 1)
            log.error("stream chunk %d decode failed for %s…: %s",
                      index, key[:16], exc)
            if distinct >= n:
                with self._streams_lock:
                    st = self._streams.get(key)
                    started = st["created"] if st is not None else None
                self._drop_stream(key)
                self._record_outcome("corrupt", started)
                raise CorruptionError(
                    f"all {n} shards of stream chunk {index} arrived for "
                    f"{key[:16]}… but decode fails: {exc}"
                ) from exc
            return None
        finally:
            # Release the in-flight compile slot on success AND failure:
            # the compile happened during the decode attempt either way,
            # and a failing decode must not let a poisoned novel geometry
            # pin the global admission budget for the whole grace window.
            self._geometry_ready(k, n)
        self.counters.add("decodes", 1)

        with self._streams_lock:
            st = streams.get(key)
            if st is None:
                return None
            data_len = min(st["B"], st["length"] - index * st["B"])
            lo = index * st["B"]
            first = index not in st["done"]
            # Compare only on RE-decodes (repair mode): on the first
            # decode the comparison is meaningless and its two 4 MiB
            # copies per chunk were ~25% of the happy path.
            changed = (not first) and (
                memoryview(chunk)[:data_len]
                != memoryview(st["buf"])[lo : lo + data_len]
            )
            if first or changed:
                st["buf"][lo : lo + data_len] = memoryview(chunk)[:data_len]
            st["done"][index] = distinct
            if len(st["done"]) < st["count"]:
                return None
            if not (first or changed):
                # A post-failure re-decode produced the same bytes: only
                # the unrecoverability verdict can have changed.
                complete = None
            else:
                # The live buffer, not a copy: the verify hash reads it
                # in place; bytes are materialized only on delivery.
                # (Per-sender serialized dispatch keeps it stable across
                # the verify.)
                complete = st["buf"]

        if complete is not None:
            delivered = self._verify_stream_object(ctx, msg, key, complete)
            if delivered is not None:
                return delivered
        # Verify failed (now or earlier): try to repair from the pooled
        # shares, then decide recoverability.
        return self._repair_stream(ctx, msg, key, k, n, count)

    def _verify_stream_object(
        self, ctx: PluginContext, msg: Shard, key: str, complete
    ):
        """Verify + deliver a fully reassembled object (``complete`` may
        be the live reassembly bytearray — hashed in place, materialized
        as bytes only on delivery); None on failure (caller decides
        repair/unrecoverability)."""
        sender = ctx.sender()
        with self._streams_lock:
            st0 = self._streams.get(key)
            started = st0["created"] if st0 is not None else None
        with span("verify", key=trace_key(msg.file_signature),
                  nbytes=len(complete), **_request_attrs(ctx)):
            ok = verify_parts(
                self.signature_policy,
                self.hash_policy,
                ctx.client_public_key(),
                serialize_message_parts(sender, complete),
                msg.file_signature,
            )
        if not ok:
            self.counters.add("verify_failures", 1)
            log.warning("stream object signature verify failed for %s…",
                        key[:16])
            with self._streams_lock:
                st = self._streams.get(key)
                if st is not None:
                    st["failed"] = True
            self._record_outcome("verify_failed", started)
            return None
        if not self._mark_completed(key):
            self.counters.add("late_shards", 1)
            return None
        self._record_outcome("ok", started)
        # Store BEFORE delivery: the on_object path below transfers
        # ownership of the reassembly buffer to the callee.
        self._store_put(
            ctx, msg, int(msg.minimum_needed_shards),
            int(msg.total_shards), complete, sender,
        )
        if self.on_object is not None and isinstance(complete, bytearray):
            # Zero-copy delivery: hand over the reassembly buffer itself.
            # _drop_stream first — the plugin must hold no reference to a
            # buffer whose ownership moves to the callee.
            self._drop_stream(key)
            self.counters.add("verified", 1)
            self.counters.add("stream_objects_in", 1)
            log.info("completed stream object %s… (%d bytes)",
                     key[:16], len(complete))
            self.on_object(complete, sender)
            return complete
        delivered = bytes(complete)
        self._drop_stream(key)
        self.counters.add("verified", 1)
        self.counters.add("stream_objects_in", 1)
        log.info("completed stream object %s… (%d bytes)",
                 key[:16], len(delivered))
        if self.on_message is not None:
            self.on_message(delivered, sender)
        return delivered

    def _repair_stream(
        self, ctx: PluginContext, msg: Shard, key: str, k: int, n: int,
        count: int,
    ) -> Optional[bytes]:
        """After a verify failure: re-decode every chunk whose pool holds
        more shares than its last decode used (the extra shares enable
        the consistency check and Berlekamp-Welch correction), re-verify
        if anything changed, and raise CorruptionError only once every
        chunk has all n shares and the signature still fails."""
        fec = self._fec_receive(k, n, ctx)
        while True:
            changed_any = False
            for i in range(count):
                shares, _ = self.pool.snapshot(f"{key}:{i}")
                if not shares:
                    continue
                with self._streams_lock:
                    st = self._streams.get(key)
                    if st is None:
                        return None
                    if len(shares) <= st["done"].get(i, 0):
                        continue
                self._geometry_decode_begin(k, n)
                try:
                    chunk = fec.decode(shares)
                except Exception as exc:  # noqa: BLE001 — keep repairing others
                    log.debug("stream chunk decode failed: %s", exc)
                    self.counters.add("decode_errors", 1)
                    continue
                finally:
                    self._geometry_ready(k, n)  # slot freed on any outcome
                self.counters.add("decodes", 1)
                with self._streams_lock:
                    st = self._streams.get(key)
                    if st is None:
                        return None
                    data_len = min(st["B"], st["length"] - i * st["B"])
                    lo = i * st["B"]
                    if bytes(st["buf"][lo : lo + data_len]) != chunk[:data_len]:
                        st["buf"][lo : lo + data_len] = (
                            memoryview(chunk)[:data_len]
                        )
                        changed_any = True
                    st["done"][i] = len(shares)
            if not changed_any:
                break
            with self._streams_lock:
                st = self._streams.get(key)
                if st is None or len(st["done"]) < st["count"]:
                    return None
                complete = st["buf"]
            delivered = self._verify_stream_object(ctx, msg, key, complete)
            if delivered is not None:
                self.counters.add("stream_repairs", 1)
                return delivered
        if self._stream_has_all_shards(key, count, n):
            with self._streams_lock:
                st = self._streams.get(key)
                started = st["created"] if st is not None else None
            self._drop_stream(key)
            self._record_outcome("corrupt", started)
            raise CorruptionError(
                f"stream object {key[:16]}… has all {n} shards of all "
                f"{count} chunks but the signature does not verify"
            )
        return None

    def _stream_has_all_shards(self, key: str, count: int, n: int) -> bool:
        return all(
            self.pool.snapshot(f"{key}:{i}")[1] >= n for i in range(count)
        )

    def _drop_stream(self, key: str) -> None:
        with self._streams_lock:
            self._drop_stream_locked(key)

    def _drop_stream_locked(self, key: str) -> None:
        st = self._streams.pop(key, None)
        if st is not None:
            self._stream_buf_bytes -= st["length"]
            for i in range(st["count"]):
                self.pool.evict(f"{key}:{i}")

    # ------------------------------------------------------------- store

    def _store_put(
        self, ctx: PluginContext, msg: Shard, k: int, n: int, data, sender
    ) -> None:
        """Land a signature-verified object in the stripe store (when one
        is wired in). The sender identity rides along so the repair
        engine can re-anchor error-corrected restores on the same
        signature the receive path just checked. A store failure must
        never break delivery."""
        self._store_put_raw(
            msg.file_signature, data, k, n,
            sender.address, bytes(ctx.client_public_key()),
        )

    def _store_put_raw(
        self, file_signature: bytes, data, k: int, n: int,
        address: str, public_key: bytes,
    ) -> None:
        if self.store is None:
            return
        try:
            self.store.put_object(
                file_signature,
                bytes(data),
                k,
                n,
                sender_address=address,
                sender_public_key=public_key,
            )
            self.counters.add("store_puts", 1)
        except Exception as exc:  # noqa: BLE001 — delivery must proceed
            self.counters.add("store_put_errors", 1)
            log.warning("stripe store put failed for %s…: %s",
                        file_signature[:8].hex(), exc)

    # ------------------------------------------------- NACK shard repair

    def _nack_note(self, key: str, msg: Shard, ctx: PluginContext) -> None:
        """An arriving shard left pool ``key`` below k: arm (or keep) its
        NACK timer. Runs on the dispatch path — one lock, no I/O."""
        if self.nack_grace_seconds <= 0 or self._network() is None:
            return
        now = time.monotonic()
        with self._nack_lock:
            st = self._nack.get(key)
            if st is None:
                self._nack[key] = {
                    "sig": bytes(msg.file_signature),
                    "k": int(msg.minimum_needed_shards),
                    "n": int(msg.total_shards),
                    "sender": self._sender_key(ctx),
                    "retries": 0,
                    "next_at": now + self.nack_grace_seconds,
                }
                # Bounded: keys are attacker-suppliable (one per forged
                # first shard); evict oldest state, the pool TTL still
                # owns the shares themselves.
                while len(self._nack) > 4096:
                    self._nack.popitem(last=False)
            if self._nack_thread is None:
                self._nack_thread = threading.Thread(
                    target=self._nack_run, name="noise-ec-nack", daemon=True
                )
                self._nack_thread.start()

    def _nack_resolve(self, key: str, delivered: bool = True) -> None:
        """The pool completed (or became unrecoverable): retire its NACK
        state; a delivery that needed at least one NACK round counts as
        a repair."""
        with self._nack_lock:
            st = self._nack.pop(key, None)
        if st is not None and delivered and st["retries"] > 0:
            self._nack_repaired.add(1)

    def _nack_run(self) -> None:
        while True:
            tick = max(
                0.05, min(self.nack_grace_seconds, self.nack_backoff_base) / 4
            )
            time.sleep(tick)
            try:
                self._nack_sweep()
            except Exception as exc:  # noqa: BLE001 — keep the sweeper up
                log.warning("NACK sweep failed: %s", exc)
            with self._nack_lock:
                if not self._nack:
                    # Idle: let the thread die; the next stuck pool
                    # restarts it (tests build many short-lived plugins).
                    self._nack_thread = None
                    return

    def _nack_sweep(self) -> None:
        now = time.monotonic()
        with self._nack_lock:
            items = list(self._nack.items())
        net = self._network()
        for key, st in items:
            entry = self.pool.get(key)
            if entry is None:
                # TTL'd or evicted underneath us: nothing left to repair.
                with self._nack_lock:
                    self._nack.pop(key, None)
                continue
            if entry.distinct() >= st["k"]:
                continue  # decode path owns it; resolve happens there
            if now < st["next_at"]:
                continue
            if st["retries"] >= self.nack_max_retries:
                with self._nack_lock:
                    self._nack.pop(key, None)
                self._nack_giveups.add(1)
                event("repair.giveup", "error", key=key[:16],
                      have=entry.distinct(), need=st["k"],
                      retries=st["retries"])
                self._record_outcome("incomplete", entry.created_at)
                log.warning(
                    "object %s… stuck at %d/%d shards after %d NACK "
                    "rounds; recording incomplete (pool TTL keeps the "
                    "shards for late repair)", key[:16], entry.distinct(),
                    st["k"], st["retries"],
                )
                continue
            if net is None:
                continue
            shares, _ = self.pool.snapshot(key)
            if not shares:
                continue
            shards = [
                Shard(
                    file_signature=st["sig"],
                    shard_data=bytes(s.data),
                    shard_number=s.number,
                    total_shards=st["n"],
                    minimum_needed_shards=st["k"],
                )
                for s in shares
            ]
            # Round 0 goes straight to the original sender (it stores
            # its own broadcasts); on sender-silence the later rounds
            # broadcast so any peer holding the stripe can answer.
            sent_direct = False
            send_to = getattr(net, "send_to", None)
            if st["retries"] == 0 and st["sender"] and send_to is not None:
                sent_direct = all(send_to(st["sender"], sh) for sh in shards)
            if not sent_direct:
                for sh in shards:
                    net.broadcast(sh)
            self._nack_requests.add(1)
            with self._nack_lock:
                cur = self._nack.get(key)
                if cur is st:
                    st["retries"] += 1
                    st["next_at"] = now + min(
                        self.nack_backoff_cap,
                        self.nack_backoff_base * (2 ** (st["retries"] - 1)),
                    )

    # -------------------------------------------------------- receive path

    def _record_outcome(self, outcome: str, started) -> None:
        """One e2e outcome event (obs/health.py): latency measured from
        the object's first-seen time (pool/stream creation) when known,
        0.0 otherwise (the outcome still burns or feeds the SLO)."""
        seconds = (
            max(0.0, time.monotonic() - started) if started is not None
            else 0.0
        )
        record_e2e(outcome, seconds, slo=self.slo)

    def _pool_started(self, key: str):
        entry = self.pool.get(key)
        return entry.created_at if entry is not None else None

    def receive(self, ctx: PluginContext) -> Optional[bytes]:
        """Shard-reassembly state machine (main.go:52-107).

        Returns the reassembled, signature-verified plaintext when this
        arrival completes an object, else None. Raises
        :class:`CorruptionError` / :class:`PoolTooLargeError` where the
        reference returns its CASE C/D errors.

        Case map vs the reference (§3.2): A/B collapse into ``pool.add``
        (first arrival and accumulation are the same code path); C fires at
        k *distinct* shares including this one; D lives in the pool.
        """
        msg = ctx.message()
        if not isinstance(msg, Shard):  # type switch, main.go:53-54
            return None
        self.counters.add("shards_in", 1)
        self.counters.add("bytes_in", len(msg.shard_data))
        if msg.stream_chunk_count:
            return self._receive_stream(ctx, msg)
        key = msg.file_signature.hex()  # mempool key, main.go:55
        if (
            self.placement is not None
            and self.store is not None
            and self.placement.absorbs(msg)
            and self.store.note_placement_shard(msg)
        ):
            # A targeted placement shard for a slot whose failure domain
            # is ours (checked BEFORE the general absorb — this is the
            # only branch allowed to CREATE a stripe entry): anchor it in
            # the store and CONSUME it — pooling a below-k targeted
            # cohort would only arm the NACK timer and pull the whole
            # stripe back over the wire, undoing the fanout savings.
            # Broadcast stripes still complete: a domain owns at most one
            # local group of any stripe, so >= k other slots reach the
            # pool — note_shard absorbs them additively (placement-born
            # stripes report unconsumed) rather than starving it.
            self.counters.add("placement_absorbed_shards", 1)
            return None
        if self.store is not None and self.store.note_shard(msg):
            # The store consumed it (BEFORE the dedup window — an
            # anti-entropy response arrives precisely for objects we
            # completed, and absorbing it must not depend on timing):
            # either a fill of a stripe we hold or a duplicate of a shard
            # we already store (the interest signal peers answer). No
            # pool work needed — the object is already durable locally.
            self.counters.add("store_absorbed_shards", 1)
            return None
        if self._recently_completed(key):
            self.counters.add("late_shards", 1)
            return None
        share = Share(msg.shard_number, bytes(msg.shard_data))
        k = int(msg.minimum_needed_shards)
        n = int(msg.total_shards)
        # Full message validation up front: geometry within the field bound
        # and share number within the geometry. One malformed (or
        # adversarial) message must neither crash the transport's dispatch
        # loop nor poison the pool for the legitimate shards.
        if not 1 <= k <= n <= self.max_total_shards:
            self.counters.add("rejected_shards", 1)
            raise ValueError(f"invalid geometry k={k} n={n} in shard message")
        if not 0 <= msg.shard_number < n:
            self.counters.add("rejected_shards", 1)
            raise ValueError(
                f"shard number {msg.shard_number} out of range for n={n}"
            )
        try:
            with span("reassemble", key=trace_key(msg.file_signature),
                      **_request_attrs(ctx)):
                snapshot, distinct, was_new = self.pool.add(key, share, k, n)
        except PoolTooLargeError:
            self.counters.add("pool_overflows", 1)
            raise
        except PoolLimitError:
            # Resource budget exhausted — a distinct signal from malformed
            # shards: this is the memory-exhaustion alarm.
            self.counters.add("pool_limit_rejections", 1)
            raise
        except ValueError:
            # Geometry or length disagrees with the pinned pool: drop this
            # share, keep the pool intact.
            self.counters.add("rejected_shards", 1)
            raise
        if distinct < k:
            # CASE A/B: keep accumulating (main.go:56-71) — and arm the
            # NACK timer so a stalled pool asks for its missing shards
            # instead of silently waiting out the TTL.
            self._nack_note(key, msg, ctx)
            return None
        if not was_new:
            # A replayed duplicate adds no information; don't pay another
            # decode + verify for it.
            return None

        # CASE C: enough distinct shares — decode + verify (main.go:72-99).
        fec = self._fec_receive(k, n, ctx)
        self._geometry_decode_begin(k, n)
        decode_nbytes = sum(len(s.data) for s in snapshot)
        try:
            with span("decode", key=trace_key(msg.file_signature), k=k, n=n,
                      **_request_attrs(ctx)), \
                    Timer(self.counters, "decode_s", nbytes=decode_nbytes,
                          histogram=self._decode_hist):
                complete = fec.decode(snapshot)
            self._decode_bytes_hist.observe(decode_nbytes)
        except Exception as exc:
            # The reference logs decode errors and falls through to a
            # doomed Verify on nil (main.go:75-80, quirk 5); we log and
            # wait for more shares — unless every share number has arrived,
            # in which case no future arrival can help (duplicates
            # short-circuit above) and the object is unrecoverable.
            self.counters.add("decode_errors", 1)
            log.error("decode failed for %s…: %s", key[:16], exc)
            if distinct >= n:
                started = self._pool_started(key)
                self.pool.evict(key)
                self._nack_resolve(key, delivered=False)
                self._record_outcome("corrupt", started)
                raise CorruptionError(
                    f"all {n} shards arrived for {key[:16]}… but decode "
                    f"fails: {exc}"
                ) from exc
            return None
        finally:
            self._geometry_ready(k, n)  # slot freed on any outcome
        self.counters.add("decodes", 1)

        sender = ctx.sender()
        with span("verify", key=trace_key(msg.file_signature),
                  **_request_attrs(ctx)):
            ok = verify(
                self.signature_policy,
                self.hash_policy,
                ctx.client_public_key(),  # transport sender == original encoder
                serialize_message(sender, complete),  # (main.go:85, quirk 6)
                msg.file_signature,
            )
        if ok:
            started = self._pool_started(key)
            self.pool.evict(key)  # main.go:90-93
            self._nack_resolve(key)
            if not self._mark_completed(key):
                # A concurrent receive() already delivered this object
                # between our pool snapshot and now; exactly-once holds.
                self.counters.add("late_shards", 1)
                return None
            self.counters.add("verified", 1)
            self._record_outcome("ok", started)
            self._store_put(ctx, msg, k, n, complete, sender)
            log.info("completed message %s… (%d bytes)", complete[:32].hex(), len(complete))
            if self.on_message is not None:
                self.on_message(complete, sender)
            return complete

        self.counters.add("verify_failures", 1)
        log.warning("signature verify failed for %s…", key[:16])
        started = self._pool_started(key)
        if distinct >= n:
            # Every shard arrived and the object still fails verification:
            # unrecoverable (main.go:96-98 made reachable — see
            # CorruptionError docstring).
            self.pool.evict(key)
            self._nack_resolve(key, delivered=False)
            self._record_outcome("corrupt", started)
            raise CorruptionError(
                f"all {n} shards arrived for {key[:16]}… but the signature "
                "does not verify"
            )
        self._record_outcome("verify_failed", started)
        return None
