"""Transports: the L0 layer (SURVEY.md §1) — peer registry, broadcast
fan-out, and plugin dispatch.

The reference delegates this layer to perlin-network/noise (SURVEY.md §2.3
D2): a builder-configured network with ordered plugin registration, a
blocking accept loop, ``Bootstrap(peers...)`` dial-out, per-message
signatures, and ``Broadcast`` fan-out to every connected peer. Two
implementations here share that contract:

- :class:`LoopbackHub` / :class:`LoopbackNetwork` — the in-process fake the
  reference lacks (SURVEY.md §4 "multi-node story"): N peers in one
  process, deterministic fault injection (drop / duplicate / corrupt /
  reorder) on every link, driving the full Receive state machine.
- :class:`TCPNetwork` — a real asyncio TCP transport with length-prefixed,
  identity-carrying, Ed25519-signed frames, serving the reference's
  multi-process deployment shape (main.go:137-173).

Both deliver messages to plugins through :class:`Ctx`, the slice of
noise's ``PluginContext`` the reference uses (main.go:53-87).
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Optional

import numpy as np

from noise_ec_tpu.host.crypto import (
    Blake2bPolicy,
    Ed25519Policy,
    KeyPair,
    PeerID,
)
from noise_ec_tpu.host.wire import Shard, WireError

__all__ = [
    "Ctx",
    "FaultInjector",
    "LoopbackHub",
    "LoopbackNetwork",
    "TCPNetwork",
    "format_address",
]

log = logging.getLogger("noise_ec_tpu.host.transport")


def format_address(protocol: str, host: str, port: int) -> str:
    """network.FormatAddress(protocol, host, port) — main.go:148."""
    return f"{protocol}://{host}:{port}"


class Ctx:
    """Plugin context handed to ``plugin.receive`` on every delivery."""

    def __init__(self, msg: object, sender: PeerID):
        self._msg = msg
        self._sender = sender

    def message(self) -> object:
        return self._msg

    def sender(self) -> PeerID:
        return self._sender

    def client_public_key(self) -> bytes:
        return self._sender.public_key


# --------------------------------------------------------------- loopback


class FaultInjector:
    """Deterministic link-fault model for the loopback transport.

    The reference has no fault-injection story at all (SURVEY.md §5 failure
    row); this is the first-class harness it calls for. Faults apply
    per-delivery, driven by a seeded generator so every run reproduces:

    - ``drop``: probability a delivery is discarded;
    - ``duplicate``: probability a delivery is made twice;
    - ``corrupt``: probability one byte of the wire bytes is flipped;
    - ``reorder``: probability a delivery is held in a one-slot delay line
      and released right after the next delivery on the same link (a
      pairwise swap; the slot is per-link, so a held message can neither
      migrate to another receiver nor be attributed to a later sender). At
      most one delivery per link is pending at stream end — within any
      k-of-n parity budget.
    """

    def __init__(
        self,
        seed: int = 0,
        drop: float = 0.0,
        duplicate: float = 0.0,
        corrupt: float = 0.0,
        reorder: float = 0.0,
    ):
        self.rng = np.random.default_rng(seed)
        self.drop = drop
        self.duplicate = duplicate
        self.corrupt = corrupt
        self.reorder = reorder
        self._slots: dict[str, bytes] = {}  # per-link delay line for reorder
        self.stats = {"delivered": 0, "dropped": 0, "duplicated": 0,
                      "corrupted": 0, "reordered": 0}

    def apply(self, deliveries: list[bytes], link: str = "") -> list[bytes]:
        """Map a list of wire-byte deliveries on ``link`` to the faulted
        list. Stateful across calls: a reordered delivery from an earlier
        call is released behind a later one on the same link."""
        out: list[bytes] = []
        for buf in deliveries:
            if self.rng.random() < self.drop:
                self.stats["dropped"] += 1
                continue
            copies = 1
            if self.rng.random() < self.duplicate:
                copies = 2
                self.stats["duplicated"] += 1
            for _ in range(copies):
                b = buf
                if self.rng.random() < self.corrupt:
                    b = bytearray(b)
                    if b:
                        b[int(self.rng.integers(0, len(b)))] ^= 1 << int(
                            self.rng.integers(0, 8)
                        )
                    b = bytes(b)
                    self.stats["corrupted"] += 1
                if link not in self._slots and self.rng.random() < self.reorder:
                    self._slots[link] = b  # held; rides behind the next delivery
                    self.stats["reordered"] += 1
                    continue
                out.append(b)
                self.stats["delivered"] += 1
                held = self._slots.pop(link, None)
                if held is not None:
                    out.append(held)
                    self.stats["delivered"] += 1
        return out


class LoopbackHub:
    """An in-process peer set: every registered network sees every other."""

    def __init__(self, fault_injector: Optional[FaultInjector] = None):
        self.nodes: dict[str, "LoopbackNetwork"] = {}
        self.faults = fault_injector

    def register(self, node: "LoopbackNetwork") -> None:
        self.nodes[node.id.address] = node

    def fan_out(self, sender: "LoopbackNetwork", wire_bytes: bytes) -> None:
        """Deliver one message to every peer except the sender
        (net.Broadcast semantics, main.go:206-208)."""
        for addr, node in self.nodes.items():
            if addr == sender.id.address:
                continue
            bufs = [wire_bytes]
            if self.faults is not None:
                bufs = self.faults.apply(bufs, link=f"{sender.id.address}->{addr}")
            for buf in bufs:
                node.deliver(buf, sender.id)


class LoopbackNetwork:
    """One fake peer. API mirrors what the plugin needs from noise's
    ``*network.Network``: ``.id``, ``.keys``, ``.broadcast``, plugin
    registration and dispatch."""

    def __init__(self, hub: LoopbackHub, address: str, keys: Optional[KeyPair] = None):
        self.keys = keys or KeyPair.random()
        self.id = PeerID.create(address, self.keys.public_key)
        self.hub = hub
        self.plugins: list = []
        # bounded: hostile traffic appends one entry per bad frame
        self.errors: deque[Exception] = deque(maxlen=256)
        self.error_count = 0
        hub.register(self)

    def add_plugin(self, plugin) -> None:
        self.plugins.append(plugin)

    def _record_error(self, exc: Exception) -> None:
        self.errors.append(exc)
        self.error_count += 1

    def broadcast(self, msg: Shard) -> None:
        self.hub.fan_out(self, msg.marshal())

    def deliver(self, wire_bytes: bytes, sender: PeerID) -> None:
        """Hub-side delivery: decode and dispatch to every plugin in
        registration order. Decode/dispatch errors are recorded, not
        raised — one bad message must not kill the receive loop."""
        try:
            msg = Shard.unmarshal(wire_bytes)
        except WireError as exc:
            self._record_error(exc)
            return
        ctx = Ctx(msg, sender)
        for plugin in self.plugins:
            try:
                plugin.receive(ctx)
            except Exception as exc:  # noqa: BLE001 — isolate the loop
                self._record_error(exc)


# -------------------------------------------------------------------- TCP

# Frame layout (all little-endian):
#   u32 frame_len | u8 opcode | u32 addr_len | addr utf-8 | 32B pubkey |
#   u32 payload_len | payload | 64B ed25519 signature over
#   blake2b256(opcode ‖ payload)
# HELLO carries an empty payload and introduces the peer (the discovery
# handshake); SHARD carries a marshaled Shard. Every frame is signed, the
# transport-level integrity the reference gets from noise's signed messages
# (SURVEY.md §2.3 D2).
_OP_HELLO = 1        # dialer -> acceptor: payload = dialer 32B nonce
_OP_HELLO_REPLY = 3  # acceptor -> dialer: payload = dialer_nonce ‖ acceptor_nonce
_OP_HELLO_ACK = 4    # dialer -> acceptor: payload = acceptor_nonce
_OP_SHARD = 2        # payload = marshaled Shard
_MAX_FRAME = 64 << 20
_NONCE_LEN = 32


@dataclass
class _Peer:
    pid: PeerID
    writer: asyncio.StreamWriter


class _Conn:
    """Per-connection handshake state.

    A peer is registered only after a fresh-nonce proof: every frame is
    signed (over opcode ‖ address ‖ payload), and registration requires the
    counterparty to echo OUR nonce for THIS connection inside one of those
    signed frames — so a captured HELLO/REPLY/ACK replayed on a new
    connection verifies as a signature but never matches the new nonce and
    never binds the victim's identity to the attacker's socket."""

    def __init__(self):
        self.nonce = os.urandom(_NONCE_LEN)
        self.peer: Optional[PeerID] = None
        self.registered = asyncio.Event()


class TCPNetwork:
    """Asyncio TCP transport with the noise-style lifecycle:
    ``listen()`` (background accept loop), ``bootstrap(peers)`` (dial out),
    ``broadcast(msg)`` (signed fan-out to all connected peers).

    Runs its event loop on a daemon thread so callers keep the reference's
    synchronous REPL shape (``go net.Listen()``, main.go:169).

    Security model (vs the reference's noise transport, SURVEY.md §2.3 D2):
    every frame is Ed25519-signed over (opcode ‖ sender address ‖ payload),
    and peers register through a three-way nonce handshake
    (HELLO → HELLO_REPLY → HELLO_ACK) so neither the address nor a replayed
    handshake can bind a foreign identity to an attacker's socket. Shards
    are accepted only from registered connections whose key matches.
    """

    # Disconnect a peer whose kernel+asyncio write buffer exceeds this —
    # a stalled reader must not grow sender memory without bound.
    MAX_PEER_WRITE_BUFFER = 32 << 20

    def __init__(
        self,
        host: str = "localhost",
        port: int = 3000,
        keys: Optional[KeyPair] = None,
        protocol: str = "tcp",
    ):
        if protocol != "tcp":
            raise ValueError(
                f"protocol {protocol!r} not supported (the reference also "
                "offers kcp; only tcp is implemented here)"
            )
        self.keys = keys or KeyPair.random()
        self.host = host
        self.port = port
        self.id = PeerID.create(format_address(protocol, host, port), self.keys.public_key)
        self.plugins: list = []
        # Keyed by PUBLIC KEY, not the self-claimed address: an address is
        # just a claim inside a signed frame, so keying by it would let any
        # handshake-completing attacker evict a legitimate peer by claiming
        # the same address. One entry per identity; addresses may collide.
        self.peers: dict[bytes, _Peer] = {}  # public key -> peer
        # bounded: hostile traffic appends one entry per bad frame
        self.errors: deque[Exception] = deque(maxlen=256)
        self.error_count = 0
        self._sig = Ed25519Policy()
        self._hash = Blake2bPolicy()
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever, daemon=True)
        self._server: Optional[asyncio.AbstractServer] = None
        self._lock = threading.Lock()
        self._tasks: set[asyncio.Task] = set()
        # Plugin dispatch (FEC decode; first-geometry jit compile can take
        # seconds on the device backend) must not run on the event-loop
        # thread, or every connection's read loop and handshake stalls
        # behind it. One worker preserves per-node delivery order.
        self._dispatch = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="noise-ec-dispatch"
        )

    # ------------------------------------------------------------ lifecycle

    def listen(self) -> None:
        """Start the accept loop in the background (go net.Listen())."""
        self._thread.start()
        fut = asyncio.run_coroutine_threadsafe(self._start_server(), self._loop)
        self._server = fut.result(timeout=10)
        addr = self._server.sockets[0].getsockname()
        self.port = addr[1]
        self.id = PeerID.create(
            format_address("tcp", self.host, self.port), self.keys.public_key
        )

    async def _start_server(self) -> asyncio.AbstractServer:
        return await asyncio.start_server(self._handle_conn, self.host, self.port)

    def bootstrap(self, peer_addresses: list[str]) -> None:
        """Dial out to peers (net.Bootstrap, main.go:171-173). Blocks until
        each handshake completes (or fails), so a broadcast immediately
        after bootstrap reaches every successfully dialed peer."""
        for addr in peer_addresses:
            if not addr:
                continue
            fut = asyncio.run_coroutine_threadsafe(self._dial(addr), self._loop)
            try:
                fut.result(timeout=15)
            except Exception as exc:  # noqa: BLE001
                self._record_error(exc)
                log.error("bootstrap %s failed: %s", addr, exc)

    def close(self) -> None:
        async def _shutdown():
            if self._server is not None:
                self._server.close()
            for peer in list(self.peers.values()):
                peer.writer.close()

        if self._thread.is_alive():
            asyncio.run_coroutine_threadsafe(_shutdown(), self._loop).result(timeout=5)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=5)
        self._dispatch.shutdown(wait=True)

    # ------------------------------------------------------------- plugins

    def add_plugin(self, plugin) -> None:
        self.plugins.append(plugin)

    def _record_error(self, exc: Exception) -> None:
        self.errors.append(exc)
        self.error_count += 1

    # --------------------------------------------------------------- wire

    def _frame(self, opcode: int, payload: bytes) -> bytes:
        addr = self.id.address.encode()
        sig = self.keys.sign(
            self._sig, self._hash, bytes([opcode]) + addr + payload
        )
        body = b"".join(
            [
                bytes([opcode]),
                struct.pack("<I", len(addr)),
                addr,
                self.keys.public_key,
                struct.pack("<I", len(payload)),
                payload,
                sig,
            ]
        )
        return struct.pack("<I", len(body)) + body

    @staticmethod
    def _parse_frame(body: bytes) -> tuple[int, PeerID, bytes, bytes]:
        """Returns (opcode, sender_pid, payload, signature)."""
        pos = 0
        opcode = body[pos]; pos += 1
        (alen,) = struct.unpack_from("<I", body, pos); pos += 4
        addr = body[pos : pos + alen].decode(); pos += alen
        pubkey = body[pos : pos + 32]; pos += 32
        (plen,) = struct.unpack_from("<I", body, pos); pos += 4
        payload = body[pos : pos + plen]; pos += plen
        sig = body[pos : pos + 64]
        if len(pubkey) != 32 or len(payload) != plen or len(sig) != 64:
            raise WireError("truncated frame")
        return opcode, PeerID.create(addr, pubkey), payload, sig

    # ------------------------------------------------------------ dataflow

    def broadcast(self, msg: Shard) -> None:
        """Signed fan-out to every connected peer (main.go:206-208)."""
        frame = self._frame(_OP_SHARD, msg.marshal())
        with self._lock:
            writers = [p.writer for p in self.peers.values()]
        for w in writers:
            self._loop.call_soon_threadsafe(self._write_safe, w, frame)

    def _write_safe(self, writer: asyncio.StreamWriter, frame: bytes) -> None:
        if writer.transport.get_write_buffer_size() > self.MAX_PEER_WRITE_BUFFER:
            # A stalled reader must not grow sender memory without bound.
            self._drop_writer(writer)
            self._record_error(
                RuntimeError("peer write buffer exceeded cap; disconnected")
            )
            return
        try:
            writer.write(frame)
        except Exception as exc:  # noqa: BLE001
            self._record_error(exc)

    def _drop_writer(self, writer: asyncio.StreamWriter) -> None:
        with self._lock:
            for key, p in list(self.peers.items()):
                if p.writer is writer:
                    del self.peers[key]
        try:
            writer.close()
        except Exception:  # noqa: BLE001
            pass

    async def _dial(self, address: str) -> None:
        host, port = self._split(address)
        reader, writer = await asyncio.open_connection(host, port)
        conn = _Conn()
        try:
            writer.write(self._frame(_OP_HELLO, conn.nonce))
            task = asyncio.create_task(self._read_loop(reader, writer, conn))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
            # Block until the HELLO_REPLY echoes our nonce and the peer is
            # registered; tear the connection down on timeout so a silent
            # acceptor does not leak a socket per bootstrap attempt.
            await asyncio.wait_for(conn.registered.wait(), timeout=10)
        except Exception:
            self._drop_writer(writer)
            raise

    @staticmethod
    def _split(address: str) -> tuple[str, int]:
        hostport = address.split("://", 1)[-1]
        host, _, port = hostport.rpartition(":")
        return host, int(port)

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        # The dialer initiates; we answer its HELLO from the read loop.
        await self._read_loop(reader, writer, _Conn())

    async def _read_loop(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        conn: _Conn,
    ) -> None:
        try:
            while True:
                hdr = await reader.readexactly(4)
                (ln,) = struct.unpack("<I", hdr)
                if ln > _MAX_FRAME:
                    raise WireError(f"frame length {ln} exceeds cap")
                body = await reader.readexactly(ln)
                self._on_frame(body, writer, conn)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception as exc:  # noqa: BLE001
            self._record_error(exc)
        finally:
            self._drop_writer(writer)

    def _register(self, pid: PeerID, writer: asyncio.StreamWriter, conn: _Conn) -> None:
        conn.peer = pid
        with self._lock:
            self.peers[pid.public_key] = _Peer(pid, writer)
        conn.registered.set()

    def _on_frame(
        self, body: bytes, writer: asyncio.StreamWriter, conn: _Conn
    ) -> None:
        try:
            opcode, pid, payload, sig = self._parse_frame(body)
        except (WireError, IndexError, struct.error, UnicodeDecodeError) as exc:
            self._record_error(WireError(f"bad frame: {exc}"))
            return
        if not self._sig.verify(
            pid.public_key,
            self._hash.hash_bytes(
                bytes([opcode]) + pid.address.encode() + payload
            ),
            sig,
        ):
            self._record_error(WireError(f"bad frame signature from {pid.address}"))
            return

        if opcode == _OP_HELLO:
            # Dialer's opening. Do NOT register yet — a replayed HELLO
            # carries a stale nonce and its sender cannot complete the ACK.
            if len(payload) != _NONCE_LEN:
                self._record_error(WireError("bad HELLO nonce length"))
                return
            self._write_safe(writer, self._frame(_OP_HELLO_REPLY, payload + conn.nonce))
            return
        if opcode == _OP_HELLO_REPLY:
            # Acceptor echoed our nonce inside a signed frame: fresh proof.
            if len(payload) != 2 * _NONCE_LEN or payload[:_NONCE_LEN] != conn.nonce:
                self._record_error(WireError(f"stale HELLO_REPLY from {pid.address}"))
                return
            self._register(pid, writer, conn)
            self._write_safe(writer, self._frame(_OP_HELLO_ACK, payload[_NONCE_LEN:]))
            return
        if opcode == _OP_HELLO_ACK:
            if payload != conn.nonce:
                self._record_error(WireError(f"stale HELLO_ACK from {pid.address}"))
                return
            self._register(pid, writer, conn)
            return
        if opcode == _OP_SHARD:
            # Only registered connections may deliver shards, and the frame
            # identity must match the handshake identity.
            if conn.peer is None or pid.public_key != conn.peer.public_key:
                self._record_error(
                    WireError(f"shard from unregistered connection ({pid.address})")
                )
                return
            try:
                msg = Shard.unmarshal(payload)
            except WireError as exc:
                self._record_error(exc)
                return
            ctx = Ctx(msg, pid)
            self._dispatch.submit(self._dispatch_plugins, ctx)

    def _dispatch_plugins(self, ctx: Ctx) -> None:
        for plugin in self.plugins:
            try:
                plugin.receive(ctx)
            except Exception as exc:  # noqa: BLE001
                self._record_error(exc)
