"""Transports: the L0 layer (SURVEY.md §1) — peer registry, broadcast
fan-out, and plugin dispatch.

The reference delegates this layer to perlin-network/noise (SURVEY.md §2.3
D2): a builder-configured network with ordered plugin registration, a
blocking accept loop, ``Bootstrap(peers...)`` dial-out, per-message
signatures, and ``Broadcast`` fan-out to every connected peer. Two
implementations here share that contract:

- :class:`LoopbackHub` / :class:`LoopbackNetwork` — the in-process fake the
  reference lacks (SURVEY.md §4 "multi-node story"): N peers in one
  process, deterministic fault injection (drop / duplicate / corrupt /
  reorder) on every link, driving the full Receive state machine.
- :class:`TCPNetwork` — a real asyncio TCP transport with length-prefixed,
  identity-carrying, Ed25519-signed frames, serving the reference's
  multi-process deployment shape (main.go:137-173).

Both deliver messages to plugins through :class:`Ctx`, the slice of
noise's ``PluginContext`` the reference uses (main.go:53-87).

The TCP wire hot loop (docs/design.md §15) is built for six-figure
msgs/s on the same three rules as the device data path (§12): re-use
every buffer, move fewer bytes, amortize every dispatch —

- **recv**: each connection is an :class:`asyncio.BufferedProtocol`
  whose ``recv_into`` target is a per-connection :class:`_FrameRing`;
  frames parse IN PLACE as memoryview slices (the ``_to_sym`` no-copy
  discipline extended to the wire marshal) and the payload is copied
  exactly once, into the ``Shard`` fields;
- **verify**: frame signatures are not checked on the loop thread; the
  digest is streamed from the ring views and the (key, digest, sig)
  triple rides a per-sender verify queue whose drain batches cohorts
  through ``crypto.verify_batch`` (per-item fan-back on batch failure)
  on the dispatch pool;
- **send**: a broadcast's shards coalesce into one ``SHARD_BATCH``
  frame (one signature per cohort), frames queue as scatter-gather
  buffer lists, and a peer flush is one ``sendmsg`` iovec syscall;
- **scale**: ``recv_shards`` > 1 opens SO_REUSEPORT acceptor shards
  (one event loop thread each, kernel-balanced) all feeding the ONE
  shared :class:`_SerialDispatcher`, so per-peer DRR fairness and
  per-sender ordering hold no matter which shard owns the socket.
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket as _socket
import struct
import threading
import time
import weakref
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Optional

import numpy as np

from noise_ec_tpu.host.crypto import (
    Blake2bPolicy,
    Ed25519Policy,
    KeyPair,
    PeerID,
)
from noise_ec_tpu.host.wire import Shard, WireError
from noise_ec_tpu.obs.events import event
from noise_ec_tpu.obs.metrics import Timer
from noise_ec_tpu.obs.registry import default_registry
from noise_ec_tpu.obs.trace import current_trace_id, span, trace_key

__all__ = [
    "Ctx",
    "FaultInjector",
    "LoopbackHub",
    "LoopbackNetwork",
    "TCPNetwork",
    "format_address",
]

log = logging.getLogger("noise_ec_tpu.host.transport")


def format_address(protocol: str, host: str, port: int) -> str:
    """network.FormatAddress(protocol, host, port) — main.go:148."""
    return f"{protocol}://{host}:{port}"


class _TransportMetrics:
    """Cached children of the per-peer transport metric families.

    ``Family.labels()`` is a lock + dict get; the frame hot path pays one
    plain dict get here instead. Peer label cardinality is bounded: the
    address inside a frame is self-claimed, so past ``MAX_PEERS`` distinct
    labels new peers collapse into ``peer="other"`` rather than letting a
    hostile churner grow the registry without bound.
    """

    MAX_PEERS = 256

    def __init__(self):
        reg = default_registry()
        self._shards_in = reg.counter("noise_ec_transport_shards_in_total")
        self._shards_out = reg.counter("noise_ec_transport_shards_out_total")
        self._bytes_in = reg.counter("noise_ec_transport_bytes_in_total")
        self._bytes_out = reg.counter("noise_ec_transport_bytes_out_total")
        self._errors = reg.counter("noise_ec_transport_frame_errors_total")
        self._in: dict[str, tuple] = {}
        self._out: dict[str, tuple] = {}
        self._err: dict[str, object] = {}

    def _pair(self, cache: dict, shards, bytes_, peer: str) -> tuple:
        pair = cache.get(peer)
        if pair is None:
            if len(cache) >= self.MAX_PEERS:
                peer = "other"
                pair = cache.get(peer)
                if pair is not None:
                    return pair
            pair = cache[peer] = (
                shards.labels(peer=peer), bytes_.labels(peer=peer)
            )
        return pair

    def record_in(self, peer: str, nbytes: int, count: int = 1) -> None:
        c, b = self._pair(self._in, self._shards_in, self._bytes_in, peer)
        c.add(count)
        b.add(nbytes)

    def record_out(self, peer: str, nbytes: int, count: int = 1) -> None:
        c, b = self._pair(self._out, self._shards_out, self._bytes_out, peer)
        c.add(count)
        b.add(nbytes)

    def error(self, kind: str) -> None:
        c = self._err.get(kind)
        if c is None:
            c = self._err[kind] = self._errors.labels(kind=kind)
        c.add(1)


_transport_metrics: Optional[_TransportMetrics] = None


def transport_metrics() -> _TransportMetrics:
    """Process-wide transport metrics (lazy: first transport constructs)."""
    global _transport_metrics
    if _transport_metrics is None:
        _transport_metrics = _TransportMetrics()
    return _transport_metrics


class _WireMetrics:
    """Cached children of the ``noise_ec_wire_*`` hot-loop families
    (docs/design.md §15): batch-verify amortization, ring occupancy,
    send-side syscall coalescing."""

    def __init__(self):
        reg = default_registry()
        self._batch_size = reg.histogram(
            "noise_ec_wire_verify_batch_size"
        ).labels()
        self._ok = reg.counter(
            "noise_ec_wire_verified_frames_total"
        ).labels(outcome="ok")
        self._bad = reg.counter(
            "noise_ec_wire_verified_frames_total"
        ).labels(outcome="bad")
        self._fallbacks = reg.counter(
            "noise_ec_wire_verify_fallbacks_total"
        ).labels()
        self._per_syscall = reg.histogram(
            "noise_ec_wire_frames_per_syscall"
        ).labels()
        self._saved = reg.counter(
            "noise_ec_wire_syscalls_saved_total"
        ).labels()
        self._per_fill = reg.histogram(
            "noise_ec_wire_frames_per_fill"
        ).labels()
        self._ring = reg.histogram("noise_ec_wire_ring_bytes").labels()
        self._shards_per_frame = reg.histogram(
            "noise_ec_wire_shards_per_frame"
        ).labels()
        self._recv_shards = reg.gauge("noise_ec_wire_recv_shards").labels()

    def verify_batch(self, size: int, ok: int, fell_back: bool) -> None:
        self._batch_size.observe(size)
        if ok:
            self._ok.add(ok)
        if size - ok:
            self._bad.add(size - ok)
        if fell_back:
            self._fallbacks.add(1)

    def flush(self, frames: int, syscalls: int = 1) -> None:
        self._per_syscall.observe(frames)
        if frames > syscalls:
            self._saved.add(frames - syscalls)

    def fill(self, frames: int, ring_pending: int) -> None:
        self._per_fill.observe(frames)
        self._ring.observe(ring_pending)

    def batch_out(self, shards: int) -> None:
        self._shards_per_frame.observe(shards)

    def set_recv_shards(self, n: int) -> None:
        self._recv_shards.set(n)


_wire_metrics: Optional[_WireMetrics] = None


def wire_metrics() -> _WireMetrics:
    global _wire_metrics
    if _wire_metrics is None:
        _wire_metrics = _WireMetrics()
    return _wire_metrics


class _FrameRing:
    """Per-connection receive ring: ``recv_into`` lands bytes in the
    tail, complete length-prefixed frames parse IN PLACE as memoryview
    slices of the ring. The views are only valid until the next
    :meth:`writable` call (compaction may slide the unread region), so
    the frame consumer materializes what it keeps — which on the shard
    path is exactly one copy, into the ``Shard`` fields.
    """

    __slots__ = ("buf", "rpos", "wpos")

    MIN_RECV = 64 << 10  # smallest recv_into window we offer the kernel

    def __init__(self, capacity: int = 256 << 10):
        self.buf = bytearray(capacity)
        self.rpos = 0
        self.wpos = 0

    def pending(self) -> int:
        """Bytes received but not yet parsed (a straddling frame)."""
        return self.wpos - self.rpos

    def writable(self, sizehint: int = 0) -> memoryview:
        """The writable tail as a memoryview ≥ max(sizehint, MIN_RECV)
        bytes, compacting (or re-allocating, for an over-ring frame)
        first when the tail ran out. Never called with live frame
        views — the parse loop consumes them before the next fill."""
        need = max(self.MIN_RECV, sizehint)
        if len(self.buf) - self.wpos < need:
            pend = self.wpos - self.rpos
            if len(self.buf) - pend >= need and self.rpos:
                # Slide the unread tail to the front (amortized: each
                # byte moves at most once per ring traversal).
                self.buf[:pend] = self.buf[self.rpos : self.wpos]
            else:
                # A single frame larger than the ring: move to a fresh,
                # bigger buffer (a plain resize would fault on any
                # still-exported view of the old one).
                cap = max(len(self.buf) * 2, pend + need)
                new = bytearray(cap)
                new[:pend] = self.buf[self.rpos : self.wpos]
                self.buf = new
            self.rpos, self.wpos = 0, pend
        return memoryview(self.buf)[self.wpos :]

    def feed(self, nbytes: int) -> None:
        self.wpos += nbytes

    def feed_bytes(self, data: bytes) -> None:
        """Copy-in fill for transports without a recv_into surface
        (the KCP reader)."""
        view = self.writable(len(data))
        view[: len(data)] = data
        view.release()
        self.wpos += len(data)

    def frames(self, max_frame: int):
        """Yield every complete frame body as a memoryview; leaves a
        partial frame (straddling the next fill) in place. Raises
        WireError on an over-cap length prefix."""
        mv = memoryview(self.buf)
        try:
            while self.wpos - self.rpos >= 4:
                (ln,) = struct.unpack_from("<I", self.buf, self.rpos)
                if ln > max_frame:
                    raise WireError(f"frame length {ln} exceeds cap")
                end = self.rpos + 4 + ln
                if end > self.wpos:
                    return
                frame = mv[self.rpos + 4 : end]
                self.rpos = end
                yield frame
            if self.rpos == self.wpos:
                self.rpos = self.wpos = 0
        finally:
            mv.release()


class _WireConn(asyncio.BufferedProtocol):
    """One TCP connection of the wire hot loop.

    Reader half: an ``asyncio.BufferedProtocol`` — the event loop
    ``recv_into``s straight into this connection's :class:`_FrameRing`
    (no intermediate bytes objects) and every complete frame is handed
    to ``TCPNetwork._on_frame`` as an in-place memoryview.

    Writer half: the StreamWriter-shaped facade the rest of the
    transport already speaks (the ``KcpWriter`` duck type): ``write`` /
    ``drain`` / ``close`` / ``transport.get_write_buffer_size``, plus
    ``vectored_socket`` — the raw socket the flush path hands scatter-
    gather frame lists to ``sendmsg`` when the transport buffer is
    empty (one syscall per peer flush).
    """

    def __init__(self, net: "TCPNetwork", conn: "_Conn"):
        self.net = net
        self.conn = conn
        self.ring = _FrameRing()
        self.transport = None
        self._wire_loop: Optional[asyncio.AbstractEventLoop] = None
        self._sock = None
        self._paused = False
        self._drain_waiters: list[asyncio.Future] = []

    # -- protocol callbacks (owning loop thread only) --

    def connection_made(self, transport) -> None:
        self.transport = transport
        self._wire_loop = asyncio.get_running_loop()
        sock = transport.get_extra_info("socket")
        # asyncio hands out a TransportSocket facade that deprecates
        # sendmsg; the flush path needs the real socket underneath.
        self._sock = getattr(sock, "_sock", sock)

    def get_buffer(self, sizehint: int) -> memoryview:
        return self.ring.writable(sizehint if sizehint > 0 else 0)

    def buffer_updated(self, nbytes: int) -> None:
        self.ring.feed(nbytes)
        try:
            count = 0
            for frame in self.ring.frames(_MAX_FRAME):
                count += 1
                self.net._on_frame(frame, self, self.conn)
            if count:
                wire_metrics().fill(count, self.ring.pending())
        except WireError as exc:
            transport_metrics().error("wire")
            self.net._record_error(exc)
            self.transport.close()
        except Exception as exc:  # noqa: BLE001 — isolate the loop
            self.net._record_error(exc)
            self.transport.close()

    def eof_received(self) -> bool:
        return False  # close on peer FIN, like the stream read loop

    def connection_lost(self, exc) -> None:
        for fut in self._drain_waiters:
            if not fut.done():
                fut.set_result(None)
        self._drain_waiters.clear()
        self.net._drop_writer(self)

    def pause_writing(self) -> None:
        self._paused = True

    def resume_writing(self) -> None:
        self._paused = False
        for fut in self._drain_waiters:
            if not fut.done():
                fut.set_result(None)
        self._drain_waiters.clear()

    # -- StreamWriter facade --

    @property
    def vectored_socket(self):
        """Raw socket for scatter-gather sendmsg flushes, or None when
        the transport buffer is non-empty / paused / closing (then the
        flush must ride the ordered transport buffer instead)."""
        if (
            self._sock is None
            or self._paused
            or self.transport is None
            or self.transport.is_closing()
            or self.transport.get_write_buffer_size() > 0
        ):
            return None
        return self._sock

    def write(self, data) -> None:
        self.transport.write(data)

    def writelines(self, bufs) -> None:
        self.transport.writelines(bufs)

    async def drain(self) -> None:
        if self.transport is None or self.transport.is_closing():
            raise ConnectionResetError("connection lost")
        if not self._paused:
            return
        fut = self._wire_loop.create_future()
        self._drain_waiters.append(fut)
        await fut

    def close(self) -> None:
        t = self.transport
        if t is None:
            return
        loop = self._wire_loop
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if loop is None or running is loop:
            t.close()
        else:
            # transports are not thread-safe; route cross-thread closes
            # (network.close(), _drop_writer from a dispatch worker)
            # through the owning loop.
            loop.call_soon_threadsafe(t.close)

    def half_close(self) -> None:
        """FIN our send side but keep draining inbound frames until the
        peer's own FIN answers (mutual-dial loser demotion): a hard
        ``close()`` discards inbound data still unread in the kernel
        buffer, losing frames the peer wrote before it learned the
        tie-break verdict. A backstop timer full-closes if the peer
        never FINs back (``eof_received`` returning False makes a
        well-behaved peer close promptly)."""
        loop = self._wire_loop
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if loop is None or running is loop:
            self._half_close_here()
        else:
            loop.call_soon_threadsafe(self._half_close_here)

    # noise-ec: loop-affine
    def _half_close_here(self) -> None:
        t = self.transport
        if t is None or t.is_closing():
            return
        try:
            if not t.can_write_eof():
                t.close()
                return
            t.write_eof()
        # noise-ec: allow(event-on-swallow) — teardown fallback — the hard close below is the only remaining action
        except Exception:  # noqa: BLE001 — fall back to the hard close
            t.close()
            return
        if self._wire_loop is not None:
            self._wire_loop.call_later(
                self.net.connection_timeout, self.close
            )

    def is_closing(self) -> bool:
        return self.transport is None or self.transport.is_closing()

    def get_extra_info(self, name, default=None):
        if self.transport is None:
            return default
        return self.transport.get_extra_info(name, default)


class Ctx:
    """Plugin context handed to ``plugin.receive`` on every delivery.

    ``trace`` is the originating request's trace id when the delivery
    arrived inside a traced user request (the SHARD_BATCH trailing
    trace block, or the loopback's same-thread request scope) — the
    receive path stamps it as a ``request_trace`` span attr so a
    collector can merge receive-side pipeline spans into the
    originator's request trace. None for untraced traffic."""

    def __init__(self, msg: object, sender: PeerID,
                 trace: Optional[str] = None):
        self._msg = msg
        self._sender = sender
        self.trace = trace

    def message(self) -> object:
        return self._msg

    def sender(self) -> PeerID:
        return self._sender

    def client_public_key(self) -> bytes:
        return self._sender.public_key


# --------------------------------------------------------------- loopback


class FaultInjector:
    """Deterministic link-fault model shared by the loopback transport and
    the TCP chaos proxy (resilience/chaos.py).

    The reference has no fault-injection story at all (SURVEY.md §5 failure
    row); this is the first-class harness it calls for. Faults apply
    per-delivery, driven by a seeded generator (``seed`` may be an int or
    a ``numpy.random.SeedSequence``) so every run reproduces:

    - ``drop``: probability a delivery is discarded;
    - ``duplicate``: probability a delivery is made twice;
    - ``corrupt``: probability one byte of the wire bytes is flipped;
    - ``reorder``: probability a delivery is held in a one-slot delay line
      and released right after the next delivery on the same link (a
      pairwise swap; the slot is per-link, so a held message can neither
      migrate to another receiver nor be attributed to a later sender). At
      most one delivery per link is pending at stream end — within any
      k-of-n parity budget.
    """

    def __init__(
        self,
        seed=0,
        drop: float = 0.0,
        duplicate: float = 0.0,
        corrupt: float = 0.0,
        reorder: float = 0.0,
    ):
        self.rng = np.random.default_rng(seed)
        self.drop = drop
        self.duplicate = duplicate
        self.corrupt = corrupt
        self.reorder = reorder
        self._slots: dict[str, bytes] = {}  # per-link delay line for reorder
        self.stats = {"delivered": 0, "dropped": 0, "duplicated": 0,
                      "corrupted": 0, "reordered": 0}

    def apply(self, deliveries: list[bytes], link: str = "") -> list[bytes]:
        """Map a list of wire-byte deliveries on ``link`` to the faulted
        list. Stateful across calls: a reordered delivery from an earlier
        call is released behind a later one on the same link."""
        out: list[bytes] = []
        for buf in deliveries:
            if self.rng.random() < self.drop:
                self.stats["dropped"] += 1
                continue
            copies = 1
            if self.rng.random() < self.duplicate:
                copies = 2
                self.stats["duplicated"] += 1
            for _ in range(copies):
                b = buf
                if self.rng.random() < self.corrupt:
                    b = bytearray(b)
                    if b:
                        b[int(self.rng.integers(0, len(b)))] ^= 1 << int(
                            self.rng.integers(0, 8)
                        )
                    b = bytes(b)
                    self.stats["corrupted"] += 1
                if link not in self._slots and self.rng.random() < self.reorder:
                    self._slots[link] = b  # held; rides behind the next delivery
                    self.stats["reordered"] += 1
                    continue
                out.append(b)
                self.stats["delivered"] += 1
                held = self._slots.pop(link, None)
                if held is not None:
                    out.append(held)
                    self.stats["delivered"] += 1
        return out

    @property
    def pending(self) -> int:
        """Reorder-held deliveries not yet released (at most one per
        link). The accounting identity every caller can rely on:
        ``delivered + dropped + pending == inputs + duplicated``."""
        return len(self._slots)

    def flush(self, link: str = "") -> Optional[bytes]:
        """Release ``link``'s reorder-held delivery, if any. Stream-end
        hook (the chaos proxy calls it when a connection closes): a held
        frame must be forwarded, not silently become an unaccounted
        drop. Counts as delivered."""
        held = self._slots.pop(link, None)
        if held is not None:
            self.stats["delivered"] += 1
        return held


class LoopbackHub:
    """An in-process peer set: every registered network sees every other."""

    def __init__(self, fault_injector: Optional[FaultInjector] = None):
        self.nodes: dict[str, "LoopbackNetwork"] = {}
        self.faults = fault_injector

    def register(self, node: "LoopbackNetwork") -> None:
        self.nodes[node.id.address] = node

    def fan_out(self, sender: "LoopbackNetwork", wire_bytes: bytes) -> None:
        """Deliver one message to every peer except the sender
        (net.Broadcast semantics, main.go:206-208)."""
        metrics = transport_metrics()
        for addr, node in self.nodes.items():
            if addr == sender.id.address:
                continue
            metrics.record_out(addr, len(wire_bytes))
            bufs = [wire_bytes]
            if self.faults is not None:
                bufs = self.faults.apply(bufs, link=f"{sender.id.address}->{addr}")
            for buf in bufs:
                node.deliver(buf, sender.id)


class LoopbackNetwork:
    """One fake peer. API mirrors what the plugin needs from noise's
    ``*network.Network``: ``.id``, ``.keys``, ``.broadcast``, plugin
    registration and dispatch."""

    def __init__(self, hub: LoopbackHub, address: str, keys: Optional[KeyPair] = None):
        self.keys = keys or KeyPair.random()
        self.id = PeerID.create(address, self.keys.public_key)
        self.hub = hub
        self.plugins: list = []
        # bounded: hostile traffic appends one entry per bad frame
        self.errors: deque[Exception] = deque(maxlen=256)
        self.error_count = 0
        hub.register(self)

    def add_plugin(self, plugin) -> None:
        self.plugins.append(plugin)
        attach = getattr(plugin, "attach_network", None)
        if attach is not None:
            attach(self)

    def _record_error(self, exc: Exception) -> None:
        self.errors.append(exc)
        self.error_count += 1

    def broadcast(self, msg: Shard) -> None:
        with span("wire_encode", key=trace_key(msg.file_signature)):
            wire = msg.marshal()
        self.hub.fan_out(self, wire)

    def broadcast_many(self, msgs) -> None:
        """Cohort broadcast, one delivery per shard: the loopback keeps
        per-shard fan-out so the fault injector's per-delivery model
        (drop/duplicate/corrupt/reorder one SHARD at a time) is
        unchanged; only the TCP transport coalesces cohorts into
        SHARD_BATCH frames."""
        for msg in msgs:
            self.broadcast(msg)

    def deliver(self, wire_bytes: bytes, sender: PeerID) -> None:
        """Hub-side delivery: decode and dispatch to every plugin in
        registration order. Decode/dispatch errors are recorded, not
        raised — one bad message must not kill the receive loop."""
        metrics = transport_metrics()
        try:
            msg = Shard.unmarshal(wire_bytes)
        except WireError as exc:
            metrics.error("wire")
            self._record_error(exc)
            return
        metrics.record_in(sender.address, len(wire_bytes))
        # Synchronous fan-out: delivery runs on the SENDER's thread, so
        # the originating request scope is still active here — adopt its
        # id, the loopback equivalent of the SHARD_BATCH trace block.
        rt = current_trace_id()
        ctx = Ctx(msg, sender, trace=rt)
        with span("deliver", key=trace_key(msg.file_signature),
                  **({"request_trace": rt} if rt else {})):
            for plugin in self.plugins:
                try:
                    plugin.receive(ctx)
                except Exception as exc:  # noqa: BLE001 — isolate the loop
                    metrics.error("handler")
                    self._record_error(exc)


# -------------------------------------------------------------------- TCP

# Frame layout (all little-endian):
#   u32 frame_len | u8 opcode | u32 addr_len | addr utf-8 | 32B pubkey |
#   u32 payload_len | payload | 64B ed25519 signature over
#   blake2b256(opcode ‖ u32le(addr_len) ‖ addr ‖ u32le(payload_len) ‖ payload)
# The preimage is length-delimited, so no byte can migrate between the addr
# and payload fields without invalidating the signature (frame malleability).
# HELLO carries the dialer's nonce and introduces the peer (the discovery
# handshake); SHARD carries a marshaled Shard; PEERS carries a list of peer
# addresses (gossip). Every frame is signed, the transport-level integrity
# the reference gets from noise's signed messages (SURVEY.md §2.3 D2).
_OP_HELLO = 1        # dialer -> acceptor: payload = dialer 32B nonce
_OP_HELLO_REPLY = 3  # acceptor -> dialer: payload = dialer_nonce ‖ acceptor_nonce
_OP_HELLO_ACK = 4    # dialer -> acceptor: payload = acceptor_nonce
_OP_SHARD = 2        # payload = marshaled Shard
_OP_PEERS = 5        # payload = u32 count | count x (u32 len | addr utf-8)
# One broadcast's shard cohort in ONE signed frame (docs/design.md §15):
# payload = u32 count | count x (u32 len | marshaled Shard). One Ed25519
# sign on the send side and one (batched) verify on the receive side
# cover the whole cohort, where _OP_SHARD paid one of each per shard.
_OP_SHARD_BATCH = 6
_MAX_FRAME = 64 << 20
_NONCE_LEN = 32
# Keep one SHARD_BATCH frame's coalescing win without queueing a
# multi-second head-of-line blob behind it: cohorts above this split.
_MAX_BATCH_FRAME = 8 << 20


def _sign_preimage(opcode: int, addr: bytes, payload: bytes) -> bytes:
    return b"".join(
        [
            bytes([opcode]),
            struct.pack("<I", len(addr)),
            addr,
            struct.pack("<I", len(payload)),
            payload,
        ]
    )


# Request-trace ids are ``req-<16 hex>`` (20 chars); the cap keeps a
# hostile frame from smuggling bulk data through the trace block.
_MAX_TRACE_LEN = 64


def _encode_shard_batch_parts(msgs, trace: Optional[str] = None) -> list:
    """SHARD_BATCH payload as scatter-gather parts: each shard's
    ``marshal_parts`` buffers ride through unjoined, so the dominant
    ``shard_data`` is never copied on the send path.

    ``trace`` (the originating request's trace id, when the cohort is
    sent inside a traced user request) rides as an OPTIONAL trailing
    ``u32 len | utf-8`` block after the shards — absent entirely for
    untraced traffic, so the frame stays byte-identical to the pre-
    trace wire format in that case and old decoders never see it."""
    parts = [struct.pack("<I", len(msgs))]
    for m in msgs:
        head, data, tail = m.marshal_parts()
        parts.append(
            struct.pack("<I", len(head) + len(data) + len(tail))
        )
        if head:
            parts.append(head)
        if data:
            parts.append(data)
        if tail:
            parts.append(tail)
    if trace:
        raw = trace.encode()[:_MAX_TRACE_LEN]
        parts.append(struct.pack("<I", len(raw)))
        parts.append(raw)
    return parts


def _decode_shard_batch(payload) -> tuple[list[Shard], Optional[str]]:
    """Parse a SHARD_BATCH payload (bytes or an in-place ring view) to
    ``(shards, trace_id)``. The trace block is optional (see
    ``_encode_shard_batch_parts``); any OTHER trailing bytes — or a
    trace block whose length does not close the payload exactly —
    still reject the frame."""
    if len(payload) < 4:
        raise WireError("truncated shard batch")
    (count,) = struct.unpack_from("<I", payload, 0)
    if count > 4096:
        raise WireError(f"shard batch count {count} exceeds cap")
    pos = 4
    out = []
    for _ in range(count):
        if pos + 4 > len(payload):
            raise WireError("truncated shard batch")
        (ln,) = struct.unpack_from("<I", payload, pos)
        pos += 4
        if pos + ln > len(payload):
            raise WireError("truncated shard batch")
        out.append(Shard.unmarshal(payload[pos : pos + ln]))
        pos += ln
    trace: Optional[str] = None
    if pos != len(payload):
        if pos + 4 > len(payload):
            raise WireError("trailing bytes in shard batch")
        (tlen,) = struct.unpack_from("<I", payload, pos)
        pos += 4
        if tlen > _MAX_TRACE_LEN or pos + tlen != len(payload):
            raise WireError("trailing bytes in shard batch")
        try:
            trace = bytes(payload[pos : pos + tlen]).decode()
        except UnicodeDecodeError as exc:
            raise WireError(f"bad trace block in shard batch: {exc}")
    return out, trace


def _encode_peer_list(addresses: list[str]) -> bytes:
    parts = [struct.pack("<I", len(addresses))]
    for a in addresses:
        raw = a.encode()
        parts.append(struct.pack("<I", len(raw)))
        parts.append(raw)
    return b"".join(parts)


def _decode_peer_list(payload: bytes) -> list[str]:
    pos = 0
    (count,) = struct.unpack_from("<I", payload, pos); pos += 4
    if count > 4096:
        raise WireError(f"peer list count {count} exceeds cap")
    out = []
    for _ in range(count):
        (ln,) = struct.unpack_from("<I", payload, pos); pos += 4
        if pos + ln > len(payload):
            raise WireError("truncated peer list")
        out.append(payload[pos : pos + ln].decode()); pos += ln
    if pos != len(payload):
        raise WireError("trailing bytes in peer list")
    return out


class _SerialDispatcher:
    """Per-key ordered dispatch on a shared worker pool.

    Deliveries from one sender run strictly in order (the reference's
    per-connection dispatch semantics), but a slow handler on one sender's
    stream — e.g. a first-geometry FEC jit taking seconds — never blocks
    delivery from other senders (the single-worker head-of-line blocking
    flagged in round 1). Each key holds a bounded FIFO; one drain task per
    key runs on the pool at a time.
    """

    # Live dispatchers for the aggregate queue-depth gauge (weak: a
    # closed network's dispatcher must not pin itself via the callback).
    _instances: "weakref.WeakSet[_SerialDispatcher]" = weakref.WeakSet()
    _gauge_registered = False

    def __init__(self, max_workers: int = 4, max_queue: int = 4096,
                 on_error=None):
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="noise-ec-dispatch"
        )
        # A Condition, not a bare Lock: submit_wait blocks on it until a
        # drain frees window space (``with self._lock`` still takes the
        # underlying lock everywhere else).
        self._lock = threading.Condition(threading.Lock())
        self._queues: dict[bytes, deque] = {}
        self._active: set[bytes] = set()
        self.max_queue = max_queue
        self.overflows = 0
        self._waiters = 0  # submit_wait callers currently blocked
        reg = default_registry()
        self._overflow_counter = reg.counter(
            "noise_ec_dispatch_overflows_total"
        ).labels()
        self._latency_hist = reg.histogram("noise_ec_dispatch_seconds").labels()
        self._bp_waits = reg.counter(
            "noise_ec_backpressure_waits_total"
        ).labels(layer="dispatch")
        self._bp_hist = reg.histogram(
            "noise_ec_backpressure_wait_seconds"
        ).labels(layer="dispatch")
        cls = type(self)
        cls._instances.add(self)
        if not cls._gauge_registered:
            cls._gauge_registered = True
            reg.gauge("noise_ec_dispatch_queue_depth").set_callback(
                lambda: sum(d.queue_depth() for d in list(cls._instances))
            )
            reg.gauge("noise_ec_backpressure_queue_depth").set_callback(
                lambda: sum(d._waiters for d in list(cls._instances)),
                layer="dispatch",
            )
        # Error contract: a handler that raises is reported to ``on_error``
        # (an ``(exc) -> None`` recorder) and counted — never silently
        # swallowed. The TCP dispatch wrapper records into Network.errors;
        # a bare function submitted by a future caller still gets counted
        # and logged rather than vanishing.
        self.dropped_errors = 0
        self._on_error = on_error

    def submit(self, key: bytes, fn, *args) -> bool:
        """Enqueue ``fn(*args)`` on ``key``'s ordered stream. Returns False
        (and counts an overflow) if the key's window is full."""
        with self._lock:
            q = self._queues.setdefault(key, deque())
            if len(q) >= self.max_queue:
                self.overflows += 1
                self._overflow_counter.add(1)
                return False
            q.append((fn, args))
            if key not in self._active:
                self._active.add(key)
                self._pool.submit(self._drain, key)
        return True

    def submit_wait(self, key: bytes, fn, *args,
                    timeout: float = 30.0) -> bool:
        """Blocking submit: when ``key``'s window is full, BLOCK the
        producer until a drain frees space instead of dropping — the
        backpressure shape for in-process producers (the fleet hub),
        who would rather slow than lose deliveries. Never call from the
        drain pool or an event-loop thread (the drain this waits for may
        be behind the caller). Returns False only when ``timeout``
        expires with the window still full (counted as an overflow)."""
        t0 = None
        deadline = 0.0
        try:
            with self._lock:
                while True:
                    q = self._queues.setdefault(key, deque())
                    if len(q) < self.max_queue:
                        q.append((fn, args))
                        if key not in self._active:
                            self._active.add(key)
                            self._pool.submit(self._drain, key)
                        return True
                    if t0 is None:
                        t0 = time.monotonic()
                        deadline = t0 + timeout
                        self._bp_waits.add(1)
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        self.overflows += 1
                        self._overflow_counter.add(1)
                        return False
                    self._waiters += 1
                    try:
                        self._lock.wait(min(remaining, 0.5))
                    finally:
                        self._waiters -= 1
        finally:
            if t0 is not None:
                self._bp_hist.observe(time.monotonic() - t0)

    # Items drained per pool turn when ONE sender is active: a
    # continuously-busy sender yields the worker back to the pool every
    # batch, so max_workers concurrent hot senders cannot starve
    # everyone else's delivery. With several senders active the quantum
    # shrinks (deficit round-robin, _drain) so a spammy peer's deep
    # queue cannot hold a worker for a full batch while a quiet peer's
    # single delivery waits.
    DRAIN_BATCH = 16

    def _drain(self, key: bytes) -> None:
        # Per-peer fairness: the per-turn quantum divides the batch
        # budget across the senders currently active, floored at 1 —
        # one 10x talker gets the same per-rotation slice as everyone
        # else, so quiet peers' deliveries interleave within ~one
        # rotation instead of waiting out full DRAIN_BATCH turns
        # (pinned by tests/test_fleet.py).
        with self._lock:
            active = len(self._active) or 1
        quantum = max(1, self.DRAIN_BATCH // active)
        for _ in range(quantum):
            with self._lock:
                q = self._queues.get(key)
                if not q:
                    self._active.discard(key)
                    self._queues.pop(key, None)
                    return
                fn, args = q.popleft()
                if self._waiters:
                    self._lock.notify_all()
            try:
                with Timer(histogram=self._latency_hist):
                    fn(*args)
            except Exception as exc:  # noqa: BLE001 — isolate the stream
                self.dropped_errors += 1
                if self._on_error is not None:
                    try:
                        self._on_error(exc)
                    # noise-ec: allow(event-on-swallow) — recorder tap must not kill the drain loop; nothing actionable
                    except Exception:  # noqa: BLE001 — recorder must not kill drain
                        pass
                else:
                    log.warning("dispatch handler error on %r: %r", key, exc)
        # Batch exhausted with work remaining: requeue behind other senders.
        self._pool.submit(self._drain, key)

    def queue_depth(self) -> int:
        """Entries enqueued across all senders (the export gauge)."""
        with self._lock:
            return sum(len(q) for q in self._queues.values())

    def shutdown(self, wait: bool = True) -> None:
        self._pool.shutdown(wait=wait)


@dataclass
class _Peer:
    pid: PeerID
    writer: asyncio.StreamWriter
    is_dialer: bool = False  # we initiated the registered connection
    # The address WE dialed to reach this peer (None on accepted
    # connections). Distinct from pid.address — the peer's self-claimed
    # address — and the one the supervisor must re-dial on loss: with a
    # chaos proxy (or NAT) in between, the dialable address and the
    # claimed address differ.
    dial_address: Optional[str] = None


class _Conn:
    """Per-connection handshake state.

    A peer is registered only after a fresh-nonce proof: every frame is
    signed (over opcode ‖ address ‖ payload), and registration requires the
    counterparty to echo OUR nonce for THIS connection inside one of those
    signed frames — so a captured HELLO/REPLY/ACK replayed on a new
    connection verifies as a signature but never matches the new nonce and
    never binds the victim's identity to the attacker's socket."""

    def __init__(self, is_dialer: bool = False,
                 dial_address: Optional[str] = None):
        self.nonce = os.urandom(_NONCE_LEN)
        self.peer: Optional[PeerID] = None
        self.registered = asyncio.Event()
        self.is_dialer = is_dialer  # we initiated this connection
        self.dial_address = dial_address  # the address we dialed (dialer side)


class TCPNetwork:
    """Asyncio TCP transport with the noise-style lifecycle:
    ``listen()`` (background accept loop), ``bootstrap(peers)`` (dial out),
    ``broadcast(msg)`` (signed fan-out to all connected peers).

    Runs its event loop on a daemon thread so callers keep the reference's
    synchronous REPL shape (``go net.Listen()``, main.go:169).

    Security model (vs the reference's noise transport, SURVEY.md §2.3 D2):
    every frame is Ed25519-signed over (opcode ‖ sender address ‖ payload),
    and peers register through a three-way nonce handshake
    (HELLO → HELLO_REPLY → HELLO_ACK) so neither the address nor a replayed
    handshake can bind a foreign identity to an attacker's socket. Shards
    are accepted only from registered connections whose key matches.
    """

    # Disconnect a peer whose kernel+asyncio write buffer exceeds this —
    # a stalled reader must not grow sender memory without bound.
    MAX_PEER_WRITE_BUFFER = 32 << 20

    def __init__(
        self,
        host: str = "localhost",
        port: int = 3000,
        keys: Optional[KeyPair] = None,
        protocol: str = "tcp",
        *,
        connection_timeout: float = 60.0,
        recv_window: int = 4096,
        send_window: int = 4096,
        write_buffer_size: int = 4096,
        write_flush_latency: float = 0.050,
        write_timeout: float = 3.0,
        discovery: bool = True,
        max_discovered_peers: int = 64,
        discovery_interval: float = 2.0,
        reconnect: bool = True,
        recv_shards: int = 1,
    ):
        """Tuning knobs default to the reference's builder options
        (/root/reference/main.go:27-33): connection timeout 60s, recv/send
        window 4096 messages, write buffer 4096 bytes, write-flush latency
        50ms, write timeout 3s. Semantics here:

        - ``connection_timeout`` bounds dial + nonce handshake;
        - ``recv_window`` caps each sender's ordered dispatch queue;
        - ``send_window`` caps coalesced-but-unflushed frames per peer
          (overflow forces an immediate flush);
        - ``write_buffer_size`` is the coalesce buffer: a pending batch at
          or above this many bytes flushes without waiting for the timer;
        - ``write_flush_latency`` is the coalescing timer for small writes;
        - ``write_timeout`` bounds the post-flush drain; a peer that cannot
          accept bytes for this long is disconnected.

        ``discovery`` enables the peer-exchange gossip the reference gets
        from noise's discovery plugin (main.go:151): on every registration
        the node sends the newcomer its known peer addresses and announces
        the newcomer to existing peers; learned addresses are dialed
        (deduped, capped at ``max_discovered_peers``). Every
        ``discovery_interval`` seconds the full peer list is re-gossiped to
        every registered peer — registration-time gossip alone cannot heal
        a lost introduction (a failed discovered dial, or simultaneous
        mutual dials where each side keeps a different connection and
        closes the other's survivor, leaves a pair partitioned with no new
        registration event to retry on).

        ``reconnect`` enables the self-healing peer lifecycle
        (resilience/peers.py): loss of an ESTABLISHED connection we
        dialed triggers supervised re-dial with exponential backoff +
        full jitter, gated by a per-peer circuit breaker fed by dial
        failures and write-timeout disconnects.

        ``recv_shards`` > 1 opens that many SO_REUSEPORT acceptor shards
        on the listen port — one extra event-loop thread per shard, the
        kernel balancing inbound connections across them — all feeding
        the ONE shared dispatcher, so a single Python loop thread stops
        being the receive ceiling while per-sender ordering and DRR
        fairness are untouched (docs/design.md §15). TCP only; clamped
        to 1 where SO_REUSEPORT is unavailable.
        """
        if protocol not in ("tcp", "kcp"):
            raise ValueError(
                f"protocol {protocol!r} not supported (tcp or kcp, the "
                "reference's option set — main.go:123)"
            )
        self.protocol = protocol
        self.keys = keys or KeyPair.random()
        self.host = host
        self.port = port
        self.id = PeerID.create(format_address(protocol, host, port), self.keys.public_key)
        self.plugins: list = []
        self.connection_timeout = connection_timeout
        self.recv_window = recv_window
        self.send_window = send_window
        self.write_buffer_size = write_buffer_size
        self.write_flush_latency = write_flush_latency
        self.write_timeout = write_timeout
        self.discovery = discovery
        self.max_discovered_peers = max_discovered_peers
        self.discovery_interval = discovery_interval
        # Keyed by PUBLIC KEY, not the self-claimed address: an address is
        # just a claim inside a signed frame, so keying by it would let any
        # handshake-completing attacker evict a legitimate peer by claiming
        # the same address. One entry per identity; addresses may collide.
        self.peers: dict[bytes, _Peer] = {}  # public key -> peer
        # bounded: hostile traffic appends one entry per bad frame
        self.errors: deque[Exception] = deque(maxlen=256)
        self.error_count = 0
        self._sig = Ed25519Policy()
        self._hash = Blake2bPolicy()
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._loop.run_forever, daemon=True)
        self._server: Optional[asyncio.AbstractServer] = None
        self._lock = threading.Lock()
        self._tasks: set[asyncio.Task] = set()
        # Plugin dispatch (FEC decode; first-geometry jit compile can take
        # seconds on the device backend) must not run on the event-loop
        # thread, or every connection's read loop and handshake stalls
        # behind it. Per-sender ordered queues on a shared pool: order is
        # preserved within a sender, and one sender's slow decode cannot
        # stall delivery from other peers.
        self._dispatch = _SerialDispatcher(
            max_workers=4, max_queue=recv_window,
            on_error=self._record_error,
        )
        # Deferred frame verification (docs/design.md §15): the loop
        # thread parses and digests; cohorts drain through verify_batch
        # on the dispatch pool, keyed (and ordered) per sender.
        self._verify_q: dict[bytes, deque] = {}
        self._verify_scheduled: set[bytes] = set()
        self._verify_lock = threading.Lock()
        # SO_REUSEPORT acceptor shards (extra loops started by listen()).
        if recv_shards > 1 and (
            protocol != "tcp" or not hasattr(_socket, "SO_REUSEPORT")
        ):
            recv_shards = 1
        self.recv_shards = max(1, int(recv_shards))
        self._shard_loops: list[tuple[asyncio.AbstractEventLoop,
                                      threading.Thread]] = []
        self._shard_servers: list[asyncio.AbstractServer] = []
        # Write coalescing state. Each writer's entries are only touched
        # on that writer's OWNING loop thread (per-connection with
        # recv_shards > 1); distinct keys make the dicts safe to share.
        self._pending: dict[asyncio.StreamWriter, list[bytes]] = {}
        self._pending_frames: dict[asyncio.StreamWriter, int] = {}
        self._pending_bytes: dict[asyncio.StreamWriter, int] = {}
        # Bytes posted cross-thread (broadcast -> call_soon queue) but not
        # yet seen by _enqueue_frame; guarded by self._lock. Part of the
        # wait_writable backpressure measurement.
        self._posted_bytes: dict[asyncio.StreamWriter, int] = {}
        self._flush_handles: dict[asyncio.StreamWriter, asyncio.TimerHandle] = {}
        self._draining: set[asyncio.StreamWriter] = set()
        # Frames addressed to a connection that died mid-swap (mutual-dial
        # tie-break demotion, or the remote's demotion FIN) are re-routed
        # to the peer's surviving connection — or parked here, keyed by
        # peer public key, until its registration lands. Guarded by
        # self._lock; entries are (parked-at monotonic, bytes, batches)
        # and expire after connection_timeout (checked lazily on insert
        # and flush — no timer), so a peer that never comes back costs at
        # most MAX_PEER_WRITE_BUFFER bytes until close().
        self._limbo: dict[bytes, tuple[float, int, list]] = {}
        # Discovery state: addresses we are responsible for dialing (dedup +
        # budget). Entries are removed on dial failure and on disconnect of
        # the dialed peer, so churned peers can be re-learned from gossip.
        self._dialing: set[str] = set()
        # Failed-dial cooldown: addr -> (next-allowed monotonic time, delay).
        # Without it, periodic re-gossip would re-dial an unreachable
        # claimed address every interval forever, flooding self.errors.
        self._dial_backoff: dict[str, tuple[float, float]] = {}
        self._gossip_task: Optional[asyncio.Task] = None
        # Handshake timing: dialed address -> seconds between sending
        # HELLO and the peer registering (≈ one network round trip plus
        # two Ed25519 verifies). The distributed-trace collector uses it
        # to tighten per-peer clock-offset uncertainty (obs/collector.py)
        # — the TCP-level handshake is a truer delay floor than an HTTP
        # poll of /spans.
        self._handshake_rtt: dict[str, float] = {}
        # Set at the top of close(): the supervisor must not re-dial peers
        # whose connections we are tearing down ourselves.
        self._closing = False
        self.supervisor = None
        if reconnect:
            from noise_ec_tpu.resilience.peers import PeerSupervisor

            self.supervisor = PeerSupervisor(self)

    # ------------------------------------------------------------ lifecycle

    def listen(self) -> None:
        """Start the accept loop in the background (go net.Listen())."""
        self._thread.start()
        fut = asyncio.run_coroutine_threadsafe(self._start_server(), self._loop)
        self._server = fut.result(timeout=10)
        addr = self._server.sockets[0].getsockname()
        self.port = addr[1]
        self.id = PeerID.create(
            format_address(self.protocol, self.host, self.port),
            self.keys.public_key,
        )
        # SO_REUSEPORT acceptor shards: the main server bound the
        # (possibly ephemeral) port with the flag set, so shard sockets
        # can join it and the kernel hashes inbound connections across
        # the whole group. Each shard is one extra daemon loop thread
        # accepting + parsing + digesting; everything downstream (verify
        # drains, plugin dispatch) already runs on the shared pool.
        wire_metrics().set_recv_shards(self.recv_shards)
        for i in range(1, self.recv_shards):
            loop = asyncio.new_event_loop()
            t = threading.Thread(
                target=loop.run_forever, daemon=True,
                name=f"noise-ec-recv-{i}",
            )
            t.start()
            self._shard_loops.append((loop, t))
            fut = asyncio.run_coroutine_threadsafe(
                self._start_shard_server(), loop
            )
            self._shard_servers.append(fut.result(timeout=10))
        if self.discovery and self.discovery_interval > 0:
            def _start_gossip():
                self._gossip_task = self._loop.create_task(self._gossip_loop())
            self._loop.call_soon_threadsafe(_start_gossip)

    async def _start_shard_server(self):
        loop = asyncio.get_running_loop()
        return await loop.create_server(
            lambda: _WireConn(self, _Conn()), self.host, self.port,
            reuse_port=True,
        )

    def _writer_loop(self, writer) -> asyncio.AbstractEventLoop:
        """The event loop that owns ``writer``'s transport (shard conns
        live on their acceptor shard's loop; everything else on the
        main loop)."""
        return getattr(writer, "_wire_loop", None) or self._loop

    async def _gossip_loop(self) -> None:
        """Periodic full-peer-list re-gossip (see ``discovery_interval``).

        One shared frame per tick: receivers already skip their own
        address (and known peers), so per-recipient exclusion would only
        multiply the Ed25519 signing work by the peer count.
        """
        while True:
            await asyncio.sleep(self.discovery_interval)
            try:
                with self._lock:
                    peers = list(self.peers.values())
                if len(peers) < 2:
                    continue
                frame = self._frame(
                    _OP_PEERS, _encode_peer_list([p.pid.address for p in peers])
                )
                for p in peers:
                    self._write_safe(p.writer, frame)
            except Exception as exc:  # noqa: BLE001 — a bad tick must not
                # kill the loop: losing it silently re-creates the very
                # unhealable-partition state this mechanism exists to fix.
                self._record_error(exc)

    async def _start_server(self):
        if self.protocol == "kcp":
            from noise_ec_tpu.host.kcp import start_kcp_server

            return await start_kcp_server(self._handle_conn, self.host, self.port)
        # TCP accepts ride the BufferedProtocol recv_into path, not
        # StreamReader (docs/design.md §15).
        return await self._loop.create_server(
            lambda: _WireConn(self, _Conn()), self.host, self.port,
            reuse_port=True if self.recv_shards > 1 else None,
        )

    def bootstrap(self, peer_addresses: list[str]) -> None:
        """Dial out to peers (net.Bootstrap, main.go:171-173). Blocks until
        each handshake completes (or fails), so a broadcast immediately
        after bootstrap reaches every successfully dialed peer."""
        for addr in peer_addresses:
            if not addr:
                continue
            fut = asyncio.run_coroutine_threadsafe(self._dial(addr), self._loop)
            try:
                fut.result(timeout=self.connection_timeout + 5)
            except Exception as exc:  # noqa: BLE001
                self._record_error(exc)
                log.error("bootstrap %s failed: %s", addr, exc)

    def close(self) -> None:
        self._closing = True
        if self.supervisor is not None:
            self.supervisor.close()
        with self._lock:
            self._limbo.clear()

        async def _shutdown():
            if self._server is not None:
                self._server.close()
            if self._gossip_task is not None:
                self._gossip_task.cancel()
                self._gossip_task = None
            for w in list(self._pending):
                # Best-effort final flush, on each writer's owning loop
                # (flush touches per-writer coalesce state, which is
                # loop-affine under recv_shards > 1).
                loop = self._writer_loop(w)
                if loop is self._loop:
                    self._flush_writer(w)
                else:
                    loop.call_soon_threadsafe(self._flush_writer, w)
            for peer in list(self.peers.values()):
                peer.writer.close()  # _WireConn.close is thread-safe

        if self._thread.is_alive():
            asyncio.run_coroutine_threadsafe(_shutdown(), self._loop).result(timeout=5)
            for (loop, _), server in zip(self._shard_loops,
                                         self._shard_servers):
                loop.call_soon_threadsafe(server.close)
            for loop, thread in self._shard_loops:
                loop.call_soon_threadsafe(loop.stop)
                thread.join(timeout=5)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=5)
        self._dispatch.shutdown(wait=True)

    # ------------------------------------------------------------- plugins

    def add_plugin(self, plugin) -> None:
        self.plugins.append(plugin)
        # Plugins that can talk back on the receive path (the NACK shard
        # repair in host/plugin.py) get a transport handle.
        attach = getattr(plugin, "attach_network", None)
        if attach is not None:
            attach(self)

    def _record_error(self, exc: Exception) -> None:
        self.errors.append(exc)
        self.error_count += 1

    def handshake_rtts(self) -> dict[str, float]:
        """HELLO round-trip seconds per dialed peer address (the
        clock-sync hint consumed by ``obs.collector.TraceCollector``)."""
        return dict(self._handshake_rtt)

    # --------------------------------------------------------------- wire

    def _frame_parts(self, opcode: int, payload_parts) -> tuple[list, int]:
        """One signed frame as scatter-gather buffer parts.

        ``b"".join(parts)`` is byte-identical to ``_frame(opcode,
        join(payload_parts))`` (Ed25519 is deterministic and the
        signing hash streams the parts), but the payload buffers —
        shard_data above all — are never copied into a joined frame:
        they travel as iovecs down to the ``sendmsg`` flush. Returns
        (parts, total frame bytes)."""
        addr = self.id.address.encode()
        plen = sum(len(p) for p in payload_parts)
        pre_head = (
            bytes([opcode]) + struct.pack("<I", len(addr)) + addr
        )
        plen_b = struct.pack("<I", plen)
        sig = self.keys.sign_parts(
            self._sig, self._hash, (pre_head, plen_b, *payload_parts)
        )
        body_len = len(pre_head) + 32 + 4 + plen + 64
        head = (
            struct.pack("<I", body_len)
            + pre_head
            + self.keys.public_key
            + plen_b
        )
        parts = [head]
        parts.extend(p for p in payload_parts if len(p))
        parts.append(sig)
        return parts, 4 + body_len

    def _frame(self, opcode: int, payload: bytes) -> bytes:
        parts, _ = self._frame_parts(opcode, (payload,))
        return b"".join(parts)

    @staticmethod
    def _parse_frame_fields(body) -> tuple[int, bytes, bytes, object, bytes]:
        """Parse a frame body (bytes or an in-place ring memoryview) to
        (opcode, addr utf-8 bytes, pubkey, payload, signature). The
        payload keeps the caller's buffer type — a view stays a view —
        so the shard path can digest + unmarshal without a whole-frame
        copy; everything else is materialized (it is tiny)."""
        pos = 0
        if len(body) < 5:
            raise WireError("truncated frame")
        opcode = body[pos]; pos += 1
        (alen,) = struct.unpack_from("<I", body, pos); pos += 4
        addr = bytes(body[pos : pos + alen]); pos += alen
        pubkey = bytes(body[pos : pos + 32]); pos += 32
        if pos + 4 > len(body):
            raise WireError("truncated frame")
        (plen,) = struct.unpack_from("<I", body, pos); pos += 4
        payload = body[pos : pos + plen]; pos += plen
        sig = bytes(body[pos : pos + 64])
        if len(addr) != alen or len(pubkey) != 32 or len(payload) != plen \
                or len(sig) != 64:
            raise WireError("truncated frame")
        if pos + 64 != len(body):
            # No unauthenticated trailing bytes: the signature must be the
            # last 64 bytes of the body, exactly.
            raise WireError("trailing bytes after frame signature")
        return opcode, addr, pubkey, payload, sig

    @staticmethod
    def _parse_frame(body: bytes) -> tuple[int, PeerID, bytes, bytes]:
        """Returns (opcode, sender_pid, payload, signature)."""
        opcode, addr, pubkey, payload, sig = TCPNetwork._parse_frame_fields(
            body
        )
        return opcode, PeerID.create(addr.decode(), pubkey), bytes(payload), sig

    # ------------------------------------------------------------ dataflow

    def broadcast(self, msg: Shard) -> None:
        """Signed fan-out to every connected peer (main.go:206-208).

        Frames ride the per-peer coalescing buffer: consecutive broadcasts
        within ``write_flush_latency`` batch into one socket write (noise's
        WriteFlushLatency semantics)."""
        with span("wire_encode", key=trace_key(msg.file_signature)):
            parts, nbytes = self._frame_parts(
                _OP_SHARD, msg.marshal_parts()
            )
        self._post_frame(parts, nbytes, shards=1)

    def broadcast_many(self, msgs) -> None:
        """Broadcast a cohort of shards — one encode call's output, a
        stream chunk's shares — as SHARD_BATCH frames: the whole cohort
        costs ONE Ed25519 sign here and one (batched) verify per
        receiver, and its buffers ride one sendmsg flush per peer
        (docs/design.md §15). Order within the cohort is preserved;
        semantics per shard are exactly ``broadcast``'s."""
        msgs = list(msgs)
        if not msgs:
            return
        if len(msgs) == 1:
            self.broadcast(msgs[0])
            return
        # Captured HERE, inside the caller's request scope (thread-local),
        # so network implementations keep their signatures: the cohort
        # frame carries the request trace id and every receiver's
        # pipeline spans can merge into the originating request's trace.
        rt = current_trace_id()
        # Split oversized cohorts so one frame never exceeds the batch
        # cap (the receive ring handles them either way, but a multi-
        # tens-of-MiB frame is a head-of-line blob for the peer).
        start = 0
        while start < len(msgs):
            group = []
            group_bytes = 0
            while start < len(msgs) and (
                not group or group_bytes + msgs[start].size() <= _MAX_BATCH_FRAME
            ):
                group_bytes += msgs[start].size() + 4
                group.append(msgs[start])
                start += 1
            if len(group) == 1:
                self.broadcast(group[0])
                continue
            with span("wire_encode", key=trace_key(group[0].file_signature),
                      **({"request_trace": rt} if rt else {})):
                parts, nbytes = self._frame_parts(
                    _OP_SHARD_BATCH,
                    _encode_shard_batch_parts(group, trace=rt),
                )
            wire_metrics().batch_out(len(group))
            self._post_frame(parts, nbytes, shards=len(group))

    def _post_frame(self, parts: list, nbytes: int, shards: int) -> None:
        """Hand one built frame (scatter-gather parts) to every peer's
        owning loop for coalescing + flush."""
        metrics = transport_metrics()
        with self._lock:
            writers = [p.writer for p in self.peers.values()]
            for p in self.peers.values():
                metrics.record_out(p.pid.address, nbytes, count=shards)
            # Count the bytes as posted BEFORE handing them to the loop
            # thread: a frame sitting in call_soon_threadsafe's queue is
            # visible to neither the kernel buffer nor the coalesce
            # batch, so without this the backpressure waiter reads
            # "empty" while a starved loop thread holds an unbounded
            # backlog (observed: cap disconnects despite per-share
            # waiting on a loaded single-core host).
            for w in writers:
                self._posted_bytes[w] = (
                    self._posted_bytes.get(w, 0) + nbytes
                )
        for w in writers:
            self._writer_loop(w).call_soon_threadsafe(
                self._enqueue_frames, w, parts, 1, nbytes
            )

    def send_to(self, public_key: bytes, msg: Shard) -> bool:
        """Send one signed shard frame to a single registered peer
        (directed NACK repair — host/plugin.py; broadcast semantics are
        otherwise unchanged). Returns False when no registered peer holds
        ``public_key``."""
        with self._lock:
            peer = self.peers.get(bytes(public_key))
            if peer is None:
                return False
            writer = peer.writer
            address = peer.pid.address
        parts, nbytes = self._frame_parts(_OP_SHARD, msg.marshal_parts())
        transport_metrics().record_out(address, nbytes)
        with self._lock:
            self._posted_bytes[writer] = (
                self._posted_bytes.get(writer, 0) + nbytes
            )
        self._writer_loop(writer).call_soon_threadsafe(
            self._enqueue_frames, writer, parts, 1, nbytes
        )
        return True

    def send_many_to(self, public_key: bytes, msgs) -> bool:
        """Send a shard cohort to ONE registered peer as SHARD_BATCH
        frames — the placement layer's targeted-delivery surface
        (docs/placement.md): same cohort splitting, signing and batch
        accounting as ``broadcast_many``, but one destination instead
        of the whole peer table. Returns False when no registered peer
        holds ``public_key``."""
        msgs = list(msgs)
        if not msgs:
            return True
        with self._lock:
            peer = self.peers.get(bytes(public_key))
            if peer is None:
                return False
            writer = peer.writer
            address = peer.pid.address
        metrics = transport_metrics()
        # Thread-local request-scope read — same contract as
        # broadcast_many: the cohort frame carries the trace id so the
        # owner's receive-side spans merge into the PUT's trace.
        rt = current_trace_id()
        start = 0
        while start < len(msgs):
            group = []
            group_bytes = 0
            while start < len(msgs) and (
                not group
                or group_bytes + msgs[start].size() <= _MAX_BATCH_FRAME
            ):
                group_bytes += msgs[start].size() + 4
                group.append(msgs[start])
                start += 1
            with span(
                "wire_encode", key=trace_key(group[0].file_signature),
                **({"request_trace": rt} if rt else {}),
            ):
                if len(group) == 1:
                    parts, nbytes = self._frame_parts(
                        _OP_SHARD, group[0].marshal_parts()
                    )
                else:
                    parts, nbytes = self._frame_parts(
                        _OP_SHARD_BATCH,
                        _encode_shard_batch_parts(group, trace=rt),
                    )
            if len(group) > 1:
                wire_metrics().batch_out(len(group))
            metrics.record_out(address, nbytes, count=len(group))
            with self._lock:
                self._posted_bytes[writer] = (
                    self._posted_bytes.get(writer, 0) + nbytes
                )
            self._writer_loop(writer).call_soon_threadsafe(
                self._enqueue_frames, writer, parts, 1, nbytes
            )
        return True

    def placement_directory(self) -> dict:
        """``{address token: public key}`` for every registered peer —
        how the placement ring's topology tokens (peer addresses) map to
        ``send_many_to`` handles. Snapshot semantics: membership may
        change after return, and a send to a departed peer just returns
        False."""
        with self._lock:
            return {p.pid.address: pk for pk, p in self.peers.items()}

    def wait_writable(
        self,
        soft_cap: Optional[int] = None,
        timeout: float = 30.0,
        headroom: int = 0,
    ) -> None:
        """Producer-side backpressure for bulk streams: block the calling
        (non-loop) thread until every peer's outgoing buffer (kernel +
        asyncio + the coalesce batch + cross-thread posted frames) is
        below ``soft_cap`` (default: the hard cap minus the caller's
        ``headroom``, floored at 1/8 of the cap).

        Without this, a sender producing faster than its peers drain —
        e.g. streaming a multi-hundred-MiB object to a receiver that is
        busy decoding — walks the write buffer into the
        MAX_PEER_WRITE_BUFFER hard cap and DISCONNECTS its own peer
        mid-stream (found by a 256 MiB real-TCP soak): the hard cap is an
        anti-DoS bound against unresponsive READERS, not a send-rate
        governor. The stream emitter calls this between chunks. Reads
        are cross-thread snapshots (plain int reads under the GIL);
        staleness costs at most one extra 5 ms poll. On timeout the
        caller proceeds — a genuinely stalled peer is then the hard
        cap's and write_timeout's job to drop.
        """
        ident = threading.get_ident()
        if ident == self._thread.ident or any(
            ident == t.ident for _, t in self._shard_loops
        ):
            # Called on an event-loop thread: the drain this would wait
            # for runs ON this thread, so blocking here deadlocks until
            # the timeout with zero progress. No current caller does this
            # (the stream emitter runs on the producer's thread); the
            # guard keeps a future loop-side caller from wedging the
            # whole transport. No-op — the hard cap still protects memory.
            return
        if soft_cap is None:
            # Derive from the hard cap MINUS what the caller is about to
            # enqueue (``headroom``): waiting to "half full" is not
            # enough when the next burst alone exceeds the other half.
            # The floor keeps progress even for outsized bursts — a
            # single frame larger than the hard cap cannot be saved by
            # any waiting policy.
            soft_cap = max(
                self.MAX_PEER_WRITE_BUFFER - headroom,
                self.MAX_PEER_WRITE_BUFFER // 8,
            )
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                with self._lock:
                    writers = [p.writer for p in self.peers.values()]
                    posted = [self._posted_bytes.get(w, 0) for w in writers]
                busy = any(
                    w.transport.get_write_buffer_size()
                    + self._pending_bytes.get(w, 0) + posted_w > soft_cap
                    for w, posted_w in zip(writers, posted)
                )
            # noise-ec: allow(event-on-swallow) — peer set mutating mid-scan — retried on the next sweep tick
            except Exception:  # noqa: BLE001 — peer set mutating mid-scan
                busy = True
            if not busy:
                return
            time.sleep(0.005)

    # -- write path (event-loop thread only) --

    # noise-ec: loop-affine
    @staticmethod
    def _writer_pubkey(writer) -> Optional[bytes]:
        """The registered peer public key a connection's frames are
        addressed to, or None for handshake-stage connections and
        writer fakes without a ``conn``."""
        conn = getattr(writer, "conn", None)
        peer = getattr(conn, "peer", None)
        return getattr(peer, "public_key", None)

    def _reroute_frames(
        self, pubkey: bytes, parts: list, nframes: int, nbytes: int,
        exclude=None,
    ) -> None:
        """Re-address coalesced frames whose connection is dying to the
        peer's CURRENT connection. Simultaneous mutual dials resolve by
        closing one of the two connections (the ``_register`` tie-break),
        and a broadcast can race that swap: its frames are posted to the
        connection that loses — on either side — and a hard teardown
        would drop them on the floor (observed: the three-process
        discovery e2e losing a one-shot broadcast sent the instant the
        gossip-built edge re-registered). If the survivor is not
        registered YET (the eviction→re-registration gap), the frames
        park in ``_limbo`` and flush when its registration lands."""
        target = None
        parked = expired = False
        with self._lock:
            if self._closing:
                return
            peer = self.peers.get(pubkey)
            if (
                peer is not None
                and peer.writer is not exclude
                and not getattr(peer.writer, "is_closing", lambda: False)()
            ):
                target = peer.writer
                self._posted_bytes[target] = (
                    self._posted_bytes.get(target, 0) + nbytes
                )
            else:
                now = time.monotonic()
                parked_at, parked_bytes, batches = self._limbo.get(
                    pubkey, (now, 0, [])
                )
                if now - parked_at > self.connection_timeout:
                    expired = bool(batches)
                    parked_at, parked_bytes, batches = now, 0, []
                if parked_bytes + nbytes <= self.MAX_PEER_WRITE_BUFFER:
                    batches.append((parts, nframes, nbytes))
                    self._limbo[pubkey] = (
                        parked_at, parked_bytes + nbytes, batches
                    )
                    parked = True
        if expired:
            event("conn.limbo_drop", "warn", peer=pubkey[:8].hex(),
                  reason="park expired before a connection registered")
        if parked:
            event("conn.limbo_park", peer=pubkey[:8].hex(),
                  frames=nframes, bytes=nbytes)
        if target is not None:
            event("conn.limbo_reroute", peer=pubkey[:8].hex(),
                  frames=nframes, bytes=nbytes)
            self._writer_loop(target).call_soon_threadsafe(
                self._enqueue_frames, target, parts, nframes, nbytes
            )

    def _flush_limbo(self, pubkey: bytes, writer) -> None:
        """Hand any parked frames for ``pubkey`` to its freshly
        registered connection (expired parks are dropped)."""
        with self._lock:
            parked = self._limbo.pop(pubkey, None)
            if parked is None:
                return
            parked_at, parked_bytes, batches = parked
            if time.monotonic() - parked_at > self.connection_timeout:
                expired = True
            else:
                expired = False
                self._posted_bytes[writer] = (
                    self._posted_bytes.get(writer, 0) + parked_bytes
                )
        if expired:
            event("conn.limbo_drop", "warn", peer=pubkey[:8].hex(),
                  bytes=parked_bytes,
                  reason="park expired before registration")
            return
        event("conn.limbo_reroute", peer=pubkey[:8].hex(),
              bytes=parked_bytes, batches=len(batches))
        loop = self._writer_loop(writer)
        for parts, nframes, nbytes in batches:
            loop.call_soon_threadsafe(
                self._enqueue_frames, writer, parts, nframes, nbytes
            )

    def _enqueue_frames(
        self, writer: asyncio.StreamWriter, parts: list, nframes: int,
        nbytes: int,
    ) -> None:
        """Coalesce one frame's scatter-gather ``parts`` into the peer's
        pending buffer list; flush when the batch reaches
        ``write_buffer_size`` bytes or ``send_window`` frames, otherwise
        after ``write_flush_latency``. Runs on the writer's owning
        loop."""
        if getattr(writer, "is_closing", lambda: False)():
            # The connection died between the broadcast's peer-table
            # snapshot and this loop callback (mutual-dial swap, remote
            # FIN): writing would vanish into a closed transport.
            with self._lock:
                left = self._posted_bytes.get(writer, 0) - nbytes
                if left > 0:
                    self._posted_bytes[writer] = left
                else:
                    self._posted_bytes.pop(writer, None)
            pubkey = self._writer_pubkey(writer)
            if pubkey is not None:
                self._reroute_frames(
                    pubkey, parts, nframes, nbytes, exclude=writer
                )
            return
        if writer.transport.get_write_buffer_size() > self.MAX_PEER_WRITE_BUFFER:
            self._drop_writer(writer)  # also clears _posted_bytes
            self._record_error(
                RuntimeError("peer write buffer exceeded cap; disconnected")
            )
            return
        pend = self._pending.setdefault(writer, [])
        pend.extend(parts)
        frames = self._pending_frames.get(writer, 0) + nframes
        self._pending_frames[writer] = frames
        total = self._pending_bytes.get(writer, 0) + nbytes
        self._pending_bytes[writer] = total
        with self._lock:
            # Decrement the cross-thread posted counter only AFTER the
            # bytes are visible in the coalesce batch: the backpressure
            # waiter must always see in-flight bytes counted SOMEWHERE
            # (posted -> pending -> transport buffer, in that order).
            left = self._posted_bytes.get(writer, 0) - nbytes
            if left > 0:
                self._posted_bytes[writer] = left
            else:
                self._posted_bytes.pop(writer, None)
        if total >= self.write_buffer_size or frames >= self.send_window:
            self._flush_writer(writer)
        elif writer not in self._flush_handles:
            self._flush_handles[writer] = self._writer_loop(
                writer
            ).call_later(self.write_flush_latency, self._flush_writer, writer)

    # sendmsg iovec budget per syscall: Linux UIO_MAXIOV is 1024; stay
    # under it and let oversized batches fall back to the joined write.
    _SENDMSG_MAX_BUFS = 512

    # noise-ec: loop-affine
    def _flush_writer(self, writer: asyncio.StreamWriter) -> None:
        handle = self._flush_handles.pop(writer, None)
        if handle is not None:
            handle.cancel()
        pend = self._pending.pop(writer, None)
        nframes = self._pending_frames.pop(writer, 0)
        if not pend:
            self._pending_bytes.pop(writer, None)
            return
        if getattr(writer, "is_closing", lambda: False)():
            # The connection died while this batch coalesced; re-address
            # it instead of writing into a closed transport.
            nbytes = self._pending_bytes.pop(writer, 0)
            pubkey = self._writer_pubkey(writer)
            if pubkey is not None:
                self._reroute_frames(
                    pubkey, pend, nframes, nbytes, exclude=writer
                )
            return
        try:
            # _pending_bytes is cleared only after the batch lands in the
            # socket or the transport buffer, so the backpressure waiter
            # never sees the bytes vanish from both counters at once.
            self._write_vectored(writer, pend, nframes)
        except Exception as exc:  # noqa: BLE001
            self._record_error(exc)
            return
        finally:
            self._pending_bytes.pop(writer, None)
        # Enforce write_timeout: a peer that cannot drain for that long is
        # disconnected. One drain task per writer at a time (asyncio allows
        # a single drain waiter).
        if writer not in self._draining:
            self._draining.add(writer)
            loop = self._writer_loop(writer)
            task = loop.create_task(self._drain_writer(writer))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)

    # noise-ec: loop-affine
    def _write_vectored(self, writer, bufs: list, nframes: int) -> None:
        """Flush a coalesced buffer list: ONE ``sendmsg`` iovec syscall
        when the transport buffer is empty (the steady state — the
        kernel buffer drains between flushes), the ordered transport
        buffer otherwise. Frames-per-syscall and syscalls-saved feed
        the ``noise_ec_wire_*`` families either way (a joined
        transport write is still one syscall's worth of coalescing)."""
        sock = getattr(writer, "vectored_socket", None)
        if sock is not None and len(bufs) > 1 and len(bufs) <= self._SENDMSG_MAX_BUFS:
            total = sum(len(b) for b in bufs)
            try:
                sent = sock.sendmsg(bufs)
            except (BlockingIOError, InterruptedError):
                sent = 0
            except OSError as exc:
                self._record_error(exc)
                self._drop_writer(writer)
                return
            if sent < total:
                # Kernel buffer filled mid-iovec: hand the tail to the
                # transport buffer (which backpressures + drains).
                rest = []
                for b in bufs:
                    if sent >= len(b):
                        sent -= len(b)
                        continue
                    rest.append(b[sent:] if sent else b)
                    sent = 0
                writer.transport.writelines(rest)
            wire_metrics().flush(nframes, syscalls=1)
            return
        writer.write(b"".join(bufs))
        wire_metrics().flush(nframes, syscalls=1)

    async def _drain_writer(self, writer: asyncio.StreamWriter) -> None:
        try:
            await asyncio.wait_for(writer.drain(), timeout=self.write_timeout)
        except (asyncio.TimeoutError, TimeoutError):
            self._record_error(
                RuntimeError(f"write timeout ({self.write_timeout}s); disconnected")
            )
            # "write_timeout" feeds the peer's circuit breaker: a reader
            # that cannot drain is peer-health evidence, not just a
            # buffer-management event.
            self._drop_writer(writer, reason="write_timeout")
        except Exception as exc:  # noqa: BLE001
            self._record_error(exc)
            self._drop_writer(writer)
        finally:
            self._draining.discard(writer)

    def _write_safe(self, writer: asyncio.StreamWriter, frame: bytes) -> None:
        """Immediate (uncoalesced) write — handshake/control frames.

        Cross-loop callers (gossip / register announcing to a peer whose
        connection lives on another acceptor shard) are routed to the
        writer's owning loop; writers without one (the KCP facade, unit-
        test fakes) write inline, exactly as before."""
        loop = getattr(writer, "_wire_loop", None)
        if loop is not None:
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if running is not loop:
                loop.call_soon_threadsafe(self._write_safe_here, writer, frame)
                return
        self._write_safe_here(writer, frame)

    # noise-ec: loop-affine
    def _write_safe_here(self, writer, frame: bytes) -> None:
        if writer.transport.get_write_buffer_size() > self.MAX_PEER_WRITE_BUFFER:
            # A stalled reader must not grow sender memory without bound.
            self._drop_writer(writer)
            self._record_error(
                RuntimeError("peer write buffer exceeded cap; disconnected")
            )
            return
        try:
            writer.write(frame)
        except Exception as exc:  # noqa: BLE001
            self._record_error(exc)

    def _drop_writer(self, writer: asyncio.StreamWriter,
                     reason: str = "") -> None:
        lost_dialed: list[str] = []
        lost_addrs: list[str] = []
        with self._lock:
            for key, p in list(self.peers.items()):
                if p.writer is writer:
                    del self.peers[key]
                    lost_addrs.append(p.pid.address)
                    # Allow gossip to re-establish a churned peer.
                    self._dialing.discard(p.pid.address)
                    if p.dial_address is not None:
                        self._dialing.discard(p.dial_address)
                        lost_dialed.append(p.dial_address)
        for address in lost_addrs:
            # INFO mirror of "registered peer": operators (and the e2e
            # tests) can pair every registration with its teardown
            # instead of inferring loss from silence.
            log.info("dropped peer %s%s", address,
                     f" ({reason})" if reason else "")
            event("peer.drop", "warn", peer=address,
                  reason=reason or "connection closed")
        handle = self._flush_handles.pop(writer, None)
        if handle is not None:
            handle.cancel()
        pend = self._pending.pop(writer, None)
        pend_frames = self._pending_frames.pop(writer, 0)
        pend_bytes = self._pending_bytes.pop(writer, 0)
        with self._lock:
            self._posted_bytes.pop(writer, None)
        if pend:
            # Shard frames already addressed to this peer must survive a
            # connection swap (mutual-dial demotion): hand them to the
            # surviving connection, or park them until it registers.
            # Handshake-stage writers have no peer identity; their
            # control frames drop with the connection, as before.
            pubkey = self._writer_pubkey(writer)
            if pubkey is not None:
                self._reroute_frames(
                    pubkey, pend, pend_frames, pend_bytes, exclude=writer
                )
        try:
            writer.close()
        # noise-ec: allow(event-on-swallow) — close() race on a dying writer; the loss is already accounted above
        except Exception:  # noqa: BLE001
            pass
        # Established-connection loss of a peer WE dialed: hand the dialed
        # address to the supervisor for backoff-gated re-dial. After the
        # peer-table cleanup above, so the supervisor's is-alive check
        # cannot race the stale entry.
        if self.supervisor is not None and not self._closing:
            for address in lost_dialed:
                self.supervisor.on_connection_lost(address, reason)

    async def _dial(self, address: str) -> None:
        # Idempotent: dialing an address we already hold a registered
        # connection to is a no-op (repeat bootstrap calls, gossip
        # re-learning a live peer) — no churn, no duplicate handshake.
        with self._lock:
            if any(p.pid.address == address for p in self.peers.values()):
                return
        self._dialing.add(address)
        host, port = self._split(address)
        conn = _Conn(is_dialer=True, dial_address=address)
        is_kcp = address.startswith("kcp://") or (
            "://" not in address and self.protocol == "kcp"
        )
        try:
            if is_kcp:
                from noise_ec_tpu.host.kcp import open_kcp_connection

                # (The kcp opener returns without any network round trip;
                # the real unreachable-peer bound is conn.registered.wait
                # below.)
                reader, writer = await asyncio.wait_for(
                    open_kcp_connection(host, port),
                    timeout=self.connection_timeout,
                )
            else:
                # TCP dials ride the same BufferedProtocol recv_into
                # path as accepted connections; the protocol IS the
                # writer facade.
                loop = asyncio.get_running_loop()
                _transport, writer = await asyncio.wait_for(
                    loop.create_connection(
                        lambda: _WireConn(self, conn), host, port
                    ),
                    timeout=self.connection_timeout,
                )
                reader = None
        except Exception:
            # Refund the dedup slot: a failed dial (bootstrap races the
            # peer's startup, say) must not block discovery from ever
            # dialing this address again.
            self._dialing.discard(address)
            raise
        try:
            t_hello = time.perf_counter()
            writer.write(self._frame(_OP_HELLO, conn.nonce))
            if reader is not None:
                task = asyncio.create_task(
                    self._read_loop(reader, writer, conn)
                )
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)
            # Block until the HELLO_REPLY echoes our nonce and the peer is
            # registered; tear the connection down on timeout so a silent
            # acceptor does not leak a socket per bootstrap attempt.
            await asyncio.wait_for(
                conn.registered.wait(), timeout=self.connection_timeout
            )
            self._handshake_rtt[address] = time.perf_counter() - t_hello
        except Exception:
            self._dialing.discard(address)
            self._drop_writer(writer)
            raise

    async def _dial_discovered(self, address: str) -> None:
        """Dial an address learned from peer gossip (best-effort). A failed
        dial refunds its budget and dedup slot so later gossip can retry
        (a crashed-and-restarted peer must not stay partitioned forever),
        but enters exponential backoff so periodic re-gossip does not
        hammer an unreachable claimed address every interval."""
        try:
            await self._dial(address)
        except Exception as exc:  # noqa: BLE001
            self._dialing.discard(address)
            loop_t = self._loop.time()
            delay = min(
                self._dial_backoff.get(address, (0.0, self.discovery_interval))[1] * 2,
                60.0,
            )
            self._dial_backoff[address] = (loop_t + delay, delay)
            self._record_error(exc)
            log.info("discovery dial %s failed: %s (retry in %.1fs)",
                     address, exc, delay)
        else:
            self._dial_backoff.pop(address, None)

    @staticmethod
    def _split(address: str) -> tuple[str, int]:
        hostport = address.split("://", 1)[-1]
        host, _, port = hostport.rpartition(":")
        return host, int(port)

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        # The dialer initiates; we answer its HELLO from the read loop.
        await self._read_loop(reader, writer, _Conn())

    # Bulk read size for transports without a recv_into surface (KCP):
    # one await + one ring fill per chunk instead of two per frame.
    READ_CHUNK = 256 << 10

    async def _read_loop(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        conn: _Conn,
    ) -> None:
        ring = _FrameRing()
        try:
            while True:
                data = await reader.read(self.READ_CHUNK)
                if not data:
                    break
                ring.feed_bytes(data)
                count = 0
                for frame in ring.frames(_MAX_FRAME):
                    count += 1
                    self._on_frame(frame, writer, conn)
                if count:
                    wire_metrics().fill(count, ring.pending())
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        except Exception as exc:  # noqa: BLE001
            self._record_error(exc)
        finally:
            self._drop_writer(writer)

    def _register(self, pid: PeerID, writer: asyncio.StreamWriter, conn: _Conn) -> None:
        conn.peer = pid
        # Simultaneous mutual dials (common under gossip) produce two
        # connections per peer pair, and each side must close the SAME one:
        # "keep the newest" is not symmetric (registration order can differ
        # per side), and if A keeps conn1 while C keeps conn2, each closes
        # the other's survivor and the pair partitions until re-gossip.
        # Deterministic tie-break both sides agree on — applied only when
        # the two connections have OPPOSITE directions (the mutual-dial
        # shape): the connection DIALED by the lexicographically smaller
        # public key survives. Same-direction conflicts (a peer crashed
        # without FIN and reconnected the same way) keep the newest: the
        # old socket is dead and the remote only knows the new one.
        with self._lock:
            others = [
                p for key, p in self.peers.items() if key != pid.public_key
            ]
            prev = self.peers.get(pid.public_key)
            if prev is not None and prev.writer is writer:
                # Idempotent re-registration (a replayed-but-valid ACK on
                # the registered connection): nothing changed, so no
                # gossip re-announce and no close-the-loser dance.
                conn.registered.set()
                return
            keep_new = True
            if prev is not None:
                if prev.is_dialer != conn.is_dialer:
                    keep_new = conn.is_dialer == (
                        self.keys.public_key < pid.public_key
                    )
            if keep_new:
                self.peers[pid.public_key] = _Peer(
                    pid, writer, conn.is_dialer,
                    dial_address=conn.dial_address,
                )
        if prev is not None and prev.writer is not writer:
            # Demote the loser GRACEFULLY: the remote may have written
            # frames on it before learning the tie-break verdict (the
            # other side registers the loser first and can broadcast on
            # it immediately), and a hard close() discards whatever is
            # still unread in the kernel buffer — a one-shot message
            # vanishes with no teardown signal the sender can act on.
            # half_close() FINs our send side while the read loop keeps
            # draining; the loser's conn identity stays verified, so
            # those late frames still deliver, and the teardown
            # completes when the remote's own FIN answers. Its read-loop
            # teardown calls _drop_writer, which only removes entries
            # whose writer matches — the surviving entry is never
            # evicted by the teardown.
            loser = prev.writer if keep_new else writer
            log.info("demoting duplicate connection to %s (%s survives)",
                     pid.address, "new" if keep_new else "previous")
            event("conn.demote", peer=pid.address,
                  survivor="new" if keep_new else "previous")
            half = getattr(loser, "half_close", None)
            try:
                if half is not None:
                    half()
                else:
                    loser.close()
            # noise-ec: allow(event-on-swallow) — loser half-close race during connection-demote teardown
            except Exception:  # noqa: BLE001
                pass
            # Frames coalescing on the loser can no longer flush (its
            # send side just FINned); re-address them to the survivor.
            handle = self._flush_handles.pop(loser, None)
            if handle is not None:
                handle.cancel()
            pend = self._pending.pop(loser, None)
            lost_frames = self._pending_frames.pop(loser, 0)
            lost_bytes = self._pending_bytes.pop(loser, 0)
            if pend:
                self._reroute_frames(
                    pid.public_key, pend, lost_frames, lost_bytes,
                    exclude=loser,
                )
        conn.registered.set()
        if keep_new:
            # A registration that swapped the peer's connection releases
            # any frames that were parked while no live connection held
            # the entry (a broadcast racing the swap).
            self._flush_limbo(pid.public_key, writer)
            # INFO so operators (and the e2e tests) can observe exactly
            # when a peer becomes reachable instead of probing with
            # retried sends.
            log.info("registered peer %s", pid.address)
            if self.supervisor is not None and conn.dial_address is not None:
                # Any successful dial (bootstrap, discovery or supervised
                # re-dial) closes the address's breaker.
                self.supervisor.breaker(conn.dial_address).record_success()
        if self.discovery and others and keep_new:
            # Peer exchange (the reference's discovery.Plugin, main.go:151):
            # tell the newcomer who we know, and announce the newcomer to
            # everyone else, so broadcast reach is transitive rather than
            # limited to the bootstrap list. (A connection that lost the
            # mutual-dial tie-break is closing; its peer was already
            # gossiped when the surviving connection registered.)
            self._write_safe(
                writer,
                self._frame(
                    _OP_PEERS, _encode_peer_list([p.pid.address for p in others])
                ),
            )
            announce = self._frame(_OP_PEERS, _encode_peer_list([pid.address]))
            for p in others:
                self._write_safe(p.writer, announce)

    # noise-ec: loop-affine
    def _on_frame(
        self, body, writer: asyncio.StreamWriter, conn: _Conn
    ) -> None:
        """One parsed frame off the wire. ``body`` may be an in-place
        ring memoryview — anything kept past this call is materialized
        here. Runs on the connection's owning loop thread; the shard
        path defers its Ed25519 work to the batched verify stage so the
        loop thread never pays per-frame crypto (docs/design.md §15)."""
        metrics = transport_metrics()
        try:
            opcode, addr_b, pubkey, payload, sig = self._parse_frame_fields(
                body
            )
            addr = addr_b.decode()
        except (WireError, IndexError, struct.error, UnicodeDecodeError) as exc:
            metrics.error("wire")
            self._record_error(WireError(f"bad frame: {exc}"))
            return

        if opcode in (_OP_SHARD, _OP_SHARD_BATCH):
            # Only registered connections may deliver shards, and the
            # frame identity must match the handshake identity — checked
            # BEFORE any crypto, so an unregistered socket costs a dict
            # miss, not a verify.
            peer = conn.peer
            if peer is None or pubkey != peer.public_key:
                metrics.error("unregistered")
                self._record_error(
                    WireError(f"shard from unregistered connection ({addr})")
                )
                return
            # Reuse the handshake PeerID in the steady state (same key,
            # same claimed address) instead of re-hashing a node id per
            # frame; a frame claiming a different address still verifies
            # against its own claim.
            pid = peer if addr == peer.address else PeerID.create(addr, pubkey)
            # Digest on the loop thread while the ring view is alive:
            # the preimage streams through the hash in parts, so the
            # payload is never joined into a fresh buffer.
            digest = self._hash.hash_parts((
                bytes([opcode]),
                struct.pack("<I", len(addr_b)),
                addr_b,
                struct.pack("<I", len(payload)),
                payload,
            ))
            try:
                if opcode == _OP_SHARD:
                    msgs, rt = [Shard.unmarshal(payload)], None
                else:
                    msgs, rt = _decode_shard_batch(payload)
            except WireError as exc:
                metrics.error("wire")
                self._record_error(exc)
                return
            self._submit_verify(pid, digest, sig, msgs, len(body) + 4, rt)
            return

        # Control frames (handshake, gossip): rare and loop-affine —
        # verified inline, exactly as before.
        payload = bytes(payload)
        pid = PeerID.create(addr, pubkey)
        if not self._sig.verify(
            pubkey,
            self._hash.hash_bytes(_sign_preimage(opcode, addr_b, payload)),
            sig,
        ):
            metrics.error("signature")
            self._record_error(WireError(f"bad frame signature from {pid.address}"))
            return

        if opcode == _OP_HELLO:
            # Dialer's opening. Do NOT register yet — a replayed HELLO
            # carries a stale nonce and its sender cannot complete the ACK.
            if len(payload) != _NONCE_LEN:
                self._record_error(WireError("bad HELLO nonce length"))
                return
            self._write_safe(writer, self._frame(_OP_HELLO_REPLY, payload + conn.nonce))
            return
        if opcode == _OP_HELLO_REPLY:
            # Acceptor echoed our nonce inside a signed frame: fresh proof.
            if len(payload) != 2 * _NONCE_LEN or payload[:_NONCE_LEN] != conn.nonce:
                self._record_error(WireError(f"stale HELLO_REPLY from {pid.address}"))
                return
            # ACK before registering: _register may immediately gossip a
            # PEERS frame on this writer, and the acceptor must see our ACK
            # (and register us) first — TCP preserves per-connection order.
            self._write_safe(writer, self._frame(_OP_HELLO_ACK, payload[_NONCE_LEN:]))
            self._register(pid, writer, conn)
            return
        if opcode == _OP_HELLO_ACK:
            if payload != conn.nonce:
                self._record_error(WireError(f"stale HELLO_ACK from {pid.address}"))
                return
            self._register(pid, writer, conn)
            return
        if opcode == _OP_PEERS:
            # Gossip is accepted only from registered peers (same gate as
            # shards): an unauthenticated socket must not steer our dials.
            if conn.peer is None or pid.public_key != conn.peer.public_key:
                self._record_error(
                    WireError(f"peer list from unregistered connection ({pid.address})")
                )
                return
            if not self.discovery:
                return
            try:
                addresses = _decode_peer_list(payload)
            except (WireError, struct.error, UnicodeDecodeError) as exc:
                self._record_error(WireError(f"bad peer list: {exc}"))
                return
            with self._lock:
                known = {p.pid.address for p in self.peers.values()}
            now = self._loop.time()
            # Prune expired cooldowns so the dict stays bounded (gossiped
            # addresses are attacker-supplied; without pruning a hostile
            # peer grows it by a batch per tick forever). The cap below
            # bounds even the pathological all-unexpired case.
            self._dial_backoff = {
                a: v for a, v in self._dial_backoff.items()
                if now < v[0] + v[1]
            }
            while len(self._dial_backoff) > 4 * self.max_discovered_peers:
                self._dial_backoff.pop(next(iter(self._dial_backoff)))
            for addr in addresses:
                backoff = self._dial_backoff.get(addr)
                if (
                    addr == self.id.address
                    or addr in known
                    or addr in self._dialing
                    or (backoff is not None and now < backoff[0])
                    or len(self._dialing) >= self.max_discovered_peers
                ):
                    continue
                self._dialing.add(addr)
                task = self._loop.create_task(self._dial_discovered(addr))
                self._tasks.add(task)
                task.add_done_callback(self._tasks.discard)
            return
    # Frames per verify cohort: matches the dispatcher's DRAIN_BATCH
    # scale so one drain's inline plugin work stays within the fairness
    # quantum; the batch-verify curve is flat past ~16 anyway.
    VERIFY_DRAIN_MAX = 16

    def _submit_verify(
        self, pid: PeerID, digest: bytes, sig: bytes, msgs: list,
        nbytes: int, trace: Optional[str] = None,
    ) -> None:
        """Queue parsed-but-unverified frames for the per-sender batched
        verify drain. Bounded by ``recv_window`` per sender (the same
        budget the dispatch queue enforces) — overflow drops the frame
        and counts it, never blocks the loop thread. ``trace`` is the
        cohort frame's request-trace id (rides to the plugin ``Ctx``
        only after the signature verifies)."""
        key = pid.public_key
        schedule = False
        overflow = False
        with self._verify_lock:
            q = self._verify_q.get(key)
            if q is None:
                q = self._verify_q[key] = deque()
            if len(q) >= self.recv_window:
                overflow = True
            else:
                q.append((pid, digest, sig, msgs, nbytes, trace))
                if key not in self._verify_scheduled:
                    self._verify_scheduled.add(key)
                    schedule = True
        if overflow:
            transport_metrics().error("overflow")
            self._record_error(
                RuntimeError(
                    f"recv window ({self.recv_window}) overflow from "
                    f"{pid.address}; shard dropped"
                )
            )
            return
        if schedule and not self._dispatch.submit(
            key, self._drain_verify, key
        ):
            with self._verify_lock:
                self._verify_scheduled.discard(key)
            transport_metrics().error("overflow")
            self._record_error(
                RuntimeError(
                    f"recv window ({self.recv_window}) overflow from "
                    f"{pid.address}; shard dropped"
                )
            )

    def _drain_verify(self, key: bytes) -> None:
        """One verify cohort for sender ``key``, on the dispatch pool:
        up to VERIFY_DRAIN_MAX queued frames verify as ONE batch
        (``crypto.verify_batch`` — per-item fan-back isolates a bad
        signature to its own frame), then the survivors' shards dispatch
        to the plugins in arrival order. Rides the per-sender serialized
        dispatcher, so ordering and DRR fairness hold unchanged."""
        with self._verify_lock:
            q = self._verify_q.get(key)
            batch = []
            while q and len(batch) < self.VERIFY_DRAIN_MAX:
                batch.append(q.popleft())
            if q:
                more = True
            else:
                more = False
                self._verify_scheduled.discard(key)
                self._verify_q.pop(key, None)
        if batch:
            metrics = transport_metrics()
            verdicts = self._sig.verify_batch(
                [(item[0].public_key, item[1], item[2]) for item in batch]
            )
            ok_count = sum(verdicts)
            wire_metrics().verify_batch(
                len(batch), ok_count,
                fell_back=len(batch) > 1 and ok_count < len(batch),
            )
            for (pid, _digest, _sig, msgs, nbytes, trace), ok in zip(
                batch, verdicts
            ):
                if not ok:
                    metrics.error("signature")
                    self._record_error(
                        WireError(f"bad frame signature from {pid.address}")
                    )
                    continue
                metrics.record_in(pid.address, nbytes, count=len(msgs))
                for msg in msgs:
                    self._dispatch_plugins(Ctx(msg, pid, trace=trace))
        if more and not self._dispatch.submit(key, self._drain_verify, key):
            with self._verify_lock:
                self._verify_scheduled.discard(key)

    def _dispatch_plugins(self, ctx: Ctx) -> None:
        metrics = transport_metrics()
        msg = ctx.message()
        key = trace_key(msg.file_signature) if isinstance(msg, Shard) else None
        rt = ctx.trace
        with span("deliver", key=key,
                  **({"request_trace": rt} if rt else {})):
            for plugin in self.plugins:
                try:
                    plugin.receive(ctx)
                except Exception as exc:  # noqa: BLE001
                    metrics.error("handler")
                    self._record_error(exc)
