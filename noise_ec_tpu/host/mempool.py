"""Shard-reassembly mempool.

The reference keeps per-object reassembly state in a ``sync.Map`` keyed by
the hex file signature (main.go:49, 55-71). Its pool logic has four
documented defects (SURVEY.md §3.2 quirks 1-4): decode fires on the
(k+1)-th arrival, the triggering share is dropped, duplicate share numbers
inflate the pool, and Load/Delete/Store is racy. This pool fixes all four
**by construction** — the observable contract (wire format, geometry read
from each arriving message, signature-keyed pools) is unchanged, and each
divergence is called out at the relevant line.
"""

from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Optional

from noise_ec_tpu.codec.fec import Share
from noise_ec_tpu.obs.registry import default_registry

__all__ = [
    "ShardPool",
    "PoolEntry",
    "PoolTooLargeError",
    "GeometryMismatchError",
    "PoolLimitError",
]


class PoolLimitError(ValueError):
    """The pool's global resource budget (pool count or pinned bytes) is
    exhausted; the arriving share is rejected. Forged first-arrival shards
    could otherwise pin unbounded memory for a full TTL — the reference has
    no cap at all (``sync.Map``, main.go:49)."""


class PoolTooLargeError(RuntimeError):
    """More distinct shares than the geometry's total — the reference's
    CASE D error ("mempool larger than the maximum amount of shards",
    main.go:100-102). With geometry pinned per pool entry and share numbers
    range-checked upstream this is unreachable; it survives as a defensive
    invariant."""


class GeometryMismatchError(ValueError):
    """A share arrived advertising a different (k, n) than the geometry
    pinned when this pool was opened. The reference trusts every arriving
    message's geometry (main.go:65,72-73), which lets a single forged
    message evict or misjudge a legitimate pool; we pin instead and drop
    the disagreeing share."""


@dataclass
class PoolEntry:
    """Reassembly state for one object (one file signature).

    Geometry (k, n) and the share length are pinned by the first accepted
    share; later shares must agree or are rejected. Pinning means a forged
    message can no longer destroy an in-progress reassembly — though a
    forged share that arrives *first* can still open a poisoned pool
    (shares are not individually authenticated, in the reference either —
    only the whole message is signed). The TTL bounds that damage."""

    shares: dict[int, Share] = field(default_factory=dict)  # number -> share
    k: int = 0
    n: int = 0
    share_len: int = -1
    created_at: float = field(default_factory=time.monotonic)

    def distinct(self) -> int:
        return len(self.shares)


class ShardPool:
    """Thread-safe reassembly pool.

    Divergences from the reference, all deliberate (SURVEY.md §7.4
    "faithfulness vs correctness"):

    - one lock guards every pool transition, replacing the non-atomic
      Load/Delete/Store on ``sync.Map`` (quirk 4, main.go:64-71);
    - shares are dict-keyed by share number, so duplicate delivery is
      idempotent (quirk 3);
    - the arriving share is always recorded before any decode decision, so
      decode fires on the k-th *distinct* share, not the (k+1)-th arrival,
      and the triggering share participates (quirks 1-2, main.go:65-72).
    """

    DEFAULT_TTL_SECONDS = 600.0
    DEFAULT_MAX_POOLS = 65536
    DEFAULT_MAX_TOTAL_BYTES = 1 << 30  # 1 GiB of pinned share data

    # Live pools for the aggregate occupancy gauges (same shape as the
    # dispatcher queue-depth gauge: callback gauges over a WeakSet, so a
    # dropped plugin's pool cannot pin itself through the registry).
    _instances: "weakref.WeakSet[ShardPool]" = weakref.WeakSet()
    _gauges_registered = False
    _eviction_counters: dict = {}

    def __init__(
        self,
        ttl_seconds: Optional[float] = DEFAULT_TTL_SECONDS,
        max_pools: int = DEFAULT_MAX_POOLS,
        max_total_bytes: int = DEFAULT_MAX_TOTAL_BYTES,
    ):
        self._lock = threading.Lock()
        self._pools: dict[str, PoolEntry] = {}
        self._ttl = ttl_seconds
        self._max_pools = max_pools
        self._max_total_bytes = max_total_bytes
        self._total_bytes = 0
        cls = type(self)
        cls._instances.add(self)
        # Re-registered on every construction (idempotent — the closures
        # read the CLASS WeakSet): the test-isolation registry reset
        # drops callback children, and a once-guard would leave the
        # gauges dead for the rest of the process.
        reg = default_registry()
        reg.gauge("noise_ec_mempool_pools").set_callback(
            lambda: sum(len(p) for p in list(ShardPool._instances))
        )
        reg.gauge("noise_ec_mempool_pinned_bytes").set_callback(
            lambda: sum(
                p.pinned_bytes for p in list(ShardPool._instances)
            )
        )
        if not ShardPool._gauges_registered:
            ShardPool._gauges_registered = True
            fam = reg.counter("noise_ec_mempool_evictions_total")
            ShardPool._eviction_counters = {
                reason: fam.labels(reason=reason)
                for reason in ("ttl", "explicit", "overflow")
            }

    def add(
        self, key: str, share: Share, k: int, n: int
    ) -> tuple[list[Share], int, bool]:
        """Record ``share`` under ``key``; returns (snapshot, distinct count,
        was_new).

        The first accepted share pins (k, n) and the share length for the
        pool; later shares that disagree are rejected
        (:class:`GeometryMismatchError` / ValueError) without touching the
        pooled shares — mixed lengths can never decode, and trusting each
        message's geometry would let one forged shard evict a legitimate
        pool. ``was_new`` is False for duplicate share numbers (the
        duplicate is ignored), letting the caller skip re-decoding on
        replays. The snapshot is ordered by share number and safe to hand
        to a decoder without further locking."""
        with self._lock:
            self._expire_locked()
            entry = self._pools.get(key)
            if entry is None:
                if len(self._pools) >= self._max_pools:
                    raise PoolLimitError(
                        f"pool count limit {self._max_pools} reached"
                    )
                entry = self._pools[key] = PoolEntry(
                    k=k, n=n, share_len=len(share.data)
                )
            elif (k, n) != (entry.k, entry.n):
                raise GeometryMismatchError(
                    f"share advertises geometry ({k}, {n}) but pool "
                    f"{key[:16]}… is pinned to ({entry.k}, {entry.n})"
                )
            was_new = share.number not in entry.shares
            if was_new:
                if len(share.data) != entry.share_len:
                    raise ValueError(
                        f"share #{share.number} length {len(share.data)} "
                        f"!= pooled share length {entry.share_len}"
                    )
                if self._total_bytes + len(share.data) > self._max_total_bytes:
                    if not entry.shares:  # don't keep an empty pool around
                        del self._pools[key]
                    raise PoolLimitError(
                        f"pinned-bytes limit {self._max_total_bytes} reached"
                    )
                entry.shares[share.number] = share
                self._total_bytes += len(share.data)
            if entry.distinct() > entry.n:
                self._drop_locked(key, reason="overflow")
                raise PoolTooLargeError(
                    f"mempool for {key[:16]}… holds {entry.distinct()} distinct "
                    f"shares, more than total_shards={entry.n}"
                )
            snapshot = [entry.shares[i] for i in sorted(entry.shares)]
            return snapshot, len(snapshot), was_new

    def _drop_locked(self, key: str, reason: str = "explicit") -> None:
        entry = self._pools.pop(key, None)
        if entry is not None:
            # every pooled share was length-checked against share_len
            self._total_bytes -= entry.share_len * len(entry.shares)
            counter = ShardPool._eviction_counters.get(reason)
            if counter is not None:
                counter.add(1)

    def evict(self, key: str) -> None:
        with self._lock:
            self._drop_locked(key)

    @property
    def pinned_bytes(self) -> int:
        with self._lock:
            return self._total_bytes

    def get(self, key: str) -> Optional[PoolEntry]:
        with self._lock:
            return self._pools.get(key)

    def snapshot(self, key: str) -> tuple[list[Share], int]:
        """(ordered share list copy, distinct count) under the pool lock —
        safe to hand to a decoder while other threads keep adding
        (iterating a live ``entry.shares`` outside the lock races with
        ``add``)."""
        with self._lock:
            entry = self._pools.get(key)
            if entry is None:
                return [], 0
            shares = [entry.shares[i] for i in sorted(entry.shares)]
            return shares, len(shares)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pools)

    def _expire_locked(self) -> None:
        """Drop pools older than the TTL. The reference keeps partial pools
        forever (in-memory ``sync.Map``, no expiry — SURVEY.md §5
        checkpoint/resume row); a TTL bounds memory under shard loss.

        Pools are only ever inserted with ``created_at = now`` and dicts
        preserve insertion order, so the stale pools are exactly a prefix of
        iteration order: this scans stale entries plus one, not all 65k
        pools per arriving shard (round-1 ADVICE finding 4)."""
        if self._ttl is None:
            return
        cutoff = time.monotonic() - self._ttl
        while self._pools:
            key = next(iter(self._pools))
            if self._pools[key].created_at >= cutoff:
                break
            self._drop_locked(key, reason="ttl")
