"""CLI / REPL driver — the reference's L5 (main.go:116-200).

Run one node per process:

    python -m noise_ec_tpu.host.cli -port 3001
    python -m noise_ec_tpu.host.cli -port 3002 -peers tcp://localhost:3001

Each stdin line is erasure-sharded, signed, and broadcast to all peers;
peers reassemble, verify, and log the completed message. A line of the
form ``/send PATH`` streams the FILE at PATH instead (chunked
erasure-coded broadcast — ``ShardPlugin.stream_and_broadcast``), which is
how objects beyond one codeword travel; receivers log the object and,
with ``-recv-dir DIR``, save it under a content-hash name. Flags mirror
the reference (`-port -host -protocol -peers`, main.go:121-124); the
codec backend, trace, recv-dir, and chunk-size flags are new.
"""

from __future__ import annotations

import argparse
import logging
import sys

from noise_ec_tpu.host.crypto import KeyPair, PeerID
from noise_ec_tpu.host.plugin import ShardPlugin
from noise_ec_tpu.host.transport import TCPNetwork
from noise_ec_tpu.obs.health import default_slo
from noise_ec_tpu.obs.profiling import device_trace, kernel_counters
from noise_ec_tpu.obs.registry import set_build_info
from noise_ec_tpu.obs.server import PeriodicReporter, StatsServer
from noise_ec_tpu.obs.trace import default_tracer
from noise_ec_tpu.utils.logging import setup_logging

log = logging.getLogger("noise_ec_tpu.host.cli")


def _kernel_label(backend: str) -> str:
    """The kernel tier actually serving this node, for the
    noise_ec_build_info deployment-identity gauge."""
    if backend != "device":
        return "numpy"
    try:
        import jax

        return "pallas" if jax.default_backend() == "tpu" else "xla"
    except Exception:  # noqa: BLE001 — identity gauge must not kill startup
        return "unknown"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="noise-ec-tpu-node",
        description="erasure-coded broadcast node (TPU codec backend)",
    )
    # single-dash long flags, like Go's flag package (main.go:121-124)
    p.add_argument("-port", type=int, default=3000, help="port to listen on")
    p.add_argument("-host", default="localhost", help="host to listen on")
    p.add_argument(
        "-protocol", default="tcp",
        help="protocol to use: tcp or kcp (reliable UDP), main.go:123",
    )
    p.add_argument("-peers", default="", help="comma-separated peer addresses")
    p.add_argument(
        "-recv-shards",
        type=int,
        default=1,
        metavar="N",
        help="SO_REUSEPORT acceptor shards on the listen port (one "
        "event-loop thread each, kernel-balanced, all feeding the one "
        "shared dispatcher — docs/design.md §15); tcp only, default 1",
    )
    p.add_argument(
        "-backend",
        default="device",
        choices=["device", "numpy"],
        help="codec backend: device (TPU/JAX) or numpy (host)",
    )
    p.add_argument(
        "-trace",
        default="",
        metavar="LOGDIR",
        help="capture a JAX/XLA profiler trace of the session into LOGDIR "
        "(view with tensorboard's profile plugin)",
    )
    p.add_argument(
        "-xprof-dir",
        default="",
        metavar="DIR",
        help="enable on-demand xprof capture: GET /xprof?seconds=N on the "
        "stats endpoint records a JAX/XLA profiler trace of the next N "
        "seconds into DIR (a live decode burst, without -trace's "
        "whole-session capture); requires -metrics-port",
    )
    p.add_argument(
        "-profile",
        action="store_true",
        help="start the always-on sampling profiler (~50 Hz folded Python "
        "stacks, obs/sampler.py) at startup; GET /profile?seconds=N on "
        "the stats endpoint serves the last N seconds as flamegraph-ready "
        "collapsed text (without this flag the sampler starts lazily on "
        "the first /profile request)",
    )
    p.add_argument(
        "-compile-cache-dir",
        default="",
        metavar="DIR",
        help="persistent JAX compilation cache under DIR "
        "(docs/design.md §14): compiled device programs — including the "
        "panel tier's K-grid sub-launch set and the batch ladder — are "
        "serialized to disk and replayed on restart, so geometry churn "
        "stops paying the cold-compile tax per process. Also arms the "
        "ladder pre-warm hook (the default geometry's power-of-two batch "
        "programs compile at startup, off the serving path). Empty "
        "disables",
    )
    p.add_argument(
        "-recv-dir",
        default="",
        metavar="DIR",
        help="save received messages/objects into DIR (file name = 16-hex "
        "BLAKE2b content hash of the bytes; logged on save)",
    )
    p.add_argument(
        "-chunk-bytes",
        type=int,
        default=4 << 20,
        help="chunk payload size for /send file streaming (bytes)",
    )
    p.add_argument(
        "-store-dir",
        default="",
        metavar="DIR",
        help="persist verified objects as erasure-coded stripes under DIR "
        "(the stripe store, docs/store.md); enables degraded reads and "
        "background repair. Empty disables unless -scrub-interval is set "
        "(then the store runs in memory only)",
    )
    p.add_argument(
        "-scrub-interval",
        type=float,
        default=0.0,
        metavar="SECONDS",
        help="walk the stripe store every SECONDS verifying parity and "
        "queueing repairs (0 disables the scrubber; repairs triggered by "
        "wire absorbs still run whenever the store is enabled)",
    )
    p.add_argument(
        "-announce-interval",
        type=float,
        default=0.0,
        metavar="SECONDS",
        help="broadcast one shard of each recently stored stripe every "
        "SECONDS (anti-entropy announce, docs/resilience.md): peers that "
        "silently lost an object — e.g. through a partition — discover "
        "and NACK-repair it. 0 disables; requires the stripe store "
        "(enabled automatically when set)",
    )
    p.add_argument(
        "-object-port",
        type=int,
        default=-1,
        metavar="PORT",
        help="serve the erasure-coded object service API "
        "(PUT/GET/range/DELETE/LIST under /objects, docs/object-service.md) "
        "on 127.0.0.1:PORT, alongside /metrics and /healthz on the same "
        "server. 0 binds an ephemeral port (logged); negative disables "
        "(default). Enables the stripe store automatically",
    )
    p.add_argument(
        "-object-cache-mb",
        type=int,
        default=256,
        metavar="MB",
        help="decoded-object cache ceiling for the GET hot path "
        "(docs/object-service.md Read path): hot reads serve from host "
        "RAM, warm addresses are advertised to peers on the announce "
        "loop, and the ceiling shrinks under the device HBM watermark. "
        "0 disables the cache tier",
    )
    p.add_argument(
        "-tenants",
        default="",
        metavar="FILE",
        help="tenant config JSON for the object service (namespaces, "
        "byte/object quotas, per-tenant geometry, replication targets, "
        "hot->archival conversion policies — docs/object-service.md, "
        "docs/lrc.md). Empty = open admission, unlimited quotas",
    )
    p.add_argument(
        "-convert-interval",
        type=float,
        default=0.0,
        metavar="SECONDS",
        help="walk the object manifests every SECONDS converting cold "
        "objects to their tenant's archival tier (policy grammar "
        "'archive=lrc:K/G+R,age=...' — docs/lrc.md). 0 disables; "
        "requires the object service (-object-port)",
    )
    p.add_argument(
        "-chaos-profile",
        default="",
        metavar="PROFILE",
        help="dial every -peers address through an in-process chaos "
        "proxy applying PROFILE (e.g. "
        "'drop=0.05,corrupt=0.01,partition@2:2:a2b,reset@5' — "
        "docs/resilience.md for the grammar). Fault injection for the "
        "REAL transport; empty disables",
    )
    p.add_argument(
        "-chaos-seed",
        type=int,
        default=0,
        metavar="SEED",
        help="seed for -chaos-profile fault decisions (same seed + "
        "profile + frame order reproduces the run)",
    )
    p.add_argument(
        "-fleet-profile",
        default="",
        metavar="PROFILE",
        help="run the in-process fleet lab instead of the REPL: spin up "
        "PROFILE's peers (e.g. 'peers=200,fanout=6,msgs=500,chat=0.9,"
        "object=0.1,chaos=lossy,churn@2:4:0.5' — docs/fleet.md for the "
        "grammar), drive the traffic mix, score delivery/shed/lost, and "
        "exit. -chaos-seed seeds the run; with -metrics-port the live "
        "status serves on GET /fleet and inside /healthz details",
    )
    p.add_argument(
        "-fleet-size",
        type=int,
        default=0,
        metavar="N",
        help="override the peers= count of -fleet-profile (0 keeps the "
        "profile's value)",
    )
    p.add_argument(
        "-fleet-report",
        default="",
        metavar="PATH",
        help="write the scored fleet report JSON to PATH and the "
        "fleet-wide merged Perfetto trace to PATH.trace.json "
        "(requires -fleet-profile)",
    )
    p.add_argument(
        "-metrics-port",
        type=int,
        default=-1,
        metavar="PORT",
        help="serve Prometheus exposition on 127.0.0.1:PORT (/metrics; "
        "also /spans for the trace ring buffer). 0 binds an ephemeral "
        "port (logged); negative disables (default)",
    )
    p.add_argument(
        "-stats-interval",
        type=float,
        default=0.0,
        metavar="SECONDS",
        help="log a stats snapshot every SECONDS while running "
        "(0 disables; stats always log once at shutdown)",
    )
    p.add_argument(
        "-trace-peers",
        default="",
        metavar="URLS",
        help="comma-separated peer metrics endpoints "
        "(http://host:port) whose /spans this node pulls and merges "
        "into distributed traces (docs/observability.md)",
    )
    p.add_argument(
        "-collect-traces",
        default="",
        metavar="PATH",
        help="write the merged local+peer spans as Chrome "
        "trace-event JSON to PATH at shutdown (open in Perfetto or "
        "chrome://tracing); implies periodic collection while running",
    )
    p.add_argument(
        "-collect-interval",
        type=float,
        default=10.0,
        metavar="SECONDS",
        help="poll interval for -trace-peers span collection "
        "(default 10)",
    )
    p.add_argument(
        "-federate",
        default="",
        metavar="URLS",
        help="comma-separated peer metrics endpoints "
        "(http://host:port) whose /metrics this node scrapes and "
        "merges; the fleet-wide view serves on GET /fleet/metrics "
        "(requires -metrics-port; docs/observability.md)",
    )
    p.add_argument(
        "-federate-interval",
        type=float,
        default=10.0,
        metavar="SECONDS",
        help="background scrape interval for -federate (default 10)",
    )
    p.add_argument(
        "-topology",
        default="",
        metavar="SPEC",
        help="failure-domain topology for placement-ring shard "
        "delivery: 'domain=rack1:peerA,peerB;domain=rack2:peerC' "
        "(docs/placement.md). Every node in the deployment must be "
        "given the SAME spec — the ring is deterministic, so "
        "identical topologies compute identical shard->peer maps. "
        "Unset = full broadcast exactly as before",
    )
    p.add_argument(
        "-incident-dir",
        default="",
        metavar="PATH",
        help="run the flight recorder: keep a byte-bounded ring of "
        "per-second metric deltas and write an incident bundle "
        "(JSON timeline + Perfetto trace) to PATH when the /healthz "
        "SLO flips to degraded, or on GET /incident "
        "(docs/observability.md)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    setup_logging()  # stderr-forced, like flag.Set("logtostderr") main.go:118
    args = build_parser().parse_args(argv)

    compile_cache_armed = False
    if args.compile_cache_dir and args.backend == "device":
        # Before the first jit: the cache decision is made once per
        # process, so arming it after a compile would strand that
        # program outside the cache.
        from noise_ec_tpu.ops.dispatch import enable_compile_cache

        compile_cache_armed = enable_compile_cache(args.compile_cache_dir)

    keys = KeyPair.random()  # fresh identity per run, main.go:132
    log.info("private key: %s", keys.private_key_hex())
    log.info("public key: %s", keys.public_key_hex())

    net = TCPNetwork(
        host=args.host, port=args.port, keys=keys, protocol=args.protocol,
        recv_shards=args.recv_shards,
    )

    def on_message(message: bytes, sender: PeerID) -> None:
        # The reference logs the full hex dump (main.go:92); for streamed
        # objects that would be megabytes of log — log a prefix + length,
        # and save the body when -recv-dir is set.
        if len(message) <= 256:
            log.info("message from %s: %s", sender.address, message.hex())
        else:
            log.info(
                "message from %s: %s… (%d bytes)",
                sender.address, message[:32].hex(), len(message),
            )
        if args.recv_dir:
            import hashlib
            import os

            # Never raise out of on_message: the plugin has already marked
            # the object completed, so an exception here would lose the
            # bytes silently (the transport only records it).
            try:
                os.makedirs(args.recv_dir, exist_ok=True)
                name = hashlib.blake2b(message, digest_size=8).hexdigest()
                path = os.path.join(args.recv_dir, name)
                # Atomic: the name claims to be the content hash, so a
                # torn write must never leave a partial file under it.
                tmp = path + ".part"
                with open(tmp, "wb") as f:
                    f.write(message)
                os.replace(tmp, path)
                log.info("saved %d bytes to %s", len(message), path)
            except OSError as exc:
                log.error("could not save received object: %s", exc)

    store = scrubber = engine = None
    if (
        args.store_dir or args.scrub_interval > 0
        or args.announce_interval > 0 or args.object_port >= 0
    ):
        from noise_ec_tpu.store import RepairEngine, Scrubber, StripeStore

        store = StripeStore(
            args.store_dir or None, backend=args.backend
        )
        engine = RepairEngine(
            store, network=net,
            announce_interval_seconds=args.announce_interval,
        )
        engine.start()
        if args.scrub_interval > 0:
            scrubber = Scrubber(
                store, engine, interval_seconds=args.scrub_interval
            )
            scrubber.start()
        log.info(
            "stripe store enabled (%s, %d stripes loaded, scrub %s)",
            args.store_dir or "in-memory",
            len(store),
            f"every {args.scrub_interval}s" if args.scrub_interval > 0
            else "disabled",
        )

    plugin = ShardPlugin(
        backend=args.backend, on_message=on_message, store=store
    )
    # Compile the default geometry before traffic arrives; with the
    # persistent cache armed, also pre-warm the batch ladder so every
    # expected program lands in (or replays from) the on-disk cache.
    plugin.prewarm(ladder=8 if compile_cache_armed else 0)
    net.add_plugin(plugin)

    rebalancer = None
    if args.topology:
        from noise_ec_tpu.placement import (
            PlacementRing, Rebalancer, TargetedDelivery, Topology,
        )
        from noise_ec_tpu.placement.rebalance import register_domain_gauges

        topology = Topology.parse(args.topology)
        # Seed pinned to 0: every node given the same -topology MUST
        # compute the same shard->peer map, or targeted delivery and
        # gather disagree about owners.
        ring = PlacementRing(topology, seed=0)
        plugin.placement = TargetedDelivery(
            ring, self_token=net.id.address
        )
        log.info(
            "placement ring active: %d failure domains, %d peers "
            "(docs/placement.md)",
            len(topology.names()), len(topology.all_peers()),
        )
        if store is not None:
            def _rebalance_send(token, msgs, _net=net):
                pk = _net.placement_directory().get(token)
                return pk is not None and _net.send_many_to(pk, msgs)

            rebalancer = Rebalancer(
                store, ring,
                self_token=net.id.address,
                send=_rebalance_send,
                self_public_key=keys.public_key,
                repair=engine,
            ).start()
            register_domain_gauges(
                lambda d, _rb=rebalancer: float(
                    _rb.census()
                    if ring.topology.domain_of(net.id.address) == d
                    else 0
                ),
                topology.names(),
            )
            if net.supervisor is not None:
                def _on_membership(address, up, _rb=rebalancer):
                    (_rb.note_up if up else _rb.note_down)(address)

                net.supervisor.add_membership_listener(_on_membership)

    net.listen()  # background accept loop (go net.Listen(), main.go:169)
    log.info("listening for peers on %s", net.id.address)

    # Node identity for distributed tracing: every span dump this node
    # serves is stamped with the transport address + pubkey prefix, so a
    # collector can merge it with other nodes' dumps unambiguously.
    default_tracer().set_node(net.id.address, keys.public_key)
    set_build_info(backend=args.backend, kernel=_kernel_label(args.backend))

    def stats_snapshot() -> dict:
        stats = plugin.counters.snapshot()
        stats.update(kernel_counters.snapshot())
        return stats

    sampler = None
    if args.profile:
        from noise_ec_tpu.obs.sampler import default_sampler

        sampler = default_sampler()
        log.info("sampling profiler running (~%.0f Hz)", sampler.hz)

    stats_server = reporter = None
    if args.metrics_port >= 0:
        stats_server = StatsServer(
            port=args.metrics_port,
            # Kernel call/byte series are registry families now
            # (noise_ec_kernel_{calls,bytes}_total{entry}); only the
            # plugin's state-machine bag still rides the prefix path.
            extra_counters={
                "noise_ec_plugin": plugin.counters,
            },
            sampler=sampler,
            xprof_dir=args.xprof_dir or None,
            # /healthz answers 503 with the verdict JSON once the
            # receive path burns the rolling SLO window (obs/health.py)
            # — orchestrators can restart/deweight on it.
            slo=default_slo(),
            # The peer supervisor's circuit-breaker summary rides the
            # /healthz JSON body (503, or 200 with ?verbose=1).
            health_details=(
                net.supervisor.health_summary
                if net.supervisor is not None else None
            ),
        )
        log.info("metrics endpoint on %s/metrics", stats_server.url)
        if args.xprof_dir:
            log.info("xprof capture armed: GET %s/xprof?seconds=N -> %s",
                     stats_server.url, args.xprof_dir)
    if args.stats_interval > 0:
        reporter = PeriodicReporter(args.stats_interval, stats_snapshot, log)

    federator = None
    federate_peers = [u for u in args.federate.split(",") if u]
    if federate_peers and stats_server is not None:
        from noise_ec_tpu.obs.federate import MetricsFederator

        federator = MetricsFederator(peers=federate_peers)
        federator.attach(stats_server)
        federator.start(interval=max(args.federate_interval, 1.0))
        log.info(
            "federating metrics from %d peer(s) on %s/fleet/metrics",
            len(federate_peers), stats_server.url,
        )

    recorder = None
    if args.incident_dir:
        from noise_ec_tpu.obs.recorder import FlightRecorder

        recorder = FlightRecorder(
            slo=default_slo(), incident_dir=args.incident_dir
        )
        recorder.start()
        if stats_server is not None:
            recorder.attach(stats_server)
        log.info(
            "flight recorder armed: incident bundles -> %s on SLO "
            "flip%s", args.incident_dir,
            " or GET /incident" if stats_server is not None else "",
        )

    object_server = converter = None
    if args.object_port >= 0:
        from noise_ec_tpu.service import ObjectAPI, ObjectStore, TenantRegistry

        tenants = (
            TenantRegistry.from_file(args.tenants) if args.tenants
            else TenantRegistry()
        )
        cache = None
        if args.object_cache_mb > 0:
            from noise_ec_tpu.service import DecodedObjectCache

            cache = DecodedObjectCache(
                max_bytes=args.object_cache_mb << 20
            )
        objects = ObjectStore(
            store, plugin, net,
            tenants=tenants, engine=engine, slo=default_slo(),
            cache=cache,
        )
        # The object API rides a StatsServer, so PORT serves /objects
        # alongside /metrics and /healthz (the route table,
        # obs/server.py) — one scrape-and-serve surface per node.
        object_server = StatsServer(
            port=args.object_port,
            extra_counters={"noise_ec_plugin": plugin.counters},
            slo=default_slo(),
            health_details=(
                net.supervisor.health_summary
                if net.supervisor is not None else None
            ),
        )
        ObjectAPI(objects).mount(object_server)
        # Warm-peer routing: advertise this node's warm addresses on the
        # announce loop so peers can serve hot reads from each other's
        # decoded caches before touching shards.
        objects.enable_peer_routing(object_server.url)
        log.info("object service on %s/objects (%d tenants configured)",
                 object_server.url, len(tenants.names()))
        if args.convert_interval > 0:
            from noise_ec_tpu.store import ConversionEngine

            converter = ConversionEngine(
                store, tenants, cache=cache, repair=engine,
                interval_seconds=args.convert_interval,
            )
            converter.start()
            log.info(
                "hot->archival conversion every %gs (per-tenant "
                "'policy' drives the tier — docs/lrc.md)",
                args.convert_interval,
            )

    collector = None
    trace_peers = [u for u in args.trace_peers.split(",") if u]
    if trace_peers or args.collect_traces:
        from noise_ec_tpu.obs.collector import TraceCollector

        # handshake_rtts is passed as the bound method: hints re-read
        # every poll, so peers dialed later still tighten clock sync.
        collector = TraceCollector(trace_peers, rtt_hints=net.handshake_rtts)
        collector.start(interval=max(args.collect_interval, 1.0))
        log.info(
            "collecting distributed traces from %d peer endpoint(s)",
            len(trace_peers),
        )

    peers = [a for a in args.peers.split(",") if a]
    chaos_proxies = []
    if peers and args.chaos_profile:
        from noise_ec_tpu.resilience.chaos import ChaosProfile, ChaosProxy

        profile = ChaosProfile.parse(args.chaos_profile)
        proxied = []
        for addr in peers:
            host, port = TCPNetwork._split(addr)
            proxy = ChaosProxy(
                host, port, profile=profile, seed=args.chaos_seed
            ).start()
            chaos_proxies.append(proxy)
            proxied.append(proxy.address)
            log.info("chaos proxy %s -> %s (seed %d)",
                     proxy.address, addr, args.chaos_seed)
        peers = proxied
    if peers:
        net.bootstrap(peers)

    fleet_lab = None
    try:
        if args.fleet_profile:
            # Fleet-lab mode (docs/fleet.md): drive the declarative
            # traffic mix across an in-process fleet, score it, and
            # exit — no REPL. The TCP node above keeps serving its
            # endpoints while the lab runs, so /fleet and /healthz show
            # live status.
            from noise_ec_tpu.fleet import FleetLab, FleetProfile

            fleet_profile = FleetProfile.parse(args.fleet_profile)
            fleet_lab = FleetLab(
                fleet_profile,
                size=args.fleet_size or None,
                seed=args.chaos_seed,
            )
            fleet_lab.start()
            if stats_server is not None:
                fleet_lab.attach(stats_server)
                log.info("fleet status on %s/fleet", stats_server.url)
            with device_trace(args.trace):
                report = fleet_lab.run()
            log.info(
                "fleet run: %d peers, %d sent, delivery %.4f "
                "(%d delivered / %d lost / %d churned), %d shed",
                report["peers"], report["sent"],
                report["delivery"]["rate"], report["delivery"]["delivered"],
                report["delivery"]["lost"], report["delivery"]["churned"],
                report["shed"]["total"],
            )
            if args.fleet_report:
                fleet_lab.write_report(args.fleet_report)
                doc = fleet_lab.write_trace(args.fleet_report + ".trace.json")
                log.info(
                    "fleet report written to %s (+%d-span Perfetto trace)",
                    args.fleet_report,
                    len(doc.get("traceEvents", [])),
                )
            return 0
        with device_trace(args.trace):
            for line in sys.stdin:  # blocking REPL, main.go:175-198
                stripped = line.rstrip("\n")
                if not stripped:
                    continue  # skip blank lines, main.go:179-181
                if stripped.startswith("/send "):
                    path = stripped[len("/send "):].strip()
                    try:
                        # O(chunk) sender memory: the plugin hashes and
                        # reads the file in passes, never loading it whole.
                        chunks = plugin.stream_and_broadcast_file(
                            net, path, chunk_bytes=args.chunk_bytes
                        )
                    except (OSError, ValueError) as exc:
                        log.error("stream of %s failed: %s", path, exc)
                        continue
                    log.info("streamed %s as %d chunks", path, chunks)
                    continue
                input_bytes = stripped.encode()
                log.info("broadcasting message: %s", input_bytes.hex())
                try:
                    plugin.shard_and_broadcast(net, input_bytes)
                except ValueError as exc:
                    # e.g. accumulated dynamic geometry exceeding the field
                    # order (main.go:185-191 reproduced) — the node must
                    # outlive a rejected line.
                    log.error("broadcast failed: %s", exc)
    except KeyboardInterrupt:
        pass
    finally:
        if fleet_lab is not None:
            fleet_lab.close()
        if converter is not None:
            converter.close()
        if rebalancer is not None:
            rebalancer.close()
        if scrubber is not None:
            scrubber.close()
        if engine is not None:
            engine.close()
        if reporter is not None:
            reporter.close()
        if collector is not None:
            collector.close()
            try:
                collector.poll()  # final sweep before the transport dies
                if args.collect_traces:
                    from noise_ec_tpu.obs.perfetto import write_chrome_trace

                    spans = collector.merged_spans()
                    doc = write_chrome_trace(args.collect_traces, spans)
                    log.info(
                        "wrote %d spans from %d node(s) to %s "
                        "(open in Perfetto / chrome://tracing)",
                        len(spans), len(doc["otherData"]["nodes"]),
                        args.collect_traces,
                    )
            except Exception as exc:  # noqa: BLE001 — telemetry teardown
                log.error("trace export failed: %s", exc)
        if recorder is not None:
            recorder.close()
        if federator is not None:
            federator.close()
        if object_server is not None:
            object_server.close()
        if stats_server is not None:
            stats_server.close()
        if sampler is not None:
            sampler.close()
        net.close()
        for proxy in chaos_proxies:
            proxy.close()
            log.info("chaos stats: %s", proxy.stats())
        stats = stats_snapshot()
        if stats:
            log.info("session stats: %s", stats)
    return 0


if __name__ == "__main__":
    sys.exit(main())
