"""CLI / REPL driver — the reference's L5 (main.go:116-200).

Run one node per process:

    python -m noise_ec_tpu.host.cli -port 3001
    python -m noise_ec_tpu.host.cli -port 3002 -peers tcp://localhost:3001

Each stdin line is erasure-sharded, signed, and broadcast to all peers;
peers reassemble, verify, and log the completed message. Flags mirror the
reference (`-port -host -protocol -peers`, main.go:121-124); the codec
backend flag is new (device = TPU/JAX kernels, numpy = host-only).
"""

from __future__ import annotations

import argparse
import logging
import sys

from noise_ec_tpu.host.crypto import KeyPair, PeerID
from noise_ec_tpu.host.plugin import ShardPlugin
from noise_ec_tpu.host.transport import TCPNetwork
from noise_ec_tpu.utils.logging import setup_logging
from noise_ec_tpu.utils.profiling import device_trace, kernel_counters

log = logging.getLogger("noise_ec_tpu.host.cli")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="noise-ec-tpu-node",
        description="erasure-coded broadcast node (TPU codec backend)",
    )
    # single-dash long flags, like Go's flag package (main.go:121-124)
    p.add_argument("-port", type=int, default=3000, help="port to listen on")
    p.add_argument("-host", default="localhost", help="host to listen on")
    p.add_argument(
        "-protocol", default="tcp",
        help="protocol to use: tcp or kcp (reliable UDP), main.go:123",
    )
    p.add_argument("-peers", default="", help="comma-separated peer addresses")
    p.add_argument(
        "-backend",
        default="device",
        choices=["device", "numpy"],
        help="codec backend: device (TPU/JAX) or numpy (host)",
    )
    p.add_argument(
        "-trace",
        default="",
        metavar="LOGDIR",
        help="capture a JAX/XLA profiler trace of the session into LOGDIR "
        "(view with tensorboard's profile plugin)",
    )
    return p


def main(argv: list[str] | None = None) -> int:
    setup_logging()  # stderr-forced, like flag.Set("logtostderr") main.go:118
    args = build_parser().parse_args(argv)

    keys = KeyPair.random()  # fresh identity per run, main.go:132
    log.info("private key: %s", keys.private_key_hex())
    log.info("public key: %s", keys.public_key_hex())

    net = TCPNetwork(
        host=args.host, port=args.port, keys=keys, protocol=args.protocol
    )

    def on_message(message: bytes, sender: PeerID) -> None:
        log.info("message from %s: %s", sender.address, message.hex())

    plugin = ShardPlugin(backend=args.backend, on_message=on_message)
    plugin.prewarm()  # compile the default geometry before traffic arrives
    net.add_plugin(plugin)

    net.listen()  # background accept loop (go net.Listen(), main.go:169)
    log.info("listening for peers on %s", net.id.address)
    peers = [a for a in args.peers.split(",") if a]
    if peers:
        net.bootstrap(peers)

    try:
        with device_trace(args.trace):
            for line in sys.stdin:  # blocking REPL, main.go:175-198
                input_bytes = line.rstrip("\n").encode()
                if not input_bytes:
                    continue  # skip blank lines, main.go:179-181
                log.info("broadcasting message: %s", input_bytes.hex())
                plugin.shard_and_broadcast(net, input_bytes)
    except KeyboardInterrupt:
        pass
    finally:
        net.close()
        stats = plugin.counters.snapshot()
        stats.update(kernel_counters.snapshot())
        if stats:
            log.info("session stats: %s", stats)
    return 0


if __name__ == "__main__":
    sys.exit(main())
