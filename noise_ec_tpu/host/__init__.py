"""Host-side plugin runtime.

The reference's layers L0-L5 above the codec (SURVEY.md §1): wire format,
signing/identity, shard-reassembly mempool, plugin dispatch, transports,
and the CLI REPL. All host code — the TPU work lives in ``ops``/``parallel``;
this package is the boundary that feeds it.
"""

from noise_ec_tpu.host.wire import Shard, WireError
from noise_ec_tpu.host.crypto import (
    Blake2bPolicy,
    Ed25519Policy,
    KeyPair,
    PeerID,
    serialize_message,
    verify,
)
from noise_ec_tpu.host.plugin import ShardPlugin, largest_prime_factor
from noise_ec_tpu.host.mempool import ShardPool

__all__ = [
    "Shard",
    "WireError",
    "Blake2bPolicy",
    "Ed25519Policy",
    "KeyPair",
    "PeerID",
    "serialize_message",
    "verify",
    "ShardPlugin",
    "ShardPool",
    "largest_prime_factor",
]
