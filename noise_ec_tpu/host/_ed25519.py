"""Pure-Python Ed25519 (RFC 8032) — the no-dependency fallback backend.

host/crypto.py prefers the ``cryptography`` package (OpenSSL: fast and
constant-time); this module keeps the host layer *functional* when that
wheel is absent (hermetic CI images, minimal containers) so the loopback
harness, transports and the observability round-trip tests still run.

Bit-identical output to the RFC 8032 test vectors (pinned in
tests/test_host_crypto.py). NOT constant-time — Python big-int arithmetic
leaks timing — so crypto.py logs a warning once when this backend is
active; production deployments install ``cryptography``
(requirements-test.txt).

Performance: the generic double-and-add costs ~2.5 ms per scalar
multiplication on a current x86 core (sign ≈ 3 ms, verify ≈ 6 ms). The
wire hot loop (docs/design.md §15) cannot live with that, so three
amortizations sit on top of the same field arithmetic:

- a windowed fixed-base table for ``B`` (built once, lazily): a base
  mult becomes ~32 table adds, which is what every sign and half of
  every verify pays;
- per-public-key power tables behind a bounded LRU
  (:func:`_verify_key`): a node verifies a small stable peer set, so
  the 256 doublings of ``k*A`` are paid once per key, not per frame;
- :func:`verify_batch` — true Ed25519 batch verification: one random
  linear combination ``(Σ zᵢSᵢ)·B == Σ zᵢRᵢ + Σ (zᵢkᵢ)·A`` checked
  with a shared-doubling multi-scalar multiplication, so a cohort of
  frames shares one pass of doublings (and, for the common one-sender
  cohort, ONE table mult of ``A``). A failing batch falls back to
  per-item verification, so the accept set is exactly the per-item
  accept set: one bad signature never poisons its cohort, and the
  only divergence is a 2^-128 false batch accept (standard RLC bound).

Still NOT constant-time either way — production installs
``cryptography``.
"""

from __future__ import annotations

import hashlib
import os
import threading

__all__ = ["public_from_seed", "sign", "verify", "verify_batch"]

_p = 2**255 - 19
_L = 2**252 + 27742317777372353535851937790883648493


def _inv(x: int) -> int:
    return pow(x, _p - 2, _p)


_d = -121665 * _inv(121666) % _p
_I = pow(2, (_p - 1) // 4, _p)
_By = 4 * _inv(5) % _p


def _recover_x(y: int, sign_bit: int) -> int | None:
    if y >= _p:
        return None
    x2 = (y * y - 1) * _inv(_d * y * y + 1) % _p
    x = pow(x2, (_p + 3) // 8, _p)
    if (x * x - x2) % _p:
        x = x * _I % _p
    if (x * x - x2) % _p:
        return None
    if x == 0 and sign_bit:
        return None
    if x & 1 != sign_bit:
        x = _p - x
    return x


# Extended homogeneous coordinates (X, Y, Z, T) with x=X/Z, y=Y/Z, T=XY/Z.
_Bx = _recover_x(_By, 0)
_B = (_Bx, _By, 1, _Bx * _By % _p)
_ZERO = (0, 1, 1, 0)


def _add(P, Q):
    X1, Y1, Z1, T1 = P
    X2, Y2, Z2, T2 = Q
    A = (Y1 - X1) * (Y2 - X2) % _p
    B = (Y1 + X1) * (Y2 + X2) % _p
    C = 2 * T1 * _d * T2 % _p
    D = 2 * Z1 * Z2 % _p
    E, F, G, H = B - A, D - C, D + C, B + A
    return (E * F % _p, G * H % _p, F * G % _p, E * H % _p)


def _mult(P, s: int):
    Q = _ZERO
    while s:
        if s & 1:
            Q = _add(Q, P)
        P = _add(P, P)
        s >>= 1
    return Q


def _compress(P) -> bytes:
    X, Y, Z, _ = P
    zi = _inv(Z)
    x, y = X * zi % _p, Y * zi % _p
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _decompress(data: bytes):
    if len(data) != 32:
        return None
    val = int.from_bytes(data, "little")
    y = val & ((1 << 255) - 1)
    x = _recover_x(y, val >> 255)
    if x is None:
        return None
    return (x, y, 1, x * y % _p)


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    return (a & ((1 << 254) - 8)) | (1 << 254)


def _hash_to_scalar(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little") % _L


def _points_equal(P, Q) -> bool:
    """Projective equality x1/z1 == x2/z2 ∧ y1/z1 == y2/z2 — two cross
    multiplications instead of the two field inversions a compressed
    compare pays."""
    X1, Y1, Z1, _ = P
    X2, Y2, Z2, _ = Q
    return (
        (X1 * Z2 - X2 * Z1) % _p == 0 and (Y1 * Z2 - Y2 * Z1) % _p == 0
    )


# ------------------------------------------------------- table scalar mult
#
# A windowed table for point P holds T[j][v] = (v << (w*j)) * P for every
# w-bit window j and digit v in 1..2^w-1, so P*s is one add per nonzero
# window digit — no doublings at mult time. Build cost is ~one generic
# scalar mult, so a table pays for itself on its second use.

_SCALAR_BITS = 256  # S < L < 2^253, but clamped scalars set bit 254


def _window_table(P, w: int):
    rows = []
    base = P  # (1 << (w * j)) * P
    span = (1 << w) - 1
    for _ in range((_SCALAR_BITS + w - 1) // w):
        row = [None] * (span + 1)
        acc = base
        row[1] = acc
        for v in range(2, span + 1):
            acc = _add(acc, base)
            row[v] = acc
        rows.append(row)
        for _ in range(w):
            base = _add(base, base)
    return rows


def _mult_table(rows, w: int, s: int):
    acc = _ZERO
    mask = (1 << w) - 1
    j = 0
    while s:
        v = s & mask
        if v:
            acc = _add(acc, rows[j][v])
        s >>= w
        j += 1
    return acc


_B_W = 8  # 32 windows; a base mult is <= 32 adds
_B_TABLE = None
_table_lock = threading.Lock()


def _base_table():
    global _B_TABLE
    if _B_TABLE is None:
        with _table_lock:
            if _B_TABLE is None:
                _B_TABLE = _window_table(_B, _B_W)
    return _B_TABLE


def _mult_base(s: int):
    return _mult_table(_base_table(), _B_W, s)


class VerifyKey:
    """Decompressed public key with lazily built, tiered mult tables.

    Tier 0 (first use): generic double-and-add — a key seen once, e.g.
    fleet-scale identity churn, pays nothing extra. Tier 1 (second
    use): ``pows[i] = 2^i * A`` (build ≈ one mult), making ``k*A``
    ~128 adds with zero doublings. Tier 2 (a hot peer,
    ``_W4_AFTER_USES``): a 4-bit window table — one add per nonzero
    window digit, ~63 adds per mult — amortized across the thousands of
    verifies a stable peer sends."""

    __slots__ = ("point", "_pows", "_w4", "_uses")

    _W4_AFTER_USES = 16

    def __init__(self, point):
        self.point = point
        self._pows = None
        self._w4 = None
        self._uses = 0

    def mult(self, s: int):
        self._uses += 1
        if self._w4 is not None:
            return _mult_table(self._w4, 4, s)
        if self._pows is None:
            if self._uses < 2:
                return _mult(self.point, s)
            pows = []
            P = self.point
            for _ in range(_SCALAR_BITS):
                pows.append(P)
                P = _add(P, P)
            self._pows = pows
        if self._uses >= self._W4_AFTER_USES:
            self._w4 = _window_table(self.point, 4)
            return _mult_table(self._w4, 4, s)
        acc = _ZERO
        i = 0
        pows = self._pows
        while s:
            if s & 1:
                acc = _add(acc, pows[i])
            s >>= 1
            i += 1
        return acc


# Parsed-key LRU: a node talks to a bounded peer set; hostile identity
# churn past the cap falls back to table-less keys (correct, slower).
_VERIFY_KEYS: dict[bytes, VerifyKey] = {}
_VERIFY_KEYS_MAX = 128


def _verify_key(public_key: bytes):
    """VerifyKey for ``public_key`` via the LRU, or None if the bytes do
    not decode to a curve point."""
    with _table_lock:
        vk = _VERIFY_KEYS.get(public_key)
    if vk is not None:
        return vk
    A = _decompress(public_key)
    if A is None:
        return None
    vk = VerifyKey(A)
    with _table_lock:
        if len(_VERIFY_KEYS) >= _VERIFY_KEYS_MAX:
            _VERIFY_KEYS.pop(next(iter(_VERIFY_KEYS)))
        _VERIFY_KEYS[public_key] = vk
    return vk


def _msm(pairs):
    """Σ sᵢ·Pᵢ by interleaved double-and-add: ONE shared run of
    doublings for the whole set (the batch-verify amortization)."""
    if not pairs:
        return _ZERO
    top = max(s.bit_length() for _, s in pairs)
    acc = _ZERO
    for bit in range(top - 1, -1, -1):
        acc = _add(acc, acc)
        for P, s in pairs:
            if (s >> bit) & 1:
                acc = _add(acc, P)
    return acc


class SigningKey:
    """Expanded signing key: the per-seed work (SHA-512 expansion plus
    the public-key scalar mult) done once, so a cached key signs with a
    single scalar mult. Same ``.sign(message)`` surface as
    ``cryptography``'s ``Ed25519PrivateKey``, which lets crypto.py's LRU
    key cache hold either backend's object."""

    __slots__ = ("_a", "_prefix", "public_key")

    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("Ed25519 seed must be 32 bytes")
        h = hashlib.sha512(seed).digest()
        self._a = _clamp(h)
        self._prefix = h[32:]
        self.public_key = _compress(_mult_base(self._a))

    def sign(self, message: bytes) -> bytes:
        r = _hash_to_scalar(self._prefix, message)
        R = _compress(_mult_base(r))
        S = (r + _hash_to_scalar(R, self.public_key, message) * self._a) % _L
        return R + S.to_bytes(32, "little")


def public_from_seed(seed: bytes) -> bytes:
    return SigningKey(seed).public_key


def sign(seed: bytes, message: bytes) -> bytes:
    return SigningKey(seed).sign(message)


def verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    if len(public_key) != 32 or len(signature) != 64:
        return False
    vk = _verify_key(bytes(public_key))
    R = _decompress(signature[:32])
    if vk is None or R is None:
        return False
    S = int.from_bytes(signature[32:], "little")
    if S >= _L:
        return False  # malleability check, RFC 8032 §5.1.7
    k = _hash_to_scalar(signature[:32], public_key, message)
    # S*B == R + k*A (projective equality — same accept set as the
    # compressed compare, minus its two field inversions).
    return _points_equal(_mult_base(S), _add(R, vk.mult(k)))


def verify_batch(items) -> list[bool]:
    """Verify ``[(public_key, message, signature), ...]`` as one batch.

    Returns per-item verdicts identical to ``[verify(*it) for it in
    items]`` (up to the 2^-128 RLC bound): structurally bad items are
    rejected up front, the rest ride one random-linear-combination
    check, and a failing batch fans back to per-item verification so a
    single bad signature costs only its cohort's fast path, never its
    cohort's verdicts. Terms sharing a public key collapse into one
    scalar mult — the per-sender drain shape of the wire hot loop, where
    a whole cohort usually carries ONE key."""
    items = list(items)
    ok = [False] * len(items)
    parsed = []  # (index, vk, R, S, k, pk_bytes)
    for i, (public_key, message, signature) in enumerate(items):
        if len(public_key) != 32 or len(signature) != 64:
            continue
        pk = bytes(public_key)
        vk = _verify_key(pk)
        R = _decompress(signature[:32])
        if vk is None or R is None:
            continue
        S = int.from_bytes(signature[32:], "little")
        if S >= _L:
            continue
        k = _hash_to_scalar(signature[:32], pk, message)
        parsed.append((i, vk, R, S, k, pk))
    if not parsed:
        return ok
    if len(parsed) == 1:
        i, vk, R, S, k, _ = parsed[0]
        ok[i] = _points_equal(_mult_base(S), _add(R, vk.mult(k)))
        return ok
    # Random 128-bit coefficients: an adversary who cannot predict z
    # passes the combined equation with probability 2^-128 unless every
    # term holds individually.
    rnd = os.urandom(16 * len(parsed))
    z = [
        int.from_bytes(rnd[16 * j : 16 * (j + 1)], "little") | 1
        for j in range(len(parsed))
    ]
    s_sum = 0
    a_coeff: dict[bytes, list] = {}  # pk -> [vk, scalar] (shared-key collapse)
    r_pairs = []
    for (i, vk, R, S, k, pk), zi in zip(parsed, z):
        s_sum = (s_sum + zi * S) % _L
        ent = a_coeff.get(pk)
        if ent is None:
            a_coeff[pk] = [vk, zi * k % _L]
        else:
            ent[1] = (ent[1] + zi * k) % _L
        r_pairs.append((R, zi))
    rhs = _msm(r_pairs)
    for vk, c in a_coeff.values():
        rhs = _add(rhs, vk.mult(c))
    if _points_equal(_mult_base(s_sum), rhs):
        for i, _vk, _R, _S, _k, _pk in parsed:
            ok[i] = True
        return ok
    # Fan back: isolate the bad item(s) without changing any verdict.
    for i, vk, R, S, k, _pk in parsed:
        ok[i] = _points_equal(_mult_base(S), _add(R, vk.mult(k)))
    return ok
