"""Pure-Python Ed25519 (RFC 8032) — the no-dependency fallback backend.

host/crypto.py prefers the ``cryptography`` package (OpenSSL: fast and
constant-time); this module keeps the host layer *functional* when that
wheel is absent (hermetic CI images, minimal containers) so the loopback
harness, transports and the observability round-trip tests still run.

Bit-identical output to the RFC 8032 test vectors (pinned in
tests/test_host_crypto.py). NOT constant-time — Python big-int arithmetic
leaks timing — so crypto.py logs a warning once when this backend is
active; production deployments install ``cryptography``
(requirements-test.txt).

Performance: ~2.5 ms per scalar multiplication on a current x86 core
(sign ≈ 3 ms, verify ≈ 6 ms) — ample for tests and REPL traffic, ~100x
off OpenSSL for bulk streams.
"""

from __future__ import annotations

import hashlib

__all__ = ["public_from_seed", "sign", "verify"]

_p = 2**255 - 19
_L = 2**252 + 27742317777372353535851937790883648493


def _inv(x: int) -> int:
    return pow(x, _p - 2, _p)


_d = -121665 * _inv(121666) % _p
_I = pow(2, (_p - 1) // 4, _p)
_By = 4 * _inv(5) % _p


def _recover_x(y: int, sign_bit: int) -> int | None:
    if y >= _p:
        return None
    x2 = (y * y - 1) * _inv(_d * y * y + 1) % _p
    x = pow(x2, (_p + 3) // 8, _p)
    if (x * x - x2) % _p:
        x = x * _I % _p
    if (x * x - x2) % _p:
        return None
    if x == 0 and sign_bit:
        return None
    if x & 1 != sign_bit:
        x = _p - x
    return x


# Extended homogeneous coordinates (X, Y, Z, T) with x=X/Z, y=Y/Z, T=XY/Z.
_Bx = _recover_x(_By, 0)
_B = (_Bx, _By, 1, _Bx * _By % _p)
_ZERO = (0, 1, 1, 0)


def _add(P, Q):
    X1, Y1, Z1, T1 = P
    X2, Y2, Z2, T2 = Q
    A = (Y1 - X1) * (Y2 - X2) % _p
    B = (Y1 + X1) * (Y2 + X2) % _p
    C = 2 * T1 * _d * T2 % _p
    D = 2 * Z1 * Z2 % _p
    E, F, G, H = B - A, D - C, D + C, B + A
    return (E * F % _p, G * H % _p, F * G % _p, E * H % _p)


def _mult(P, s: int):
    Q = _ZERO
    while s:
        if s & 1:
            Q = _add(Q, P)
        P = _add(P, P)
        s >>= 1
    return Q


def _compress(P) -> bytes:
    X, Y, Z, _ = P
    zi = _inv(Z)
    x, y = X * zi % _p, Y * zi % _p
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _decompress(data: bytes):
    if len(data) != 32:
        return None
    val = int.from_bytes(data, "little")
    y = val & ((1 << 255) - 1)
    x = _recover_x(y, val >> 255)
    if x is None:
        return None
    return (x, y, 1, x * y % _p)


def _clamp(h: bytes) -> int:
    a = int.from_bytes(h[:32], "little")
    return (a & ((1 << 254) - 8)) | (1 << 254)


def _hash_to_scalar(*parts: bytes) -> int:
    h = hashlib.sha512()
    for part in parts:
        h.update(part)
    return int.from_bytes(h.digest(), "little") % _L


class SigningKey:
    """Expanded signing key: the per-seed work (SHA-512 expansion plus
    the public-key scalar mult) done once, so a cached key signs with a
    single scalar mult. Same ``.sign(message)`` surface as
    ``cryptography``'s ``Ed25519PrivateKey``, which lets crypto.py's LRU
    key cache hold either backend's object."""

    __slots__ = ("_a", "_prefix", "public_key")

    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("Ed25519 seed must be 32 bytes")
        h = hashlib.sha512(seed).digest()
        self._a = _clamp(h)
        self._prefix = h[32:]
        self.public_key = _compress(_mult(_B, self._a))

    def sign(self, message: bytes) -> bytes:
        r = _hash_to_scalar(self._prefix, message)
        R = _compress(_mult(_B, r))
        S = (r + _hash_to_scalar(R, self.public_key, message) * self._a) % _L
        return R + S.to_bytes(32, "little")


def public_from_seed(seed: bytes) -> bytes:
    return SigningKey(seed).public_key


def sign(seed: bytes, message: bytes) -> bytes:
    return SigningKey(seed).sign(message)


def verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    if len(public_key) != 32 or len(signature) != 64:
        return False
    A = _decompress(public_key)
    R = _decompress(signature[:32])
    if A is None or R is None:
        return False
    S = int.from_bytes(signature[32:], "little")
    if S >= _L:
        return False  # malleability check, RFC 8032 §5.1.7
    k = _hash_to_scalar(signature[:32], public_key, message)
    # S*B == R + k*A, compared in compressed form (projective equality).
    return _compress(_mult(_B, S)) == _compress(_add(R, _mult(A, k)))
