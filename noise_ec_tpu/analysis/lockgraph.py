"""Dynamic lock-order + loop-blocking harness (lockdep / tsan-lite).

Opt-in instrumentation for test time: :func:`install` monkeypatches
``threading.Lock`` / ``threading.RLock`` so every lock created while
installed is a recording wrapper, and patches ``time.sleep`` to see
sleeps on loop threads. The wrappers feed one process-wide
:class:`LockGraph`:

- **lock-order edges** — acquiring B while holding A records the
  directed edge A→B (per lock *instance*, with the creation sites and
  the first acquisition stack kept for the report). A cycle in that
  graph is a latent deadlock: two threads interleaving the two orders
  stop forever, which a test run only catches if it actually hangs —
  the graph catches the *order*, which every passing run exercises.
- **loop-blocking events** — a thread running an asyncio event loop
  must never park: a contended lock acquire that waits longer than
  ``block_threshold`` on a loop thread, or any ``time.sleep`` on a loop
  thread, records an event (the dynamic twin of the static
  ``loop-affinity`` rule).
- **sleep-under-lock events** — ``time.sleep`` while holding an
  instrumented lock on any thread is recorded separately (reported, not
  asserted: worker-side lingers are sometimes deliberate, but they are
  exactly what turns a benign lock into a loop-stalling one).

The chaos-soak and fleet acceptance tests run under this harness via
the ``lockgraph`` fixture (tests/conftest.py), which asserts zero
cycles and zero loop-blocking events over the run — tier-1 itself is
the race detector. Locks created *before* :func:`install` are not
instrumented; the fixture installs before the test constructs its
networks/stores, so everything the test builds is covered.

The instrumentation's own bookkeeping uses raw ``_thread`` locks so it
can never recurse into itself, and the wrappers implement the full
``acquire(blocking, timeout)`` / context-manager surface (including
what ``threading.Condition`` needs from a user-supplied lock).
"""

from __future__ import annotations

import _thread
import threading
import time
import traceback
from typing import Optional

__all__ = [
    "LockGraph",
    "current_graph",
    "install",
    "uninstall",
]

_REAL_LOCK = _thread.allocate_lock  # never patched; recursion-proof
_REAL_SLEEP = time.sleep


def _on_loop_thread() -> bool:
    """True while the current thread is inside a running asyncio loop
    (protocol callbacks, call_soon callbacks, coroutine steps)."""
    try:
        import asyncio

        return asyncio._get_running_loop() is not None
    except Exception:  # pragma: no cover — defensive
        return False


def _site(skip: int = 2) -> str:
    """file:line of the caller outside this module/threading."""
    for frame in reversed(traceback.extract_stack()[:-skip]):
        if "analysis/lockgraph" in frame.filename or \
                frame.filename.endswith("threading.py"):
            continue
        return f"{frame.filename}:{frame.lineno}"
    return "<unknown>"


class LockGraph:
    """The process-wide recording target while installed."""

    def __init__(self, block_threshold: float = 0.2):
        self.block_threshold = block_threshold
        self._raw = _REAL_LOCK()
        self._tls = threading.local()
        # lock id -> creation site
        self.locks: dict[int, str] = {}
        # (id_a, id_b) -> {"sites", "count", "stack"}
        self.edges: dict[tuple[int, int], dict] = {}
        self.loop_block_events: list[dict] = []
        self.sleep_under_lock_events: list[dict] = []
        self.acquisitions = 0

    # ------------------------------------------------------- bookkeeping

    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def register(self, lock_id: int, site: str) -> None:
        with self._raw:
            self.locks[lock_id] = site

    def before_acquire(self, lock_id: int) -> None:
        """Record order edges from every held lock to this one (called
        for blocking acquires only — try-locks cannot deadlock)."""
        held = self._held()
        if not held:
            return
        with self._raw:
            self.acquisitions += 1
            for h in held:
                if h == lock_id:
                    continue  # reentrant wrappers handle their own state
                key = (h, lock_id)
                entry = self.edges.get(key)
                if entry is None:
                    self.edges[key] = {
                        "sites": (self.locks.get(h, "?"),
                                  self.locks.get(lock_id, "?")),
                        "count": 1,
                        "stack": "".join(traceback.format_stack()[-8:-2]),
                    }
                else:
                    entry["count"] += 1

    def acquired(self, lock_id: int) -> None:
        self._held().append(lock_id)

    def released(self, lock_id: int) -> None:
        held = self._held()
        # remove the most recent occurrence (lock discipline is LIFO in
        # practice, but release-out-of-order must not corrupt the stack)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == lock_id:
                del held[i]
                return

    def blocked_wait(self, lock_id: int, waited: float) -> None:
        if waited >= self.block_threshold and _on_loop_thread():
            with self._raw:
                self.loop_block_events.append({
                    "kind": "loop-lock-wait",
                    "lock": self.locks.get(lock_id, "?"),
                    "waited": waited,
                    "thread": threading.current_thread().name,
                    "stack": "".join(traceback.format_stack()[-8:-2]),
                })

    def note_sleep(self, seconds: float) -> None:
        if _on_loop_thread():
            with self._raw:
                self.loop_block_events.append({
                    "kind": "loop-sleep",
                    "seconds": seconds,
                    "thread": threading.current_thread().name,
                    "stack": "".join(traceback.format_stack()[-8:-2]),
                })
        elif self._held():
            with self._raw:
                self.sleep_under_lock_events.append({
                    "kind": "sleep-under-lock",
                    "seconds": seconds,
                    "locks": [self.locks.get(h, "?") for h in self._held()],
                    "thread": threading.current_thread().name,
                    "stack": "".join(traceback.format_stack()[-8:-2]),
                })

    # ----------------------------------------------------------- reports

    def cycles(self) -> list[list[str]]:
        """Lock-order cycles, as lists of creation sites. Tarjan SCCs
        over the instance graph: an SCC with more than one node (or a
        self-edge) means both orders were observed — a latent deadlock."""
        with self._raw:
            adj: dict[int, list[int]] = {}
            for (a, b) in self.edges:
                adj.setdefault(a, []).append(b)
                adj.setdefault(b, [])
        index: dict[int, int] = {}
        low: dict[int, int] = {}
        on_stack: set[int] = set()
        stack: list[int] = []
        counter = [0]
        out: list[list[str]] = []

        def strongconnect(v: int) -> None:
            # iterative Tarjan (recursion depth is unbounded otherwise)
            work = [(v, iter(adj[v]))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(adj[w])))
                        advanced = True
                        break
                    if w in on_stack:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1 or (node, node) in self.edges:
                        out.append([self.locks.get(i, "?") for i in scc])

        for v in list(adj):
            if v not in index:
                strongconnect(v)
        return out

    def report(self) -> dict:
        cycles = self.cycles()  # takes _raw itself (non-reentrant)
        with self._raw:
            return {
                "locks": len(self.locks),
                "edges": len(self.edges),
                "acquisitions": self.acquisitions,
                "cycles": cycles,
                "loop_block_events": list(self.loop_block_events),
                "sleep_under_lock_events":
                    list(self.sleep_under_lock_events),
            }


class _InstrumentedLock:
    """Drop-in ``threading.Lock`` recording into a :class:`LockGraph`."""

    def __init__(self, graph: LockGraph):
        self._inner = _REAL_LOCK()
        self._graph = graph
        self.site = _site()
        graph.register(id(self), self.site)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        g = self._graph
        if not blocking:
            got = self._inner.acquire(False)
            if got:
                g.acquired(id(self))
            return got
        g.before_acquire(id(self))
        got = self._inner.acquire(False)
        if not got:
            t0 = time.monotonic()
            if timeout is None or timeout < 0:
                got = self._inner.acquire(True)
            else:
                got = self._inner.acquire(True, timeout)
            g.blocked_wait(id(self), time.monotonic() - t0)
            if not got:
                return False
        g.acquired(id(self))
        return True

    def release(self) -> None:
        self._inner.release()
        self._graph.released(id(self))

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        # stdlib registers this (os.register_at_fork in
        # concurrent.futures.thread, threading internals): the child
        # process starts with the lock free.
        self._inner._at_fork_reinit()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"<InstrumentedLock {self.site} {self._inner!r}>"


class _InstrumentedRLock:
    """Drop-in ``threading.RLock``: reentrant re-acquires record no
    edges (holding yourself is not an order) and push/pop the held
    stack exactly once per outermost acquire/release."""

    def __init__(self, graph: LockGraph):
        self._inner = _REAL_LOCK()
        self._graph = graph
        self._owner: Optional[int] = None
        self._count = 0
        self.site = _site()
        graph.register(id(self), self.site)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = _thread.get_ident()
        if self._owner == me:
            self._count += 1
            return True
        g = self._graph
        if not blocking:
            got = self._inner.acquire(False)
            if not got:
                return False
        else:
            g.before_acquire(id(self))
            got = self._inner.acquire(False)
            if not got:
                t0 = time.monotonic()
                if timeout is None or timeout < 0:
                    got = self._inner.acquire(True)
                else:
                    got = self._inner.acquire(True, timeout)
                g.blocked_wait(id(self), time.monotonic() - t0)
                if not got:
                    return False
        self._owner = me
        self._count = 1
        g.acquired(id(self))
        return True

    def release(self) -> None:
        if self._owner != _thread.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            self._inner.release()
            self._graph.released(id(self))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def _at_fork_reinit(self) -> None:
        self._inner._at_fork_reinit()
        self._owner = None
        self._count = 0

    # threading.Condition support for user-supplied rlocks
    def _is_owned(self) -> bool:
        return self._owner == _thread.get_ident()

    def _release_save(self):
        count, owner = self._count, self._owner
        self._count = 0
        self._owner = None
        self._inner.release()
        self._graph.released(id(self))
        return (count, owner)

    def _acquire_restore(self, state) -> None:
        self.acquire()
        self._count, self._owner = state


_installed: Optional[dict] = None


def current_graph() -> Optional[LockGraph]:
    return _installed["graph"] if _installed else None


def install(block_threshold: float = 0.2) -> LockGraph:
    """Patch ``threading.Lock``/``RLock`` + ``time.sleep`` and return
    the recording graph. Locks created while installed stay
    instrumented (and functional) after :func:`uninstall`."""
    global _installed
    if _installed is not None:
        raise RuntimeError("lockgraph already installed")
    graph = LockGraph(block_threshold=block_threshold)

    def make_lock():
        return _InstrumentedLock(graph)

    def make_rlock():
        return _InstrumentedRLock(graph)

    def sleep(seconds):
        graph.note_sleep(seconds)
        _REAL_SLEEP(seconds)

    _installed = {
        "graph": graph,
        "Lock": threading.Lock,
        "RLock": threading.RLock,
        "sleep": time.sleep,
    }
    threading.Lock = make_lock
    threading.RLock = make_rlock
    time.sleep = sleep
    return graph


def uninstall() -> Optional[LockGraph]:
    """Restore the real factories; returns the graph for assertions."""
    global _installed
    if _installed is None:
        return None
    threading.Lock = _installed["Lock"]
    threading.RLock = _installed["RLock"]
    time.sleep = _installed["sleep"]
    graph = _installed["graph"]
    _installed = None
    return graph
