"""Concurrency + dataflow rules: loop-affinity, donation, zero-copy.

Each rule encodes an invariant this codebase already paid to learn
(docs/static-analysis.md has the catalog with the motivating PRs):

- **loop-affinity** — event-loop threads must never block. Contexts
  checked: every ``async def`` body, the callback methods of
  ``asyncio.(Buffered|Datagram)Protocol`` subclasses, and any function
  annotated ``# noise-ec: loop-affine`` on its ``def`` line (the
  transport write path's documented contract, now machine-checked).
  Flagged: direct blocking calls (``time.sleep``, ``submit_wait``,
  sync socket ops, un-awaited ``.wait()``/``.result()``, blocking
  ``.acquire()``), acquiring a **blocking-held** lock (one whose spans
  anywhere in the module contain a blocking call while held — acquiring
  such a lock on the loop inherits the holder's stall), and one-hop
  calls to same-module functions whose bodies directly block.

- **donation** — a device array donated to a jit entry
  (``donate_argnums``) is invalidated by the dispatch; reading the name
  afterwards in the same scope is a use-after-free that XLA surfaces as
  a deleted-buffer error only on donating backends (TPU/GPU), i.e.
  never in CPU CI. Donation marks: ``<pool>.donate(name)`` and literal
  ``donate=True`` call arguments.

- **zero-copy** — memoryview slices of ``_FrameRing`` buffers
  (``.frames()`` / ``.writable()``) are valid only until the next ring
  fill/compaction; storing one on ``self``, returning or yielding it,
  or parking it in a container lets it dangle. Escape requires an
  explicit ``bytes()`` copy. (``get_buffer`` returning the writable
  tail is the BufferedProtocol contract — the loop owns that view for
  exactly one fill — and is exempt.)

- **span-coverage** — every handler mounted on an ``/objects`` route
  (the object-service route table on the stats server) must open a
  request span (``trace_request(...)`` / ``request(...)``) in its
  body: an untraced route is invisible to the tail sampler, carries no
  exemplars and never joins the collector-merged fleet view. A
  deliberately untraced route takes a
  ``# noise-ec: allow(span-coverage)`` suppression on its mount line.

- **event-on-swallow** — in a module that imports the wide-event API
  (``noise_ec_tpu.obs.events``, i.e. an instrumented subsystem), a
  broad exception handler (bare ``except:``, ``except Exception`` /
  ``BaseException``) must leave a footprint: re-``raise``, emit a wide
  ``event(...)``, log at some level, or feed the subsystem's error
  accounting (``*._record_error`` / ``metrics.error``). A silent broad
  swallow is exactly the failure class the event log exists to
  surface; the diagnosis engine cannot rank what never lands in the
  window. A deliberate swallow (environment probe, error re-delivered
  through another channel) takes a justified
  ``# noise-ec: allow(event-on-swallow)`` on the ``except`` line.
  Narrow typed handlers (``ValueError``, ``UnknownStripeError``, ...)
  are expected control flow and are exempt.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from noise_ec_tpu.analysis.core import (
    Finding,
    SourceFile,
    call_name,
    const_str,
    dotted,
    rule,
)

# ------------------------------------------------------------- blocking model

# Fully-dotted callables that block the calling thread.
BLOCKING_DOTTED = {
    "time.sleep",
    "select.select",
    "socket.create_connection",
    "socket.getaddrinfo",
    "subprocess.run",
    "subprocess.check_output",
    "os.system",
}
# Method names that block regardless of receiver.
BLOCKING_METHODS = {"submit_wait", "sendall", "recvfrom"}
# Method names that block unless awaited (asyncio twins exist).
BLOCKING_UNLESS_AWAITED = {"wait", "result"}
# Names that look like locks/conditions when used as a context manager
# or ``.acquire()`` receiver.
_LOCK_TOKENS = ("lock", "cond", "mutex")

PROTOCOL_BASES = {
    "BufferedProtocol",
    "Protocol",
    "DatagramProtocol",
    "SubprocessProtocol",
}
PROTOCOL_CALLBACKS = {
    "connection_made",
    "connection_lost",
    "data_received",
    "buffer_updated",
    "get_buffer",
    "eof_received",
    "pause_writing",
    "resume_writing",
    "datagram_received",
    "error_received",
    "pipe_data_received",
    "process_exited",
}


def _is_lock_name(name: str) -> bool:
    low = name.lower()
    return any(tok in low for tok in _LOCK_TOKENS)


def _lock_expr_name(node: ast.expr) -> Optional[str]:
    """The lock-ish final name of ``self._lock`` / ``net._lock`` /
    ``_lock``, or None when the expression is not lock-shaped."""
    if isinstance(node, ast.Attribute) and _is_lock_name(node.attr):
        return node.attr
    if isinstance(node, ast.Name) and _is_lock_name(node.id):
        return node.id
    return None


def _nonblocking_acquire(call: ast.Call) -> bool:
    """``.acquire(blocking=False)`` / ``.acquire(False)`` /
    ``.acquire(timeout=0)`` never park the thread."""
    for kw in call.keywords:
        if kw.arg == "blocking" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
        if kw.arg == "timeout" and isinstance(kw.value, ast.Constant) \
                and kw.value.value == 0:
            return True
    if call.args and isinstance(call.args[0], ast.Constant) \
            and call.args[0].value in (False, 0):
        return True
    return False


def _blocking_call(call: ast.Call, awaited_ids: set[int],
                   same_lock: Optional[str] = None) -> Optional[str]:
    """A short description when ``call`` blocks the calling thread,
    else None. ``same_lock``: the lock name whose span we are inside —
    ``<lock>.wait()`` there is the Condition pattern (the wait releases
    the lock) and does not count."""
    d = dotted(call.func)
    if d in BLOCKING_DOTTED:
        return f"{d}()"
    name = call_name(call)
    if name in BLOCKING_METHODS:
        return f".{name}()"
    if name == "acquire" and isinstance(call.func, ast.Attribute):
        if _lock_expr_name(call.func.value) and not _nonblocking_acquire(call):
            return ".acquire()"
        return None
    if name in BLOCKING_UNLESS_AWAITED and id(call) not in awaited_ids:
        if isinstance(call.func, ast.Attribute):
            recv = dotted(call.func.value)
            if same_lock is not None and recv is not None \
                    and recv.endswith(same_lock):
                return None  # Condition.wait inside its own lock span
            # Only lock/event/future-shaped receivers: bare ``x.wait()``
            # on arbitrary objects is too common to flag blindly.
            base = recv.rsplit(".", 1)[-1].lower() if recv else ""
            if name == "wait" and not (
                _is_lock_name(base) or "event" in base or "fut" in base
                or "cond" in base or base == "registered"
            ):
                return None
            return f".{name}() (un-awaited)"
    return None


def _awaited_call_ids(root: ast.AST) -> set[int]:
    """ids of every Call inside an ``await`` expression — including
    nested ones (``await asyncio.wait_for(ev.wait(), ...)`` awaits the
    inner wait too)."""
    out: set[int] = set()
    for n in ast.walk(root):
        if isinstance(n, ast.Await):
            out.update(
                id(c) for c in ast.walk(n.value) if isinstance(c, ast.Call)
            )
    return out


def _own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk ``fn``'s body without descending into nested function
    definitions (a nested def is not executed by entering the outer
    scope; nested async defs are their own loop context)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _ModuleIndex:
    """Per-file context shared by the loop-affinity walk."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        # (class name or None, method name) -> FunctionDef — for one-hop
        # resolution a bare name map is enough when unique.
        self.defs_by_name: dict[str, list[ast.AST]] = {}
        self.loop_contexts: list[tuple[ast.AST, str]] = []
        # lock key (class, attr) -> list of blocking descriptions found
        # inside any ``with <lock>`` span of that key
        self.blocking_held: dict[tuple[Optional[str], str], str] = {}
        self._index()

    def _index(self) -> None:
        for node in ast.walk(self.sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, []).append(node)
        for cls in [n for n in ast.walk(self.sf.tree)
                    if isinstance(n, ast.ClassDef)]:
            is_protocol = any(
                (d := dotted(b)) and d.rsplit(".", 1)[-1] in PROTOCOL_BASES
                for b in cls.bases
            )
            for item in cls.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if isinstance(item, ast.AsyncFunctionDef):
                    continue  # picked up by the async walk below
                if is_protocol and item.name in PROTOCOL_CALLBACKS:
                    self.loop_contexts.append(
                        (item, f"{cls.name}.{item.name} (protocol callback)")
                    )
            # lock spans per enclosing class
            self._index_lock_spans(cls, cls.name)
        self._index_lock_spans(self.sf.tree, None, top_only=True)
        for node in ast.walk(self.sf.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                self.loop_contexts.append(
                    (node, f"async {node.name}")
                )
            elif isinstance(node, ast.FunctionDef) and (
                node.lineno in self.sf.loop_affine_lines
                or node.lineno - 1 in self.sf.loop_affine_lines
            ):
                self.loop_contexts.append(
                    (node, f"{node.name} (marked loop-affine)")
                )

    def _index_lock_spans(self, scope: ast.AST, cls_name: Optional[str],
                          top_only: bool = False) -> None:
        nodes = ast.walk(scope) if not top_only else (
            n for n in ast.walk(scope)
            if not isinstance(n, ast.ClassDef)
        )
        for node in nodes:
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                lname = _lock_expr_name(item.context_expr)
                if lname is None:
                    continue
                key = self._lock_key(item.context_expr, cls_name, lname)
                if key in self.blocking_held:
                    continue
                awaited = _awaited_call_ids(node)
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        desc = _blocking_call(sub, awaited, same_lock=lname)
                        if desc:
                            self.blocking_held[key] = (
                                f"{desc} at line {sub.lineno}"
                            )
                            break

    @staticmethod
    def _lock_key(expr: ast.expr, cls_name: Optional[str],
                  lname: str) -> tuple[Optional[str], str]:
        """``self.X`` binds to the enclosing class; anything else is an
        unknown-receiver lock keyed module-wide by name."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return (cls_name, lname)
        return ("?", lname)

    def resolve_local(self, call: ast.Call) -> Optional[ast.FunctionDef]:
        """One-hop callee: a unique same-module plain function matching
        a bare-name call or a ``self.method(...)`` call. Arbitrary
        receivers (``writer.close()``) stay unresolved — matching them
        by method name alone mistakes stdlib objects for our defs."""
        f = call.func
        if isinstance(f, ast.Attribute):
            if not (isinstance(f.value, ast.Name) and f.value.id == "self"):
                return None
            name = f.attr
        elif isinstance(f, ast.Name):
            name = f.id
        else:
            return None
        defs = self.defs_by_name.get(name, [])
        if len(defs) == 1 and isinstance(defs[0], ast.FunctionDef):
            return defs[0]
        return None


def _context_class(sf: SourceFile, fn: ast.AST) -> Optional[str]:
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ClassDef) and fn in node.body:
            return node.name
    return None


@rule(
    "loop-affinity",
    scope="file",
    invariant="event-loop threads must not execute blocking calls or "
              "acquire locks whose holders block",
    motivation="PR 4 (wait_writable deadlock guard), PR 7 (submit_wait "
               "split: 'loop threads must not block'), PR 11 (batched "
               "verify moved off the loop)",
)
def check_loop_affinity(sf: SourceFile):
    idx = _ModuleIndex(sf)
    for fn, label in idx.loop_contexts:
        awaited = _awaited_call_ids(fn)
        cls = _context_class(sf, fn)
        for node in _own_nodes(fn):
            if isinstance(node, ast.Call):
                desc = _blocking_call(node, awaited)
                if desc:
                    yield Finding(
                        "loop-affinity", sf.rel, node.lineno,
                        f"blocking call {desc} inside {label}: the event "
                        "loop stalls every connection while this waits — "
                        "move it to the dispatch pool or use the asyncio "
                        "form",
                    )
                    continue
                callee = idx.resolve_local(node)
                if callee is not None:
                    c_awaited = _awaited_call_ids(callee)
                    for sub in _own_nodes(callee):
                        if isinstance(sub, ast.Call):
                            d = _blocking_call(sub, c_awaited)
                            if d:
                                yield Finding(
                                    "loop-affinity", sf.rel, node.lineno,
                                    f"call to {callee.name}() inside "
                                    f"{label}, whose body blocks "
                                    f"({d} at line {sub.lineno})",
                                )
                                break
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lname = _lock_expr_name(item.context_expr)
                    if lname is None:
                        continue
                    key = _ModuleIndex._lock_key(
                        item.context_expr, cls, lname
                    )
                    held = idx.blocking_held.get(key)
                    if held:
                        yield Finding(
                            "loop-affinity", sf.rel, node.lineno,
                            f"acquiring {lname} inside {label}, but a "
                            f"holder of this lock blocks while holding "
                            f"it ({held}): the loop inherits that stall "
                            "— shrink the holder's critical section or "
                            "hand the work to the dispatch pool",
                        )


# ---------------------------------------------------------------- donation


# Donating entries whose positional arguments (by index) hand their
# device buffers to the dispatch when called with donate=True. Index 0
# is the generator matrix everywhere in this codebase (replicated, never
# donated); the words/stripes operand is index 1.
DONATED_ARG_INDEX = {None: (1,)}


def _donation_marks(fn: ast.AST) -> list[tuple[str, ast.stmt, str]]:
    """(name, donating statement, kind) triples in ``fn``'s own body.

    ``kind="call"``: a literal ``donate=True`` argument — that call IS
    the consuming dispatch, so the buffer dies with the statement.
    ``kind="mark"``: ``<pool>.donate(name)`` bookkeeping — the buffer
    dies at the NEXT statement that reads the name (the dispatch the
    mark announces), so exactly one downstream consumer is legal.
    """
    marks: dict[int, tuple[str, ast.stmt, str]] = {}
    for stmt in _own_nodes(fn):
        if not isinstance(stmt, ast.stmt):
            continue
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name == "donate" and node.args \
                    and isinstance(node.args[0], ast.Name):
                prev = marks.get(id(node))
                # innermost containing statement wins (nested compound
                # statements each see the same call)
                if prev is None or stmt.lineno > prev[1].lineno:
                    marks[id(node)] = (node.args[0].id, stmt, "mark")
                continue
            if any(
                kw.arg == "donate" and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            ):
                for i in DONATED_ARG_INDEX[None]:
                    if i < len(node.args) and isinstance(node.args[i],
                                                         ast.Name):
                        prev = marks.get(id(node))
                        if prev is None or stmt.lineno > prev[1].lineno:
                            marks[id(node)] = (
                                node.args[i].id, stmt, "call"
                            )
    return list(marks.values())


def _branch_excluded_lines(fn: ast.AST, stmt: ast.stmt) -> set[int]:
    """Lines on no control path through ``stmt``: for every ancestor
    ``if``, the lines of the branch not containing it. Keeps the
    donation dataflow from chasing reads in a mutually-exclusive arm."""
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(fn):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    excluded: set[int] = set()
    child: ast.AST = stmt
    while child is not fn:
        par = parents.get(id(child))
        if par is None:
            break
        if isinstance(par, ast.If):
            other = par.orelse if child in par.body else (
                par.body if child in par.orelse else []
            )
            for s in other:
                excluded.update(
                    range(s.lineno, getattr(s, "end_lineno", s.lineno) + 1)
                )
        child = par
    return excluded


@rule(
    "donation",
    scope="file",
    invariant="a name whose device buffer was donated (donate=True / "
              "pool.donate) must not be read again in the same scope",
    motivation="PR 8 (donated arrays are invalidated exactly once; "
               "maybe_analyze_program takes ShapeDtypeStructs because "
               "donated arrays must not be re-touched)",
)
def check_donation(sf: SourceFile):
    for fn in ast.walk(sf.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        marks = _donation_marks(fn)
        if not marks:
            continue
        loads: list[tuple[str, int]] = []
        stores: list[tuple[str, int]] = []
        for node in _own_nodes(fn):
            if isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    loads.append((node.id, node.lineno))
                else:  # Store and Del both end the donated binding
                    stores.append((node.id, node.lineno))
        for name, stmt, kind in marks:
            end = getattr(stmt, "end_lineno", stmt.lineno)
            dead = _branch_excluded_lines(fn, stmt)
            m_loads = [(n, l) for n, l in loads if l not in dead]
            m_stores = [(n, l) for n, l in stores if l not in dead]
            if kind == "mark":
                # The buffer survives until the dispatch the mark
                # announces: the first later read is the consumer.
                consumer = min(
                    (ll for ln, ll in m_loads if ln == name and ll > end),
                    default=None,
                )
                if consumer is None:
                    continue
                # ... through the end of the innermost statement
                # containing that read (a multi-line dispatch call).
                end = min(
                    getattr(s, "end_lineno", s.lineno)
                    for s in _own_nodes(fn)
                    if isinstance(s, ast.stmt)
                    and s.lineno <= consumer
                    and getattr(s, "end_lineno", s.lineno) >= consumer
                )
            for lname, lline in m_loads:
                if lname != name or lline <= end:
                    continue
                # A rebind on the donating statement itself
                # (``x = f(x, donate=True)``) or anywhere before the
                # read re-points the name at a live buffer.
                rebound = any(
                    sname == name and stmt.lineno <= sline <= lline
                    for sname, sline in m_stores
                )
                if rebound:
                    continue
                yield Finding(
                    "donation", sf.rel, lline,
                    f"{name!r} was donated at line {stmt.lineno} "
                    "(its device buffer now belongs to the dispatch "
                    "output) but is read again here — on TPU/GPU this "
                    "is a deleted-buffer error that CPU CI never sees; "
                    "rebind the name or capture a ShapeDtypeStruct "
                    "before donating",
                )
                break  # one finding per donated name


# ------------------------------------------------------------ span coverage


# The route prefix the object-service request-tracing contract covers
# (docs/observability.md "Request tracing"): handlers on these routes
# are the request roots the tail sampler, exemplars and collector merge
# all key off.
_TRACED_ROUTE_PREFIX = "/objects"
# Call names that open a request scope: the module-level helper under
# either of its import spellings, and the tracer method.
_REQUEST_OPENERS = {"request", "trace_request"}


def _opens_request_span(fn: ast.AST) -> bool:
    """True when ``fn``'s own body (nested defs excluded — a scope
    opened inside a closure does not cover the handler) calls a request
    opener, bare or as a method (``tracer.request``)."""
    for node in _own_nodes(fn):
        if isinstance(node, ast.Call) \
                and call_name(node) in _REQUEST_OPENERS:
            return True
    return False


@rule(
    "span-coverage",
    scope="file",
    invariant="every handler mounted on an /objects route opens a "
              "request span (trace_request/request) in its body",
    motivation="PR 18 (tail-sampled request tracing: an untraced route "
               "records no request root, so it is invisible to the "
               "sampler, carries no exemplars and never joins the "
               "collector-merged fleet trace)",
)
def check_span_coverage(sf: SourceFile):
    module_defs = {
        n.name: n for n in sf.tree.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    # Innermost enclosing class per node (inner classes walk later and
    # overwrite the outer assignment).
    cls_of: dict[int, ast.ClassDef] = {}
    for cls in ast.walk(sf.tree):
        if isinstance(cls, ast.ClassDef):
            for sub in ast.walk(cls):
                cls_of[id(sub)] = cls
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call) or call_name(node) != "mount":
            continue
        if len(node.args) < 3:
            continue
        path = const_str(node.args[1])
        if path is None or not path.startswith(_TRACED_ROUTE_PREFIX):
            continue
        hexpr = node.args[2]
        handler = None
        if isinstance(hexpr, ast.Attribute) \
                and isinstance(hexpr.value, ast.Name) \
                and hexpr.value.id == "self":
            cls = cls_of.get(id(node))
            if cls is not None:
                for item in cls.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                            and item.name == hexpr.attr:
                        handler = item
                        break
        elif isinstance(hexpr, ast.Name):
            handler = module_defs.get(hexpr.id)
        if handler is None:
            continue  # dynamic handler — unresolvable statically
        if _opens_request_span(handler):
            continue
        hname = getattr(handler, "name", "?")
        yield Finding(
            "span-coverage", sf.rel, node.lineno,
            f"handler {hname}() mounted on traced route {path!r} opens "
            "no request span — the route is invisible to the tail "
            "sampler and the collector-merged trace; wrap the handler "
            "body in trace_request(op, ...) or suppress with "
            "# noise-ec: allow(span-coverage) for a deliberately "
            "untraced route",
        )


# ---------------------------------------------------------------- zero-copy


_VIEW_SOURCES = ("frames", "writable")
_STORE_METHODS = {"append", "add", "appendleft", "put", "put_nowait",
                  "insert"}


def _is_view_source(call: ast.Call) -> bool:
    return isinstance(call.func, ast.Attribute) \
        and call.func.attr in _VIEW_SOURCES


@rule(
    "zero-copy",
    scope="file",
    invariant="_FrameRing views (.frames()/.writable()) must not escape "
              "the parse scope without an explicit bytes() copy",
    motivation="PR 11 (frames parse IN PLACE as memoryview slices; the "
               "ring compacts/relocates under any escaped view)",
)
def check_zero_copy(sf: SourceFile):
    for fn in ast.walk(sf.tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        views: dict[str, int] = {}  # name -> bound line
        rebinds: list[tuple[str, int]] = []
        for node in _own_nodes(fn):
            if isinstance(node, (ast.For, ast.AsyncFor)) \
                    and isinstance(node.iter, ast.Call) \
                    and _is_view_source(node.iter) \
                    and isinstance(node.target, ast.Name):
                views[node.target.id] = node.lineno
            elif isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                if isinstance(node.value, ast.Call) \
                        and _is_view_source(node.value):
                    views[node.targets[0].id] = node.lineno
                else:
                    rebinds.append((node.targets[0].id, node.lineno))
        if not views:
            continue

        def is_live_view(name_node: ast.expr, use_line: int) -> bool:
            if not isinstance(name_node, ast.Name):
                return False
            bound = views.get(name_node.id)
            if bound is None or use_line < bound:
                return False
            return not any(
                rn == name_node.id and bound < rl <= use_line
                for rn, rl in rebinds
            )

        for node in _own_nodes(fn):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)) \
                            and is_live_view(node.value, node.lineno):
                        yield Finding(
                            "zero-copy", sf.rel, node.lineno,
                            f"ring view {node.value.id!r} stored outside "
                            "the parse scope — it dangles at the next "
                            "ring fill/compaction; store bytes(view) "
                            "instead",
                        )
            elif isinstance(node, ast.Return) \
                    and is_live_view(node.value, node.lineno):
                if fn.name == "get_buffer":
                    continue  # BufferedProtocol fill contract
                yield Finding(
                    "zero-copy", sf.rel, node.lineno,
                    f"ring view {node.value.id!r} returned from "
                    f"{fn.name}() — the caller outlives the parse scope; "
                    "return bytes(view) instead",
                )
            elif isinstance(node, (ast.Yield, ast.YieldFrom)) \
                    and is_live_view(getattr(node, "value", None),
                                     node.lineno):
                yield Finding(
                    "zero-copy", sf.rel, node.lineno,
                    f"ring view yielded from {fn.name}() — the consumer "
                    "may hold it across the next fill; yield bytes(view) "
                    "or document the single-fill contract at the source",
                )
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _STORE_METHODS \
                    and isinstance(node.func.value, ast.Attribute):
                for arg in node.args:
                    if is_live_view(arg, node.lineno):
                        yield Finding(
                            "zero-copy", sf.rel, node.lineno,
                            f"ring view {arg.id!r} parked in a container "
                            f"(.{node.func.attr}) — it dangles at the "
                            "next ring fill; store bytes(view) instead",
                        )


# ----------------------------------------------------------- event-on-swallow


_EVENTS_MODULE = "noise_ec_tpu.obs.events"
_EVENT_EMITTERS = {"event", "emit"}
_LOG_LEVELS = {"debug", "info", "warning", "error", "exception",
               "critical"}
_ERROR_SINKS = {"_record_error", "record_error"}


def _imports_event_api(sf: SourceFile) -> bool:
    """True when the module imports ``noise_ec_tpu.obs.events`` anywhere
    (top level or deferred inside a function — both idioms are live in
    the instrumented subsystems)."""
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom) \
                and node.module == _EVENTS_MODULE:
            return True
        if isinstance(node, ast.Import) \
                and any(a.name == _EVENTS_MODULE for a in node.names):
            return True
    return False


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        name = e.id if isinstance(e, ast.Name) else (
            e.attr if isinstance(e, ast.Attribute) else None
        )
        if name in ("Exception", "BaseException"):
            return True
    return False


def _handler_leaves_footprint(handler: ast.ExceptHandler) -> bool:
    """Re-raise, wide event, log call, or error-accounting sink
    anywhere in the handler body."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in _EVENT_EMITTERS:
            return True
        if isinstance(f, ast.Attribute):
            if f.attr in _EVENT_EMITTERS or f.attr in _ERROR_SINKS:
                return True
            if f.attr == "error":
                return True  # metrics.error(...) / log.error(...)
            if f.attr in _LOG_LEVELS:
                base = f.value
                base_name = base.id if isinstance(base, ast.Name) \
                    else getattr(base, "attr", None)
                if base_name and "log" in base_name.lower():
                    return True
    return False


@rule(
    "event-on-swallow",
    scope="file",
    invariant="in modules importing noise_ec_tpu.obs.events, a broad "
              "except (bare/Exception/BaseException) must raise, emit "
              "an event, log, or record the error",
    motivation="PR 20 (wide-event log: a silently swallowed failure in "
               "an instrumented subsystem never reaches the event "
               "window, so the diagnosis engine ranks verdicts against "
               "a hole where the incident evidence should be)",
)
def check_event_on_swallow(sf: SourceFile):
    if not _imports_event_api(sf):
        return
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _is_broad_handler(node):
            continue
        if _handler_leaves_footprint(node):
            continue
        yield Finding(
            "event-on-swallow", sf.rel, node.lineno,
            "broad except swallows the failure with no footprint — in "
            "an instrumented subsystem emit event(...)/log or feed "
            "_record_error so the diagnosis window sees it, or justify "
            "with # noise-ec: allow(event-on-swallow)",
        )
