"""Machine-checked invariants (docs/static-analysis.md).

Thirteen PRs of this codebase rest on conventions that used to live
only in prose and hard-won runtime fixes: loop threads must not block
(PR 7's ``submit_wait`` split, PR 4's ``wait_writable`` guard), donated
device arrays are invalidated exactly once (PR 8), exported
``_FrameRing`` views never escape the parse scope (PR 11), and every
metric/span name is declared before use (PR 1). This package turns
those conventions into analyzers that run in tier-1:

- :mod:`core` — the AST-walking framework: rule registry, per-line
  ``# noise-ec: allow(<rule>)`` suppressions, the project model;
- :mod:`rules` — the concurrency/dataflow rules (loop-affinity,
  donation, zero-copy);
- :mod:`registry_rules` — the metric/span/docs discipline rules
  (subsuming ``tools/check_metrics.py``, which remains as a CLI shim);
- :mod:`lockgraph` — the dynamic lock-order + loop-blocking harness
  (lockdep/tsan-lite) that the chaos-soak and fleet tests run under.

Entry points: ``tools/lint.py --all`` on the command line,
:func:`run_project` in-process (tests/test_static_analysis.py).
"""

from noise_ec_tpu.analysis.core import (
    FILE_RULES,
    PROJECT_RULES,
    Finding,
    Project,
    SourceFile,
    all_rules,
    run_project,
)

# Importing the rule modules registers their rules with the framework.
from noise_ec_tpu.analysis import rules as _rules  # noqa: F401,E402
from noise_ec_tpu.analysis import registry_rules as _registry_rules  # noqa: F401,E402

__all__ = [
    "FILE_RULES",
    "PROJECT_RULES",
    "Finding",
    "Project",
    "SourceFile",
    "all_rules",
    "run_project",
]
