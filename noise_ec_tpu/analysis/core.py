"""Static-analysis framework: rules, suppressions, the project model.

Everything is stdlib ``ast`` — no new dependencies. A **rule** is a
callable registered under a stable id; it either checks one
:class:`SourceFile` at a time (``scope="file"``) or the whole
:class:`Project` at once (``scope="project"``, for registry/docs
cross-checks that have no single home file). Rules yield
:class:`Finding`s; the runner filters findings suppressed by a

    # noise-ec: allow(<rule-id>) — <one-line justification>

comment on the flagged line or the line directly above it. The
suppression syntax is deliberately loud (greppable, justified) — the
catalog in docs/static-analysis.md is the contract for when one is
acceptable.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable, Optional

__all__ = [
    "FILE_RULES",
    "PROJECT_RULES",
    "Finding",
    "Project",
    "Rule",
    "SourceFile",
    "all_rules",
    "rule",
    "run_project",
]

REPO = Path(__file__).resolve().parent.parent.parent
PKG = REPO / "noise_ec_tpu"

_ALLOW = re.compile(r"#\s*noise-ec:\s*allow\(([A-Za-z0-9_,\- ]+)\)")
_LOOP_AFFINE = re.compile(r"#\s*noise-ec:\s*loop-affine\b")


@dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a repo-relative path + line."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Rule:
    id: str
    scope: str  # "file" | "project"
    invariant: str  # one-line statement of what must hold
    motivation: str  # the PR / incident that made it a rule
    check: Callable = field(repr=False, default=None)


FILE_RULES: dict[str, Rule] = {}
PROJECT_RULES: dict[str, Rule] = {}


def rule(id: str, *, scope: str, invariant: str, motivation: str):
    """Register a rule. File rules take ``(SourceFile) -> Iterable[
    Finding]``; project rules take ``(Project) -> Iterable[Finding]``."""
    if scope not in ("file", "project"):
        raise ValueError(f"bad rule scope {scope!r}")
    registry = FILE_RULES if scope == "file" else PROJECT_RULES

    def deco(fn):
        if id in FILE_RULES or id in PROJECT_RULES:
            raise ValueError(f"duplicate rule id {id!r}")
        registry[id] = Rule(
            id=id, scope=scope, invariant=invariant,
            motivation=motivation, check=fn,
        )
        return fn

    return deco


def all_rules() -> dict[str, Rule]:
    return {**FILE_RULES, **PROJECT_RULES}


class SourceFile:
    """One parsed Python source file plus its per-line suppressions."""

    def __init__(self, path: Path, root: Path = REPO,
                 text: Optional[str] = None):
        self.path = Path(path)
        try:
            self.rel = str(self.path.relative_to(root))
        except ValueError:
            self.rel = str(self.path)
        self.text = self.path.read_text(encoding="utf-8") if text is None else text
        self.tree = ast.parse(self.text, filename=self.rel)
        self.lines = self.text.splitlines()
        # line number (1-based) -> rule ids allowed there
        self.allows: dict[int, set[str]] = {}
        # line numbers carrying the loop-affine marker (annotating a def)
        self.loop_affine_lines: set[int] = set()
        for i, line in enumerate(self.lines, start=1):
            m = _ALLOW.search(line)
            if m:
                ids = {s.strip() for s in m.group(1).split(",") if s.strip()}
                self.allows.setdefault(i, set()).update(ids)
            if _LOOP_AFFINE.search(line):
                self.loop_affine_lines.add(i)

    def suppressed(self, rule_id: str, line: int) -> bool:
        """Allowed on the flagged line or the line directly above."""
        for ln in (line, line - 1):
            ids = self.allows.get(ln)
            if ids and rule_id in ids:
                return True
        return False


class Project:
    """The analyzable tree: package sources + docs + the live registry.

    ``metrics`` / ``pipeline_stages`` default to the real
    ``obs.registry`` declarations but are injectable so rule tests can
    pin firing behavior against synthetic registries without touching
    the production one.
    """

    def __init__(
        self,
        root: Path = REPO,
        package: Path = PKG,
        files: Optional[list[SourceFile]] = None,
        metrics: Optional[dict] = None,
        pipeline_stages: Optional[tuple] = None,
    ):
        self.root = Path(root)
        self.package = Path(package)
        if files is None:
            files = [
                SourceFile(p, root=self.root)
                for p in sorted(self.package.rglob("*.py"))
                if "__pycache__" not in p.parts
            ]
        self.files = files
        self._metrics = metrics
        self._pipeline_stages = pipeline_stages
        self._docs: dict[str, Optional[str]] = {}

    @property
    def metrics(self) -> dict:
        if self._metrics is None:
            from noise_ec_tpu.obs.registry import METRICS

            self._metrics = METRICS
        return self._metrics

    @property
    def pipeline_stages(self) -> tuple:
        if self._pipeline_stages is None:
            from noise_ec_tpu.obs.registry import PIPELINE_STAGES

            self._pipeline_stages = PIPELINE_STAGES
        return self._pipeline_stages

    def doc_text(self, relpath: str) -> Optional[str]:
        """The text of a repo doc (cached), or None when absent."""
        if relpath not in self._docs:
            p = self.root / relpath
            self._docs[relpath] = (
                p.read_text(encoding="utf-8") if p.exists() else None
            )
        return self._docs[relpath]

    def set_doc(self, relpath: str, text: Optional[str]) -> None:
        """Inject doc content (rule tests)."""
        self._docs[relpath] = text


def run_project(
    project: Optional[Project] = None,
    rule_ids: Optional[Iterable[str]] = None,
) -> list[Finding]:
    """Run the selected rules (default: all) over the project, dropping
    suppressed findings. Findings sort by (path, line, rule)."""
    project = project or Project()
    wanted = set(rule_ids) if rule_ids is not None else None
    findings: list[Finding] = []
    by_rel = {f.rel: f for f in project.files}
    for rid, r in FILE_RULES.items():
        if wanted is not None and rid not in wanted:
            continue
        for sf in project.files:
            for f in r.check(sf):
                if not sf.suppressed(f.rule, f.line):
                    findings.append(f)
    for rid, r in PROJECT_RULES.items():
        if wanted is not None and rid not in wanted:
            continue
        for f in r.check(project):
            sf = by_rel.get(f.path)
            if sf is not None and sf.suppressed(f.rule, f.line):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---------------------------------------------------------- AST helpers


def call_name(node: ast.Call) -> Optional[str]:
    """``foo(...)`` -> "foo"; ``a.b.c(...)`` -> "c"; else None."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def dotted(node: ast.expr) -> Optional[str]:
    """``a.b.c`` -> "a.b.c" for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
