"""Registry + docs discipline rules (the former tools/check_metrics.py).

These port the metric-name lints that have gated every PR since PR 1
into the analysis framework, as first-class rules with per-line
suppressions and corpus pins. ``tools/check_metrics.py`` remains as a
thin CLI shim over this module so existing invocations (and
tests/test_obs.py's ``check()``/``scan_source()`` contract) keep
working.

Rules:

- **metric-name** — every ``reg.counter("x")`` / ``.gauge`` /
  ``.histogram`` literal must be declared in ``obs.registry.METRICS``
  with the matching type (a typo forks a time series silently in looser
  systems; here the runtime raises, but only when the code path runs).
- **span-stage** — every ``span("x")`` literal must appear in
  ``PIPELINE_STAGES`` (span names become bounded ``stage`` label
  values).
- **metric-registry** — registry-level hygiene: no unused declarations,
  counters end in ``_total`` (and nothing else does), histogram
  generated series (``_bucket``/``_sum``/``_count``) collide with no
  declared family.
- **docs-observability** — every declared family and every span/dump
  schema field is documented in docs/observability.md.
- **docs-subsystem** — the two-home rule: each subsystem's families and
  operator surfaces (flags, endpoints, wire magics, class names) must
  appear in the doc that owns their semantics (resilience, device,
  object, cache, fleet, datapath, mesh, panel, wire, LRC).
- **docs-catalog** — docs/static-analysis.md's rule catalog matches the
  registered rule set, both directions.
"""

from __future__ import annotations

import ast
import re

from noise_ec_tpu.analysis.core import (
    Finding,
    Project,
    call_name,
    const_str,
    rule,
)

__all__ = [
    "scan_metric_calls",
    "scan_span_calls",
    "SUBSYSTEM_DOCS",
]

_METRIC_FACTORIES = ("counter", "gauge", "histogram")


def scan_metric_calls(project: Project) -> dict[str, list]:
    """name -> [(rel path, line, requested type), ...] across sources."""
    used: dict[str, list] = {}
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            mtype = call_name(node)
            if mtype not in _METRIC_FACTORIES or not node.args:
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            name = const_str(node.args[0])
            if name is not None:
                used.setdefault(name, []).append((sf.rel, node.lineno, mtype))
    return used


def scan_span_calls(project: Project) -> dict[str, list]:
    """span stage literal -> [(rel path, line), ...]. Only bare
    ``span("x")`` calls count — method spans (``tracer.span``) are the
    tracer's own API, the bare name is the package-wide helper."""
    used: dict[str, list] = {}
    for sf in project.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            if not (isinstance(node.func, ast.Name)
                    and node.func.id == "span"):
                continue
            if not node.args:
                continue
            name = const_str(node.args[0])
            if name is not None:
                used.setdefault(name, []).append((sf.rel, node.lineno))
    return used


def _registry_line(project: Project, name: str) -> tuple[str, int]:
    """Anchor a registry-level finding at the declaration line."""
    rel = "noise_ec_tpu/obs/registry.py"
    for sf in project.files:
        if sf.rel == rel:
            for i, line in enumerate(sf.lines, start=1):
                if f'"{name}"' in line:
                    return rel, i
    return rel, 1


@rule(
    "metric-name",
    scope="project",
    invariant="every metric name used in source is declared in "
              "obs.registry.METRICS with the matching type",
    motivation="PR 1 (declared-name registry; a typo forks a series "
               "silently in looser systems)",
)
def check_metric_names(project: Project):
    metrics = project.metrics
    for name, sites in sorted(scan_metric_calls(project).items()):
        decl = metrics.get(name)
        for rel, line, mtype in sites:
            if decl is None:
                yield Finding(
                    "metric-name", rel, line,
                    f"undeclared metric {name!r} (used as {mtype}); "
                    "declare it in noise_ec_tpu/obs/registry.py METRICS",
                )
            elif mtype != decl[0]:
                yield Finding(
                    "metric-name", rel, line,
                    f"metric {name!r} declared {decl[0]} but requested "
                    f"as {mtype}",
                )


@rule(
    "span-stage",
    scope="project",
    invariant="every span(\"x\") literal appears in "
              "obs.registry.PIPELINE_STAGES",
    motivation="PR 1/PR 2 (span names become 'stage' label values; the "
               "label set stays bounded only if the tuple is the single "
               "source of truth)",
)
def check_span_stages(project: Project):
    stages = project.pipeline_stages
    for stage, sites in sorted(scan_span_calls(project).items()):
        if stage in stages:
            continue
        for rel, line in sites:
            yield Finding(
                "span-stage", rel, line,
                f"span stage {stage!r} is not declared in "
                "obs.registry.PIPELINE_STAGES",
            )


@rule(
    "metric-registry",
    scope="project",
    invariant="no unused declarations; counters end in _total (nothing "
              "else does); histogram suffixes collide with no family",
    motivation="PR 1/PR 2 (dead registry entries rot the docs; "
               "Prometheus conventions; generated-series aliasing)",
)
def check_metric_registry(project: Project):
    metrics = project.metrics
    used = scan_metric_calls(project)
    for name in metrics:
        if name not in used:
            rel, line = _registry_line(project, name)
            yield Finding(
                "metric-registry", rel, line,
                f"declared metric {name!r} has no call site; remove it "
                "from METRICS or wire it up",
            )
    names = set(metrics)
    for name, (mtype, _, _) in metrics.items():
        rel, line = _registry_line(project, name)
        if mtype == "histogram":
            for g in (f"{name}_bucket", f"{name}_sum", f"{name}_count"):
                if g in names:
                    yield Finding(
                        "metric-registry", rel, line,
                        f"histogram {name!r} generates {g!r}, which is "
                        "also declared as its own metric",
                    )
        if mtype == "counter" and not name.endswith("_total"):
            yield Finding(
                "metric-registry", rel, line,
                f"counter {name!r} must end in '_total' (Prometheus "
                "convention)",
            )
        if mtype != "counter" and name.endswith("_total"):
            yield Finding(
                "metric-registry", rel, line,
                f"{mtype} {name!r} must not end in '_total'",
            )


@rule(
    "docs-observability",
    scope="project",
    invariant="every registry family and every span/dump schema field "
              "is documented in docs/observability.md",
    motivation="PR 3 (an undocumented series is invisible to the "
               "operator the docs' metric table exists for)",
)
def check_docs_observability(project: Project):
    doc = "docs/observability.md"
    text = project.doc_text(doc)
    if text is None:
        yield Finding("docs-observability", doc, 1, f"docs file {doc} missing")
        return
    for name in project.metrics:
        if not re.search(rf"\b{re.escape(name)}\b", text):
            yield Finding(
                "docs-observability", doc, 1,
                f"metric {name!r} is not documented in {doc} "
                "(registry table)",
            )
    try:
        from noise_ec_tpu.obs.server import SPANS_DOC_FIELDS
        from noise_ec_tpu.obs.trace import SPAN_FIELDS
    except Exception:  # pragma: no cover — synthetic projects
        return
    for field in SPAN_FIELDS:
        if f"`{field}`" not in text:
            yield Finding(
                "docs-observability", doc, 1,
                f"span field {field!r} (obs.trace.SPAN_FIELDS) is not "
                f"documented in {doc}",
            )
    for field in SPANS_DOC_FIELDS:
        if f"`{field}`" not in text:
            yield Finding(
                "docs-observability", doc, 1,
                f"/spans document key {field!r} "
                f"(obs.server.SPANS_DOC_FIELDS) is not documented in {doc}",
            )


# ------------------------------------------------------- subsystem parity

# The two-home rule, one row per subsystem: (doc path, metric-name
# prefixes that must ALSO appear there, exact extra family names, and
# the operator surfaces — flags/endpoints/magics/identifiers — that
# exist only as strings in the code so the METRICS walk cannot see them
# drift). The tables match tools/check_metrics.py's historical checks.
SUBSYSTEM_DOCS: dict[str, dict] = {
    "resilience": {
        "doc": "docs/resilience.md",
        "prefixes": ("noise_ec_peer_", "noise_ec_reconnect_",
                     "noise_ec_nack_", "noise_ec_codec_"),
        "extras": ("noise_ec_store_announces_total",),
        "tokens": (),
    },
    "device": {
        "doc": "docs/observability.md",
        "prefixes": (),
        "extras": (),
        "tokens": ("/profile", "/xprof", "-xprof-dir", "-profile",
                   "tools/bench_gate.py", "cost_analysis",
                   "DEVICE_LATENCY_BUCKETS"),
    },
    "object": {
        "doc": "docs/object-service.md",
        "prefixes": ("noise_ec_object_",),
        "extras": (),
        "tokens": ("/objects", "-object-port", "-tenants", "Retry-After",
                   "noise-ec-manifest/1"),
    },
    "cache": {
        "doc": "docs/object-service.md",
        "prefixes": (),
        "extras": (),
        "tokens": ("Read path", "DecodedObjectCache", "noise-ec-warmset/1",
                   "submit_shared", "X-NoiseEC-Route", "-object-cache-mb",
                   "object_get_hot_mb_per_s", "object_get_hit_rate"),
    },
    "fleet": {
        "doc": "docs/fleet.md",
        "prefixes": ("noise_ec_fleet_", "noise_ec_backpressure_"),
        "extras": (),
        "tokens": ("-fleet-profile", "-fleet-size", "-fleet-report",
                   "/fleet", "churn@", "Retry-After", "slow@",
                   "noisy=", "hedge="),
    },
    "datapath": {
        "doc": "docs/design.md",
        "prefixes": ("noise_ec_coalesce_", "noise_ec_device_buffer_pool_"),
        "extras": (),
        "tokens": ("CoalescingDispatcher", "DeviceBufferPool",
                   "donate_argnums", "copy_to_host_async", "submit_many",
                   "submit_shared", "matmul_stripes_many"),
    },
    "mesh": {
        "doc": "docs/design.md",
        "prefixes": ("noise_ec_mesh_",),
        "extras": (),
        "tokens": ("MeshRouter", "configure_mesh_router", "shard_map",
                   "pjit", "in_shardings", "out_shardings"),
    },
    "panel": {
        "doc": "docs/design.md",
        "prefixes": ("noise_ec_kernel_tile_",
                     "noise_ec_kernel_sublaunch_"),
        "extras": ("noise_ec_compile_cache_hits_total",),
        "tokens": ("gf2_matmul_pallas_panel_rows", "panel_plan",
                   "split_bits_rows_panels", "pack_words_lanes_blocked",
                   "decode1_words_bytesliced", "PANEL_TEMP_ALIVE_FRACTION",
                   "pl.when", "PANEL_XOR_BUDGET",
                   "PANEL_SUBLAUNCH_XOR_BUDGET", "sublaunch_count",
                   "input_output_aliases", "-compile-cache-dir",
                   "prewarm_ladder"),
    },
    "wire": {
        "doc": "docs/design.md",
        "prefixes": ("noise_ec_wire_",),
        "extras": (),
        "tokens": ("recv_into", "sendmsg", "SO_REUSEPORT", "verify_batch",
                   "SHARD_BATCH", "-recv-shards", "_FrameRing",
                   "broadcast_many"),
    },
    "federation": {
        "doc": "docs/observability.md",
        "prefixes": ("noise_ec_federate_",),
        "extras": (),
        "tokens": ("/fleet/metrics", "-federate", "parse_prometheus",
                   "MetricsFederator", "GAUGE_POLICIES"),
    },
    "incident": {
        "doc": "docs/observability.md",
        "prefixes": ("noise_ec_incident_",),
        "extras": (),
        "tokens": ("/incident", "-incident-dir", "FlightRecorder",
                   "--incident", "min_bundle_interval"),
    },
    "tenant-attribution": {
        "doc": "docs/object-service.md",
        "prefixes": (),
        "extras": ("noise_ec_object_op_seconds",
                   "noise_ec_object_tenant_shed_total"),
        "tokens": ("Tenant attribution", "object_get_p99_ms",
                   "tenant_isolation_p99_ratio"),
    },
    "hedge-qos": {
        "doc": "docs/object-service.md",
        "prefixes": ("noise_ec_hedge_", "noise_ec_lane_"),
        "extras": ("noise_ec_peer_fetch_seconds",),
        "tokens": ("Hedged", "X-NoiseEC-Hedge", "hedge_extra",
                   "hedge_floor_seconds", "hedge_ceiling_seconds",
                   "lane=", "weight=", "background_floor",
                   "object_get_p99_hedged_ms"),
    },
    "request-tracing": {
        "doc": "docs/observability.md",
        "prefixes": ("noise_ec_trace_",),
        "extras": (),
        "tokens": ("Request tracing", "X-NoiseEC-Trace", "request_trace",
                   "trace_id=", "--op", "hold_max_bytes", "sample_n",
                   "trace_overhead_pct", "trace_keep_rate",
                   "span-coverage"),
    },
    "placement": {
        "doc": "docs/placement.md",
        "prefixes": ("noise_ec_placement_",),
        "extras": (),
        "tokens": ("Topology.parse", "-topology", "domains@",
                   "killdomain@", "PlacementRing", "TargetedDelivery",
                   "Rebalancer", "straw2", "placement_fanout_ratio",
                   "rebalance_amplification", "prev_stripes",
                   "SHARD_BATCH"),
    },
    "wide-events": {
        "doc": "docs/observability.md",
        "prefixes": ("noise_ec_events_", "noise_ec_event_"),
        "extras": (),
        "tokens": ("/events", "EventLog", "EVENT_NAMES",
                   "event-on-swallow", "event_log_overhead_pct",
                   "suppressed"),
    },
    "diagnosis": {
        "doc": "docs/observability.md",
        "prefixes": ("noise_ec_diagnose_",),
        "extras": (),
        "tokens": ("/diagnose", "DiagnosisEngine", "slow-peer",
                   "noisy-tenant", "tools/diagnose.py",
                   "diagnose_verdict_ms", "add_flip_listener"),
    },
    "lrc": {
        "doc": "docs/lrc.md",
        "prefixes": ("noise_ec_lrc_", "noise_ec_convert_"),
        "extras": ("noise_ec_store_repair_shards_read_total",),
        "tokens": ("LocalReconstructionCode", "ConversionEngine",
                   "ConversionPolicy", "lrc:K/G+R", "archive=", "lrc@",
                   "-convert-interval", "repair_fetch_amplification",
                   "convert_mb_per_s", "prev_stripes"),
    },
}


@rule(
    "docs-subsystem",
    scope="project",
    invariant="each subsystem's metric families and operator surfaces "
              "appear in the doc that owns their semantics (the "
              "two-home rule)",
    motivation="PR 2 onward (every subsystem doc owns the fault model / "
               "API its series instrument)",
)
def check_docs_subsystem(project: Project):
    metrics = project.metrics
    for sub, spec in SUBSYSTEM_DOCS.items():
        names = [n for n in metrics if n.startswith(spec["prefixes"])] \
            if spec["prefixes"] else []
        names += [n for n in spec["extras"] if n in metrics]
        if not names and not spec["tokens"]:
            continue
        text = project.doc_text(spec["doc"])
        if text is None:
            if names:
                yield Finding(
                    "docs-subsystem", spec["doc"], 1,
                    f"docs file {spec['doc']} missing "
                    f"({sub} metrics exist)",
                )
            continue
        for n in names:
            if not re.search(rf"\b{re.escape(n)}\b", text):
                yield Finding(
                    "docs-subsystem", spec["doc"], 1,
                    f"{sub} metric {n!r} is not documented in "
                    f"{spec['doc']}",
                )
        for tok in spec["tokens"]:
            if tok not in text:
                yield Finding(
                    "docs-subsystem", spec["doc"], 1,
                    f"{sub} surface {tok} is not documented in "
                    f"{spec['doc']}",
                )


@rule(
    "docs-catalog",
    scope="project",
    invariant="docs/static-analysis.md's rule catalog matches the "
              "registered rule set, both directions",
    motivation="this PR (an analyzer whose rules drift from its catalog "
               "repeats the docs-drift failure mode it exists to catch)",
)
def check_docs_catalog(project: Project):
    from noise_ec_tpu.analysis.core import all_rules

    doc = "docs/static-analysis.md"
    text = project.doc_text(doc)
    if text is None:
        yield Finding(
            "docs-catalog", doc, 1,
            f"docs file {doc} missing (the rule catalog lives there)",
        )
        return
    registered = set(all_rules())
    for rid in sorted(registered):
        if f"`{rid}`" not in text:
            yield Finding(
                "docs-catalog", doc, 1,
                f"rule {rid!r} is not documented in {doc} (catalog "
                "table)",
            )
    # Stale catalog rows: ids documented as rules but not registered.
    for m in re.finditer(r"^\|\s*`([a-z0-9-]+)`", text, re.MULTILINE):
        rid = m.group(1)
        if rid not in registered:
            yield Finding(
                "docs-catalog", doc, 1,
                f"catalog documents rule {rid!r}, which is not "
                "registered in the analysis framework",
            )
