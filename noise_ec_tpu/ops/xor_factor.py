"""Greedy common-subexpression factoring for GF(2) XOR networks.

The geometry-baked kernels evaluate each output bit-plane as an XOR chain
over the input planes with a set bit in the expanded generator matrix
(gf/bitmatrix.py). For RS(10,4)/GF(2^8) that is a (32, 80) 0/1 matrix at
~50% density: ~1,230 two-input XORs evaluated straight off the rows.
Because generator rows are algebraically related, many column pairs
co-occur in several rows; Paar's greedy algorithm (Paar 1997, "Optimized
arithmetic for Reed-Solomon encoders") repeatedly materializes the most
frequent pair as a shared temporary, typically cutting the XOR count by
30-45% for these matrices. The factoring runs once per geometry at trace
time (host side, tiny matrices) and is baked into the compiled program.
"""

from __future__ import annotations

import functools
from collections import Counter


@functools.lru_cache(maxsize=512)
def paar_factor(
    bits_rows: tuple[tuple[int, ...], ...],
    num_inputs: int,
    min_freq: int = 2,
    max_temps: int = 100_000,
) -> tuple[tuple[tuple[int, int, int], ...], tuple[tuple[int, ...], ...]]:
    """Factor shared pairs out of XOR rows.

    Returns ``(ops, rows)``: ``ops`` is an ordered tuple of
    ``(temp_id, a, b)`` meaning ``t[temp_id] = t[a] ^ t[b]`` (ids >=
    ``num_inputs`` are temporaries, evaluated in order); ``rows[r]`` is the
    remaining term tuple for output row r over inputs and temporaries.
    Total two-input XORs = ``len(ops) + sum(max(len(row)-1, 0))``.

    Incremental implementation: the pair-frequency table is built once and
    updated only for the rows a factoring touches, with a lazy max-heap
    over (frequency, pair); distinct pairs are bounded by the (small)
    column count squared, not by rows x terms^2. ``min_freq`` stops the
    factoring when the best pair saves fewer than ``min_freq - 1`` XORs
    per round; ``max_temps`` bounds temporary count (VMEM pressure in the
    baked kernels).
    """
    import heapq

    import numpy as np

    rows = [set(t) for t in bits_rows]
    where: dict[int, set[int]] = {}  # column id -> set of row indices
    for ri, s in enumerate(rows):
        for c in s:
            where.setdefault(c, set()).add(ri)
    # Initial pair table via one co-occurrence matmul: distinct pairs are
    # bounded by num_inputs^2, far below rows x terms^2 Counter updates.
    M = np.zeros((len(rows), num_inputs), dtype=np.int32)
    for ri, s in enumerate(rows):
        M[ri, list(s)] = 1
    P = M.T @ M
    iu = np.triu_indices(num_inputs, k=1)
    nz = P[iu] > 0
    cnt: Counter = Counter(
        {
            (int(a), int(b)): int(f)
            for a, b, f in zip(iu[0][nz], iu[1][nz], P[iu][nz])
        }
    )
    heap = [(-f, p) for p, f in cnt.items()]
    heapq.heapify(heap)

    def bump(pair: tuple[int, int]) -> None:
        cnt[pair] += 1
        heapq.heappush(heap, (-cnt[pair], pair))

    ops: list[tuple[int, int, int]] = []
    next_id = num_inputs
    while heap and len(ops) < max_temps:
        negf, (a, b) = heapq.heappop(heap)
        cur = cnt.get((a, b), 0)
        if cur != -negf:  # stale lazy-heap entry
            # Decrements don't push, so a pair whose only entry went stale
            # would otherwise vanish from the heap while still profitable:
            # re-enqueue it at its current count.
            if cur >= min_freq:
                heapq.heappush(heap, (-cur, (a, b)))
            continue
        if -negf < min_freq:
            break
        t = next_id
        next_id += 1
        ops.append((t, a, b))
        affected = where[a] & where[b]
        del cnt[(a, b)]
        where[t] = set()
        for ri in affected:
            s = rows[ri]
            s.discard(a)
            s.discard(b)
            for x in s:
                pa = (min(a, x), max(a, x))
                pb = (min(b, x), max(b, x))
                cnt[pa] -= 1
                cnt[pb] -= 1
                bump((x, t))  # x < t always: temps get the largest ids
            s.add(t)
            where[a].discard(ri)
            where[b].discard(ri)
            where[t].add(ri)
    return tuple(ops), tuple(tuple(sorted(s)) for s in rows)


def eval_factored(ops, rows, get_input, make_zero):
    """Evaluate a factored XOR network during kernel tracing.

    ``get_input(c)`` fetches input plane c; ``make_zero()`` builds an
    all-zero tile for empty rows. Returns the list of output-row values.
    Temps live in the traced SSA graph — the dict here only spans tracing,
    so compiled liveness is last-use, not whole-program.
    """
    vals: dict[int, object] = {}

    def get(c):
        return vals[c] if c in vals else get_input(c)

    for t, a, b in ops:
        vals[t] = get(a) ^ get(b)
    outs = []
    for terms in rows:
        if not terms:
            outs.append(make_zero())
            continue
        acc = get(terms[0])
        for c in terms[1:]:
            acc = acc ^ get(c)
        outs.append(acc)
    return outs


def eval_bits_rows(bits_rows, C: int, get_plane, make_zero,
                   max_temps: int = 100_000):
    """Factor ``bits_rows`` and evaluate it over input planes 0..C-1.

    The one entry point both baked kernels (the fused single-kernel encode
    and the standalone sparse matmul) trace through: hoists each used input
    plane once via ``get_plane``, then runs the factored network. Returns
    the list of output-row values. ``max_temps`` caps the temporary count
    (VMEM stack pressure) at the price of more XORs — see paar_factor.
    """
    ops, rows = paar_factor(bits_rows, C, max_temps=max_temps)
    used = {c for terms in rows for c in terms if c < C}
    used |= {c for _, a, b in ops for c in (a, b) if c < C}
    vs = {c: get_plane(c) for c in sorted(used)}
    return eval_factored(ops, rows, vs.__getitem__, make_zero)


def xor_cost(bits_rows: tuple[tuple[int, ...], ...]) -> int:
    """Two-input XOR count of the unfactored row evaluation."""
    return sum(max(len(t) - 1, 0) for t in bits_rows)


# --------------------------------------------------------------------- panels
#
# The block-panel kernels (pallas_gf2mm "panel tier") split a wide
# (R, C) network into a 2-D grid of (RB output-rows x KB input-cols)
# panels and evaluate one panel's sub-network per grid step. Factoring
# runs PER PANEL, which is what makes near-field-limit geometries
# plannable at all: Paar is super-linear in terms, so the whole
# RS(200,56) network (~361k raw XORs) ran >9 min while its 64x128
# panels factor in seconds total — and the temp count (VMEM stack
# pressure) is bounded per panel instead of per program.


def split_bits_rows_panels(
    bits_rows: tuple[tuple[int, ...], ...], C: int, KB: int, RB: int
) -> tuple[tuple[tuple[tuple[int, ...], ...], ...], ...]:
    """Partition an (R rows x C cols) network into ceil(R/RB) x
    ceil(C/KB) panels.

    ``out[pr][pk]`` is the sub-network of output rows
    [pr*RB, (pr+1)*RB) over input columns [pk*KB, (pk+1)*KB), columns
    re-indexed to the panel-local [0, KB) range. A padded final row
    block simply carries empty rows; a padded final column block has
    columns no term references — XOR over GF(2) is associative and
    commutative, so the row sum of a panel row over all pk panels
    equals the original row.
    """
    R = len(bits_rows)
    PR = -(-R // RB) if R else 1
    PK = -(-C // KB) if C else 1
    out = []
    for pr in range(PR):
        rows = bits_rows[pr * RB : (pr + 1) * RB]
        rows = rows + ((),) * (RB - len(rows))
        row_panels = []
        for pk in range(PK):
            lo, hi = pk * KB, (pk + 1) * KB
            row_panels.append(
                tuple(
                    tuple(c - lo for c in row if lo <= c < hi)
                    for row in rows
                )
            )
        out.append(tuple(row_panels))
    return tuple(out)


def panel_raw_costs(panels) -> tuple[int, int]:
    """(total, max_single) raw XOR cost over a panel grid — the
    planner's cheap pre-factoring score inputs."""
    costs = [xor_cost(p) for row in panels for p in row]
    return sum(costs), max(costs) if costs else 0


def factor_panels(panels, KB: int, max_temps: int = 100_000):
    """Factor every panel (cached per panel via paar_factor) and return
    ``(total_factored_cost, max_temps_used)`` — the exact numbers the
    VMEM model and the tile telemetry report, where the planner's
    pre-factoring estimates were ratios."""
    total = 0
    worst = 0
    for row in panels:
        for p in row:
            ops, rem = paar_factor(p, KB, max_temps=max_temps)
            total += factored_cost(ops, rem)
            worst = max(worst, len(ops))
    return total, worst


def factored_cost(
    ops: tuple[tuple[int, int, int], ...], rows: tuple[tuple[int, ...], ...]
) -> int:
    return len(ops) + sum(max(len(t) - 1, 0) for t in rows)
