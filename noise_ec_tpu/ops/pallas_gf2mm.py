"""Pallas TPU kernel for the bitsliced GF(2) matmul (the encode/reconstruct
hot loop — SURVEY.md §3.5, north-star "bitsliced Pallas kernels").

Formulation: out (R, W) = B (R, C) @ planes (C, W) over GF(2), computed as
an AND/XOR accumulation on uint32 lanes:

    for c in range(C): acc ^= maskT[c, :, None] & planes[c, None, :]

- ``maskT`` is the (C, R) *transposed* select-mask matrix (rows are read with
  a dynamic leading index, which the TPU lowers cheaply).
- The grid tiles the stripe-word axis W; masks and the full C-row plane tile
  live in VMEM. R and C are multiples of 8 by construction (8 or 16 planes
  per shard), W tiles are multiples of 128 — aligned to the (8, 128) int32
  layout.
- The same kernel serves encode (masks = parity rows of the generator) and
  reconstruct (masks = inverted-submatrix rows): only the mask operand
  changes (reference equivalents: main.go:262 and main.go:77).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_TILE_WORDS = 512
DEFAULT_TILE_LANES = 512
# Scoped-VMEM ceiling for one grid step's buffers. The hardware limit is
# 16 MiB; Pallas double-buffers grid inputs/outputs, so wide codes (e.g.
# RS(50,20): 400 input + 160 output plane-rows) must shrink the lane tile
# or the launch OOMs at compile time.
VMEM_BUDGET_BYTES = 14 << 20
# Paar temporaries ((8, TL) uint32 each) also live on the Mosaic stack.
# Counting every temp at full size over-estimates (the allocator reuses
# slots as liveness ends); 0.4 is calibrated against observed compiles
# of WHOLE-PLANE kernels — grid steps that evaluate the full factored
# network over all C input rows, where liveness windows are long enough
# for the allocator to overlap ~60% of the temps (anchors: RS(50,20)
# sparse at TL=256 OOMed at 24.7M scoped and must reject; the fused
# RS(50,20) kernel at TL=128 compiled and must accept —
# tests/test_panel.py pins both boundaries). It is NOT valid for the
# block-panel tier: see PANEL_TEMP_ALIVE_FRACTION below.
TEMP_ALIVE_FRACTION = 0.4

# Panel-tier temp accounting. A panel kernel evaluates ONE small
# sub-network per grid step inside a lax.switch branch; Mosaic's stack
# overlap across branch boundaries is unmeasured, and the planner caps
# the per-panel temp count explicitly (paar_factor max_temps derived
# from VMEM headroom), so every temp is counted at FULL size — the cap,
# not an overlap fraction, is what keeps the estimate honest. The
# accept/reject boundary cases are pinned in tests/test_panel.py so the
# estimator cannot silently OOM a launch.
PANEL_TEMP_ALIVE_FRACTION = 1.0


def xor_temp_bytes_per_lane(bits_rows: tuple, C: int) -> int:
    """Estimated per-lane stack bytes of the factored network's temps."""
    from noise_ec_tpu.ops.xor_factor import paar_factor

    ops, _ = paar_factor(bits_rows, C)
    return int(len(ops) * 8 * 4 * TEMP_ALIVE_FRACTION)


def _kernel(maskT_ref, planes_ref, out_ref):
    C = planes_ref.shape[0]
    R = maskT_ref.shape[1]
    TW = planes_ref.shape[1]

    def body(c, acc):
        m = maskT_ref[c, :]  # (R,)
        p = planes_ref[c, :]  # (TW,)
        return acc ^ (m[:, None] & p[None, :])

    out_ref[:, :] = jax.lax.fori_loop(
        0, C, body, jnp.zeros((R, TW), dtype=jnp.uint32)
    )


@functools.partial(jax.jit, static_argnames=("tile_words", "interpret"))
def gf2_matmul_pallas(
    masks: jnp.ndarray,
    planes: jnp.ndarray,
    *,
    tile_words: int = DEFAULT_TILE_WORDS,
    interpret: bool = False,
) -> jnp.ndarray:
    """(R, C) uint32 masks x (C, W) uint32 planes -> (R, W) uint32.

    W is padded to a tile boundary internally; output is sliced back.
    """
    R, C = masks.shape
    Cp, W = planes.shape
    assert C == Cp, (C, Cp)
    TW = min(tile_words, max(128, -(-W // 128) * 128))
    Wpad = -(-W // TW) * TW
    if Wpad != W:
        planes = jnp.pad(planes, ((0, 0), (0, Wpad - W)))
    maskT = masks.T  # (C, R): dynamic *row* reads inside the kernel

    out = pl.pallas_call(
        _kernel,
        grid=(Wpad // TW,),
        in_specs=[
            pl.BlockSpec((C, R), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((C, TW), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, TW), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, Wpad), jnp.uint32),
        interpret=interpret,
    )(maskT, planes)
    return out[:, :W] if Wpad != W else out


# ---------------------------------------------------------------------------
# Geometry-specialized sparse kernel (the fast path)
#
# The dense kernel above broadcasts a lane-vector of masks across sublanes
# every iteration and does ~4x the necessary work (AND+XOR over zero entries).
# This version bakes the bit-matrix into the program at trace time (the
# reference's runtime-dynamic geometry is handled by caching one compiled
# kernel per generator matrix — SURVEY.md §7.4): each output plane-row is a
# balanced XOR tree over exactly the input rows with a set bit. Planes use a
# "tiled" (C, 8, W8) layout so every XOR is a full (8, lanes) vreg op with no
# relayouts.


def planes_to_tiled(planes: jnp.ndarray) -> jnp.ndarray:
    """(C, W) packed planes -> (C, 8, W/8) tiled layout (pure reshape).

    Word w of plane c lands at [c, w // (W//8), w % (W//8)]... i.e. row-major
    reshape; all codec ops are positionwise so any fixed bijection works as
    long as pack/compute/unpack agree.
    """
    C, W = planes.shape
    if W % 8:
        planes = jnp.pad(planes, ((0, 0), (0, 8 - W % 8)))
        W = planes.shape[1]
    return planes.reshape(C, 8, W // 8)


def tiled_to_planes(tiled: jnp.ndarray, num_words: int) -> jnp.ndarray:
    C = tiled.shape[0]
    return tiled.reshape(C, -1)[:, :num_words]


def _make_sparse_kernel(bits_rows: tuple[tuple[int, ...], ...], C: int,
                        sublanes: int, TL: int):
    """bits_rows[r] = tuple of input-row indices feeding output row r.

    Measured-on-v5e structure (see git history for the experiment): hoist ONE
    VMEM read per input plane per grid step, then XOR evaluation per output
    row through the Paar-factored network (ops/xor_factor.py, ~2-3x fewer
    XORs than the raw chains). Per-row reads (C*density loads instead of C)
    cost 4x; tree reduction instead of chains costs ~25%. This shape runs at
    the HBM roofline (~650 GB/s data-in for RS(10,4)).
    """
    from noise_ec_tpu.ops.xor_factor import eval_bits_rows

    def kernel(planes_ref, out_ref):
        outs = eval_bits_rows(
            bits_rows, C,
            lambda c: planes_ref[c, :, :],
            lambda: jnp.zeros((sublanes, TL), dtype=jnp.uint32),
        )
        for r, val in enumerate(outs):
            out_ref[r, :, :] = val

    return kernel


@functools.lru_cache(maxsize=512)
def _sparse_call(bits_rows: tuple[tuple[int, ...], ...], C: int, W8: int, TL: int,
                 interpret: bool):
    R = len(bits_rows)
    kernel = _make_sparse_kernel(bits_rows, C, 8, TL)
    grid = (W8 // TL,)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((C, 8, TL), lambda i: (0, 0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, 8, TL), lambda i: (0, 0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, 8, W8), jnp.uint32),
        interpret=interpret,
    )


def _tiled_dense_kernel(maskT_ref, planes_ref, out_ref):
    # Per-sublane 2D broadcasts: Mosaic's layout inference rejects the 3D
    # (R,1,1)x(1,8,TL) broadcast, so unroll the 8 sublane rows statically.
    C = planes_ref.shape[0]
    R = maskT_ref.shape[1]
    TL = planes_ref.shape[2]
    for s in range(planes_ref.shape[1]):
        def body(c, acc, s=s):
            m = maskT_ref[c, :]  # (R,)
            p = planes_ref[c, s, :]  # (TL,)
            return acc ^ (m[:, None] & p[None, :])

        out_ref[:, s, :] = jax.lax.fori_loop(
            0, C, body, jnp.zeros((R, TL), dtype=jnp.uint32)
        )


@functools.partial(jax.jit, static_argnames=("tile_lanes", "interpret"))
def gf2_matmul_pallas_tiled(
    masks: jnp.ndarray,
    tiled_planes: jnp.ndarray,
    *,
    tile_lanes: int = DEFAULT_TILE_LANES,
    interpret: bool = False,
) -> jnp.ndarray:
    """Dense-mask GF(2) matmul on TILED (C, 8, W8) planes -> (R, 8, W8).

    Unlike the geometry-baked sparse kernel, the mask matrix is an
    OPERAND — use it when the matrix changes per call and a recompile per
    geometry is unacceptable. NOT on any production hot path: the mesh TP
    path (parallel/batch.py) instead selects per-device geometry-baked
    sparse programs with lax.switch, which measured ~13x faster than
    this kernel. Kept as the runtime-dynamic-matrix option, tested in
    tests/test_pallas_pack.py.
    """
    R, C = masks.shape
    Cp, sub, W8 = tiled_planes.shape
    assert C == Cp and sub == 8, (masks.shape, tiled_planes.shape)
    per_lane = (C + R) * sub * 4 * 2
    cap = max(128, VMEM_BUDGET_BYTES // per_lane // 128 * 128)
    TL = min(tile_lanes, cap, max(128, -(-W8 // 128) * 128))
    W8p = -(-W8 // TL) * TL
    if W8p != W8:
        tiled_planes = jnp.pad(tiled_planes, ((0, 0), (0, 0), (0, W8p - W8)))
    maskT = masks.T  # (C, R): dynamic row reads in the kernel

    out = pl.pallas_call(
        _tiled_dense_kernel,
        grid=(W8p // TL,),
        in_specs=[
            pl.BlockSpec((C, R), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((C, 8, TL), lambda i: (0, 0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, 8, TL), lambda i: (0, 0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, 8, W8p), jnp.uint32),
        interpret=interpret,
    )(maskT, tiled_planes)
    return out[:, :, :W8] if W8p != W8 else out


def bits_to_rows(bits) -> tuple[tuple[int, ...], ...]:
    """(R, C) 0/1 matrix -> hashable per-output-row term tuples."""
    import numpy as _np

    bits = _np.asarray(bits)
    return tuple(
        tuple(int(c) for c in _np.nonzero(bits[r])[0]) for r in range(bits.shape[0])
    )


def sparse_lane_tl(bits_rows: tuple, C: int, W8: int,
                   tile_lanes: int = DEFAULT_TILE_LANES) -> int:
    """The whole-plane sparse kernel's lane-tile choice: double-buffered
    in+out bytes per lane of tile, plus the factored network's
    temporaries (TEMP_ALIVE_FRACTION-scaled), capped to the VMEM
    budget. Exposed so the calibration boundary tests can pin the
    accept/reject edge without building a kernel."""
    per_lane = (C + len(bits_rows)) * 8 * 4 * 2 + xor_temp_bytes_per_lane(
        bits_rows, C
    )
    cap = max(128, VMEM_BUDGET_BYTES // per_lane // 128 * 128)
    return min(tile_lanes, cap, max(128, -(-W8 // 128) * 128))


def gf2_matmul_pallas_sparse_rows(
    bits_rows: tuple[tuple[int, ...], ...],  # STATIC: baked into the kernel
    tiled_planes: jnp.ndarray,  # (C, 8, W8) uint32
    *,
    tile_lanes: int = DEFAULT_TILE_LANES,
    interpret: bool = False,
) -> jnp.ndarray:
    """Sparse geometry-specialized GF(2) matmul in tiled layout.

    Returns (R, 8, W8) uint32. W8 is padded to a tile boundary internally.
    """
    C, sub, W8 = tiled_planes.shape
    assert sub == 8, tiled_planes.shape
    TL = sparse_lane_tl(bits_rows, C, W8, tile_lanes)
    W8p = -(-W8 // TL) * TL
    if W8p != W8:
        tiled_planes = jnp.pad(tiled_planes, ((0, 0), (0, 0), (0, W8p - W8)))
    out = _sparse_call(bits_rows, C, W8p, TL, interpret)(tiled_planes)
    return out[:, :, :W8] if W8p != W8 else out


def gf2_matmul_pallas_sparse(
    bits,  # (R, C) numpy 0/1 — STATIC: baked into the kernel
    tiled_planes: jnp.ndarray,
    *,
    tile_lanes: int = DEFAULT_TILE_LANES,
    interpret: bool = False,
) -> jnp.ndarray:
    return gf2_matmul_pallas_sparse_rows(
        bits_to_rows(bits), tiled_planes, tile_lanes=tile_lanes, interpret=interpret
    )


# ---------------------------------------------------------------------------
# Block-panel K-tiled kernel: the WIDE-GEOMETRY tier.
#
# The whole-plane kernels above grid only over the lane axis and keep all
# C input plane-rows plus all R output rows resident in VMEM per grid
# step, so wide codes must shrink the lane tile to dodge the VMEM ceiling
# (RS(50,20) already forces TL=128) and near-field-limit codes
# (RS(200,56): C=1600, R=448 plane rows) cannot fit at ANY tile. This
# tier adds a K dimension (input-row axis) to the Pallas grid:
#
#   grid = (PR, NL, PK)          # R-blocks x lane tiles x K-blocks
#   planes block (KB, 8, TL)     index (pr, i, pk) -> (pk, 0, i)
#   out block    (RB, 8, TL)     index (pr, i, pk) -> (pr, 0, i)
#
# The out BlockSpec ignores the (innermost, fastest-varying) K axis, so
# Pallas keeps the output tile VMEM-resident across the K steps and each
# step XOR-accumulates its panel's partial into it — revision-safe via
# @pl.when on the first K step (the MXU-matmul accumulator idiom), so no
# garbage from a previous (pr, i) tile ever leaks in. VMEM per step is
# (KB + RB) plane rows plus ONE panel's capped temporaries, independent
# of C and R — which is what buys wide codes TL >= 256 instead of
# falling off the route.
#
# Each (pr, pk) panel's sub-network is geometry-baked (Paar-factored
# PER PANEL — xor_factor.split_bits_rows_panels; factoring whole
# near-limit networks ran >9 min, panels factor in seconds) and selected
# by lax.switch on the flattened panel id; the compiled program contains
# every panel exactly once, the grid loop executes one per step.


# A panel program's instruction count is O(total factored XORs) even
# though each grid step runs one panel: every panel's branch is traced
# into the switch. Past this raw-XOR budget the program is not worth
# baking and the matrix routes to the dense MXU kernel instead (on the
# interpret/CPU tier the budget is far lower — ops/dispatch.py).
PANEL_XOR_BUDGET = 600_000

# Per-SUB-LAUNCH raw-XOR budget. A single pallas_call carrying the whole
# K axis bakes every panel's branch into ONE Mosaic program, and Mosaic
# has a program-size limit independent of VMEM: the RS(200,56) panel
# program (~361k raw XORs, ~132k factored ops) trips it on v5e even
# though every grid step fits VMEM. Instead of demoting the matrix to
# the dense MXU tier (whose int8 roofline at r=56 is ~110 GB/s), the
# planner splits the K-BLOCK axis into G K-grid-major sub-launches —
# each its own pallas_call over a contiguous K-block slice with the
# same (KB, RB, TL) plan, chained by XOR accumulation into the
# HBM-resident output (see gf2_matmul_pallas_panel_rows). G is picked
# up front as ceil(raw / budget), capped at PK (one K-block per
# launch); raw XORs are the deliberately RATIO-FREE currency here so
# the G boundary is deterministic and pinnable (the factored op count
# depends on per-panel Paar yield, which the planner only estimates).
# The AOT compile probe confirms the choice and escalates G when
# Mosaic still rejects (ops/dispatch.py panel_plan_for).
PANEL_SUBLAUNCH_XOR_BUDGET = 130_000


def sublaunch_count(raw_xors: int, PK: int) -> int:
    """The program-size model's G: K-grid sub-launches for a panel
    network of ``raw_xors`` over ``PK`` K-blocks. 1 = single launch
    (the largest such plan is pinned in tests/test_panel.py, as is the
    smallest G=2 split)."""
    G = max(1, -(-raw_xors // PANEL_SUBLAUNCH_XOR_BUDGET))
    return min(G, max(1, PK))


def sublaunch_bounds(PK: int, G: int) -> list[int]:
    """Even contiguous partition of PK K-blocks into G chunks:
    boundaries[g]..boundaries[g+1] is sub-launch g's K-block slice.
    Round-to-nearest keeps chunk sizes within one block of each other
    and every chunk non-empty for G <= PK."""
    return [round(g * PK / G) for g in range(G + 1)]


def panel_vmem_bytes(KB: int, RB: int, TL: int, temps: int) -> int:
    """VMEM bytes of one panel-kernel grid step: double-buffered input
    panel, revisited output tile (counted twice — Pallas may overlap the
    writeback of tile (pr, i) with the first K step of the next), and
    one panel's temporaries at PANEL_TEMP_ALIVE_FRACTION (= full size;
    the planner caps the count instead of guessing overlap)."""
    blocks = (2 * KB + 2 * RB) * 8 * TL * 4
    return blocks + int(temps * 8 * TL * 4 * PANEL_TEMP_ALIVE_FRACTION)


def panel_temp_cap(KB: int, RB: int, TL: int) -> int:
    """Largest per-panel temp count whose working set still fits the
    VMEM budget at (KB, RB, TL) — the max_temps handed to the per-panel
    Paar factoring. <= 0 means the tile triple cannot fit at all."""
    headroom = VMEM_BUDGET_BYTES - (2 * KB + 2 * RB) * 8 * TL * 4
    return int(headroom // (8 * TL * 4 * PANEL_TEMP_ALIVE_FRACTION))


# Pre-factoring estimates for the candidate scan (factoring every
# candidate would cost seconds each): factored/raw cost ratio measured
# on 64x128 panels of real generator networks (RS(50,20) 0.38,
# RS(100,30) 0.38, RS(200,56) 0.37; 0.45 keeps the estimate
# conservative), and the same wide-tile preference the fused planner
# measured.
_PANEL_FACTOR_RATIO = 0.45
_PANEL_TL_FACTOR = {512: 1.0, 256: 1.08, 128: 1.15}


@functools.lru_cache(maxsize=512)
def panel_plan(bits_rows: tuple, C: int) -> tuple:
    """Auto-tuned (KB, RB, TL, temp_cap, G) for the panel kernel.

    Scored by estimated VPU bytes per input byte from the same VMEM
    cost model the whole-plane kernels use — factored network cost
    (ratio-estimated; the chosen plan's panels are factored exactly at
    build time under ``temp_cap``) plus the K-step accumulate traffic
    ((PK-1) XOR+write passes over the R output rows) — instead of the
    single shrinking lane knob. ``G`` is the program-size model's
    sub-launch count (:func:`sublaunch_count`): how many K-grid-major
    pallas_call programs the network splits into so no single Mosaic
    program exceeds PANEL_SUBLAUNCH_XOR_BUDGET. The roofline telemetry
    attributes the result per tile triple (``noise_ec_kernel_tile_*``,
    obs/device.py), which is how a mis-scored plan shows up instead of
    hiding inside one aggregate kernel series. Raises ValueError when
    no tile triple fits VMEM (cannot happen for KB=RB=32, TL=128 under
    the 14 MiB budget, but the model guards it anyway).
    """
    from noise_ec_tpu.ops.xor_factor import xor_cost

    R = len(bits_rows)
    raw = xor_cost(bits_rows)
    density = raw / max(1, R * C)
    best = None
    for TL in (512, 256, 128):
        for KB in (256, 128, 64, 32):
            for RB in (256, 128, 64, 32):
                cap = panel_temp_cap(KB, RB, TL)
                if cap < 32:  # factoring needs real headroom to help
                    continue
                PK = -(-C // KB)
                # Factoring yield degrades when the VMEM headroom caps
                # the per-panel temps below what an unconstrained Paar
                # pass would use (~1/14 of the panel's terms, measured
                # on 64x128 panels of real generator networks): the
                # ratio interpolates linearly from the measured
                # factored ratio back toward raw cost.
                temps_want = max(1, int(KB * RB * density / 7))
                ratio = _PANEL_FACTOR_RATIO
                if cap < temps_want:
                    ratio += (1.0 - ratio) * (1.0 - cap / temps_want)
                # Panel evaluation + per-K-step accumulate into the
                # revisited output tile (read + XOR + write ~ 3 passes
                # counted as ops over R rows per extra K step).
                est = raw * ratio + (PK - 1) * R * 3
                score = _PANEL_TL_FACTOR[TL] * 32 * est
                # Larger panels factor better and switch less; prefer
                # them at equal score.
                key = (score, -KB, -RB)
                if best is None or key < best[0]:
                    best = (key, (KB, RB, TL, min(cap, 4096)))
    if best is None:
        raise ValueError(
            f"no panel tile fits VMEM for R={R}, C={C}"
        )
    KB = best[1][0]
    return best[1] + (sublaunch_count(raw, -(-C // KB)),)


def _make_panel_kernel(nets_flat: tuple, PK: int, KB: int, RB: int,
                       TL: int, temp_cap: int):
    """nets_flat[pr * PK + pk] = the (pr, pk) panel's local sub-network
    (RB rows over [0, KB) columns)."""
    from noise_ec_tpu.ops.xor_factor import eval_bits_rows

    def kernel(planes_ref, out_ref):
        pr = pl.program_id(0)
        pk = pl.program_id(2)
        x = planes_ref[...]  # (KB, 8, TL)

        def branch(net):
            def f(xv):
                outs = eval_bits_rows(
                    net, KB,
                    lambda c: xv[c],
                    lambda: jnp.zeros((8, TL), dtype=jnp.uint32),
                    max_temps=temp_cap,
                )
                return jnp.stack(outs)

            return f

        partial = jax.lax.switch(
            pr * PK + pk, [branch(n) for n in nets_flat], x
        )

        @pl.when(pk == 0)
        def _init():
            out_ref[...] = partial

        @pl.when(pk != 0)
        def _accumulate():
            out_ref[...] = out_ref[...] ^ partial

    return kernel


def _record_sublaunch_program() -> None:
    """Count one freshly built sub-launch pallas_call program (the
    _panel_call* builder bodies run on lru-cache miss only, so this is
    the distinct-program count the compile-churn telemetry watches)."""
    try:
        from noise_ec_tpu.obs.registry import default_registry

        default_registry().counter(
            "noise_ec_kernel_sublaunch_programs_total"
        ).labels().add(1)
    except Exception:  # noqa: BLE001 — telemetry must not fail a build
        pass


@functools.lru_cache(maxsize=128)
def _panel_call(nets_flat: tuple, PR: int, PK: int, Cp: int, W8p: int,
                KB: int, RB: int, TL: int, temp_cap: int, interpret: bool):
    _record_sublaunch_program()
    kernel = _make_panel_kernel(nets_flat, PK, KB, RB, TL, temp_cap)
    return pl.pallas_call(
        kernel,
        grid=(PR, W8p // TL, PK),
        in_specs=[
            pl.BlockSpec((KB, 8, TL), lambda pr, i, pk: (pk, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((RB, 8, TL), lambda pr, i, pk: (pr, 0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((PR * RB, 8, W8p), jnp.uint32),
        interpret=interpret,
    )


def _make_panel_acc_kernel(nets_flat: tuple, PK: int, KB: int, RB: int,
                           TL: int, temp_cap: int):
    """The non-first sub-launch's kernel: same panel evaluation as
    _make_panel_kernel, but the first K step of each (pr, i) tile XORs
    the previous sub-launch's accumulator tile in instead of
    initializing from zero — so the chain of G launches computes the
    same sum as one launch over the whole K axis (XOR is abelian)."""
    from noise_ec_tpu.ops.xor_factor import eval_bits_rows

    def kernel(acc_ref, planes_ref, out_ref):
        pr = pl.program_id(0)
        pk = pl.program_id(2)
        x = planes_ref[...]  # (KB, 8, TL)

        def branch(net):
            def f(xv):
                outs = eval_bits_rows(
                    net, KB,
                    lambda c: xv[c],
                    lambda: jnp.zeros((8, TL), dtype=jnp.uint32),
                    max_temps=temp_cap,
                )
                return jnp.stack(outs)

            return f

        partial = jax.lax.switch(
            pr * PK + pk, [branch(n) for n in nets_flat], x
        )

        @pl.when(pk == 0)
        def _init():
            out_ref[...] = acc_ref[...] ^ partial

        @pl.when(pk != 0)
        def _accumulate():
            out_ref[...] = out_ref[...] ^ partial

    return kernel


@functools.lru_cache(maxsize=128)
def _panel_call_acc(nets_flat: tuple, PR: int, PK: int, W8p: int,
                    KB: int, RB: int, TL: int, temp_cap: int,
                    interpret: bool):
    """Accumulating sub-launch: (acc (PR*RB, 8, W8p), planes slice) ->
    acc ^ partial. The accumulator is DONATED between launches via
    ``input_output_aliases={0: 0}`` — XLA reuses its HBM buffer for the
    output, so chaining G sub-launches costs zero extra HBM copies of
    the output panel (the accumulator-donation rule, design.md §14)."""
    _record_sublaunch_program()
    kernel = _make_panel_acc_kernel(nets_flat, PK, KB, RB, TL, temp_cap)
    return pl.pallas_call(
        kernel,
        grid=(PR, W8p // TL, PK),
        in_specs=[
            pl.BlockSpec((RB, 8, TL), lambda pr, i, pk: (pr, 0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((KB, 8, TL), lambda pr, i, pk: (pk, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((RB, 8, TL), lambda pr, i, pk: (pr, 0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((PR * RB, 8, W8p), jnp.uint32),
        input_output_aliases={0: 0},
        interpret=interpret,
    )


def gf2_matmul_pallas_panel_rows(
    bits_rows: tuple[tuple[int, ...], ...],  # STATIC: baked per panel
    tiled_planes: jnp.ndarray,  # (C, 8, W8) uint32
    *,
    plan: tuple | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Block-panel K-tiled GF(2) matmul (module comment above).

    Returns (R, 8, W8) uint32, byte-identical to the whole-plane sparse
    kernel. ``plan`` overrides the auto-tuner's (KB, RB, TL, temp_cap,
    G) — tests force small panels and sub-launch counts; dispatch
    passes its cached plan so the telemetry tile key and the kernel
    agree. A 4-tuple plan (the pre-split form) is accepted as G=1.

    With G > 1 the K-block axis splits into G contiguous K-grid-major
    SUB-LAUNCHES (:func:`sublaunch_bounds`): sub-launch 0 initializes
    the HBM-resident output exactly like the single-launch kernel, and
    each later sub-launch XOR-accumulates its K-slice's partial into
    the accumulator in place (``_panel_call_acc``,
    ``input_output_aliases={0: 0}`` — the accumulator's HBM is donated
    launch to launch, no extra copy). Every sub-launch program carries
    only its own K-slice's panels, so Mosaic's program-size limit
    bounds one slice, not the whole network.
    """
    from noise_ec_tpu.ops.xor_factor import split_bits_rows_panels

    C, sub, W8 = tiled_planes.shape
    assert sub == 8, tiled_planes.shape
    R = len(bits_rows)
    if plan is None:
        plan = panel_plan(bits_rows, C)
    KB, RB, TL, temp_cap = plan[:4]
    G = plan[4] if len(plan) > 4 else 1
    # Sub-tile payloads: shrink the lane tile to the padded lane count
    # (strictly less VMEM than planned, so the temp cap stays valid) —
    # a 128-lane probe under a TL=512 plan must not compute 4x padding.
    TL = min(TL, max(128, -(-W8 // 128) * 128))
    PR = -(-R // RB)
    PK = -(-C // KB)
    G = max(1, min(G, PK))
    Cp = PK * KB
    W8p = -(-W8 // TL) * TL
    pad_c = Cp - C
    pad_w = W8p - W8
    if pad_c or pad_w:
        tiled_planes = jnp.pad(
            tiled_planes, ((0, pad_c), (0, 0), (0, pad_w))
        )
    panels = split_bits_rows_panels(bits_rows, Cp, KB, RB)
    if G == 1:
        nets_flat = tuple(p for row in panels for p in row)
        out = _panel_call(
            nets_flat, PR, PK, Cp, W8p, KB, RB, TL, temp_cap, interpret
        )(tiled_planes)
    else:
        bounds = sublaunch_bounds(PK, G)
        out = None
        for g in range(G):
            lo, hi = bounds[g], bounds[g + 1]
            PKg = hi - lo
            nets_g = tuple(p for row in panels for p in row[lo:hi])
            planes_g = tiled_planes[lo * KB : hi * KB]
            if g == 0:
                out = _panel_call(
                    nets_g, PR, PKg, PKg * KB, W8p, KB, RB, TL,
                    temp_cap, interpret,
                )(planes_g)
            else:
                out = _panel_call_acc(
                    nets_g, PR, PKg, W8p, KB, RB, TL, temp_cap,
                    interpret,
                )(out, planes_g)
    if PR * RB != R or pad_w:
        out = out[:R, :, :W8]
    return out
