"""Pallas TPU kernel for the bitsliced GF(2) matmul (the encode/reconstruct
hot loop — SURVEY.md §3.5, north-star "bitsliced Pallas kernels").

Formulation: out (R, W) = B (R, C) @ planes (C, W) over GF(2), computed as
an AND/XOR accumulation on uint32 lanes:

    for c in range(C): acc ^= maskT[c, :, None] & planes[c, None, :]

- ``maskT`` is the (C, R) *transposed* select-mask matrix (rows are read with
  a dynamic leading index, which the TPU lowers cheaply).
- The grid tiles the stripe-word axis W; masks and the full C-row plane tile
  live in VMEM. R and C are multiples of 8 by construction (8 or 16 planes
  per shard), W tiles are multiples of 128 — aligned to the (8, 128) int32
  layout.
- The same kernel serves encode (masks = parity rows of the generator) and
  reconstruct (masks = inverted-submatrix rows): only the mask operand
  changes (reference equivalents: main.go:262 and main.go:77).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_TILE_WORDS = 512
DEFAULT_TILE_LANES = 512
# Scoped-VMEM ceiling for one grid step's buffers. The hardware limit is
# 16 MiB; Pallas double-buffers grid inputs/outputs, so wide codes (e.g.
# RS(50,20): 400 input + 160 output plane-rows) must shrink the lane tile
# or the launch OOMs at compile time.
VMEM_BUDGET_BYTES = 14 << 20
# Paar temporaries ((8, TL) uint32 each) also live on the Mosaic stack.
# Counting every temp at full size over-estimates (the allocator reuses
# slots as liveness ends); 0.4 is calibrated against observed compiles:
# RS(50,20) sparse at TL=256 OOMed at 24.7M scoped (must reject), the
# fused RS(50,20) kernel at TL=128 compiled (must accept).
TEMP_ALIVE_FRACTION = 0.4


def xor_temp_bytes_per_lane(bits_rows: tuple, C: int) -> int:
    """Estimated per-lane stack bytes of the factored network's temps."""
    from noise_ec_tpu.ops.xor_factor import paar_factor

    ops, _ = paar_factor(bits_rows, C)
    return int(len(ops) * 8 * 4 * TEMP_ALIVE_FRACTION)


def _kernel(maskT_ref, planes_ref, out_ref):
    C = planes_ref.shape[0]
    R = maskT_ref.shape[1]
    TW = planes_ref.shape[1]

    def body(c, acc):
        m = maskT_ref[c, :]  # (R,)
        p = planes_ref[c, :]  # (TW,)
        return acc ^ (m[:, None] & p[None, :])

    out_ref[:, :] = jax.lax.fori_loop(
        0, C, body, jnp.zeros((R, TW), dtype=jnp.uint32)
    )


@functools.partial(jax.jit, static_argnames=("tile_words", "interpret"))
def gf2_matmul_pallas(
    masks: jnp.ndarray,
    planes: jnp.ndarray,
    *,
    tile_words: int = DEFAULT_TILE_WORDS,
    interpret: bool = False,
) -> jnp.ndarray:
    """(R, C) uint32 masks x (C, W) uint32 planes -> (R, W) uint32.

    W is padded to a tile boundary internally; output is sliced back.
    """
    R, C = masks.shape
    Cp, W = planes.shape
    assert C == Cp, (C, Cp)
    TW = min(tile_words, max(128, -(-W // 128) * 128))
    Wpad = -(-W // TW) * TW
    if Wpad != W:
        planes = jnp.pad(planes, ((0, 0), (0, Wpad - W)))
    maskT = masks.T  # (C, R): dynamic *row* reads inside the kernel

    out = pl.pallas_call(
        _kernel,
        grid=(Wpad // TW,),
        in_specs=[
            pl.BlockSpec((C, R), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((C, TW), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, TW), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, Wpad), jnp.uint32),
        interpret=interpret,
    )(maskT, planes)
    return out[:, :W] if Wpad != W else out


# ---------------------------------------------------------------------------
# Geometry-specialized sparse kernel (the fast path)
#
# The dense kernel above broadcasts a lane-vector of masks across sublanes
# every iteration and does ~4x the necessary work (AND+XOR over zero entries).
# This version bakes the bit-matrix into the program at trace time (the
# reference's runtime-dynamic geometry is handled by caching one compiled
# kernel per generator matrix — SURVEY.md §7.4): each output plane-row is a
# balanced XOR tree over exactly the input rows with a set bit. Planes use a
# "tiled" (C, 8, W8) layout so every XOR is a full (8, lanes) vreg op with no
# relayouts.


def planes_to_tiled(planes: jnp.ndarray) -> jnp.ndarray:
    """(C, W) packed planes -> (C, 8, W/8) tiled layout (pure reshape).

    Word w of plane c lands at [c, w // (W//8), w % (W//8)]... i.e. row-major
    reshape; all codec ops are positionwise so any fixed bijection works as
    long as pack/compute/unpack agree.
    """
    C, W = planes.shape
    if W % 8:
        planes = jnp.pad(planes, ((0, 0), (0, 8 - W % 8)))
        W = planes.shape[1]
    return planes.reshape(C, 8, W // 8)


def tiled_to_planes(tiled: jnp.ndarray, num_words: int) -> jnp.ndarray:
    C = tiled.shape[0]
    return tiled.reshape(C, -1)[:, :num_words]


def _make_sparse_kernel(bits_rows: tuple[tuple[int, ...], ...], C: int,
                        sublanes: int, TL: int):
    """bits_rows[r] = tuple of input-row indices feeding output row r.

    Measured-on-v5e structure (see git history for the experiment): hoist ONE
    VMEM read per input plane per grid step, then XOR evaluation per output
    row through the Paar-factored network (ops/xor_factor.py, ~2-3x fewer
    XORs than the raw chains). Per-row reads (C*density loads instead of C)
    cost 4x; tree reduction instead of chains costs ~25%. This shape runs at
    the HBM roofline (~650 GB/s data-in for RS(10,4)).
    """
    from noise_ec_tpu.ops.xor_factor import eval_bits_rows

    def kernel(planes_ref, out_ref):
        outs = eval_bits_rows(
            bits_rows, C,
            lambda c: planes_ref[c, :, :],
            lambda: jnp.zeros((sublanes, TL), dtype=jnp.uint32),
        )
        for r, val in enumerate(outs):
            out_ref[r, :, :] = val

    return kernel


@functools.lru_cache(maxsize=512)
def _sparse_call(bits_rows: tuple[tuple[int, ...], ...], C: int, W8: int, TL: int,
                 interpret: bool):
    R = len(bits_rows)
    kernel = _make_sparse_kernel(bits_rows, C, 8, TL)
    grid = (W8 // TL,)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((C, 8, TL), lambda i: (0, 0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, 8, TL), lambda i: (0, 0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, 8, W8), jnp.uint32),
        interpret=interpret,
    )


def _tiled_dense_kernel(maskT_ref, planes_ref, out_ref):
    # Per-sublane 2D broadcasts: Mosaic's layout inference rejects the 3D
    # (R,1,1)x(1,8,TL) broadcast, so unroll the 8 sublane rows statically.
    C = planes_ref.shape[0]
    R = maskT_ref.shape[1]
    TL = planes_ref.shape[2]
    for s in range(planes_ref.shape[1]):
        def body(c, acc, s=s):
            m = maskT_ref[c, :]  # (R,)
            p = planes_ref[c, s, :]  # (TL,)
            return acc ^ (m[:, None] & p[None, :])

        out_ref[:, s, :] = jax.lax.fori_loop(
            0, C, body, jnp.zeros((R, TL), dtype=jnp.uint32)
        )


@functools.partial(jax.jit, static_argnames=("tile_lanes", "interpret"))
def gf2_matmul_pallas_tiled(
    masks: jnp.ndarray,
    tiled_planes: jnp.ndarray,
    *,
    tile_lanes: int = DEFAULT_TILE_LANES,
    interpret: bool = False,
) -> jnp.ndarray:
    """Dense-mask GF(2) matmul on TILED (C, 8, W8) planes -> (R, 8, W8).

    Unlike the geometry-baked sparse kernel, the mask matrix is an
    OPERAND — use it when the matrix changes per call and a recompile per
    geometry is unacceptable. NOT on any production hot path: the mesh TP
    path (parallel/batch.py) instead selects per-device geometry-baked
    sparse programs with lax.switch, which measured ~13x faster than
    this kernel. Kept as the runtime-dynamic-matrix option, tested in
    tests/test_pallas_pack.py.
    """
    R, C = masks.shape
    Cp, sub, W8 = tiled_planes.shape
    assert C == Cp and sub == 8, (masks.shape, tiled_planes.shape)
    per_lane = (C + R) * sub * 4 * 2
    cap = max(128, VMEM_BUDGET_BYTES // per_lane // 128 * 128)
    TL = min(tile_lanes, cap, max(128, -(-W8 // 128) * 128))
    W8p = -(-W8 // TL) * TL
    if W8p != W8:
        tiled_planes = jnp.pad(tiled_planes, ((0, 0), (0, 0), (0, W8p - W8)))
    maskT = masks.T  # (C, R): dynamic row reads in the kernel

    out = pl.pallas_call(
        _tiled_dense_kernel,
        grid=(W8p // TL,),
        in_specs=[
            pl.BlockSpec((C, R), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((C, 8, TL), lambda i: (0, 0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, 8, TL), lambda i: (0, 0, i),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((R, 8, W8p), jnp.uint32),
        interpret=interpret,
    )(maskT, tiled_planes)
    return out[:, :, :W8] if W8p != W8 else out


def bits_to_rows(bits) -> tuple[tuple[int, ...], ...]:
    """(R, C) 0/1 matrix -> hashable per-output-row term tuples."""
    import numpy as _np

    bits = _np.asarray(bits)
    return tuple(
        tuple(int(c) for c in _np.nonzero(bits[r])[0]) for r in range(bits.shape[0])
    )


def gf2_matmul_pallas_sparse_rows(
    bits_rows: tuple[tuple[int, ...], ...],  # STATIC: baked into the kernel
    tiled_planes: jnp.ndarray,  # (C, 8, W8) uint32
    *,
    tile_lanes: int = DEFAULT_TILE_LANES,
    interpret: bool = False,
) -> jnp.ndarray:
    """Sparse geometry-specialized GF(2) matmul in tiled layout.

    Returns (R, 8, W8) uint32. W8 is padded to a tile boundary internally.
    """
    C, sub, W8 = tiled_planes.shape
    assert sub == 8, tiled_planes.shape
    # Double-buffered in+out bytes per lane of tile, plus the factored
    # network's temporaries; cap TL to the budget.
    per_lane = (C + len(bits_rows)) * sub * 4 * 2 + xor_temp_bytes_per_lane(
        bits_rows, C
    )
    cap = max(128, VMEM_BUDGET_BYTES // per_lane // 128 * 128)
    TL = min(tile_lanes, cap, max(128, -(-W8 // 128) * 128))
    W8p = -(-W8 // TL) * TL
    if W8p != W8:
        tiled_planes = jnp.pad(tiled_planes, ((0, 0), (0, 0), (0, W8p - W8)))
    out = _sparse_call(bits_rows, C, W8p, TL, interpret)(tiled_planes)
    return out[:, :, :W8] if W8p != W8 else out


def gf2_matmul_pallas_sparse(
    bits,  # (R, C) numpy 0/1 — STATIC: baked into the kernel
    tiled_planes: jnp.ndarray,
    *,
    tile_lanes: int = DEFAULT_TILE_LANES,
    interpret: bool = False,
) -> jnp.ndarray:
    return gf2_matmul_pallas_sparse_rows(
        bits_to_rows(bits), tiled_planes, tile_lanes=tile_lanes, interpret=interpret
    )
