"""Device-side bitplane packing: (k, S) symbols <-> (k*m, W) uint32 planes.

Same layout as the NumPy reference ``gf.bitmatrix.pack_bitplanes`` (tested
bit-exact): bit t of word w of plane (j*m + i) is bit i of symbol
shards[j, 32w + t]. Symbol axes are padded to multiples of 32 on the way in;
``unpack`` takes the true symbol count and slices the padding back off.

These are jnp implementations XLA fuses into a handful of elementwise
kernels; the Pallas SWAR versions (``pallas_gf2mm``) exist for the
throughput-critical fused paths.
"""

from __future__ import annotations

import jax.numpy as jnp

WORD_BITS = 32


def _padded_words(num_symbols: int) -> int:
    return -(-num_symbols // WORD_BITS)


def pack_bitplanes_jax(shards: jnp.ndarray, degree: int) -> jnp.ndarray:
    """(k, S) uint8/uint16 symbols -> (k*degree, ceil(S/32)) uint32 planes."""
    k, S = shards.shape
    W = _padded_words(S)
    x = shards.astype(jnp.uint32)
    if W * WORD_BITS != S:
        x = jnp.pad(x, ((0, 0), (0, W * WORD_BITS - S)))
    # (k, m, W*32) bits
    bits = (x[:, None, :] >> jnp.arange(degree, dtype=jnp.uint32)[None, :, None]) & 1
    bits = bits.reshape(k * degree, W, WORD_BITS)
    # Bits are disjoint powers of two, so sum == bitwise-or.
    weights = (jnp.uint32(1) << jnp.arange(WORD_BITS, dtype=jnp.uint32))[None, None, :]
    return jnp.sum(bits * weights, axis=-1, dtype=jnp.uint32)


def unpack_bitplanes_jax(
    planes: jnp.ndarray, num_shards: int, num_symbols: int, degree: int
) -> jnp.ndarray:
    """(k*degree, W) uint32 planes -> (k, S) symbols. Inverse of pack."""
    km, W = planes.shape
    assert km == num_shards * degree, (km, num_shards, degree)
    bits = (planes[:, :, None] >> jnp.arange(WORD_BITS, dtype=jnp.uint32)[None, None, :]) & 1
    bits = bits.reshape(num_shards, degree, W * WORD_BITS)[:, :, :num_symbols]
    weights = (jnp.uint32(1) << jnp.arange(degree, dtype=jnp.uint32))[None, :, None]
    out = jnp.sum(bits * weights, axis=1, dtype=jnp.uint32)
    return out.astype(jnp.uint8 if degree == 8 else jnp.uint16)
