"""Pallas bitplane pack/unpack: delta-swap bit-matrix transpose kernels.

The jnp pack in ``ops.bitops`` expands every bit to a uint32 lane (a 32x
blow-up XLA materializes in HBM — measured ~1 GB/s on v5e). These kernels do
the same bit-plane transpose in-register with the classic 3-round delta-swap
8x8 bit-matrix transpose (~3 vector ops per word, no blow-up).

Layout contract (consumed by ``ops.dispatch`` fused paths):

- Input words: ``(k, TW)`` uint32 viewed from ``(k, S)`` uint8 shards
  (TW = S/4), metadata-reshaped to ``(k, G8, 8, TL)`` so 8 consecutive
  TL-lane runs sit on the sublane axis.
- One group = the 8 words ``[j, g, 0..7, l]``; the kernel transposes each
  group's 8x(4x8-bit) matrix so sublane ``i`` holds bit ``i`` of all 32
  symbols of the group (bit position 8b+c <-> symbol 4c+b — a fixed,
  bit-index-independent bijection, which is all the positionwise GF(2)
  matmul needs; see pallas_gf2mm).
- Pack output: ``(k, 8, W)`` uint32, W = TW/8; row-major reshape to the
  matmul's ``(k*8, W)`` plane layout is metadata-only (sublane structure is
  preserved: plane (j, i) = row 8j+i).
- Unpack is the SAME transform (the transpose is an involution), reading
  ``(r, 8, W)`` planes and writing ``(r, G8, 8, TL)`` -> ``(r, TW)`` words.

The transpose network (verified against the bit-level spec in
tests/test_pallas_pack.py): for d in (1, 2, 4) with masks 0x55..., 0x33...,
0x0F...: t = ((V >> d) ^ roll(V, -d)) & m; V[c] ^= t[c] << d for c&d==0,
V[c] ^= t[c-d] otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

PACK_TILE_LANES = 512
_ROUNDS = ((1, 0x55555555), (2, 0x33333333), (4, 0x0F0F0F0F))
_ROUNDS16 = _ROUNDS + ((8, 0x00FF00FF),)


def _delta_swap(V: jnp.ndarray, axis: int, rounds) -> jnp.ndarray:
    idx = lax.broadcasted_iota(jnp.uint32, V.shape, axis)
    for d, m in rounds:
        s = jnp.roll(V, -d, axis=axis)
        t = ((V >> jnp.uint32(d)) ^ s) & jnp.uint32(m)
        lo = V ^ (t << jnp.uint32(d))
        hi = V ^ jnp.roll(t, d, axis=axis)
        V = jnp.where((idx & jnp.uint32(d)) == 0, lo, hi)
    return V


def delta_swap8(V: jnp.ndarray, axis: int) -> jnp.ndarray:
    """8x8 bit transpose across the size-8 ``axis`` of uint32 words.

    Involution: applying twice returns the input.
    """
    return _delta_swap(V, axis, _ROUNDS)


def delta_swap16(V: jnp.ndarray, axis: int) -> jnp.ndarray:
    """16x16 bit transpose across the size-16 ``axis`` of uint32 words.

    Each uint32 word holds two independent 16-bit columns (halves h = 0, 1):
    out[i] bit (16h + j) == in[j] bit (16h + i). One extra delta-swap round
    (d=8, mask 0x00FF00FF) on top of the 8x8 network; all exchanged bit
    positions satisfy (p & d) == 0 so p+d never crosses a 16-bit half.
    Involution.
    """
    return _delta_swap(V, axis, _ROUNDS16)


def _pack_kernel(in_ref, out_ref):
    # in: (k, 1, 8, TL) word groups; out: (k, 8, TL) bit-planes.
    out_ref[:, :, :] = delta_swap8(in_ref[:, 0, :, :], axis=1)


def _unpack_kernel(in_ref, out_ref):
    # in: (r, 8, TL) bit-planes; out: (r, 1, 8, TL) word groups.
    out_ref[:, 0, :, :] = delta_swap8(in_ref[:, :, :], axis=1)


@functools.lru_cache(maxsize=256)
def _pack_call(k: int, G8: int, TL: int, interpret: bool):
    return pl.pallas_call(
        _pack_kernel,
        grid=(G8,),
        in_specs=[
            pl.BlockSpec((k, 1, 8, TL), lambda g: (0, g, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((k, 8, TL), lambda g: (0, 0, g),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, 8, G8 * TL), jnp.uint32),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=256)
def _unpack_call(r: int, G8: int, TL: int, interpret: bool):
    return pl.pallas_call(
        _unpack_kernel,
        grid=(G8,),
        in_specs=[
            pl.BlockSpec((r, 8, TL), lambda g: (0, 0, g),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, 1, 8, TL), lambda g: (0, g, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r, G8, 8, TL), jnp.uint32),
        interpret=interpret,
    )


def _tile_lanes(TW: int, tile_lanes: int, group: int = 8) -> int:
    TL = min(tile_lanes, max(128, TW // group))
    while TW % (group * TL):
        TL //= 2
        if TL < 128:
            raise ValueError(f"word count {TW} not divisible by {group}*128")
    return TL


def pack_words_pallas(xw: jnp.ndarray, *, tile_lanes: int = PACK_TILE_LANES,
                      interpret: bool = False) -> jnp.ndarray:
    """(k, TW) uint32 data words -> (k, 8, TW/8) uint32 bit-planes.

    Row [j, i] is bit-plane i of shard j; reshape to (k*8, TW/8) for the
    GF(2) matmul. TW must be a multiple of 8*128 (wrappers pad).
    """
    k, TW = xw.shape
    TL = _tile_lanes(TW, tile_lanes)
    G8 = TW // (8 * TL)
    grouped = xw.reshape(k, G8, 8, TL)
    return _pack_call(k, G8, TL, interpret)(grouped)


def unpack_words_pallas(planes: jnp.ndarray, *,
                        tile_lanes: int = PACK_TILE_LANES,
                        interpret: bool = False) -> jnp.ndarray:
    """(r, 8, W) uint32 bit-planes -> (r, 8*W) uint32 words (pack inverse)."""
    r, eight, W = planes.shape
    assert eight == 8, planes.shape
    TW = 8 * W
    TL = _tile_lanes(TW, tile_lanes)
    G8 = TW // (8 * TL)
    out = _unpack_call(r, G8, TL, interpret)(planes)
    return out.reshape(r, TW)


# ---------------------------------------------------------------------------
# Lane-axis pack: the zero-relayout fast path.
#
# The sublane-group kernels above need the words reshaped (k, TW) ->
# (k, G8, 8, TL) BEFORE the pallas call — and that reshape is a physical
# relayout of the whole buffer (measured ~0.5-0.8 ms for 80 MiB on v5e,
# dominating the fused encode). These variants keep the group axis on
# LANES: a group's m words sit in m TL-lane windows of one m*TL-lane
# sub-slab, so
#
# - the input block is a native 2D (k, 8*m*TL) slice of (k, TW) — no
#   XLA-level reshape, no relayout;
# - the delta-swap rolls move by d*TL lanes (TL a multiple of 128), i.e.
#   whole-vreg permutations instead of sublane shuffles;
# - the output block (k, m, 8, TL) writes plane words DIRECTLY into the
#   matmul's (k, m, 8, W8) tiled layout, so the downstream reshape to
#   (k*m, 8, W8) is a metadata-only leading-dim merge.
#
# Tile-content bijection: one grid step c consumes input words
# [8*m*TL*c, 8*m*TL*(c+1)) as 8 sub-slabs sigma of m*TL lanes; plane
# (j, i)'s tile position (sigma, c*TL + l) holds plane word
# c*8*TL + sigma*TL + l. Any fixed bijection works — the GF(2) matmul is
# positionwise and pack/unpack share this one (pack_words_lanes and
# unpack_words_lanes are inverses; the sublane kernels use a different,
# equally valid bijection).
#
# Constraint: TW must be a multiple of 8*m*TL (TL >= 128 -> lane_quantum
# = 1024*m words). Wrappers pad; zero symbols are positionwise-inert.


def lane_delta_swap(V: jnp.ndarray, TL: int, rounds=_ROUNDS) -> jnp.ndarray:
    """Bit transpose across TL-lane windows of a (rows, G*TL) slab.

    Window u holds group member u; out window i bit (G*b + j) == in window
    j bit (G*b + i) per lane (G = 8 for GF(2^8) rounds, 16 with
    ``_ROUNDS16``). Involution.
    """
    win = lax.broadcasted_iota(jnp.uint32, V.shape, 1) // jnp.uint32(TL)
    for d, m in rounds:
        s = jnp.roll(V, -d * TL, axis=1)
        t = ((V >> jnp.uint32(d)) ^ s) & jnp.uint32(m)
        lo = V ^ (t << jnp.uint32(d))
        hi = V ^ jnp.roll(t, d * TL, axis=1)
        V = jnp.where((win & jnp.uint32(d)) == 0, lo, hi)
    return V


def transpose_windows(ws: list, rounds) -> list:
    """Bit transpose across a list of window arrays (delta-swap, pairwise).

    Same math as :func:`lane_delta_swap` with each TL-lane window as its
    own array, but the classic two-word swap form: per round only the
    i & d == 0 half does work (5 vector ops per PAIR), with no cross-lane
    rolls and no iota/select over the full slab — ~2.8x fewer vector ops,
    and the kernels' window slices map to it directly. Involution.

    Measured on v5e the win only materializes for WIDE windows (TL >= 256):
    RS(10,4) at TL=512 gains ~16%, but RS(50,20) at TL=128 loses ~24% (the
    narrow per-window ops vectorize worse than full-slab rolls), so the
    kernels pick per TL — see ``_use_pairwise``.
    """
    for d, mask in rounds:
        nxt = list(ws)
        for i in range(len(ws)):
            if i & d == 0:
                t = ((ws[i] >> jnp.uint32(d)) ^ ws[i + d]) & jnp.uint32(mask)
                nxt[i] = ws[i] ^ (t << jnp.uint32(d))
                nxt[i + d] = ws[i + d] ^ t
        ws = nxt
    return ws


def _use_pairwise(TL: int) -> bool:
    return TL >= 256


def _pack_lanes_kernel(m, TL, rounds, in_ref, out_ref):
    for sigma in range(8):
        if _use_pairwise(TL):
            ws = transpose_windows(
                [
                    in_ref[:, (sigma * m + i) * TL : (sigma * m + i + 1) * TL]
                    for i in range(m)
                ],
                rounds,
            )
        else:
            V = lane_delta_swap(
                in_ref[:, sigma * m * TL : (sigma + 1) * m * TL], TL, rounds
            )
            ws = [V[:, i * TL : (i + 1) * TL] for i in range(m)]
        for i in range(m):
            out_ref[:, i, sigma, :] = ws[i]


def _unpack_lanes_kernel(m, TL, rounds, in_ref, out_ref):
    for sigma in range(8):
        if _use_pairwise(TL):
            ws = transpose_windows(
                [in_ref[:, i, sigma, :] for i in range(m)], rounds
            )
            for i in range(m):
                out_ref[:, (sigma * m + i) * TL : (sigma * m + i + 1) * TL] = ws[i]
        else:
            V = jnp.concatenate(
                [in_ref[:, i, sigma, :] for i in range(m)], axis=1
            )
            out_ref[:, sigma * m * TL : (sigma + 1) * m * TL] = lane_delta_swap(
                V, TL, rounds
            )


_LANE_VMEM_BUDGET = 12 << 20


def _lane_tl(TW: int, m: int, rows: int) -> int:
    """Largest TL in {512, 256, 128} with TL | W8 that fits the in+out
    blocks (double-buffered) in the scoped-VMEM budget."""
    W8 = TW // (8 * m)
    for TL in (512, 256, 128):
        if W8 % TL == 0 and rows * 8 * m * TL * 4 * 4 <= _LANE_VMEM_BUDGET:
            return TL
    raise ValueError(
        f"no lane tile for TW={TW}, m={m}, rows={rows} "
        f"(need TW % {1024 * m} == 0 and a tile within VMEM)"
    )


def lane_quantum(m: int) -> int:
    """Pad-to multiple for the lane kernels: 8*m*128 = 1024*m words."""
    return 1024 * m


@functools.lru_cache(maxsize=256)
def _pack_lanes_call(k: int, TW: int, m: int, rows_budget: int, interpret: bool):
    TL = _lane_tl(TW, m, rows_budget)
    W8 = TW // (8 * m)
    rounds = _ROUNDS if m == 8 else _ROUNDS16
    return pl.pallas_call(
        functools.partial(_pack_lanes_kernel, m, TL, rounds),
        grid=(W8 // TL,),
        in_specs=[
            pl.BlockSpec((k, 8 * m * TL), lambda c: (0, c),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((k, m, 8, TL), lambda c: (0, 0, 0, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, m, 8, W8), jnp.uint32),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=256)
def _unpack_lanes_call(r: int, TW: int, m: int, rows_budget: int, interpret: bool):
    TL = _lane_tl(TW, m, rows_budget)
    W8 = TW // (8 * m)
    rounds = _ROUNDS if m == 8 else _ROUNDS16
    return pl.pallas_call(
        functools.partial(_unpack_lanes_kernel, m, TL, rounds),
        grid=(W8 // TL,),
        in_specs=[
            pl.BlockSpec((r, m, 8, TL), lambda c: (0, 0, 0, c),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, 8 * m * TL), lambda c: (0, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r, TW), jnp.uint32),
        interpret=interpret,
    )


def pack_words_lanes(xw: jnp.ndarray, m: int = 8, *,
                     rows_budget: int = 0,
                     interpret: bool = False) -> jnp.ndarray:
    """(k, TW) uint32 words -> (k, m, 8, TW/(8m)) tiled bit-planes.

    Reshape the result to (k*m, 8, W8) for the sparse GF(2) matmul
    (leading-dim merge: metadata-only). TW must be a multiple of
    ``lane_quantum(m)``. Inverse: :func:`unpack_words_lanes`.

    The tile-content bijection depends on the lane tile TL, and TL shrinks
    with the row count to fit VMEM — so a pack/unpack PAIR must agree on
    TL. Pass ``rows_budget = max(rows of every kernel in the pipeline)``
    to BOTH ends (DeviceCodec passes max(k, r)); geometries where k and r
    straddle a VMEM row bracket silently corrupt otherwise.
    """
    k, TW = xw.shape
    return _pack_lanes_call(k, TW, m, max(rows_budget, k), interpret)(xw)


def unpack_words_lanes(tiled: jnp.ndarray, *,
                       rows_budget: int = 0,
                       interpret: bool = False) -> jnp.ndarray:
    """(r, m, 8, W8) tiled bit-planes -> (r, m*8*W8) uint32 words.

    ``rows_budget`` must match the value given to
    :func:`pack_words_lanes` (see its docstring).
    """
    r, m, eight, W8 = tiled.shape
    assert eight == 8, tiled.shape
    return _unpack_lanes_call(r, 8 * m * W8, m, max(rows_budget, r), interpret)(tiled)


# ---------------------------------------------------------------------------
# Row-blocked lane pack: wide-geometry (many-row) variant.
#
# The lane kernels above hold ALL rows of the in+out blocks in VMEM per
# grid step, so _lane_tl rejects row counts past ~91 (m=8, TL=128) — the
# (3, 200)-shaped reconstruction inputs and the near-field-limit codes
# the panel matmul tier exists for. The pack transpose is row-wise
# independent (every op acts within one row), so these variants simply
# add a row-block grid axis: grid step (rb, c) packs rows
# [rb*RB, (rb+1)*RB) of lane tile c. The TL choice is pinned to the
# BLOCK row count, so the pack/unpack bijection is independent of the
# total row count — both ends of a pipeline agree by construction.

PACK_ROW_BLOCK = 32  # _lane_tl(…, rows=32) yields TL=256: pairwise bracket


@functools.lru_cache(maxsize=256)
def _pack_lanes_blocked_call(kp: int, TW: int, m: int, interpret: bool):
    RB = PACK_ROW_BLOCK
    TL = _lane_tl(TW, m, RB)
    W8 = TW // (8 * m)
    rounds = _ROUNDS if m == 8 else _ROUNDS16
    return pl.pallas_call(
        functools.partial(_pack_lanes_kernel, m, TL, rounds),
        grid=(kp // RB, W8 // TL),
        in_specs=[
            pl.BlockSpec((RB, 8 * m * TL), lambda rb, c: (rb, c),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((RB, m, 8, TL), lambda rb, c: (rb, 0, 0, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((kp, m, 8, W8), jnp.uint32),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=256)
def _unpack_lanes_blocked_call(rp: int, TW: int, m: int, interpret: bool):
    RB = PACK_ROW_BLOCK
    TL = _lane_tl(TW, m, RB)
    W8 = TW // (8 * m)
    rounds = _ROUNDS if m == 8 else _ROUNDS16
    return pl.pallas_call(
        functools.partial(_unpack_lanes_kernel, m, TL, rounds),
        grid=(rp // RB, W8 // TL),
        in_specs=[
            pl.BlockSpec((RB, m, 8, TL), lambda rb, c: (rb, 0, 0, c),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((RB, 8 * m * TL), lambda rb, c: (rb, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rp, TW), jnp.uint32),
        interpret=interpret,
    )


def pack_words_lanes_blocked(xw: jnp.ndarray, m: int = 8, *,
                             interpret: bool = False) -> jnp.ndarray:
    """Row-blocked :func:`pack_words_lanes`: any row count (rows padded
    to the PACK_ROW_BLOCK internally, sliced back). Inverse:
    :func:`unpack_words_lanes_blocked` — the blocked pair shares one
    TL by construction, so no ``rows_budget`` coordination is needed.
    """
    k, TW = xw.shape
    kp = -(-k // PACK_ROW_BLOCK) * PACK_ROW_BLOCK
    if kp != k:
        xw = jnp.pad(xw, ((0, kp - k), (0, 0)))
    out = _pack_lanes_blocked_call(kp, TW, m, interpret)(xw)
    return out[:k] if kp != k else out


def unpack_words_lanes_blocked(tiled: jnp.ndarray, *,
                               interpret: bool = False) -> jnp.ndarray:
    """(r, m, 8, W8) tiled bit-planes -> (r, m*8*W8) words (row-blocked
    pack inverse; see :func:`pack_words_lanes_blocked`)."""
    r, m, eight, W8 = tiled.shape
    assert eight == 8, tiled.shape
    rp = -(-r // PACK_ROW_BLOCK) * PACK_ROW_BLOCK
    if rp != r:
        tiled = jnp.pad(tiled, ((0, rp - r), (0, 0), (0, 0), (0, 0)))
    out = _unpack_lanes_blocked_call(rp, 8 * m * W8, m, interpret)(tiled)
    return out[:r] if rp != r else out


# ---------------------------------------------------------------------------
# GF(2^16): 16-plane variant. A group is 16 words = 32 little-endian uint16
# symbols; after the 16x16 transpose, sublane i holds bit i of all 32 symbols
# (bit position 16h + w of plane word <-> symbol (w, half h) — a fixed
# bijection, which is all the positionwise GF(2) matmul needs).


def _pack16_kernel(in_ref, out_ref):
    # in: (k, 1, 16, TL) word groups; out: (k, 16, TL) bit-planes.
    out_ref[:, :, :] = delta_swap16(in_ref[:, 0, :, :], axis=1)


def _unpack16_kernel(in_ref, out_ref):
    # in: (r, 16, TL) bit-planes; out: (r, 1, 16, TL) word groups.
    out_ref[:, 0, :, :] = delta_swap16(in_ref[:, :, :], axis=1)


@functools.lru_cache(maxsize=256)
def _pack16_call(k: int, G16: int, TL: int, interpret: bool):
    return pl.pallas_call(
        _pack16_kernel,
        grid=(G16,),
        in_specs=[
            pl.BlockSpec((k, 1, 16, TL), lambda g: (0, g, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((k, 16, TL), lambda g: (0, 0, g),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((k, 16, G16 * TL), jnp.uint32),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=256)
def _unpack16_call(r: int, G16: int, TL: int, interpret: bool):
    return pl.pallas_call(
        _unpack16_kernel,
        grid=(G16,),
        in_specs=[
            pl.BlockSpec((r, 16, TL), lambda g: (0, 0, g),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, 1, 16, TL), lambda g: (0, g, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r, G16, 16, TL), jnp.uint32),
        interpret=interpret,
    )


def pack_words16_pallas(xw: jnp.ndarray, *, tile_lanes: int = PACK_TILE_LANES,
                        interpret: bool = False) -> jnp.ndarray:
    """(k, TW) uint32 data words (2 uint16 symbols each) -> (k, 16, TW/16)
    uint32 bit-planes.

    Row [j, i] is bit-plane i of shard j; reshape to (k*16, TW/16) for the
    GF(2) matmul. TW must be a multiple of 16*128 (wrappers pad).
    """
    k, TW = xw.shape
    TL = _tile_lanes(TW, tile_lanes, group=16)
    G16 = TW // (16 * TL)
    grouped = xw.reshape(k, G16, 16, TL)
    return _pack16_call(k, G16, TL, interpret)(grouped)


def unpack_words16_pallas(planes: jnp.ndarray, *,
                          tile_lanes: int = PACK_TILE_LANES,
                          interpret: bool = False) -> jnp.ndarray:
    """(r, 16, W) uint32 bit-planes -> (r, 16*W) uint32 words (pack
    inverse)."""
    r, sixteen, W = planes.shape
    assert sixteen == 16, planes.shape
    TW = 16 * W
    TL = _tile_lanes(TW, tile_lanes, group=16)
    G16 = TW // (16 * TL)
    out = _unpack16_call(r, G16, TL, interpret)(planes)
    return out.reshape(r, TW)


def u16_to_words(x: jnp.ndarray) -> jnp.ndarray:
    """(k, S) uint16 -> (k, S/2) uint32 (bitcast; S % 2 == 0)."""
    k, S = x.shape
    return lax.bitcast_convert_type(x.reshape(k, S // 2, 2), jnp.uint32)


def words_to_u16(xw: jnp.ndarray) -> jnp.ndarray:
    """(r, TW) uint32 -> (r, 2*TW) uint16 (bitcast inverse)."""
    r, TW = xw.shape
    return lax.bitcast_convert_type(xw, jnp.uint16).reshape(r, 2 * TW)


def bytes_to_words(x: jnp.ndarray) -> jnp.ndarray:
    """(k, S) uint8 -> (k, S/4) uint32 (bitcast; S % 4 == 0)."""
    k, S = x.shape
    return lax.bitcast_convert_type(x.reshape(k, S // 4, 4), jnp.uint32)


# ---------------------------------------------------------------------------
# GF(2^16) PACKED byte-sliced layout.
#
# The byte-sliced route splits each u16 symbol into (lo, hi) byte rows
# and runs the m=8 pipeline over 2k rows — 3 delta-swap rounds and the
# TL=512 tile instead of the 16-plane kernels' 4 rounds and TL<=256, so
# the m=8 expansion stops doubling the round count. The PACKED layout is
# the canonical device-resident form of that route: shard j's byte rows
# sit ADJACENT (row 2j = lo bytes, row 2j+1 = hi bytes) in one (2k, S)
# panel, so a k-shard u16 object is ONE contiguous operand for the
# words/panel kernels and the matrix's unpermuted bit expansion (flat
# plane index 16j + b == (2j + b//8)*8 + b%8) applies with no row
# shuffle. The helpers below convert between the interleaved-u16 word
# layout and the packed byte-sliced layout on either side of the device
# boundary.


def pack_u16_bytesliced(x: "np.ndarray") -> "np.ndarray":
    """HOST: (k, S) uint16 symbols -> (2k, S) uint8 packed byte rows
    (row 2j = lo bytes of shard j, row 2j+1 = hi bytes; little-endian).
    The single relayout pass every host-side GF(2^16) dispatch pays —
    shared by ops/dispatch.py and parallel/mesh.py so the layout cannot
    fork."""
    import numpy as np

    k, S = x.shape
    return np.ascontiguousarray(
        np.ascontiguousarray(x).view(np.uint8).reshape(k, S, 2)
        .transpose(0, 2, 1)
        .reshape(2 * k, S)
    )


def unpack_u16_bytesliced(b: "np.ndarray") -> "np.ndarray":
    """HOST: (2r, S) uint8 packed byte rows -> (r, S) uint16 symbols
    (:func:`pack_u16_bytesliced` inverse)."""
    import numpy as np

    r2, S = b.shape
    r = r2 // 2
    return (
        np.ascontiguousarray(
            b.reshape(r, 2, S).transpose(0, 2, 1)
        ).view("<u2").reshape(r, S)
    )


def words16_to_bytesliced(xw: jnp.ndarray) -> jnp.ndarray:
    """DEVICE: (k, TW) u32 interleaved-u16 words (two LE symbols per
    word) -> (2k, TW/2) u32 packed byte-sliced words, pure lane-local
    bit ops (no sub-word dtype relayout — see ops/dispatch.py on the
    u8<->u32 bitcast cost). Involution partner:
    :func:`bytesliced_to_words16`. TW must be even."""
    k, TW = xw.shape
    pairs = xw.reshape(k, TW // 2, 2)
    w0, w1 = pairs[..., 0], pairs[..., 1]
    ff = jnp.uint32(0xFF)
    lo = (
        (w0 & ff)
        | (((w0 >> jnp.uint32(16)) & ff) << jnp.uint32(8))
        | ((w1 & ff) << jnp.uint32(16))
        | (((w1 >> jnp.uint32(16)) & ff) << jnp.uint32(24))
    )
    hi = (
        ((w0 >> jnp.uint32(8)) & ff)
        | (((w0 >> jnp.uint32(24)) & ff) << jnp.uint32(8))
        | (((w1 >> jnp.uint32(8)) & ff) << jnp.uint32(16))
        | (((w1 >> jnp.uint32(24)) & ff) << jnp.uint32(24))
    )
    return jnp.stack([lo, hi], axis=1).reshape(2 * k, TW // 2)


def bytesliced_to_words16(bw: jnp.ndarray) -> jnp.ndarray:
    """DEVICE: (2r, TW8) u32 packed byte-sliced words -> (r, 2*TW8) u32
    interleaved-u16 words (:func:`words16_to_bytesliced` inverse)."""
    r2, TW8 = bw.shape
    r = r2 // 2
    pairs = bw.reshape(r, 2, TW8)
    lo, hi = pairs[:, 0, :], pairs[:, 1, :]
    ff = jnp.uint32(0xFF)
    w0 = (
        (lo & ff)
        | (((hi & ff)) << jnp.uint32(8))
        | (((lo >> jnp.uint32(8)) & ff) << jnp.uint32(16))
        | (((hi >> jnp.uint32(8)) & ff) << jnp.uint32(24))
    )
    w1 = (
        ((lo >> jnp.uint32(16)) & ff)
        | (((hi >> jnp.uint32(16)) & ff) << jnp.uint32(8))
        | (((lo >> jnp.uint32(24)) & ff) << jnp.uint32(16))
        | (((hi >> jnp.uint32(24)) & ff) << jnp.uint32(24))
    )
    return jnp.stack([w0, w1], axis=2).reshape(r, 2 * TW8)


def words_to_bytes(xw: jnp.ndarray) -> jnp.ndarray:
    """(r, TW) uint32 -> (r, 4*TW) uint8 (bitcast inverse)."""
    r, TW = xw.shape
    return lax.bitcast_convert_type(xw, jnp.uint8).reshape(r, 4 * TW)
