"""GF(2) generator matmul on the MXU: int8 bit-planes, mod-2 accumulators.

The fused VPU kernels (ops/pallas_fused.py) compute the bitsliced encode as
a Paar-factored XOR network on u32 lanes; for wide codes the XOR count is
the wall (RS(50,20): ~10.1k XORs — BASELINE.md config 3). This module is
the alternative formulation VERDICT r3 asked to measure before conceding
that bound: treat the (8r, 8k) GF(2) generator bit-matrix as an int8
operand, the data bits as an int8 (8k, S) matrix of 0/1, and run the whole
product on the 128x128 systolic array —

    acc (8r, S) = M2 (8r, 8k) @ bits (8k, S)   in int8 x int8 -> int32
    parity_bit  = acc & 1                       (popcount parity == mod 2)

Everything (u32 -> byte -> bit unpack, the dot, bit -> byte -> u32 repack)
lives inside ONE Pallas kernel so the 8x bit-plane blowup and the 32-bit
accumulators never touch HBM: per grid step the kernel reads a (k, TWt)
u32 block and writes the (r, TWt) parity block, HBM traffic identical to
the VPU kernels. Arithmetic cost is fixed at 64*r*k MACs per data byte —
on a v5e (394 INT8 TOPS) the roofline for RS(50,20) is ~308 GB/s, which is
why this only makes sense for wide codes; RS(10,4)'s XOR network is far
below its MXU MAC count.

Reference contract: the same encode hot loop as ops/pallas_fused.py
(/root/reference/main.go:262 via infectious Encode).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from noise_ec_tpu.gf.bitmatrix import expand_generator_bits
from noise_ec_tpu.gf.field import GF

# Lane-tile width in u32 words per grid step. 512 words = 2048 byte
# columns; the in-kernel int8 bit matrix is (8k, 2048) = 16k * k bytes —
# ~800 KiB at k=50, comfortably VMEM-resident beside the i32 accumulator.
MXU_TILE_WORDS = 512


def _trace_state_clean() -> bool:
    """True when no jax trace is active (private API with a conservative
    fallback: treating the state as dirty only skips a cache promotion)."""
    try:
        from jax._src.core import trace_state_clean

        return bool(trace_state_clean())
    except Exception:  # noqa: BLE001 — API moved; assume tracing
        return False


def _mxu_kernel(r: int, k: int, kernel_tw: int, m2_ref, w_ref, o_ref):
    # Mosaic cannot reshape across the minor (lane) dim, so the u32 words
    # are never byte-deinterleaved: all 32 bits unpack along a NEW sublane
    # axis (lane dim untouched), and the four byte lanes of each word run
    # as four MXU dots sharing one (8r, 8k) bit-matrix — bit i of byte
    # lane c is u32 bit 8c+i, so slice [8c:8c+8] of the bit axis is
    # exactly byte lane c's plane group.
    w = w_ref[...]  # (k, TWt) uint32
    bit32 = jnp.arange(32, dtype=jnp.uint32)
    bits = ((w[:, None, :] >> bit32[None, :, None]) & 1).astype(jnp.int8)
    m2 = m2_ref[...]
    out = None
    for c in range(4):
        xc = bits[:, 8 * c : 8 * c + 8, :].reshape(8 * k, kernel_tw)
        acc = jax.lax.dot_general(
            m2,
            xc,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # (8r, TWt) int32
        pbits = (acc & 1).astype(jnp.uint32).reshape(r, 8, kernel_tw)
        # OR-fold (shifted bits are disjoint; Mosaic has no unsigned
        # reductions) straight into the output u32: byte c bit bo is u32
        # bit 8c+bo.
        for bo in range(8):
            term = pbits[:, bo, :] << (8 * c + bo)
            out = term if out is None else out | term
    o_ref[...] = out


@functools.partial(
    jax.jit, static_argnames=("r", "k", "tile_words", "interpret")
)
def _mxu_encode_words_jit(m2, words, *, r, k, tile_words, interpret):
    from jax.experimental import pallas as pl

    kt = tile_words
    tw = words.shape[1]
    grid = (tw // kt,)
    return pl.pallas_call(
        functools.partial(_mxu_kernel, r, k, kt),
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * r, 8 * k), lambda i: (0, 0)),
            pl.BlockSpec((k, kt), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((r, kt), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((r, tw), jnp.uint32),
        interpret=interpret,
    )(m2, words)


def cached_bit_expansion(cache: dict, gf: GF, M: np.ndarray,
                         *, bound: int = 256):
    """Cached int8 GF(2) bit expansion of ``M`` with device promotion.

    One implementation for every MXU caller (MxuCodec and the dispatch
    wide-field route) so the cache-key scheme (full shape + bytes — the
    r4 collision fix), the size bound, and the tracer-leak guard cannot
    diverge: the promotion to a device-resident jnp array happens ONLY
    outside an active trace (jnp.asarray under tracing returns a tracer,
    and caching that leaks it into later calls).
    """
    M = np.ascontiguousarray(np.asarray(M, dtype=gf.dtype))
    key = (M.shape, M.tobytes())
    hit = cache.get(key)
    if hit is None:
        hit = expand_generator_bits(gf, M).astype(np.int8)
        if len(cache) > bound:
            cache.clear()
        cache[key] = hit
    if isinstance(hit, np.ndarray) and _trace_state_clean():
        hit = jnp.asarray(hit)
        cache[key] = hit
    return hit


def mxu_encode_words_bits(
    m2: np.ndarray,
    words,
    *,
    r: int,
    k: int,
    interpret: bool = False,
):
    """Low-level MXU entry on a PRE-EXPANDED GF(2) bit matrix.

    ``m2``: (8r, 8k) 0/1 int8 bit matrix over k byte rows; ``words``:
    (k, TW) uint32 with TW a multiple of the chosen lane tile. The field
    is irrelevant here — the kernel is pure GF(2) — which is what lets
    the BYTE-SLICED GF(2^16) path run on the MXU: its expanded (16r, 16k)
    bit matrix over 2k byte rows IS an (8R, 8K) matrix with R = 2r,
    K = 2k (design.md: the flat plane index needs no permutation).
    The lane tile narrows for many-byte-row geometries so the in-kernel
    bit tensor (k * 32 * tile bytes) stays VMEM-resident.
    """
    tile = MXU_TILE_WORDS if k <= 256 else MXU_TILE_WORDS // 2
    words = jnp.asarray(words)
    if words.shape[1] % tile:
        raise ValueError(
            f"TW {words.shape[1]} not a multiple of tile {tile}"
        )
    if isinstance(m2, np.ndarray):
        # Callers that cache a device-resident operand pass it through
        # untouched; only host ndarrays get staged here.
        m2 = jnp.asarray(np.ascontiguousarray(m2, dtype=np.int8))
    return _mxu_encode_words_jit(
        m2,
        words,
        r=r,
        k=k,
        tile_words=tile,
        interpret=interpret,
    )


class MxuCodec:
    """Experimental MXU-route encoder over u32 word stripes.

    Same contract as DeviceCodec.matmul_words (parity rows only); kept
    separate so the verified planner can measure it against the XOR
    network per geometry instead of hardwiring either.
    """

    def __init__(self, gf: GF, tile_words: int = MXU_TILE_WORDS,
                 interpret: bool = False):
        if gf.degree != 8:
            raise ValueError("MXU route currently GF(2^8) only")
        self.gf = gf
        self.tile_words = tile_words
        self.interpret = interpret
        self._m2_cache: dict = {}

    def _m2_for(self, M: np.ndarray):
        return cached_bit_expansion(self._m2_cache, self.gf, M)

    def encode_words(self, M: np.ndarray, words) -> jnp.ndarray:
        """(r, k) GF matrix x (k, TW) u32 words -> (r, TW) parity words.

        TW must be a multiple of ``tile_words`` (callers pad, exactly as
        for the fused VPU kernels)."""
        r, k = np.asarray(M).shape
        words = jnp.asarray(words)
        if words.shape[0] != k:
            raise ValueError(f"matrix cols {k} != word rows {words.shape[0]}")
        if words.shape[1] % self.tile_words:
            raise ValueError(
                f"TW {words.shape[1]} not a multiple of tile {self.tile_words}"
            )
        return _mxu_encode_words_jit(
            self._m2_for(M),
            words,
            r=r,
            k=k,
            tile_words=self.tile_words,
            interpret=self.interpret,
        )

    def encode_stripes(self, M: np.ndarray, D: np.ndarray) -> np.ndarray:
        """Byte-stripe convenience wrapper (pads to the word tile)."""
        D = np.ascontiguousarray(np.asarray(D, dtype=np.uint8))
        r, k = np.asarray(M).shape
        S = D.shape[1]
        quantum = 4 * self.tile_words
        Sp = -(-S // quantum) * quantum
        if Sp != S:
            buf = np.zeros((k, Sp), dtype=np.uint8)
            buf[:, :S] = D
        else:
            buf = D
        out = np.array(self.encode_words(M, buf.view("<u4")))
        return out.view(np.uint8)[:, :S]
