"""Geometry-cached device codec: the bridge from GF matrices to TPU kernels.

The reference changes RS geometry (k, n) at runtime per message
(/root/reference/main.go:185-191), so kernels must be re-jitted per geometry
with bounded caching (SURVEY.md §7.4 "dynamic geometry"). ``DeviceCodec``
caches one fused (pack -> GF(2) matmul -> unpack) compiled program per
(matrix, stripe-length, kernel) signature.

Kernel selection:

- "pallas" (default on TPU): the geometry-specialized sparse Pallas kernel —
  the matrix's bit pattern is baked into the program as XOR chains; runs at
  the HBM roofline on v5e.
- "xla": masked AND/XOR fori_loop — portable, used for CPU tests and as the
  shape-generic fallback.
- "pallas_interpret": Pallas interpreter mode (CPU debugging).
"""

from __future__ import annotations

import functools
import logging
import threading
import time
import weakref
from collections import deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from noise_ec_tpu.gf.bitmatrix import (
    expand_generator_bits,
    expand_generator_masks_cached,
)
from noise_ec_tpu.gf.field import GF, GF256, GF65536
from noise_ec_tpu.ops.bitops import pack_bitplanes_jax, unpack_bitplanes_jax
from noise_ec_tpu.ops.gf2mm import gf2_matmul_jax
from noise_ec_tpu.ops.pallas_gf2mm import (
    PANEL_XOR_BUDGET,
    bits_to_rows,
    gf2_matmul_pallas_panel_rows,
    gf2_matmul_pallas_sparse_rows,
    panel_plan,
    planes_to_tiled,
    tiled_to_planes,
)
from noise_ec_tpu.obs.device import (
    device_op,
    dispatch_key,
    maybe_analyze_program,
)
from noise_ec_tpu.obs.profiling import record_kernel
from noise_ec_tpu.ops.coalesce import QOS_LANES, current_qos

_FIELDS = {"gf256": GF256, "gf65536": GF65536}

log = logging.getLogger("noise_ec_tpu.ops")

# Jitted shape-generic planes-level matmul (retraces per shape, cached by jit).
_gf2_matmul_jax_jit = jax.jit(gf2_matmul_jax)


# ------------------------------------------------- codec graceful degradation
#
# The device is ONE process-wide resource: when a dispatch fails (XLA
# runtime error, preempted/recycled device, injected fault), every codec
# sharing it will fail the same way — so the circuit breaker guarding the
# device route is process-wide too. codec callers (codec/rs.py _mul)
# consult it around each device matmul: a failure is retried once
# in-call (transient allocator hiccups recover), a second failure trips
# the breaker and the call — and every call while it is open — runs the
# golden host arithmetic instead (noise_ec_codec_fallback_total{reason}).
# A background prober re-tries a tiny canary matmul on the breaker's
# widening half-open schedule and closes it when the device answers
# correctly again (noise_ec_codec_circuit_state 1 -> 2 -> 0).

_codec_breaker = None
_codec_breaker_lock = threading.Lock()
_fallback_children: dict[str, object] = {}
_prober_thread: Optional[threading.Thread] = None
_probe_dev = None


def codec_breaker():
    """The process-wide device-route breaker (lazy singleton)."""
    global _codec_breaker
    with _codec_breaker_lock:
        if _codec_breaker is None:
            from noise_ec_tpu.obs.registry import default_registry
            from noise_ec_tpu.resilience.breakers import CircuitBreaker

            _codec_breaker = CircuitBreaker(
                failure_threshold=1,  # the in-call retry already absorbed
                # one failure; a second is a tripped route
                reset_timeout=5.0,
                max_reset_timeout=60.0,
            )
            default_registry().gauge(
                "noise_ec_codec_circuit_state"
            ).set_callback(lambda: _codec_breaker.state_code())
        return _codec_breaker


def configure_codec_breaker(**kwargs):
    """Replace the process breaker (tests shrink the timeouts; a fresh
    instance also resets state). Returns the new breaker."""
    global _codec_breaker
    from noise_ec_tpu.obs.registry import default_registry
    from noise_ec_tpu.resilience.breakers import CircuitBreaker

    with _codec_breaker_lock:
        _codec_breaker = CircuitBreaker(
            failure_threshold=kwargs.pop("failure_threshold", 1), **kwargs
        )
        default_registry().gauge("noise_ec_codec_circuit_state").set_callback(
            lambda: _codec_breaker.state_code()
        )
        return _codec_breaker


def record_codec_fallback(reason: str) -> None:
    child = _fallback_children.get(reason)
    if child is None:
        from noise_ec_tpu.obs.registry import default_registry

        child = _fallback_children[reason] = default_registry().counter(
            "noise_ec_codec_fallback_total"
        ).labels(reason=reason)
    child.add(1)
    from noise_ec_tpu.obs.events import event

    event("codec.fallback", "warn", reason=reason)


def _probe_device() -> None:
    """Canary: one tiny encode-shaped matmul, checked against the host
    truth. Raises when the device route is still broken."""
    global _probe_dev
    if _probe_dev is None:
        _probe_dev = DeviceCodec(field="gf256")
    M = np.array([[1, 1], [1, 2]], dtype=np.uint8)
    D = np.arange(2 * 64, dtype=np.uint8).reshape(2, 64)
    out = np.asarray(_probe_dev.matmul_stripes(M, D))
    from noise_ec_tpu.matrix.hostmath import host_matvec

    want = host_matvec(_probe_dev.gf, M, D)
    if out.shape != want.shape or not np.array_equal(out, want):
        raise RuntimeError("codec probe produced wrong bytes")


def ensure_codec_prober() -> None:
    """Run the background half-open prober while the breaker is not
    closed (idempotent; the thread exits once the breaker closes)."""
    global _prober_thread
    with _codec_breaker_lock:
        if _prober_thread is not None and _prober_thread.is_alive():
            return
        _prober_thread = threading.Thread(
            target=_probe_loop, name="noise-ec-codec-probe", daemon=True
        )
        _prober_thread.start()


def _probe_loop() -> None:
    br = codec_breaker()
    while True:
        if br.closed:
            return
        remaining = br.open_remaining()
        if remaining > 0:
            time.sleep(min(remaining, 0.05))
            continue
        if not br.allow():  # another caller holds the half-open probe
            time.sleep(0.02)
            continue
        try:
            _probe_device()
        except Exception as exc:  # noqa: BLE001 — any failure keeps it open
            br.record_failure()
            log.warning("codec device probe failed: %s (breaker re-opened "
                        "for %.1fs)", exc, br.open_remaining())
        else:
            br.record_success()
            log.info("codec device probe succeeded; device route restored")
            from noise_ec_tpu.obs.events import event

            event("codec.restore", route="device")
            return


def _resolve_kernel(kernel: str) -> str:
    if kernel == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return kernel


# --------------------------------------------------- device dispatch gate
#
# The device is one shared resource fed from many producer threads (the
# transport dispatcher's workers, the streaming encoder, repair drains,
# the object service). Unbounded, a burst of concurrent dispatches
# queues arbitrarily deep work onto the device while every producer
# keeps allocating host+device buffers for payloads that cannot run yet
# — the OOM shape the fleet lab exposes at scale. The gate is the
# bounded DEVICE QUEUE: at most ``capacity`` dispatches are in flight;
# further callers BLOCK (yield their thread) until a slot frees, which
# propagates backpressure up through the plugin encode/decode paths to
# whatever transport or service admitted the work. Waits are visible as
# the noise_ec_backpressure_* family (layer="device"); a wait past
# ``wait_timeout`` proceeds anyway — the gate is a governor, not a
# deadlock (same escape contract as TCPNetwork.wait_writable).
#
# QoS lanes (docs/object-service.md "QoS lanes"): a contended gate no
# longer drains FIFO. Waiters queue per (lane, tenant) — the lane and
# tenant come from the ambient ``qos_lane`` context the admitting layer
# set (ops/coalesce.py) — and freed slots are HANDED to a queued ticket
# in release() rather than raced for, so the pick order below is the
# actual service order:
#
# - live beats background: a repair/scrub/convert burst queued in the
#   background lane cannot delay an interactive GET behind it;
# - starvation floor: background still gets >= 1 of every
#   ``background_floor`` contended grants, so a saturating live tenant
#   cannot park repair forever (durability work must progress);
# - inside a lane, tenants share by smooth weighted round-robin on
#   their ``weight=`` policy token — a 10x-noisy tenant's queue drains
#   at its weight's share, not at its arrival rate.
#
# The governor escape is unchanged: a ticket that waits past
# ``wait_timeout`` abandons its queue slot and proceeds anyway.


class _Ticket:
    """One queued waiter; ``granted`` flips under the gate lock when
    release() hands it the freed slot (in_flight already charged)."""

    __slots__ = ("granted",)

    def __init__(self):
        self.granted = False


class _TenantQueue:
    """One tenant's FIFO inside a lane + its smooth-WRR credit."""

    __slots__ = ("weight", "current", "tickets")

    def __init__(self, weight: int):
        self.weight = max(1, int(weight))
        self.current = 0
        self.tickets: "deque[_Ticket]" = deque()


class DeviceGate:
    """Bounded admission to the device dispatch path (module comment).

    ``with gate:`` around a dispatch; reentrant nesting is NOT supported
    (DeviceCodec acquires only at its public entry points, which never
    nest). Tests shrink ``capacity`` to pin the blocking behavior and
    ``background_floor`` to pin the lane arbitration.
    """

    def __init__(
        self,
        capacity: int = 8,
        wait_timeout: float = 120.0,
        background_floor: int = 8,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if background_floor < 2:
            raise ValueError(
                f"background_floor must be >= 2, got {background_floor}"
            )
        self.capacity = capacity
        self.wait_timeout = wait_timeout
        self.background_floor = background_floor
        self._cv = threading.Condition()
        self.in_flight = 0
        self.waiters = 0
        self.waits = 0  # local mirror of the counter (tests, reports)
        # lane -> tenant -> _TenantQueue; lanes fixed, tenant queues are
        # created on first wait and deleted when drained (bounds memory
        # and resets a departed tenant's WRR credit).
        self._queues: dict[str, dict[str, _TenantQueue]] = {
            lane: {} for lane in QOS_LANES
        }
        self._lane_waiters = {lane: 0 for lane in QOS_LANES}
        # Contended live grants since the last background grant (or
        # since background stopped waiting) — the starvation-floor odometer.
        self._live_streak = 0
        from noise_ec_tpu.obs.registry import default_registry

        reg = default_registry()
        self._waits_total = reg.counter(
            "noise_ec_backpressure_waits_total"
        ).labels(layer="device")
        self._wait_hist = reg.histogram(
            "noise_ec_backpressure_wait_seconds"
        ).labels(layer="device")
        reg.gauge("noise_ec_backpressure_queue_depth").set_callback(
            lambda: self.in_flight + self.waiters, layer="device"
        )
        depth_gauge = reg.gauge("noise_ec_lane_queue_depth")
        for lane in QOS_LANES:
            depth_gauge.set_callback(
                lambda lane=lane: self._lane_waiters[lane], lane=lane
            )
        self._grants = {
            lane: reg.counter("noise_ec_lane_grants_total").labels(lane=lane)
            for lane in QOS_LANES
        }

    def acquire(self) -> None:
        lane, tenant, weight = current_qos()
        with self._cv:
            if self.in_flight < self.capacity and not self._queued():
                self.in_flight += 1
                self._grants[lane].add(1)
                return
            ticket = _Ticket()
            q = self._queues[lane].get(tenant)
            if q is None:
                q = self._queues[lane][tenant] = _TenantQueue(weight)
            else:
                q.weight = max(1, int(weight))  # latest policy wins
            q.tickets.append(ticket)
            self._lane_waiters[lane] += 1
            self.waits += 1
            self._waits_total.add(1)
            t0 = time.monotonic()
            deadline = t0 + self.wait_timeout
            self.waiters += 1
            try:
                while not ticket.granted:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break  # governor, not a deadlock: proceed
                    self._cv.wait(min(remaining, 0.5))
            finally:
                self.waiters -= 1
                if not ticket.granted:
                    # Governor escape: leave the queue and barge. The
                    # ungranted ticket is still queued (grants happen
                    # under this same lock), so discard is exact.
                    self._discard(lane, tenant, ticket)
                    self.in_flight += 1
                    self._grants[lane].add(1)
            self._wait_hist.observe(time.monotonic() - t0)

    def release(self) -> None:
        with self._cv:
            self.in_flight -= 1
            self._grant_free_slots()
            self._cv.notify_all()

    # ------------------------------------------------ queue internals

    def _queued(self) -> bool:
        return any(self._lane_waiters[lane] for lane in QOS_LANES)

    def _discard(self, lane: str, tenant: str, ticket: _Ticket) -> None:
        q = self._queues[lane].get(tenant)
        if q is None:
            return
        try:
            q.tickets.remove(ticket)
        except ValueError:
            return
        self._lane_waiters[lane] -= 1
        if not q.tickets:
            del self._queues[lane][tenant]

    def _grant_free_slots(self) -> None:
        """Hand every free slot to the next queued ticket (lock held)."""
        while self.in_flight < self.capacity:
            picked = self._pick()
            if picked is None:
                return
            lane, ticket = picked
            ticket.granted = True
            self.in_flight += 1
            self._grants[lane].add(1)
            if lane == "background":
                self._live_streak = 0
            elif self._lane_waiters["background"]:
                self._live_streak += 1
                from noise_ec_tpu.obs.events import event

                # Rate-limited by the event log's per-name bucket; the
                # streak odometer says how starved background is.
                event("qos.preempt", lane=lane, streak=self._live_streak,
                      background_waiting=self._lane_waiters["background"])
            else:
                self._live_streak = 0

    def _pick(self) -> Optional[tuple[str, _Ticket]]:
        live = self._queues["live"]
        background = self._queues["background"]
        if background and (
            not live or self._live_streak >= self.background_floor - 1
        ):
            lane = "background"
        elif live:
            lane = "live"
        else:
            return None
        queues = self._queues[lane]
        # Smooth weighted round-robin (each queue's credit grows by its
        # weight; the max-credit queue serves and repays the total), so
        # grants interleave proportionally instead of bursting.
        total = sum(q.weight for q in queues.values())
        best_name = best = None
        for name, q in queues.items():
            q.current += q.weight
            if best is None or q.current > best.current:
                best_name, best = name, q
        best.current -= total
        ticket = best.tickets.popleft()
        self._lane_waiters[lane] -= 1
        if not best.tickets:
            del queues[best_name]
        return lane, ticket

    def __enter__(self) -> "DeviceGate":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.release()
        return False


_device_gate: Optional[DeviceGate] = None
_device_gate_lock = threading.Lock()


def device_gate() -> DeviceGate:
    """The process-wide device dispatch gate (lazy singleton)."""
    global _device_gate
    with _device_gate_lock:
        if _device_gate is None:
            _device_gate = DeviceGate()
        return _device_gate


def configure_device_gate(**kwargs) -> DeviceGate:
    """Replace the process gate (tests shrink capacity; a fresh instance
    also resets occupancy). Returns the new gate."""
    global _device_gate
    with _device_gate_lock:
        _device_gate = DeviceGate(**kwargs)
        return _device_gate


# ------------------------------------------------- device buffer pool
#
# The host↔device data path used to allocate per dispatch: a fresh
# zeroed pad buffer on the host (a full memset of k * TWp bytes even
# when only the tail columns needed zeroing), a fresh device input
# buffer, and a fresh HBM output buffer. In steady state every one of
# those is the same shape call after call. The pool closes the loop:
#
# - HOST staging: ``acquire_padded`` hands back a recycled page of the
#   right (rows, cols) shape whose pad tail is ALREADY zero (only the
#   columns the previous lease dirtied are re-zeroed), so the per-call
#   cost is the payload memcpy alone. Leases are released only after
#   the dispatch's output has materialized — the buffer backs the H2D
#   transfer, so handing it to the next caller earlier would race an
#   in-flight copy.
# - DEVICE buffers: JAX arrays are immutable, so a device input cannot
#   be refilled in place — instead the stripe-matmul entry points are
#   jitted with ``donate_argnums`` (``_fused_words_fn(..., donate=True)``)
#   so XLA recycles the input's HBM for the output and steady-state
#   encode/decode never grows the allocation high-water mark. Donation
#   is only legal for arrays this module itself staged (callers of the
#   words entries keep ownership of theirs); the pool's ``donate``
#   bookkeeping enforces the invalidated-exactly-once rule.
#
# noise_ec_device_buffer_pool_{hits,misses}_total count the staging
# reuse rate; a miss rate that climbs under steady traffic means the
# shape working set outgrew max_per_key.


class BufferLease:
    """One checked-out staging buffer (see DeviceBufferPool)."""

    __slots__ = ("arr", "key", "payload_cols")

    def __init__(self, arr: np.ndarray, key: tuple, payload_cols: int):
        self.arr = arr
        self.key = key
        self.payload_cols = payload_cols


class DeviceBufferPool:
    """Reusable host staging buffers + device donation bookkeeping
    (module comment above)."""

    def __init__(self, max_per_key: int = 8):
        self.max_per_key = max_per_key
        self._lock = threading.Lock()
        self._free: dict[tuple, list[tuple[np.ndarray, int]]] = {}
        # id(arr) -> weakref (or the array itself when weakrefs are not
        # supported); presence means the buffer was already donated.
        self._donated: dict[int, object] = {}
        from noise_ec_tpu.obs.registry import default_registry

        reg = default_registry()
        self._hits = reg.counter(
            "noise_ec_device_buffer_pool_hits_total"
        ).labels()
        self._misses = reg.counter(
            "noise_ec_device_buffer_pool_misses_total"
        ).labels()

    def acquire_padded(self, rows: int, cols: int, payload_cols: int,
                       dtype=np.uint8) -> BufferLease:
        """A (rows, cols) staging buffer whose columns >= payload_cols
        are zero. Fill ``[:, :payload_cols]`` and release after the
        dispatch's output materializes."""
        key = (rows, cols, np.dtype(dtype).str)
        with self._lock:
            stack = self._free.get(key)
            entry = stack.pop() if stack else None
        if entry is not None:
            arr, prev_payload = entry
            if payload_cols < prev_payload:
                # Only the columns the previous lease dirtied: the rest
                # of the tail is still zero from its own zeroing.
                arr[:, payload_cols:prev_payload] = 0
            self._hits.add(1)
        else:
            arr = np.zeros((rows, cols), dtype=dtype)
            self._misses.add(1)
        return BufferLease(arr, key, payload_cols)

    def release(self, lease: BufferLease) -> None:
        with self._lock:
            stack = self._free.setdefault(lease.key, [])
            if len(stack) < self.max_per_key:
                stack.append((lease.arr, lease.payload_cols))

    def donate(self, arr) -> None:
        """Record that ``arr``'s device buffer is being donated to a
        jitted call. A buffer may be invalidated exactly once; a second
        donation is a use-after-free in waiting and raises."""
        key = id(arr)
        with self._lock:
            prior = self._donated.get(key)
            if prior is not None:
                held = prior() if isinstance(prior, weakref.ref) else prior
                if held is arr:
                    raise RuntimeError(
                        "device buffer donated twice (donation invalidates "
                        "the input exactly once)"
                    )
            try:
                self._donated[key] = weakref.ref(
                    arr, lambda _, k=key: self._donated.pop(k, None)
                )
            except TypeError:  # non-weakref-able: keep a bounded record
                self._donated[key] = arr
            while len(self._donated) > 4096:
                self._donated.pop(next(iter(self._donated)))

    def was_donated(self, arr) -> bool:
        with self._lock:
            prior = self._donated.get(id(arr))
        if prior is None:
            return False
        held = prior() if isinstance(prior, weakref.ref) else prior
        return held is arr


_buffer_pool: Optional[DeviceBufferPool] = None
_buffer_pool_lock = threading.Lock()


def buffer_pool() -> DeviceBufferPool:
    """The process-wide staging buffer pool (lazy singleton)."""
    global _buffer_pool
    with _buffer_pool_lock:
        if _buffer_pool is None:
            _buffer_pool = DeviceBufferPool()
        return _buffer_pool


def configure_buffer_pool(**kwargs) -> DeviceBufferPool:
    """Replace the process pool (tests shrink max_per_key; a fresh
    instance also drops all cached buffers)."""
    global _buffer_pool
    with _buffer_pool_lock:
        _buffer_pool = DeviceBufferPool(**kwargs)
        return _buffer_pool


def donation_supported() -> bool:
    """True when the backend honors donate_argnums (TPU/GPU; the CPU
    backend ignores donation and would warn per call)."""
    try:
        return jax.default_backend() in ("tpu", "gpu")
    # noise-ec: allow(event-on-swallow) — environment probe: no backend means no donation, not an incident
    except Exception:  # noqa: BLE001 — no backend, no donation
        return False


@functools.lru_cache(maxsize=256)
def _fused_xla_fn(degree: int, r: int, k: int, S: int):
    """Compiled (masks, shards) -> product stripes, shape-generic kernel."""

    def f(masks, shards):
        planes = pack_bitplanes_jax(shards, degree)
        out = gf2_matmul_jax(masks, planes)
        return unpack_bitplanes_jax(out, r, S, degree)

    return jax.jit(f)


def _fused_words_pipeline(r: int, m: int, bits_rows: tuple, interpret: bool):
    """Words -> parity-words encode: lane pack -> sparse matmul -> unpack.

    The device never touches sub-word symbol dtypes: XLA's 8-bit (32, 128)
    tiling makes u8<->u32 bitcasts a ~19 ms relayout on v5e, while
    host-side ``ndarray.view('<u4')`` is free and HBM holds the same bytes
    either way. TW must be a multiple of ``lane_quantum(m)`` = 1024*m
    (callers pad; symbols are positionwise so zero padding is inert).

    All three stages consume/produce each other's native layouts — the
    only reshapes are leading-dim merges (metadata-only). Replacing the
    sublane pack (whose (k, TW) -> (k, G8, 8, TL) input reshape was a
    physical relayout) took the RS(10,4) 8 MiB-shard encode from 1.06 ms
    to 0.33 ms on v5e (79 -> 258 GB/s data-in).

    Falls back to the sublane kernels when the lane tile cannot fit VMEM
    (rows > ~96 at m=8).
    """
    from noise_ec_tpu.ops.pallas_pack import (
        _lane_tl,
        pack_words_lanes,
        unpack_words_lanes,
    )

    def f(words):
        from noise_ec_tpu.ops.pallas_fused import (
            NoFusedPlanError,
            fused_encode_words_planned,
        )

        k, TW = words.shape
        W8 = TW // (8 * m)
        # Tier 1: fused kernel (pack -> matmul -> unpack in VMEM, no HBM
        # intermediates — 1.4D total traffic instead of 4.2D), through the
        # verified planner: candidates (single-phase, temp-capped
        # single-phase, manual-DMA split for wide codes) are ordered by
        # estimated VPU cost and compile-probed, so a Mosaic stack OOM
        # demotes to the next plan instead of failing the encode (see
        # pallas_fused "Verified planning"). Only the no-candidate signal
        # falls through to tier 2; a ValueError out of the chosen kernel's
        # build/run is a real bug and must surface.
        try:
            return fused_encode_words_planned(
                bits_rows, words, r, m, interpret=interpret
            )
        except NoFusedPlanError:
            pass
        # Tier 2: three-kernel lane pipeline (packed planes round-trip HBM).
        mr = max(k, r)  # ONE rows budget -> ONE TL for pack AND unpack
        try:
            _lane_tl(TW, m, mr)
        except ValueError:
            return _fused_words_sublane(r, m, interpret, words)
        tiled = pack_words_lanes(words, m, rows_budget=mr, interpret=interpret)
        out = gf2_matmul_pallas_sparse_rows(
            bits_rows, tiled.reshape(k * m, 8, W8), interpret=interpret
        )  # (r*m, 8, W8)
        return unpack_words_lanes(
            out.reshape(r, m, 8, W8), rows_budget=mr, interpret=interpret
        )

    def _fused_words_sublane(r, m, interpret, words):
        from noise_ec_tpu.ops.pallas_pack import (
            pack_words_pallas,
            pack_words16_pallas,
            unpack_words_pallas,
            unpack_words16_pallas,
        )

        pack = pack_words_pallas if m == 8 else pack_words16_pallas
        unpack = unpack_words_pallas if m == 8 else unpack_words16_pallas
        k, TW = words.shape
        planes = pack(words, interpret=interpret)  # (k, m, W)
        W = planes.shape[2]
        tiled = planes.reshape(k * m, 8, W // 8)
        out = gf2_matmul_pallas_sparse_rows(
            bits_rows, tiled, interpret=interpret
        )
        planes_out = tiled_to_planes(out, W).reshape(r, m, W)
        return unpack(planes_out, interpret=interpret)

    return f


def _jit_words(f, donate: bool):
    """jit a words pipeline, donating the input words' HBM into the
    output when asked AND the backend supports it (docs/design.md
    donation rules: only callers that staged the device array themselves
    may ask — the words entries' public contract keeps caller
    ownership)."""
    if donate and donation_supported():
        return jax.jit(f, donate_argnums=(0,))
    return jax.jit(f)


@functools.lru_cache(maxsize=256)
def _fused_words_fn(r: int, bits_rows: tuple, interpret: bool,
                    donate: bool = False):
    """GF(2^8) fused encode on uint32 WORDS: (k, TW) -> (r, TW)."""
    return _jit_words(_fused_words_pipeline(r, 8, bits_rows, interpret),
                      donate)


# Pad-to multiples for the words entry points: the lane-pack grouping unit
# (8*m*128 words — see pallas_pack lane_quantum).
WORD_QUANTUM = 8192  # uint32 words; 32 KiB per shard (GF(2^8))
WORD_QUANTUM16 = 16384  # uint32 words; 64 KiB per shard (GF(2^16))


def pad_words(TW: int) -> int:
    return -(-TW // WORD_QUANTUM) * WORD_QUANTUM


def pad_words16(TW: int) -> int:
    return -(-TW // WORD_QUANTUM16) * WORD_QUANTUM16


@functools.lru_cache(maxsize=256)
def _fused_words16_fn(r: int, bits_rows: tuple, interpret: bool,
                      donate: bool = False):
    """GF(2^16) fused encode on uint32 WORDS: (k, TW) -> (r, TW).

    Each word holds two little-endian uint16 symbols; the 16x16 delta-swap
    network packs 16 planes per shard.
    """
    return _jit_words(_fused_words_pipeline(r, 16, bits_rows, interpret),
                      donate)


# ----------------------------------------------------- panel words tier


def _panel_words_pipeline(r_rows: int, m: int, bits_rows: tuple,
                          plan: tuple, interpret: bool):
    """Wide-geometry words pipeline: row-blocked lane pack -> block-panel
    K-tiled matmul -> row-blocked unpack. Same layout contract as
    _fused_words_pipeline (pack and unpack share one TL by construction
    — pallas_pack.PACK_ROW_BLOCK), so the two tiers are byte-identical
    and interchangeable per matrix."""
    from noise_ec_tpu.ops.pallas_pack import (
        pack_words_lanes_blocked,
        unpack_words_lanes_blocked,
    )

    def f(words):
        k, TW = words.shape
        W8 = TW // (8 * m)
        tiled = pack_words_lanes_blocked(words, m, interpret=interpret)
        out = gf2_matmul_pallas_panel_rows(
            bits_rows, tiled.reshape(k * m, 8, W8), plan=plan,
            interpret=interpret,
        )
        return unpack_words_lanes_blocked(
            out.reshape(r_rows, m, 8, W8), interpret=interpret
        )

    return f


@functools.lru_cache(maxsize=128)
def _panel_words_fn(r_rows: int, m: int, bits_rows: tuple, plan: tuple,
                    interpret: bool, donate: bool = False):
    """Jitted panel-tier words entry: (k, TW) u32 -> (r_rows, TW) u32
    with the (KB, RB, TL) plan baked (the plan is part of the program —
    and of the dispatch cache key, so a plan change is a visible
    recompile, not a silent one)."""
    return _jit_words(
        _panel_words_pipeline(r_rows, m, bits_rows, plan, interpret),
        donate,
    )


@functools.lru_cache(maxsize=256)
def _panel_probe_compiles(bits_rows: tuple, C: int, plan: tuple) -> bool:
    """AOT-compile two lane tiles of the panel matmul under ``plan``;
    True iff Mosaic accepts it (same two-tile rationale as the fused
    planner's probe: past two tiles VMEM pressure is grid-length
    independent). With G > 1 in the plan this compiles the whole
    sub-launch CHAIN — every one of the G programs — so a Mosaic
    program-size rejection of any slice fails the probe and
    panel_plan_for escalates G instead of demoting straight to MXU."""
    TL = plan[2]
    try:
        shape = jax.ShapeDtypeStruct((C, 8, 2 * TL), jnp.uint32)

        def f(planes):
            return gf2_matmul_pallas_panel_rows(
                bits_rows, planes, plan=plan
            )

        jax.jit(f).lower(shape).compile()
        return True
    except Exception:  # noqa: BLE001 — any compile failure escalates
        log.warning("panel plan %s failed the compile probe", plan)
        return False


def tile_label(plan: tuple) -> str:
    """The (KB, RB, TL) triple as the `tile` label value of the
    noise_ec_kernel_tile_* families (temp cap and sub-launch count
    excluded: both are derived from the network + triple and the label
    set must stay bounded)."""
    return f"kb{plan[0]}_rb{plan[1]}_tl{plan[2]}"


def plan_sublaunches(plan: tuple) -> int:
    """G of a panel plan (1 for legacy 4-tuple plans)."""
    return plan[4] if len(plan) > 4 else 1


_sublaunch_children: dict[str, object] = {}


def record_sublaunch_dispatch(entry: str, g: int) -> None:
    """Count a panel-routed dispatch's G sub-launches against
    ``noise_ec_kernel_sublaunch_dispatches_total{entry}`` — the
    execution-side view of the split (the program-side count lives in
    pallas_gf2mm._record_sublaunch_program)."""
    child = _sublaunch_children.get(entry)
    if child is None:
        from noise_ec_tpu.obs.registry import default_registry

        child = _sublaunch_children[entry] = default_registry().counter(
            "noise_ec_kernel_sublaunch_dispatches_total"
        ).labels(entry=entry)
    child.add(g)


# ------------------------------------------------ persistent compile cache
#
# The sub-launch split multiplies the panel program set (G programs per
# wide geometry instead of one) and the batch ladder multiplies it
# again — and every one of those programs was re-compiled from scratch
# on every process restart, seconds each on real hardware. The
# persistent JAX compilation cache (CLI -compile-cache-dir) keeps the
# serialized executables on disk keyed by program fingerprint, so a
# restarted node replays the whole set as cache hits; the ladder
# pre-warm hook (prewarm_ladder) compiles the expected program set at
# startup so even the FIRST restart after a deploy pays the compile
# tax off the serving path.

_cache_hits_child = None
_cache_listener_installed = False


def _note_cache_event(event: str) -> None:
    """jax.monitoring listener body: count persistent-compile-cache
    hits into noise_ec_compile_cache_hits_total (split out for tests —
    the monitoring hook itself cannot be fired on demand)."""
    global _cache_hits_child
    if not event.startswith("/jax/compilation_cache/cache_hits"):
        return
    if _cache_hits_child is None:
        from noise_ec_tpu.obs.registry import default_registry

        _cache_hits_child = default_registry().counter(
            "noise_ec_compile_cache_hits_total"
        ).labels()
    _cache_hits_child.add(1)


def enable_compile_cache(cache_dir: str) -> bool:
    """Arm the persistent JAX compilation cache at ``cache_dir``
    (module comment above). Returns True when armed; safe to call
    before OR after the first jit (jax memoizes its is-cache-used
    check per task, so the cache state is reset after reconfiguring).
    Size/time floors are zeroed: the program set here is many SMALL
    kernels, exactly what the defaults would skip."""
    global _cache_listener_installed
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception as exc:  # noqa: BLE001 — cache is an optimization
        log.warning("persistent compile cache unavailable: %s", exc)
        return False
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()  # drop the memoized pre-config decision
    # noise-ec: allow(event-on-swallow) — environment probe: older jax initializes lazily
    except Exception:  # noqa: BLE001 — older jax initializes lazily
        pass
    if not _cache_listener_installed:
        try:
            from jax import monitoring

            def _listener(event, **kwargs):  # noqa: ANN001 — jax hook
                _note_cache_event(event)

            monitoring.register_event_listener(_listener)
            _cache_listener_installed = True
        except Exception:  # noqa: BLE001 — hit counter is best-effort
            log.debug("jax monitoring listener unavailable")
    log.info("persistent JAX compile cache at %s", cache_dir)
    return True


def prewarm_ladder(codec: "DeviceCodec", M: np.ndarray,
                   stripe_bytes: int = 4096, max_batch: int = 8) -> int:
    """The ladder pre-warm hook: compile (and, with the persistent
    cache armed, serialize) the power-of-two batch-ladder programs for
    matrix ``M`` before traffic arrives, so geometry churn after a
    restart replays them as compile-cache hits instead of paying the
    cold-compile tax per novel batch size. Returns the number of
    ladder rungs warmed."""
    M = np.asarray(M)
    k = M.shape[1]
    warmed = 0
    B = 1
    while B <= max_batch:
        Ds = [np.zeros((k, stripe_bytes), dtype=codec.gf.dtype)
              for _ in range(B)]
        codec.matmul_stripes_many(M, Ds)
        warmed += 1
        B *= 2
    return warmed


# Whole-plane baked XOR-network kernels scale with the generator's
# set-bit count: Mosaic program size is O(XORs) and Paar factoring is
# super-linear in terms, so geometries past this raw-XOR budget leave the
# whole-plane kernels. They used to fall straight to the dense MXU
# bit-plane kernel (ops/mxu_gf2.py: fixed 64*r int8 MACs per input byte —
# a ~110 GB/s roofline at r=56, under half the ROADMAP bar); the
# block-panel tier (pallas_gf2mm "panel tier") now sits between: Paar
# factoring runs PER PANEL (seconds, not the >9 min a whole RS(200,56)
# network costs) and VMEM per grid step is panel-sized, so the XOR
# network stays on the VPU up to PANEL_XOR_BUDGET raw XORs. RS(50,20)
# (~32k raw XORs, the widest code the single-step kernel wins) stays on
# the whole-plane route.
_BAKED_XOR_BUDGET = 60_000

# The panel tier's raw-XOR ceiling on the interpret kernel (CPU tests).
# Its OWN constant, deliberately NOT aliased to _BAKED_XOR_BUDGET even
# though the values coincide today: the two budgets answer different
# questions (_BAKED_XOR_BUDGET = "when does the whole-plane kernel stop
# winning", this = "how big a network can interpret-mode afford to
# trace at all"), so tuning the baked budget must never silently move
# interpret-mode panel routing with it. Rationale for the value:
# interpret mode exists for correctness coverage, and tracing +
# XLA:CPU-compiling a multi-hundred-k-op unrolled network takes minutes
# per geometry there (measured ~220 s for RS(200,56)) — the MXU route
# is bit-exact and cheap to build, so wide interpret runs use it. Tests
# that need the panel kernels at interpret force them via the explicit
# plan override.
_PANEL_XOR_BUDGET_INTERPRET = 60_000

# The baked pipeline's pack/unpack stages hold (rows, 8, 2*TL) u32 tiles in
# VMEM regardless of the XOR cost, so a matrix with many INPUT or OUTPUT
# rows OOMs even when its network is tiny (measured: a (3, 200)
# reconstruction matrix — 19k XORs — died in pallas_pack at 24.8M scoped
# vs the 16M VMEM limit). RS(50,20) (70 rows total) is measured-good; 96
# keeps ~2x VMEM margin on the pack tile model (96*8*1024*4 = 3.1 MiB).
_BAKED_MAX_ROWS = 96


def decode1_fold_matrix(gf: GF, A: np.ndarray, j: int) -> np.ndarray:
    """(r2, m) matrix folding the single-corrupt-row decode into ONE
    generator-shaped product (the device analogue of the host shim's
    rs_decode1_fused; same per-column guarantee as matrix/bw.py).

    With aug = [A | I] the parity check over the m received rows and
    p0 the first check row seeing basis column j:

    - row 0 = e_j ^ inv(A[p0,j]) * aug[p0]  — applied to the received
      rows this is rows[j] ^ inv(A[p0,j]) * s_p0, i.e. row j with the
      single-support correction applied (the e_j and aug terms cancel
      at column j, so the corrupted row is reconstructed from the
      others — correcting a fully-corrupt row IS reconstruction);
    - rows 1.. = aug[q] ^ (A[q,j]/A[p0,j]) * aug[p0] for q != p0 —
      each is s_q ^ c_q * s_p0, zero exactly where check row q is
      consistent with the hypothesis "only row j is in error". A
      column with ANY nonzero verify byte must be re-decoded by the
      general host path; columns that verify (including clean columns,
      where s_p0 = 0 makes the correction a no-op) are exact.

    Module-level so the parallel layer can build the fold for mesh-
    sharded decode steps without constructing a DeviceCodec.
    """
    A = np.asarray(A, dtype=gf.dtype)
    r2, k = A.shape
    if r2 < 2:
        # One parity row leaves NO consistency rows: the mask would
        # claim every column verified with zero verification behind
        # it. Matches the host kernel's e >= 1 requirement (a single
        # redundant share cannot correct anyway).
        raise ValueError(
            f"single-support decode needs >= 2 check rows, got {r2}"
        )
    if not 0 <= j < k:
        raise ValueError(f"j must index a basis row, got {j}")
    nz = np.flatnonzero(A[:, j])
    if nz.size == 0:
        raise ValueError(f"check column {j} is identically zero")
    p0 = int(nz[0])
    aug = np.concatenate([A, np.eye(r2, dtype=gf.dtype)], axis=1)
    inv_c = int(gf.inv(int(A[p0, j])))
    D = np.zeros((r2, k + r2), dtype=gf.dtype)
    D[0, j] = 1
    D[0] ^= gf.mul(inv_c, aug[p0].astype(np.int64)).astype(gf.dtype)
    out_i = 1
    for q in range(r2):
        if q == p0:
            continue
        c_q = int(gf.mul(int(A[q, j]), inv_c))
        D[out_i] = aug[q] ^ gf.mul(
            c_q, aug[p0].astype(np.int64)
        ).astype(gf.dtype)
        out_i += 1
    return D


class DeviceCodec:
    """Runs GF matrix x stripes products on the default JAX device.

    This one primitive is both reference hot loops: encode is
    parity_rows @ data (main.go:262), reconstruct is
    inverted_submatrix_rows @ survivors (main.go:77).
    """

    def __init__(self, field: str = "gf256", kernel: str = "auto"):
        if field not in _FIELDS:
            raise ValueError(f"unknown field {field!r}")
        self.field = field
        self.gf: GF = _FIELDS[field]()
        self.kernel = _resolve_kernel(kernel)
        if self.kernel not in ("pallas", "pallas_interpret", "xla"):
            raise ValueError(f"unknown kernel {self.kernel!r}")
        self._mask_dev_cache: dict[bytes, jnp.ndarray] = {}
        self._rows_cache: dict[bytes, tuple] = {}
        self._cost_cache: dict[bytes, int] = {}
        self._m2w_cache: dict = {}
        self._mxu = None

    def _key(self, M: np.ndarray) -> bytes:
        return M.tobytes() + M.shape[1].to_bytes(4, "little")

    def masks_for(self, M: np.ndarray) -> np.ndarray:
        """(r, k) GF matrix -> (m*r, m*k) uint32 select-mask matrix, cached."""
        return expand_generator_masks_cached(self.gf, M)

    def _panel_xor_budget(self) -> int:
        """The raw-XOR ceiling of the panel tier for THIS codec's kernel
        (module comment at _PANEL_XOR_BUDGET_INTERPRET: interpret mode
        cannot afford multi-hundred-k-op unrolled programs)."""
        if self.kernel == "pallas_interpret":
            return _PANEL_XOR_BUDGET_INTERPRET
        return PANEL_XOR_BUDGET

    def bits_rows_for(self, M: np.ndarray) -> tuple:
        """(r, k) GF matrix -> hashable per-row term tuples for the sparse
        kernel (cached).

        The shared choke point for EVERY baked-kernel entry (words,
        planes, byte-sliced, panel), so the PLANNING-TIME guard lives
        here: a network past THIS KERNEL'S panel budget must never reach
        Paar factoring (the panel tier factors per panel, but raw
        expansion/term-listing of a truly huge network is itself wasted
        work) or bake an unboundedly large program, through any path.
        Only the XOR-cost bound applies at this level — the row bound
        models the words entries' pack-stage VMEM, which the planes
        entry never runs, so it is enforced by route_for at the
        words/stripes routing decision instead (a (3, 200)
        reconstruction matrix stays legal here for matmul_planes).
        matmul_stripes/matmul_words route over-budget matrices to the
        MXU before ever calling this; direct callers get the clear error.
        """
        if self._xor_cost_for(M) > self._panel_xor_budget():
            raise NotImplementedError(
                "matrix exceeds the panel-tier XOR budget; use "
                "matmul_stripes/matmul_words (gf256) or the byte-sliced "
                "entries (gf65536) — the MXU route"
            )
        M = np.ascontiguousarray(np.asarray(M, dtype=self.gf.dtype))
        key = self._key(M)
        hit = self._rows_cache.get(key)
        if hit is None:
            hit = bits_to_rows(expand_generator_bits(self.gf, M))
            if len(self._rows_cache) > 4096:
                self._rows_cache.clear()
            self._rows_cache[key] = hit
        return hit

    def _xor_cost_for(self, M: np.ndarray) -> int:
        """Raw two-input XOR count of M's GF(2) bit-network (set bits
        minus output rows), cached — the route_for decision input."""
        M = np.ascontiguousarray(np.asarray(M, dtype=self.gf.dtype))
        key = self._key(M)
        hit = self._cost_cache.get(key)
        if hit is None:
            bits = expand_generator_bits(self.gf, M)
            hit = int(np.count_nonzero(bits)) - bits.shape[0]
            if len(self._cost_cache) > 4096:
                self._cost_cache.clear()
            self._cost_cache[key] = hit
        return hit

    def route_for(self, M: np.ndarray) -> str:
        """Which kernel family runs this matrix: "baked" (whole-plane
        XOR-network VPU kernels), "panel" (block-panel K-tiled VPU
        kernels — wide geometries), or "mxu" (dense int8 bit-plane
        matmul — past every XOR-network budget). Exposed so tests can
        pin the tier decision; NO supported geometry raises here — the
        old "must not even attempt" refusal became this routing.

        The row bound counts the rows the WHOLE-PLANE pipeline runs:
        symbol rows for gf256, 2x byte rows for the byte-sliced wide
        field — one bound (_BAKED_MAX_ROWS) for the one pack stage both
        share. Past the row bound OR the whole-plane XOR budget the
        matrix moves to the panel tier (row-blocked pack, K-tiled
        matmul — no whole-matrix VMEM residency, so no row bound), and
        past this kernel's panel XOR budget to the MXU.
        """
        r, k = np.asarray(M).shape
        rows = 2 * max(r, k) if self.gf.degree == 16 else max(r, k)
        cost = self._xor_cost_for(M)
        if cost > self._panel_xor_budget():
            return "mxu"
        if rows > _BAKED_MAX_ROWS or cost > _BAKED_XOR_BUDGET:
            return "panel"
        return "baked"

    def panel_plan_for(self, M: np.ndarray):
        """The verified (KB, RB, TL, temp_cap, G) panel plan for a
        panel-routed matrix, or None when no split compiles (the
        dispatch then falls back to the MXU route). Cached per matrix;
        the plan triple AND the sub-launch count G join the dispatch
        cache key, the triple labels the ``noise_ec_kernel_tile_*``
        telemetry.

        G starts at the program-size model's choice
        (``panel_plan`` / ``sublaunch_count``: estimated Mosaic op
        count per sub-launch vs PANEL_SUBLAUNCH_XOR_BUDGET) and the
        AOT probe confirms it. A Mosaic rejection ESCALATES G
        (doubling, capped at PK = one K-block per launch) and
        re-probes; only when even G = PK fails does the matrix demote
        to the MXU route — the split path replaced the old
        demote-on-first-rejection behavior."""
        bits_rows = self.bits_rows_for(M)
        m = self.gf.degree
        C = (2 * M.shape[1] * 8) if m == 16 else (M.shape[1] * 8)
        plan = panel_plan(bits_rows, C)
        if self.kernel == "pallas_interpret":
            return plan  # no scoped-vmem limit to probe against
        PK = max(1, -(-C // plan[0]))
        while True:
            if _panel_probe_compiles(bits_rows, C, plan):
                return plan
            G = plan[4]
            if G >= PK:
                log.warning(
                    "panel plan %s rejected even at G = K-blocks; "
                    "demoting matrix to the MXU route", plan,
                )
                return None
            plan = plan[:4] + (min(PK, G * 2),)
            log.info(
                "panel probe escalating to %d sub-launches for a "
                "%d-col network", plan[4], C,
            )

    def _route_plan(self, M: np.ndarray):
        """(route, plan): the tier decision plus, for the panel tier,
        the verified tile plan. A panel-routed matrix whose plan fails
        the compile probe demotes to ("mxu", None) here — the one place
        the demotion can happen, so every entry point agrees."""
        route = self.route_for(M)
        if route != "panel":
            return route, None
        plan = self.panel_plan_for(M)
        return ("panel", plan) if plan is not None else ("mxu", None)

    def _key_shape(self, M: np.ndarray, shape: tuple) -> tuple:
        """Dispatch-cache key shape: panel-routed matrices append the
        (KB, RB, TL) tile triple AND the sub-launch count G, so a plan
        change (auto-tuner update, probe escalation, demotion) reads as
        a compile-route dispatch in the telemetry instead of silently
        re-timing under the old key."""
        if self.kernel != "xla":
            route, plan = self._route_plan(M)
            if route == "panel":
                return shape + ("panel",) + plan[:3] + (plan[4],)
        return shape

    def _m2_for_wide(self, M: np.ndarray):
        """Cached (16r, 16k) int8 bit expansion of a gf65536 matrix for
        the byte-sliced MXU route (shared implementation — see
        mxu_gf2.cached_bit_expansion for the key scheme, bound, and
        tracer-leak guard)."""
        from noise_ec_tpu.ops.mxu_gf2 import cached_bit_expansion

        return cached_bit_expansion(self._m2w_cache, self.gf, M, bound=64)

    def _mxu_for(self):
        if self._mxu is None:
            from noise_ec_tpu.ops.mxu_gf2 import MxuCodec

            self._mxu = MxuCodec(
                self.gf, interpret=self.kernel == "pallas_interpret"
            )
        return self._mxu

    def supports_matrix(self, M: np.ndarray) -> bool:
        """Cheap predicate: does a device kernel exist for ``M``?

        Always True since the wide-field MXU route landed — every matrix
        has a device route on the stripes/byte-sliced entries (baked
        network or dense MXU). Kept as an API so decode dispatch code
        written against the predicate keeps working, and as the hook if a
        future backend ever reintroduces an unsupported region.
        """
        del M
        return True

    def supports_syndrome(self, A: np.ndarray) -> bool:
        """supports_matrix for the syndrome route (see supports_matrix)."""
        del A
        return True

    def matmul_stripes(self, M: np.ndarray, D) -> np.ndarray:
        """(r, k) GF matrix x (k, S) stripes -> (r, S), computed on device.

        Device-telemetry wrapper: every dispatch lands in
        ``noise_ec_device_op_seconds{kernel,route}`` — the first call per
        (matrix, shape, kernel) cache key as ``route="compile"`` (feeding
        the recompile counter), warm calls as ``route="execute"``. This
        entry materializes the result on host, so the timing covers the
        device round trip, not just the async submit (obs/device.py).
        """
        M = np.asarray(M)
        D = np.asarray(D, dtype=self.gf.dtype)
        r, k = M.shape
        if D.shape[0] != k:
            raise ValueError(f"matrix cols {k} != stripe rows {D.shape[0]}")
        entry = f"matmul_stripes_{self.kernel}"
        record_kernel(entry, D.nbytes)
        key = dispatch_key(entry, self.kernel, M, self._key_shape(M, D.shape))
        # Bounded device queue: admission BEFORE the telemetry window so
        # a gated wait reads as backpressure, not kernel latency.
        with device_gate(), device_op(entry, key, nbytes=D.nbytes) as dt:
            return self._matmul_stripes_dispatch(M, D, dt)

    def _matmul_stripes_dispatch(self, M: np.ndarray, D: np.ndarray,
                                 dt) -> np.ndarray:
        r, k = M.shape
        S = D.shape[1]
        m = self.gf.degree
        if self.kernel == "xla":
            fn = _fused_xla_fn(m, r, k, S)
            masks_dev = jnp.asarray(self.masks_for(M))
            D_dev = jnp.asarray(D)
            out = fn(masks_dev, D_dev)
            if dt.route == "compile":
                # Roofline: cost_analysis of the freshly cached program
                # (rate-limited per entry — the AOT walk is not free and
                # must not ride a geometry-churn storm).
                maybe_analyze_program(dt.entry, fn, masks_dev, D_dev)
            # np.array (copy) so callers get an ordinary writable ndarray,
            # not a read-only view of the device buffer.
            return np.array(out)
        if m == 16:
            # PACKED BYTE-SLICED GF(2^16): each u16 symbol splits into
            # ADJACENT (lo, hi) byte rows (the packed (2k, S) panel —
            # pallas_pack.pack_u16_bytesliced), and the device runs the
            # GF(2^8)-shaped m=8 pipeline — the expanded bit matrix needs
            # NO permutation because the flat plane index is identical:
            # 16*j + b == (2*j + b//8)*8 + b%8. This trades two host
            # relayout passes for the 3-round delta-swap transpose
            # (vs 4 rounds for 16-plane groups) and the m=8 lane quantum.
            from noise_ec_tpu.ops.pallas_pack import (
                pack_u16_bytesliced,
                unpack_u16_bytesliced,
            )

            out_b = self._bytesliced_words(
                M, pack_u16_bytesliced(D), 2 * r, dt
            )
            return unpack_u16_bytesliced(out_b)
        route, plan = self._route_plan(M)
        if route == "mxu":
            # Past every XOR-network budget (_BAKED_XOR_BUDGET /
            # PANEL_XOR_BUDGET, or a panel plan the probe demoted):
            # dense MXU bit-plane product.
            # Already charged to matmul_stripes_{kernel} above; a second
            # record here would double-count the traffic.
            return self._mxu_for().encode_stripes(M, D)
        TWp = pad_words(-(-S // 4))
        lease = None
        if 4 * TWp != S:
            # Pooled staging page with a pre-zeroed pad tail: the per-call
            # cost is the payload memcpy, not an allocation + full memset.
            lease = buffer_pool().acquire_padded(
                k, 4 * TWp, S, dtype=self.gf.dtype
            )
            buf = lease.arr
            buf[:, :S] = D
        else:
            buf = np.ascontiguousarray(D)
        words = buf.view("<u4")
        # This entry stages its own device array (device_put below), so
        # the input HBM is donated into the output: steady-state encode /
        # reconstruct reuses one allocation instead of growing two.
        if route == "panel":
            dt.tile = tile_label(plan)
            record_sublaunch_dispatch(dt.entry, plan_sublaunches(plan))
            fn = _panel_words_fn(
                r, 8, self.bits_rows_for(M), plan,
                self.kernel == "pallas_interpret", True,
            )
        else:
            fn = _fused_words_fn(
                r, self.bits_rows_for(M),
                self.kernel == "pallas_interpret", True,
            )
        words_dev = jax.device_put(words)
        if donation_supported():
            buffer_pool().donate(words_dev)
        # np.array: writable copy (np.asarray of a jax array is read-only
        # and callers are promised an ordinary ndarray).
        out_w = np.array(fn(words_dev))
        if lease is not None:
            # Output materialized => the H2D copy is long done; the
            # staging page is safe to hand to the next dispatch.
            buffer_pool().release(lease)
        if dt.route == "compile":
            # ShapeDtypeStruct, not the live array: the input was donated
            # and must not be touched again.
            maybe_analyze_program(
                dt.entry, fn, jax.ShapeDtypeStruct(words.shape, words.dtype)
            )
        return np.ascontiguousarray(out_w.view(self.gf.dtype)[:, :S])

    def matmul_stripes_many(self, M: np.ndarray, Ds: list) -> list:
        """B same-shape stripes products through ONE gated dispatch.

        The CoalescingDispatcher's batch entry: concurrent live requests
        sharing (matrix, stripe shape) stack into a single
        ``matmul_words_batch``-class device call (vmap over the batch
        axis) on the baked GF(2^8) routes, or a stripe-axis concatenation
        (symbols are positionwise, so ``M @ [D1|D2|..]`` is exact) on the
        XLA kernel and the byte-sliced wide field. Results are
        byte-identical to B separate :meth:`matmul_stripes` calls; one
        DeviceGate slot and one telemetry window cover the whole batch.
        """
        Ds = [np.asarray(D, dtype=self.gf.dtype) for D in Ds]
        if not Ds:
            return []
        if len(Ds) == 1:
            return [self.matmul_stripes(M, Ds[0])]
        M = np.asarray(M)
        r, k = M.shape
        S = Ds[0].shape[1]
        for D in Ds:
            if D.shape != (k, S):
                raise ValueError(
                    "matmul_stripes_many requires same-shape stripes "
                    f"(got {D.shape} vs {(k, S)})"
                )
        # Batch-size LADDER: runtime batch sizes are whatever concurrency
        # produced (3 today, 7 the next call), but every distinct batched
        # shape is its own jitted program — unquantized, a traffic wave
        # would compile once per novel size (seconds each over the
        # tunnel). Rounding B up to the next power of two bounds the
        # program set to log2(max_batch) variants; the pad members are
        # DISCARDED rows, so they need no zeroing — whatever bytes the
        # pooled staging page already holds are valid GF symbols.
        B = len(Ds)
        B_pad = 1 << (B - 1).bit_length()
        entry = f"matmul_stripes_{self.kernel}"
        nbytes = sum(D.nbytes for D in Ds)
        record_kernel(entry, nbytes)
        key = dispatch_key(
            entry, self.kernel, M,
            self._key_shape(M, (B_pad,) + Ds[0].shape),
        )
        with device_gate(), device_op(entry, key, nbytes=nbytes) as dt:
            if self.kernel != "xla" and self.gf.degree == 8:
                return self._stripes_many_words(M, Ds, B_pad, dt)
            # Mesh dispatch tier (parallel/mesh.py, docs/design.md §13):
            # the batch dimension shards over the "stripes" axis of all
            # visible chips — the XLA kernel on the pjit tier, the baked
            # wide field on the byte-sliced words tier. Same gate slot,
            # telemetry window and breaker wrapping as the single-device
            # routes (a mesh fault fans out through the callers' own
            # fallback arms like any other dispatch error).
            from noise_ec_tpu.parallel.mesh import mesh_router

            router = mesh_router()
            if router.should_shard(B_pad):
                if self.kernel == "xla":
                    return router.matmul_sym_many(self, M, Ds, B_pad)
                if self.gf.degree == 16 and self._route_plan(M)[0] != "mxu":
                    return router.matmul_bytesliced_many(self, M, Ds, B_pad)
            pad = (
                [np.empty((k, (B_pad - B) * S), dtype=self.gf.dtype)]
                if B_pad != B else []
            )
            out = self._matmul_stripes_dispatch(
                M, np.concatenate(Ds + pad, axis=1), dt
            )
            return [
                np.ascontiguousarray(out[:, b * S : (b + 1) * S])
                for b in range(B)
            ]

    def _stripes_many_words(self, M: np.ndarray, Ds: list, B_pad: int,
                            dt) -> list:
        """GF(2^8) batch route: stack into (B_pad, k, TWp) pooled staging
        words and run the one vmapped fused dispatch."""
        B = len(Ds)
        k, S = Ds[0].shape
        TWp = pad_words(-(-S // 4))
        lease = buffer_pool().acquire_padded(B_pad * k, 4 * TWp, S)
        buf = lease.arr
        for b, D in enumerate(Ds):
            buf[b * k : (b + 1) * k, :S] = D
        words = buf.view("<u4").reshape(B_pad, k, TWp)
        out_w = np.array(self._matmul_words_batch_dispatch(M, words, dt))
        buffer_pool().release(lease)
        res = out_w.view(self.gf.dtype)  # (B_pad, r, 4*TWp) symbols
        return [np.ascontiguousarray(res[b, :, :S]) for b in range(B)]

    def syndrome_stripes(
        self, A: np.ndarray, rows: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Decode syndrome on device: s = A @ rows[:k] ^ rows[k:].

        ``A`` is the (m-k, k) basis-prediction matrix from the
        error-correcting decode (matrix/bw.py); ``rows`` the full (m, S)
        received stripes. Because XOR is addition in the field, the fused
        form is ONE generator-shaped device matmul with the augmented
        matrix [A | I] over all m rows — the same kernel as encode, so the
        decode guarantee (infectious Decode, /root/reference/main.go:77)
        rides the 400 GB/s path when stripes are device-resident. Returns
        (s, per-column nonzero-row counts); the count reduction is host-side
        (O(S) bytes, negligible next to the matmul).
        """
        A = np.asarray(A, dtype=self.gf.dtype)
        r2, k = A.shape
        rows = np.asarray(rows, dtype=self.gf.dtype)
        if rows.shape[0] != k + r2:
            raise ValueError(f"expected {k + r2} rows, got {rows.shape[0]}")
        aug = np.concatenate(
            [A, np.eye(r2, dtype=self.gf.dtype)], axis=1
        )
        s = self.matmul_stripes(aug, rows)
        return s, np.count_nonzero(s, axis=0)

    def decode1_matrix(self, A: np.ndarray, j: int) -> np.ndarray:
        """See :func:`decode1_fold_matrix` (instance sugar over self.gf)."""
        return decode1_fold_matrix(self.gf, A, j)

    def decode1_words(
        self, A: np.ndarray, j: int, rows_words
    ) -> tuple:
        """Device-resident single-corrupt-row decode step.

        ``rows_words``: (m, TW) uint32 device words of all m received
        stripes. Returns (corrected_row_j_words (TW,), verify_or (TW,))
        — verify_or is the OR-fold of the consistency rows; a byte of it
        nonzero means that byte column defeated the single-support
        hypothesis and must go through the general path. One fused
        generator-shaped matmul (same kernel and rate class as encode)
        plus an elementwise OR — jit-composable for chained timing.
        """
        D = self.decode1_matrix(A, j)  # raises for r2 < 2 (no verify rows)
        out = self.matmul_words(D, rows_words)
        corrected = out[0]
        bad = out[1]
        for q in range(2, out.shape[0]):
            bad = bad | out[q]
        return corrected, bad

    def _bytesliced_words(self, M: np.ndarray, Db: np.ndarray,
                          r2: int, dt=None) -> np.ndarray:
        """(2k, S) uint8 packed byte rows x the gf65536 matrix ->
        (2r, S) uint8.

        Runs the m=8 words pipeline over byte rows with the UNPERMUTED
        expanded GF(2^16) bits (see matmul_stripes).
        """
        k2, S = Db.shape
        TWp = pad_words(-(-S // 4))
        if 4 * TWp != S:
            buf = np.zeros((k2, 4 * TWp), dtype=np.uint8)
            buf[:, :S] = Db
        else:
            buf = np.ascontiguousarray(Db)
        route, plan = self._route_plan(M)
        if route == "mxu":
            # Over-budget wide-field matrices run the dense MXU kernel
            # directly on the byte rows: the kernel is pure GF(2) and
            # the UNPERMUTED (16r, 16k) expansion over 2k byte rows IS
            # an (8R, 8K) bit matrix with R = 2r, K = 2k. Same route
            # gate as gf256 (route_for), closing the round-5 refusal gap.
            from noise_ec_tpu.ops.mxu_gf2 import mxu_encode_words_bits

            out_w = np.array(mxu_encode_words_bits(
                self._m2_for_wide(M), buf.view("<u4"),
                r=r2, k=k2,
                interpret=self.kernel == "pallas_interpret",
            ))
            return out_w.view(np.uint8)[:, :S]
        if route == "panel":
            if dt is not None:
                dt.tile = tile_label(plan)
            record_sublaunch_dispatch(
                dt.entry if dt is not None else "matmul_words_bytesliced",
                plan_sublaunches(plan),
            )
            fn = _panel_words_fn(
                r2, 8, self.bits_rows_for(M), plan,
                self.kernel == "pallas_interpret",
            )
        else:
            fn = _fused_words_fn(
                r2, self.bits_rows_for(M),
                self.kernel == "pallas_interpret",
            )
        out_w = np.array(fn(jnp.asarray(buf.view("<u4"))))
        return out_w.view(np.uint8)[:, :S]

    def matmul_words_bytesliced(self, M: np.ndarray,
                                words: jnp.ndarray) -> jnp.ndarray:
        """Device-resident BYTE-SLICED gf65536 words entry.

        ``words`` is (2k, TW8) uint32 over byte rows (shard j's lo-byte
        row at 2j, hi-byte row at 2j+1 — the framework's device-resident
        GF(2^16) layout); returns (2r, TW8) parity byte-row words. This
        is the fast path the bench times; ``matmul_words`` keeps the
        interleaved-u16 contract on the 16-plane kernels for callers
        holding that layout.
        """
        if self.gf.degree != 16:
            raise ValueError("matmul_words_bytesliced is gf65536-only")
        r2 = 2 * M.shape[0]
        TW = words.shape[1]
        TWp = pad_words(TW)
        route, plan = self._route_plan(M)
        if route == "mxu":
            # Over-budget wide-field matrices: the dense MXU kernel
            # over the same byte rows (see _bytesliced_words).
            from noise_ec_tpu.ops.mxu_gf2 import mxu_encode_words_bits

            fn = functools.partial(
                mxu_encode_words_bits,
                self._m2_for_wide(M),
                r=r2,
                k=2 * M.shape[1],
                interpret=self.kernel == "pallas_interpret",
            )
        elif route == "panel":
            record_sublaunch_dispatch(
                "matmul_words_bytesliced", plan_sublaunches(plan)
            )
            fn = _panel_words_fn(
                r2, 8, self.bits_rows_for(M), plan,
                self.kernel == "pallas_interpret",
            )
        else:
            fn = _fused_words_fn(
                r2, self.bits_rows_for(M), self.kernel == "pallas_interpret"
            )
        if TWp != TW:
            return fn(jnp.pad(words, ((0, 0), (0, TWp - TW))))[:, :TW]
        return fn(words)

    def decode1_words_bytesliced(
        self, A: np.ndarray, j: int, rows_words: jnp.ndarray
    ) -> tuple:
        """Device-resident single-corrupt-row decode on the PACKED
        byte-sliced GF(2^16) layout (the wide-field analogue of
        :meth:`decode1_words`).

        ``rows_words``: (2m, TW8) uint32 packed byte-sliced words of
        all m received stripes (share i's lo-byte row at 2i, hi at
        2i+1 — pallas_pack.words16_to_bytesliced). Returns
        (corrected_lo_hi (2, TW8), verify_or (TW8,)): the corrected row
        j as its two byte rows, and the OR-fold of every consistency
        BYTE row — a nonzero byte defeats the single-support hypothesis
        for that column exactly as in the gf256 entry (a u16 column is
        bad iff either of its byte columns is). One generator-shaped
        byte-sliced matmul, so GF(2^16) decode rides the same m=8
        kernel tier (and panel route, when wide) as GF(2^8) instead of
        the 4-round 16-plane expansion that doubled its round count.
        """
        D = self.decode1_matrix(A, j)  # raises for r2 < 2
        out = self.matmul_words_bytesliced(D, rows_words)  # (2*r2, TW8)
        corrected = out[:2]
        bad = out[2]
        for q in range(3, out.shape[0]):
            bad = bad | out[q]
        return corrected, bad

    def matmul_words(self, M: np.ndarray, words: jnp.ndarray) -> jnp.ndarray:
        """Device-resident words entry: (k, TW) uint32 -> (r, TW) uint32.

        The words ARE the shard bytes (little-endian u32 view; 4 GF(2^8) or
        2 GF(2^16) symbols per word). Any TW is accepted: non-quantum sizes
        are zero-padded on device and the product sliced back (symbols are
        positionwise, so padding is inert; under an enclosing jit the
        pad/slice fuse into the program). This is the zero-relayout hot
        path used by bench and the parallel layer.
        """
        return self.matmul_words_batch(M, words[None])[0]

    def matmul_words_batch(self, M: np.ndarray, words: jnp.ndarray, *,
                           donate: bool = False) -> jnp.ndarray:
        """Batched words entry: (B, k, TW) uint32 -> (B, r, TW) uint32.

        vmap of the fused lane pipeline per object (the same kernels the
        single-object path runs; vmap adds a grid dimension).
        ``matmul_words`` delegates here with B=1; the streaming encoder
        uses it directly for many same-geometry device-resident objects.

        ``donate=True`` is an explicit caller opt-in that the input device
        array will never be touched again: on TPU/GPU the B=1 baked route
        then donates the words' HBM into the output (the streaming
        encoder's steady-state no-realloc contract). The default keeps
        caller ownership — bench's chained loops reuse their input.
        """
        if self.kernel == "xla":
            raise ValueError(
                "matmul_words/matmul_words_batch require a pallas kernel; "
                "use matmul_stripes (or BatchCodec.encode_batch) on the XLA path"
            )
        M = np.asarray(M)
        nbytes = 4 * int(np.prod(words.shape))
        record_kernel("matmul_words", nbytes)
        # Async-entry caveat: this path returns a device array without
        # materializing, so the execute-route timing is the submit cost;
        # the compile route still times the synchronous trace+compile.
        key = dispatch_key(
            "matmul_words", self.kernel, M,
            self._key_shape(M, tuple(words.shape)),
        )
        # Same bounded-queue admission as matmul_stripes (device gate).
        with device_gate(), device_op("matmul_words", key, nbytes=nbytes) as dt:
            return self._matmul_words_batch_dispatch(
                M, words, dt, donate=donate
            )

    def _matmul_words_batch_dispatch(self, M: np.ndarray, words: jnp.ndarray,
                                     dt, donate: bool = False) -> jnp.ndarray:
        # Mesh dispatch tier (parallel/mesh.py, docs/design.md §13): a
        # real batch on the baked GF(2^8) route shards its batch axis
        # over the "stripes" mesh axis — ONE shard_map program of the
        # same vmapped fused pipeline, donate_argnums preserved
        # per-shard. The router's compile helper quantizes to the
        # power-of-two ladder, so program count stays bounded. Roofline
        # analysis is skipped here (the mesh families carry their own
        # dispatch/shard-bytes telemetry).
        if words.shape[0] > 1 and self.gf.degree == 8 and (
            self._route_plan(M)[0] != "mxu"
        ):
            from noise_ec_tpu.parallel.mesh import mesh_router

            router = mesh_router()
            if router.should_shard(words.shape[0]):
                return router.matmul_words_batch(
                    self, M, words, donate=donate
                )
        TW = words.shape[2]
        TWp = pad_words(TW) if self.gf.degree == 8 else pad_words16(TW)
        route, plan = self._route_plan(M)
        if self.gf.degree == 8 and route == "mxu":
            # Past every XOR-network budget (see _BAKED_XOR_BUDGET /
            # PANEL_XOR_BUDGET): the dense MXU product, same words
            # contract. WORD_QUANTUM is a multiple of the MXU lane
            # tile, so the padding below fits both kernel families.
            mx = self._mxu_for()
            fn = functools.partial(mx.encode_words, M)
        else:
            if self.gf.degree == 16 and route == "mxu":
                # The MXU route consumes BYTE rows; this entry's
                # interleaved-u16 layout has no kernel at this size.
                raise NotImplementedError(
                    "over-budget GF(2^16) matrices run the MXU route "
                    "on the byte-sliced entries (matmul_words_bytesliced "
                    "/ matmul_stripes), not the interleaved words entry"
                )
            # Donation only on the single-object baked route: vmap wraps
            # the jit (donation would not thread through), and a padded
            # input is a fresh on-device copy anyway.
            donate = donate and words.shape[0] == 1 and TWp == TW
            if route == "panel":
                # Panel tier — the interleaved entry rides the m=16
                # blocked pack; the packed byte-sliced entries stay the
                # wide-field fast path (3 rounds, m=8 quantum).
                dt.tile = tile_label(plan)
                record_sublaunch_dispatch(
                    dt.entry, plan_sublaunches(plan)
                )
                fn = _panel_words_fn(
                    M.shape[0], self.gf.degree, self.bits_rows_for(M),
                    plan, self.kernel == "pallas_interpret", donate,
                )
            else:
                mk = (_fused_words_fn if self.gf.degree == 8
                      else _fused_words16_fn)
                fn = mk(
                    M.shape[0], self.bits_rows_for(M),
                    self.kernel == "pallas_interpret", donate,
                )
        if TWp != TW:
            words = jnp.pad(words, ((0, 0), (0, 0), (0, TWp - TW)))
        if words.shape[0] == 1:
            # Single object: skip the vmap wrapper (its extra grid
            # dimension measurably slows wide codes — RS(50,20) 243 vs
            # 201 GB/s on v5e).
            shape0 = jax.ShapeDtypeStruct(words.shape[1:], words.dtype)
            out = fn(words[0])[None]
        else:
            shape0 = jax.ShapeDtypeStruct(words.shape[1:], words.dtype)
            out = jax.vmap(fn)(words)
        if dt.route == "compile":
            # Best-effort: the MXU partial has no .lower and a traced
            # call passes tracers; the analysis degrades to None. Shape
            # struct, not the live array — it may have been donated.
            maybe_analyze_program("matmul_words", fn, shape0)
        return out[:, :, :TW] if TWp != TW else out

    def matmul_planes(self, M: np.ndarray, planes: jnp.ndarray) -> jnp.ndarray:
        """Device-level entry on packed (C, W) planes (HBM-resident path).

        Returns (m*r, W) planes on device; used by benches and the parallel
        layer to avoid host round-trips.
        """
        W = planes.shape[1]
        if self.kernel == "xla":
            M = np.ascontiguousarray(np.asarray(M, dtype=self.gf.dtype))
            key = self._key(M)
            dev = self._mask_dev_cache.get(key)
            if dev is None:
                dev = jnp.asarray(self.masks_for(M))
                if len(self._mask_dev_cache) > 1024:
                    self._mask_dev_cache.clear()
                self._mask_dev_cache[key] = dev
            return _gf2_matmul_jax_jit(dev, planes)
        M = np.asarray(M)
        route, plan = self._route_plan(M)
        if route == "mxu":
            raise NotImplementedError(
                "over-budget matrices have no planes-level XOR-network "
                "kernel; use matmul_stripes/matmul_words (the MXU route)"
            )
        if route == "panel":
            record_sublaunch_dispatch(
                "matmul_planes", plan_sublaunches(plan)
            )
            out = gf2_matmul_pallas_panel_rows(
                self.bits_rows_for(M),
                planes_to_tiled(planes),
                plan=plan,
                interpret=self.kernel == "pallas_interpret",
            )
        else:
            out = gf2_matmul_pallas_sparse_rows(
                self.bits_rows_for(M),
                planes_to_tiled(planes),
                interpret=self.kernel == "pallas_interpret",
            )
        return tiled_to_planes(out, W)
