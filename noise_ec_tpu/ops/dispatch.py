"""Geometry-cached device codec: the bridge from GF matrices to TPU kernels.

The reference changes RS geometry (k, n) at runtime per message
(/root/reference/main.go:185-191), so kernels must be re-jitted per geometry
with bounded caching (SURVEY.md §7.4 "dynamic geometry"). ``DeviceCodec``
caches one fused (pack -> GF(2) matmul -> unpack) compiled program per
(matrix, stripe-length, kernel) signature.

Kernel selection:

- "pallas" (default on TPU): the geometry-specialized sparse Pallas kernel —
  the matrix's bit pattern is baked into the program as XOR chains; runs at
  the HBM roofline on v5e.
- "xla": masked AND/XOR fori_loop — portable, used for CPU tests and as the
  shape-generic fallback.
- "pallas_interpret": Pallas interpreter mode (CPU debugging).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from noise_ec_tpu.gf.bitmatrix import expand_generator_bits, expand_generator_masks
from noise_ec_tpu.gf.field import GF, GF256, GF65536
from noise_ec_tpu.ops.bitops import pack_bitplanes_jax, unpack_bitplanes_jax
from noise_ec_tpu.ops.gf2mm import gf2_matmul_jax
from noise_ec_tpu.ops.pallas_gf2mm import (
    bits_to_rows,
    gf2_matmul_pallas_sparse_rows,
    planes_to_tiled,
    tiled_to_planes,
)

_FIELDS = {"gf256": GF256, "gf65536": GF65536}

# Jitted shape-generic planes-level matmul (retraces per shape, cached by jit).
_gf2_matmul_jax_jit = jax.jit(gf2_matmul_jax)


def _resolve_kernel(kernel: str) -> str:
    if kernel == "auto":
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    return kernel


@functools.lru_cache(maxsize=256)
def _fused_xla_fn(degree: int, r: int, k: int, S: int):
    """Compiled (masks, shards) -> product stripes, shape-generic kernel."""

    def f(masks, shards):
        planes = pack_bitplanes_jax(shards, degree)
        out = gf2_matmul_jax(masks, planes)
        return unpack_bitplanes_jax(out, r, S, degree)

    return jax.jit(f)


@functools.lru_cache(maxsize=256)
def _fused_sparse_fn(
    degree: int, r: int, S: int, bits_rows: tuple, interpret: bool
):
    """Compiled shards -> product stripes with the matrix baked in."""

    def f(shards):
        planes = pack_bitplanes_jax(shards, degree)
        W = planes.shape[1]
        tiled = planes_to_tiled(planes)
        out = gf2_matmul_pallas_sparse_rows(bits_rows, tiled, interpret=interpret)
        return unpack_bitplanes_jax(tiled_to_planes(out, W), r, S, degree)

    return jax.jit(f)


class DeviceCodec:
    """Runs GF matrix x stripes products on the default JAX device.

    This one primitive is both reference hot loops: encode is
    parity_rows @ data (main.go:262), reconstruct is
    inverted_submatrix_rows @ survivors (main.go:77).
    """

    def __init__(self, field: str = "gf256", kernel: str = "auto"):
        if field not in _FIELDS:
            raise ValueError(f"unknown field {field!r}")
        self.field = field
        self.gf: GF = _FIELDS[field]()
        self.kernel = _resolve_kernel(kernel)
        if self.kernel not in ("pallas", "pallas_interpret", "xla"):
            raise ValueError(f"unknown kernel {self.kernel!r}")
        self._mask_cache: dict[bytes, np.ndarray] = {}
        self._mask_dev_cache: dict[bytes, jnp.ndarray] = {}
        self._rows_cache: dict[bytes, tuple] = {}

    def _key(self, M: np.ndarray) -> bytes:
        return M.tobytes() + M.shape[1].to_bytes(4, "little")

    def masks_for(self, M: np.ndarray) -> np.ndarray:
        """(r, k) GF matrix -> (m*r, m*k) uint32 select-mask matrix, cached."""
        M = np.ascontiguousarray(np.asarray(M, dtype=self.gf.dtype))
        key = self._key(M)
        hit = self._mask_cache.get(key)
        if hit is None:
            hit = expand_generator_masks(self.gf, M)
            if len(self._mask_cache) > 4096:
                self._mask_cache.clear()
            self._mask_cache[key] = hit
        return hit

    def bits_rows_for(self, M: np.ndarray) -> tuple:
        """(r, k) GF matrix -> hashable per-row term tuples for the sparse
        kernel (cached)."""
        M = np.ascontiguousarray(np.asarray(M, dtype=self.gf.dtype))
        key = self._key(M)
        hit = self._rows_cache.get(key)
        if hit is None:
            hit = bits_to_rows(expand_generator_bits(self.gf, M))
            if len(self._rows_cache) > 4096:
                self._rows_cache.clear()
            self._rows_cache[key] = hit
        return hit

    def matmul_stripes(self, M: np.ndarray, D) -> np.ndarray:
        """(r, k) GF matrix x (k, S) stripes -> (r, S), computed on device."""
        M = np.asarray(M)
        D = np.asarray(D, dtype=self.gf.dtype)
        r, k = M.shape
        if D.shape[0] != k:
            raise ValueError(f"matrix cols {k} != stripe rows {D.shape[0]}")
        S = D.shape[1]
        m = self.gf.degree
        if self.kernel == "xla":
            fn = _fused_xla_fn(m, r, k, S)
            out = fn(jnp.asarray(self.masks_for(M)), jnp.asarray(D))
        else:
            fn = _fused_sparse_fn(
                m, r, S, self.bits_rows_for(M), self.kernel == "pallas_interpret"
            )
            out = fn(jnp.asarray(D))
        # np.array (copy) so callers get an ordinary writable ndarray, not a
        # read-only view of the device buffer.
        return np.array(out)

    def matmul_planes(self, M: np.ndarray, planes: jnp.ndarray) -> jnp.ndarray:
        """Device-level entry on packed (C, W) planes (HBM-resident path).

        Returns (m*r, W) planes on device; used by benches and the parallel
        layer to avoid host round-trips.
        """
        W = planes.shape[1]
        if self.kernel == "xla":
            M = np.ascontiguousarray(np.asarray(M, dtype=self.gf.dtype))
            key = self._key(M)
            dev = self._mask_dev_cache.get(key)
            if dev is None:
                dev = jnp.asarray(self.masks_for(M))
                if len(self._mask_dev_cache) > 1024:
                    self._mask_dev_cache.clear()
                self._mask_dev_cache[key] = dev
            return _gf2_matmul_jax_jit(dev, planes)
        out = gf2_matmul_pallas_sparse_rows(
            self.bits_rows_for(np.asarray(M)),
            planes_to_tiled(planes),
            interpret=self.kernel == "pallas_interpret",
        )
        return tiled_to_planes(out, W)
