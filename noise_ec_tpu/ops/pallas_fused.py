"""Single-kernel fused encode: pack -> GF(2) matmul -> unpack, one launch.

The three-kernel words pipeline (ops/dispatch.py) round-trips both packed
operands through HBM: for RS(10,4) on D data bytes it moves D (pack read)
+ D (pack write) + D (matmul read) + 0.4D (matmul write) + 0.4D (unpack
read) + 0.4D (unpack write) = 4.2D of HBM traffic to produce 0.4D of
parity. This kernel keeps the packed planes in VMEM scratch and moves
exactly D + 0.4D: per grid step it

1. packs the (k, 8*m*TL) input slab with the lane-axis delta-swap
   (pallas_pack.lane_delta_swap — same bijection as the standalone
   kernels),
2. runs the geometry-baked XOR chains of the sparse matmul on the
   scratch-resident (k*m, 8, TL) plane tiles,
3. applies the inverse delta-swap (an involution) to the (r, m, 8, TL)
   parity planes and writes parity WORDS straight to the output block.

The layout contract is identical to the three-kernel path (the hot-path
unit tests compare both against the golden codec), so DeviceCodec can pick
whichever fits VMEM: the fused kernel needs in + out blocks (double-
buffered) plus both plane scratches resident at once, so very wide codes
fall back to the pipeline. Reference hot loop: /root/reference/main.go:262.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from noise_ec_tpu.ops.pallas_pack import (
    _ROUNDS,
    _ROUNDS16,
    _pack_lanes_kernel,
    _unpack_lanes_kernel,
)
from noise_ec_tpu.ops.xor_factor import eval_bits_rows

# 1 MiB tighter than pallas_gf2mm's VMEM_BUDGET_BYTES: the fused kernel
# additionally keeps delta-swap pack/unpack temporaries on the Mosaic stack,
# which the shared Paar-temp estimate does not cover. Calibration anchors:
# GF(2^16) RS(10,4) at TL=512 OOMed at 17.97M scoped and must be REJECTED
# (accounted 14.44M > 13M); GF(2^8) RS(50,20) at TL=128 compiled and must be
# ACCEPTED (accounted 12.75M <= 13M).
_FUSED_VMEM_BUDGET = 13 << 20


def fused_lane_tl(TW: int, m: int, k: int, r: int, bits_rows: tuple) -> int:
    """Largest TL in {512, 256, 128} whose fused working set fits VMEM.

    Working set per lane of tile: in block (k rows) and out block (r rows)
    are double-buffered by the grid pipeline; the two plane scratches
    (k and r rows) are single-buffered; the Paar network's temporaries are
    charged via the shared calibrated estimate (see pallas_gf2mm).
    """
    from noise_ec_tpu.ops.pallas_gf2mm import xor_temp_bytes_per_lane

    W8 = TW // (8 * m)
    per_lane = 4 * 8 * m * (2 * k + 2 * r + k + r) + xor_temp_bytes_per_lane(
        bits_rows, k * m
    )
    for TL in (512, 256, 128):
        if W8 % TL == 0 and per_lane * TL <= _FUSED_VMEM_BUDGET:
            return TL
    raise ValueError(
        f"no fused tile for TW={TW}, m={m}, k={k}, r={r} "
        f"(need TW % {1024 * m} == 0 and a tile within VMEM)"
    )


def _fused_kernel(m, TL, rounds, bits_rows, in_ref, out_ref, pk_ref, po_ref):
    k = in_ref.shape[0]
    # 1. pack into VMEM scratch — the standalone lane-pack kernel body,
    # pointed at the scratch ref instead of an HBM-backed output block.
    _pack_lanes_kernel(m, TL, rounds, in_ref, pk_ref)
    # 2. geometry-baked sparse GF(2) matmul on (8, TL) plane tiles, with
    # Paar common-subexpression factoring (~2-3x fewer XORs).
    outs = eval_bits_rows(
        bits_rows, k * m,
        lambda c: pk_ref[c // m, c % m, :, :],
        lambda: jnp.zeros((8, TL), dtype=jnp.uint32),
    )
    for row, val in enumerate(outs):
        po_ref[row // m, row % m, :, :] = val
    # 3. unpack scratch parity planes -> output words (same sharing).
    _unpack_lanes_kernel(m, TL, rounds, po_ref, out_ref)


@functools.lru_cache(maxsize=512)
def _fused_call(bits_rows: tuple, k: int, r: int, TW: int, m: int,
                interpret: bool):
    TL = fused_lane_tl(TW, m, k, r, bits_rows)
    rounds = _ROUNDS if m == 8 else _ROUNDS16
    return pl.pallas_call(
        functools.partial(_fused_kernel, m, TL, rounds, bits_rows),
        grid=(TW // (8 * m * TL),),
        in_specs=[
            pl.BlockSpec((k, 8 * m * TL), lambda c: (0, c),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, 8 * m * TL), lambda c: (0, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r, TW), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((k, m, 8, TL), jnp.uint32),
            pltpu.VMEM((r, m, 8, TL), jnp.uint32),
        ],
        interpret=interpret,
    )


def fused_encode_words(
    bits_rows: tuple,  # STATIC (r*m)-row term tuples over k*m plane rows
    words: jnp.ndarray,  # (k, TW) uint32
    r: int,
    m: int = 8,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """(k, TW) uint32 data words -> (r, TW) uint32 parity words, one launch.

    TW must be a multiple of ``lane_quantum(m)`` = 1024*m (callers pad).
    Raises ValueError when no tile fits VMEM — callers fall back to the
    three-kernel pipeline.
    """
    k, TW = words.shape
    return _fused_call(bits_rows, k, r, TW, m, interpret)(words)
