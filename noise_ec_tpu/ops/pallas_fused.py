"""Single-kernel fused encode: pack -> GF(2) matmul -> unpack, one launch.

The three-kernel words pipeline (ops/dispatch.py) round-trips both packed
operands through HBM: for RS(10,4) on D data bytes it moves D (pack read)
+ D (pack write) + D (matmul read) + 0.4D (matmul write) + 0.4D (unpack
read) + 0.4D (unpack write) = 4.2D of HBM traffic to produce 0.4D of
parity. This kernel keeps the packed planes in VMEM scratch and moves
exactly D + 0.4D: per grid step it

1. packs the (k, 8*m*TL) input slab with the lane-axis delta-swap
   (pallas_pack.lane_delta_swap — same bijection as the standalone
   kernels),
2. runs the geometry-baked XOR chains of the sparse matmul on the
   scratch-resident (k*m, 8, TL) plane tiles,
3. applies the inverse delta-swap (an involution) to the (r, m, 8, TL)
   parity planes and writes parity WORDS straight to the output block.

The layout contract is identical to the three-kernel path (the hot-path
unit tests compare both against the golden codec), so DeviceCodec can pick
whichever fits VMEM: the fused kernel needs in + out blocks (double-
buffered) plus both plane scratches resident at once, so very wide codes
fall back to the pipeline — and geometries past the whole-plane budgets
leave this module entirely for the block-panel K-tiled tier
(ops/pallas_gf2mm "panel tier", docs/design.md §14; dispatch.route_for
owns the decision). Reference hot loop: /root/reference/main.go:262.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from noise_ec_tpu.ops.pallas_pack import (
    _ROUNDS,
    _ROUNDS16,
    _pack_lanes_kernel,
    _unpack_lanes_kernel,
    _use_pairwise,
    lane_delta_swap,
    transpose_windows,
)
from noise_ec_tpu.ops.xor_factor import eval_bits_rows

# 1 MiB tighter than pallas_gf2mm's VMEM_BUDGET_BYTES: the fused kernel
# additionally keeps delta-swap pack/unpack temporaries on the Mosaic stack,
# which the shared Paar-temp estimate does not cover. Calibration anchors
# (valid for WHOLE-PLANE kernels only — the panel tier counts its capped
# per-panel temps at full size instead, pallas_gf2mm
# PANEL_TEMP_ALIVE_FRACTION): GF(2^16) RS(10,4) at TL=512 OOMed at 17.97M
# scoped and must be REJECTED (accounted 14.44M > 13M); GF(2^8) RS(50,20)
# at TL=128 compiled and must be ACCEPTED (accounted 12.75M <= 13M).
_FUSED_VMEM_BUDGET = 13 << 20


def fused_lane_tl(TW: int, m: int, k: int, r: int, bits_rows: tuple) -> int:
    """Largest TL in {512, 256, 128} whose fused working set fits VMEM
    WITH the fully-factored network (no temp cap).

    Conservative by design: callers that guard on this (parallel/batch.py
    tier selection) do not run the compile probe, and temp-capped plans
    are exactly the ones whose real Mosaic stack usage the static model
    cannot predict — those are only reachable through the verified
    planner (fused_encode_words_planned). Raises ValueError when no
    uncapped tile fits.
    """
    from noise_ec_tpu.ops.pallas_gf2mm import xor_temp_bytes_per_lane

    W8 = TW // (8 * m)
    per_lane = 4 * 8 * m * (2 * k + 2 * r + k + r) + xor_temp_bytes_per_lane(
        bits_rows, k * m
    )
    for TL in (512, 256, 128):
        if W8 % TL == 0 and per_lane * TL <= _FUSED_VMEM_BUDGET:
            return TL
    raise ValueError(
        f"no uncapped fused tile for TW={TW}, m={m}, k={k}, r={r}"
    )


# A temp cap is accepted only while the refactored network stays within
# this factor of the fully-factored XOR cost — beyond it, the extra VPU
# work outweighs the larger lane tile it buys.
_CAP_COST_RATIO = 1.25


def single_fused_plan(TW: int, m: int, k: int, r: int,
                      bits_rows: tuple) -> tuple:
    """(TL, temp_cap) for the single-phase fused kernel.

    For each candidate TL (largest first), the Paar temporaries either fit
    outright (temp_cap = None) or are re-factored under the cap the VMEM
    headroom allows — accepted when the capped network costs at most
    _CAP_COST_RATIO of the full factoring (GF(2^16) RS(10,4): cap 400
    costs +9% XORs but lifts TL 256 -> 512). Raises ValueError when no
    tile fits.
    """
    from noise_ec_tpu.ops.pallas_gf2mm import (
        TEMP_ALIVE_FRACTION,
        xor_temp_bytes_per_lane,
    )
    from noise_ec_tpu.ops.xor_factor import factored_cost, paar_factor

    W8 = TW // (8 * m)
    blocks_per_lane = 4 * 8 * m * (2 * k + 2 * r + k + r)
    temps_full = xor_temp_bytes_per_lane(bits_rows, k * m)
    bytes_per_temp = 8 * 4 * TEMP_ALIVE_FRACTION
    # Pass 1 — any UNCAPPED tile, largest first: an uncapped smaller tile
    # beats a capped larger one here, because this planner's callers
    # (fused_encode_words, via parallel/batch.py) compile WITHOUT the
    # probe, and capped plans are exactly the ones whose real Mosaic
    # stack usage the static model cannot predict. The probing planner
    # (fused_plan_candidates) makes its own capped-vs-uncapped ordering.
    for TL in (512, 256, 128):
        if W8 % TL:
            continue
        headroom = _FUSED_VMEM_BUDGET // TL - blocks_per_lane
        if headroom >= temps_full:
            return (TL, None)
    # Pass 2 — capped fallback (last resort; only reached when nothing
    # fits uncapped at any tile).
    full_cost = None
    for TL in (512, 256, 128):
        if W8 % TL:
            continue
        headroom = _FUSED_VMEM_BUDGET // TL - blocks_per_lane
        cap = int(headroom // bytes_per_temp) if headroom > 0 else 0
        if cap < 1:
            continue
        if full_cost is None:
            ops, rows = paar_factor(bits_rows, k * m)
            full_cost = factored_cost(ops, rows)
        ops_c, rows_c = paar_factor(bits_rows, k * m, max_temps=cap)
        if factored_cost(ops_c, rows_c) <= _CAP_COST_RATIO * full_cost:
            return (TL, cap)
    raise ValueError(
        f"no fused tile for TW={TW}, m={m}, k={k}, r={r} "
        f"(need TW % {1024 * m} == 0 and a tile within VMEM)"
    )


def _fused_kernel(m, TL, rounds, bits_rows, temp_cap, in_ref, out_ref,
                  pk_ref, po_ref):
    k = in_ref.shape[0]
    # 1. pack into VMEM scratch — the standalone lane-pack kernel body,
    # pointed at the scratch ref instead of an HBM-backed output block.
    _pack_lanes_kernel(m, TL, rounds, in_ref, pk_ref)
    # 2. geometry-baked sparse GF(2) matmul on (8, TL) plane tiles, with
    # Paar common-subexpression factoring (~2-3x fewer XORs), optionally
    # temp-capped to fit a larger lane tile (single_fused_plan).
    outs = eval_bits_rows(
        bits_rows, k * m,
        lambda c: pk_ref[c // m, c % m, :, :],
        lambda: jnp.zeros((8, TL), dtype=jnp.uint32),
        max_temps=temp_cap if temp_cap is not None else 100_000,
    )
    for row, val in enumerate(outs):
        po_ref[row // m, row % m, :, :] = val
    # 3. unpack scratch parity planes -> output words (same sharing).
    _unpack_lanes_kernel(m, TL, rounds, po_ref, out_ref)


@functools.lru_cache(maxsize=512)
def _fused_call(bits_rows: tuple, k: int, r: int, TW: int, m: int,
                interpret: bool):
    TL, temp_cap = single_fused_plan(TW, m, k, r, bits_rows)
    rounds = _ROUNDS if m == 8 else _ROUNDS16
    return pl.pallas_call(
        functools.partial(_fused_kernel, m, TL, rounds, bits_rows, temp_cap),
        grid=(TW // (8 * m * TL),),
        in_specs=[
            pl.BlockSpec((k, 8 * m * TL), lambda c: (0, c),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r, 8 * m * TL), lambda c: (0, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r, TW), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((k, m, 8, TL), jnp.uint32),
            pltpu.VMEM((r, m, 8, TL), jnp.uint32),
        ],
        interpret=interpret,
    )


def fused_encode_words(
    bits_rows: tuple,  # STATIC (r*m)-row term tuples over k*m plane rows
    words: jnp.ndarray,  # (k, TW) uint32
    r: int,
    m: int = 8,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """(k, TW) uint32 data words -> (r, TW) uint32 parity words, one launch.

    TW must be a multiple of ``lane_quantum(m)`` = 1024*m (callers pad).
    Raises ValueError when no tile fits VMEM — callers fall back to the
    three-kernel pipeline.
    """
    k, TW = words.shape
    return _fused_call(bits_rows, k, r, TW, m, interpret)(words)


# ---------------------------------------------------------------------------
# Split-phase fused encode: wide codes at full lane tiles.
#
# The single-launch fused kernel's VMEM working set scales with k (input
# block + packed scratch) AND with the Paar network's temporaries, so wide
# codes (RS(50,20): 400 input planes, ~3.8k temps) are forced down to
# TL=128 — below the TL>=256 bracket where the pairwise delta-swap
# transpose (2.8x fewer vector ops than the full-slab form) applies, and
# the kernel runs ~VPU-bound at half the flagship rate.
#
# The split formulation processes the input in P contiguous K-SLICES:
# phase p packs only its slice into a slice-sized scratch and evaluates
# only the sub-network over that slice's plane columns, XOR-accumulating
# into the parity-plane scratch; the last phase applies the inverse
# transpose and writes parity words. A Pallas-pipelined (lanes x phases)
# grid version re-fetched the revisited input block from HBM every phase
# step (measured: throughput ~ 1/P) and was removed; the kernel below
# keeps the input in HBM (memory_space=ANY) and hand-rolls the slice DMA
# with double buffering, so input bytes move exactly once.
#
# Reference hot loop: /root/reference/main.go:262 (contract accepts any
# k <= n <= 256, so wide geometries are first-class).


def split_bits_rows_ksl(bits_rows: tuple, k: int, m: int, ksl: int) -> tuple:
    """Partition the (r*m)-row network into ceil(k/ksl) sub-networks by
    contiguous ksl-row input slices; sub-network p's column ids are
    re-indexed to its local [0, ksl*m) plane range (a padded final slice
    simply has columns no term references)."""
    P = -(-k // ksl)
    out = []
    for p in range(P):
        lo, hi = p * ksl * m, min((p + 1) * ksl * m, k * m)
        out.append(
            tuple(
                tuple(c - lo for c in row if lo <= c < hi) for row in bits_rows
            )
        )
    return tuple(out)


def _pack_rows_kernel(m, TL, rounds, in_ref, out_ref, row_lo, rows):
    """_pack_lanes_kernel on a static row slice of the input block."""
    for sigma in range(8):
        if _use_pairwise(TL):
            ws = transpose_windows(
                [
                    in_ref[row_lo : row_lo + rows,
                           (sigma * m + i) * TL : (sigma * m + i + 1) * TL]
                    for i in range(m)
                ],
                rounds,
            )
        else:
            V = lane_delta_swap(
                in_ref[row_lo : row_lo + rows,
                       sigma * m * TL : (sigma + 1) * m * TL],
                TL, rounds,
            )
            ws = [V[:, i * TL : (i + 1) * TL] for i in range(m)]
        for i in range(m):
            out_ref[:rows, i, sigma, :] = ws[i]


# ---------------------------------------------------------------------------
# Manual-DMA split kernel: the production wide-code formulation.
#
# The Pallas-pipelined split kernel above re-fetches its (revisited) input
# block from HBM on EVERY phase step — measured: RS(10,4) P=2 drops from
# 421 to 299 GB/s and P=5 to 193, i.e. throughput ~ 1/P, the signature of
# P-fold input traffic. This variant keeps the input in HBM
# (memory_space=ANY) and hand-rolls the slice movement: one grid step per
# lane tile runs ALL phases, DMA-ing each phase's ceil(k/P)-row slice into
# a double-buffered VMEM scratch (phase p+1's copy overlaps phase p's
# pack + XOR network). Input bytes move exactly once; VMEM holds only two
# slices, one slice's packed planes, the parity planes, and one phase's
# Paar temporaries — which is what buys TL >= 256 (pairwise transpose)
# for codes whose single-phase working set forces TL=128.


def _dma_split_kernel(m, TL, rounds, nets, ksl,
                      in_ref, out_ref, buf_ref, pk_ref, po_ref, sems):
    # The input array is padded to P*ksl rows with ksl a multiple of 8:
    # Mosaic requires HBM row slices aligned to the (8, 128) tiling, and
    # full slices keep every DMA identical. Padded rows are zero and no
    # sub-network references their plane columns.
    P = len(nets)
    L = 8 * m * TL
    c = pl.program_id(0)

    def copy(ph, slot):
        return pltpu.make_async_copy(
            in_ref.at[pl.ds(ph * ksl, ksl), pl.ds(c * L, L)],
            buf_ref.at[slot],
            sems.at[slot],
        )

    copy(0, 0).start()
    for ph, net in enumerate(nets):
        slot = ph % 2
        copy(ph, slot).wait()
        if ph + 1 < P:
            copy(ph + 1, 1 - slot).start()
        _pack_rows_kernel(m, TL, rounds, buf_ref.at[slot], pk_ref, 0, ksl)
        outs = eval_bits_rows(
            net, ksl * m,
            lambda col: pk_ref[col // m, col % m, :, :],
            lambda: jnp.zeros((8, TL), dtype=jnp.uint32),
        )
        for row, val in enumerate(outs):
            if ph == 0:
                po_ref[row // m, row % m, :, :] = val
            else:
                po_ref[row // m, row % m, :, :] ^= val
    _unpack_lanes_kernel(m, TL, rounds, po_ref, out_ref)


@functools.lru_cache(maxsize=512)
def _dma_split_call(nets: tuple, r: int, TW: int, m: int, ksl: int,
                    TL: int, interpret: bool):
    P = len(nets)
    rounds = _ROUNDS if m == 8 else _ROUNDS16
    return pl.pallas_call(
        functools.partial(_dma_split_kernel, m, TL, rounds, nets, ksl),
        grid=(TW // (8 * m * TL),),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],  # stays in HBM
        out_specs=pl.BlockSpec((r, 8 * m * TL), lambda c: (0, c),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((r, TW), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((2, ksl, 8 * m * TL), jnp.uint32),  # slice buffers
            pltpu.VMEM((ksl, m, 8, TL), jnp.uint32),
            pltpu.VMEM((r, m, 8, TL), jnp.uint32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=interpret,
    )


# ---------------------------------------------------------------------------
# Verified planning: candidates ordered by estimated cost, compile-probed.
#
# The static VMEM models above are PRE-FILTERS, not guarantees: Mosaic's
# stack allocator overlaps XOR-network temporaries by a geometry-dependent
# fraction (measured 0.4 for RS(10,4)'s 193 temps, ~0.9 for capped
# GF(2^16) networks), so a plan that fits the model can still OOM the 16M
# scoped-vmem limit at compile time — and the model must stay conservative
# enough that it rejects plans a different geometry would have run fine.
# Rather than tightening the model until every geometry loses headroom,
# the planner AOT-compiles one lane tile of each candidate (cheap, cached
# per geometry — VMEM usage is TW-independent) and picks the first that
# actually compiles.


def _pack_w(TL: int) -> int:
    # VPU bytes per packed word: pairwise delta-swap (7.5 ops x 4 B) at
    # TL >= 256, full-slab rolls (~21 ops x 4 B) at TL = 128.
    return 30 if TL >= 256 else 84


_PROBE_BUDGET = 15_750_000  # loose pre-filter; the probe is the real gate
# Calibrations shared by the candidate scan (single source of truth):
# - split-kernel temporaries don't overlap across traced phase bodies the
#   way the single-phase calibration assumes (observed: RS(50,20) P=5
#   hit 16.25M scoped vs 12.76M accounted) -> scale the shared estimate.
# - scan-time estimates for sub-network factoring yield and temp count
#   (conservative fits to measured matrices: RS(50,20) 0.32/0.12,
#   GF(2^16) RS(10,4) 0.34/0.13).
_SPLIT_TEMP_SCALE = 2.5
_FACTOR_RATIO = 0.35
_TEMP_RATIO = 0.15


def _temp_bytes_per_op() -> float:
    from noise_ec_tpu.ops.pallas_gf2mm import TEMP_ALIVE_FRACTION

    return 8 * 4 * TEMP_ALIVE_FRACTION


def fused_plan_candidates(TW: int, m: int, k: int, r: int,
                          bits_rows: tuple) -> list:
    """Ordered candidate plans: ("single", TL, cap) and ("dma", TL, ksl).

    Scored by estimated VPU bytes per input byte (XOR network + transpose
    work, including split accumulates and row padding); ascending score =
    descending predicted throughput.
    """
    from noise_ec_tpu.ops.pallas_gf2mm import xor_temp_bytes_per_lane
    from noise_ec_tpu.ops.xor_factor import (
        factored_cost,
        paar_factor,
        xor_cost,
    )

    W8 = TW // (8 * m)
    out = []
    blocks_single = 4 * 8 * m * (2 * k + 2 * r + k + r)
    temps_full = xor_temp_bytes_per_lane(bits_rows, k * m)
    ops_f, rows_f = paar_factor(bits_rows, k * m)
    full_cost = factored_cost(ops_f, rows_f)
    # Mild preference for wider lane tiles beyond what the op counts
    # capture (fewer grid steps, better vectorization; RS(10,4) measured
    # +16% at 512 vs 256).
    tl_factor = {512: 1.0, 256: 1.08, 128: 1.15}

    def single_score(TL, cost):
        return tl_factor[TL] * (32 * cost + _pack_w(TL) * 8 * m * (k + r))

    for TL in (512, 256, 128):
        if W8 % TL:
            continue
        headroom = _PROBE_BUDGET // TL - blocks_single
        if headroom >= temps_full:
            out.append((single_score(TL, full_cost), ("single", TL, 0)))
        # Capped variants whenever the STRICT model would demand a cap at
        # this TL — emitted alongside the uncapped candidate (the probe
        # decides which actually compiles), at the model cap and a
        # tighter 0.6x fallback for geometries whose temporaries Mosaic
        # overlaps poorly.
        strict_headroom = _FUSED_VMEM_BUDGET // TL - blocks_single
        if strict_headroom > 0 and temps_full > strict_headroom:
            cap_model = int(strict_headroom // _temp_bytes_per_op())
            for cap in (cap_model, max(1, int(cap_model * 0.6))):
                if cap < 1 or cap * _temp_bytes_per_op() >= temps_full:
                    continue
                ops_c, rows_c = paar_factor(bits_rows, k * m, max_temps=cap)
                cost_c = factored_cost(ops_c, rows_c)
                if cost_c <= _CAP_COST_RATIO * full_cost:
                    out.append((single_score(TL, cost_c), ("single", TL, cap)))
    # DMA-split candidates (ksl multiple of 8 — Mosaic HBM row slices must
    # align to the (8, 128) tiling; the runner zero-pads the input rows).
    max_ksl = -(-k // 8) * 8
    for TL in (512, 256):
        if W8 % TL:
            continue
        for ksl in range(8, max_ksl + 1, 8):
            P = -(-k // ksl)
            if P < 2:
                continue
            nets = split_bits_rows_ksl(bits_rows, k, m, ksl)
            raw_max = max(xor_cost(net) for net in nets)
            est_temps = raw_max * _TEMP_RATIO * _temp_bytes_per_op()
            per_lane_est = (
                4 * 8 * m * (3 * ksl + 3 * r)
                + est_temps * _SPLIT_TEMP_SCALE
            )
            if per_lane_est * TL > _PROBE_BUDGET:
                continue
            sumf_est = sum(xor_cost(net) for net in nets) * _FACTOR_RATIO
            # 1.5x: measured overhead of the manual-DMA formulation beyond
            # the op counts (per-phase parity-plane accumulate traffic,
            # first-phase DMA bubbles, slice-pad pack) — RS(50,20) measured
            # 178.8 GB/s dma(TL=256) vs 243.6 single(TL=128) on v5e, so
            # the score must not prefer dma on op counts alone.
            score = 1.5 * tl_factor[TL] * (
                32 * (sumf_est + (P - 1) * r * m)
                + _pack_w(TL) * 8 * m * (P * ksl + r)
            )
            out.append((score, ("dma", TL, ksl)))
    out.sort(key=lambda t: t[0])
    # Bound probe work: a handful of best candidates is always enough.
    return [cand for _, cand in out[:8]]


def _build_planned_call(bits_rows: tuple, k: int, r: int, TW: int, m: int,
                        cand: tuple, interpret: bool):
    """(callable, padded_k) for a candidate plan at the given TW."""
    kind, TL = cand[0], cand[1]
    if kind == "single":
        cap = cand[2] or None
        rounds = _ROUNDS if m == 8 else _ROUNDS16
        call = pl.pallas_call(
            functools.partial(_fused_kernel, m, TL, rounds, bits_rows, cap),
            grid=(TW // (8 * m * TL),),
            in_specs=[
                pl.BlockSpec((k, 8 * m * TL), lambda c: (0, c),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((r, 8 * m * TL), lambda c: (0, c),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((r, TW), jnp.uint32),
            scratch_shapes=[
                pltpu.VMEM((k, m, 8, TL), jnp.uint32),
                pltpu.VMEM((r, m, 8, TL), jnp.uint32),
            ],
            interpret=interpret,
        )
        return call, k
    ksl = cand[2]
    nets = split_bits_rows_ksl(bits_rows, k, m, ksl)
    return _dma_split_call(nets, r, TW, m, ksl, TL, interpret), len(nets) * ksl


@functools.lru_cache(maxsize=1024)
def _probe_compiles(bits_rows: tuple, k: int, r: int, m: int,
                    cand: tuple) -> bool:
    """AOT-compile TWO lane tiles of the candidate; True iff it compiles.

    Past two tiles VMEM pressure is independent of the grid length (the
    pipeline double-buffers at grid >= 2 — a ONE-tile probe skips the
    second buffer and falsely passed plans that OOM on real grids), so a
    two-tile probe validates any TW with the same TL.
    """
    TW = 2 * 8 * m * cand[1]
    try:
        call, k_pad = _build_planned_call(bits_rows, k, r, TW, m, cand, False)
        shape = jax.ShapeDtypeStruct((k_pad, TW), jnp.uint32)
        jax.jit(call).lower(shape).compile()
        return True
    except Exception:  # noqa: BLE001 — any compile failure disqualifies
        return False


@functools.lru_cache(maxsize=512)
def verified_fused_plan(bits_rows: tuple, k: int, r: int, TW: int, m: int,
                        interpret: bool):
    """Best candidate that actually compiles, or None.

    Interpret mode (CPU tests) has no scoped-vmem limit: the first
    candidate wins without probing.
    """
    cands = fused_plan_candidates(TW, m, k, r, bits_rows)
    if interpret:
        return cands[0] if cands else None
    for cand in cands:
        if _probe_compiles(bits_rows, k, r, m, cand):
            return cand
    return None


class NoFusedPlanError(ValueError):
    """No fused-kernel candidate compiles for this geometry — the caller
    should fall back to the three-kernel pipeline. A distinct type so the
    dispatch fallback cannot swallow a genuine ValueError raised while
    building or running a chosen kernel (that is a bug and must surface)."""


def fused_encode_words_planned(
    bits_rows: tuple,
    words: jnp.ndarray,  # (k, TW) uint32
    r: int,
    m: int = 8,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused encode through the verified planner (single or DMA-split).

    Raises :class:`NoFusedPlanError` when no candidate compiles — callers
    fall back to the three-kernel pipeline.
    """
    k, TW = words.shape
    cand = verified_fused_plan(bits_rows, k, r, TW, m, interpret)
    if cand is None:
        raise NoFusedPlanError(
            f"no fused plan compiles for k={k}, r={r}, m={m}"
        )
    call, k_pad = _build_planned_call(bits_rows, k, r, TW, m, cand, interpret)
    if k_pad != k:
        words = jnp.pad(words, ((0, k_pad - k), (0, 0)))
    return call(words)
