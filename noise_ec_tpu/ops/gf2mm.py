"""GF(2) matrix multiply over packed bitplanes — pure-XLA version.

out[r] = XOR over {c : mask[r, c] set} of planes[c]; masks are uint32
select-masks (0 / 0xFFFFFFFF) from ``gf.bitmatrix.expand_generator_masks``.
One accumulate step per input plane: acc ^= mask[:, c] & planes[c] — an
AND+XOR on full 32-bit VPU lanes. XLA keeps the accumulator on-chip and
fuses the loop body; the Pallas version adds explicit VMEM tiling.

This single primitive is BOTH hot loops of the reference (encode
main.go:262, reconstruct main.go:77): only the mask matrix changes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def gf2_matmul_jax(masks: jnp.ndarray, planes: jnp.ndarray) -> jnp.ndarray:
    """(R, C) uint32 masks x (C, W) uint32 planes -> (R, W) uint32.

    Shapes are static under jit; the loop is a lax.fori_loop so the unrolled
    program size stays O(1) in C.
    """
    R, C = masks.shape
    Cp, W = planes.shape
    if C != Cp:
        raise ValueError(f"masks cols {C} != planes rows {Cp}")

    def body(c, acc):
        return acc ^ (masks[:, c][:, None] & planes[c][None, :])

    init = jnp.zeros((R, W), dtype=jnp.uint32)
    return jax.lax.fori_loop(0, C, body, init)


def gf2_matmul_batched(masks: jnp.ndarray, planes: jnp.ndarray) -> jnp.ndarray:
    """Batched object axis: masks (R, C), planes (B, C, W) -> (B, R, W)."""
    return jax.vmap(lambda p: gf2_matmul_jax(masks, p))(planes)
