"""JAX/XLA/Pallas compute kernels — the TPU hot path.

The reference's hot loops are ``infectious``'s GF(2^8) generator-matrix
multiply (encode, /root/reference/main.go:262) and submatrix-inversion x
multiply (decode, main.go:77), CPU table/assembly code. Here both become ONE
device primitive: a binary (GF(2)) matrix multiply over bitsliced shard
planes — AND/XOR on 32-bit lanes, no gathers, no byte-granular multiplies
(SURVEY.md §7.4).

Layers:

- ``bitops``   — bitplane pack/unpack on device (jnp)
- ``gf2mm``    — jitted masked AND/XOR GF(2) matmul (pure XLA; runs anywhere)
- ``pallas_gf2mm`` — the Pallas TPU kernel version (VMEM-tiled, grid over
  stripe words); falls back to ``gf2mm`` off-TPU
- ``dispatch`` — geometry-cached jitted encode/reconstruct entry points
"""

from noise_ec_tpu.ops.bitops import pack_bitplanes_jax, unpack_bitplanes_jax  # noqa: F401
from noise_ec_tpu.ops.gf2mm import gf2_matmul_jax  # noqa: F401
from noise_ec_tpu.ops.dispatch import DeviceCodec  # noqa: F401
