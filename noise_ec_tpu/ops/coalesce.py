"""Live-path coalescing: batch concurrent same-shape matmul requests.

The store's repair engine proved the shape (PR 2): same-geometry stripes
folded into ONE batched device reconstruct turn B dispatch round trips
into one. But that trick lived behind the repair queue only — the LIVE
paths (plugin encode/decode, the object service, the fleet lab) still
dispatched one device call per request, so heavy concurrent traffic paid
per-call dispatch overhead B times. ``CoalescingDispatcher`` generalizes
the trick to every codec matmul: concurrent requests for the same
(backend, field, matrix, stripe-shape) bucket are batched into a single
batched dispatch (``DeviceCodec.matmul_stripes_many`` →
``matmul_words_batch`` on the device route) and the results fanned back
out to the waiting callers.

Flush policy (admission and batching share one queue):

- a lone request on an idle dispatcher flushes IMMEDIATELY — coalescing
  must never tax the uncontended path;
- when other coalesced work is already in flight (or another thread
  submitted within the hot window), the bucket leader lingers up to
  ``max(linger_seconds, linger_seconds * device-gate depth)`` — a
  bounded latency budget that grows only when the device queue is
  already deep (the request would have waited at the
  :class:`~noise_ec_tpu.ops.dispatch.DeviceGate` anyway, so the linger
  is free) — collecting followers before dispatching;
- a full bucket (``max_batch``) flushes at once;
- explicit batches (:meth:`submit_many` — the repair engine's group
  dispatch) merge into any open bucket for their key and flush without
  linger: they already ARE a batch;
- idempotent reads (:meth:`submit_shared` — the object service's
  per-(address, stripe) decoded-stripe fetch) ride a SINGLE-FLIGHT
  tier: same-key callers share one in-flight call's result (followers
  join even mid-call), flushed as ``reason="shared"``.

The batch function runs on the leader's thread; an exception propagates
to every member (each caller then applies its own fallback — e.g. the
codec breaker's golden-host degradation, so a breaker trip mid-batch
still returns correct bytes to all members through their own ``_mul``
fallback arm).

Metrics: ``noise_ec_coalesce_batches_total``,
``noise_ec_coalesce_flush_reason_total{reason}`` and
``noise_ec_coalesce_batch_size`` (one observation PER MEMBER request —
the distribution answers "what batch size did a request ride", so a p50
above 1 means most requests were amortized).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional, Sequence

__all__ = [
    "CoalescingDispatcher",
    "QOS_LANES",
    "coalescer",
    "configure_coalescer",
    "current_qos",
    "qos_lane",
]

# A follower must never wait forever on a leader that died violently
# (thread killed between append and flush); after this many seconds it
# raises instead of hanging the receive path.
_FOLLOWER_TIMEOUT_S = 120.0

# ------------------------------------------------------------ QoS lanes
#
# The device gate and this dispatcher are SHARED by every producer in
# the process: live GET decodes, repair drains, scrub verifies, archival
# conversions. Without classification, one tenant's decode storm (or a
# background repair burst) queues ahead of everyone at the gate — the
# noisy-neighbor tail the ISSUE's DRF-style fairness addresses. The QoS
# context is a thread-local (lane, tenant, weight) tag set by the layer
# that KNOWS the traffic class (the object service tags per-tenant live
# work from the Tenant policy grammar; repair/scrub/convert/rebalance
# loops tag themselves background) and read by the admission points
# (DeviceGate.acquire's weighted lane queues, this dispatcher's linger
# budget). Thread-local — not a call argument — because the tag must
# survive the codec call stack without threading a parameter through
# every matmul signature. A coalesced batch runs on its leader's thread
# and therefore rides the leader's lane; members of one bucket share a
# (backend, field, matrix, shape) key, so cross-lane mixing inside one
# batch is bounded by the linger window and costs at most one batch.

QOS_LANES = ("live", "background")

_qos_local = threading.local()


def current_qos() -> tuple[str, str, int]:
    """The calling thread's ``(lane, tenant, weight)`` QoS tag —
    ``("live", "", 1)`` outside any :func:`qos_lane` scope."""
    return getattr(_qos_local, "ctx", ("live", "", 1))


@contextmanager
def qos_lane(lane: str, tenant: str = "", weight: int = 1):
    """Tag the calling thread's device-gate/coalescer admissions with a
    QoS class for the duration of the scope (module comment). Nests:
    the previous tag is restored on exit."""
    if lane not in QOS_LANES:
        raise ValueError(
            f"unknown QoS lane {lane!r} (lanes: {', '.join(QOS_LANES)})"
        )
    prev = getattr(_qos_local, "ctx", None)
    _qos_local.ctx = (lane, tenant, max(1, int(weight)))
    try:
        yield
    finally:
        if prev is None:
            del _qos_local.ctx
        else:
            _qos_local.ctx = prev


class _Bucket:
    __slots__ = ("key", "fn", "payloads", "results", "error", "done",
                 "closed")

    def __init__(self, key, fn):
        self.key = key
        self.fn = fn
        self.payloads: list = []
        self.results: Optional[list] = None
        self.error: Optional[BaseException] = None
        self.done = threading.Event()
        self.closed = False


class _Flight:
    __slots__ = ("done", "result", "error", "members")

    def __init__(self):
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.members = 1


class CoalescingDispatcher:
    """Batches concurrent same-key requests into single dispatches
    (module docstring). One process-wide instance fronts every codec
    ``_mul``; tests build their own with shrunk knobs."""

    def __init__(self, *, linger_seconds: float = 0.0005,
                 max_batch: int = 32, hot_window_seconds: float = 0.005,
                 background_linger_x: float = 4.0):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if background_linger_x < 1.0:
            raise ValueError(
                f"background_linger_x must be >= 1, got {background_linger_x}"
            )
        self.linger_seconds = linger_seconds
        self.max_batch = max_batch
        self.hot_window_seconds = hot_window_seconds
        self.background_linger_x = background_linger_x
        self._lock = threading.Lock()
        self._buckets: dict = {}
        self._flights: dict = {}  # single-flight tier (submit_shared)
        self._inflight = 0  # batch dispatches currently running
        self._last_submit_t = 0.0
        self._last_submit_thread: Optional[int] = None
        from noise_ec_tpu.obs.registry import default_registry

        reg = default_registry()
        self._batches = reg.counter("noise_ec_coalesce_batches_total").labels()
        self._size_hist = reg.histogram("noise_ec_coalesce_batch_size").labels()
        self._flush_children = {
            reason: reg.counter(
                "noise_ec_coalesce_flush_reason_total"
            ).labels(reason=reason)
            for reason in ("solo", "linger", "full", "bulk", "shared")
        }

    # ------------------------------------------------------------- submit

    def submit(self, key, batch_fn: Callable[[list], list], payload):
        """One request: returns its result once a batch containing it has
        dispatched. ``batch_fn(payloads) -> results`` must be equivalent
        for every caller sharing ``key`` (it runs on the leader's
        thread)."""
        now = time.monotonic()
        me = threading.get_ident()
        with self._lock:
            hot = (
                self._inflight > 0
                or (
                    now - self._last_submit_t < self.hot_window_seconds
                    and self._last_submit_thread != me
                )
            )
            self._last_submit_t = now
            self._last_submit_thread = me
            bucket = self._buckets.get(key)
            if bucket is not None and not bucket.closed and len(
                bucket.payloads
            ) < self.max_batch:
                idx = len(bucket.payloads)
                bucket.payloads.append(payload)
                follower = True
            else:
                bucket = _Bucket(key, batch_fn)
                bucket.payloads.append(payload)
                self._buckets[key] = bucket
                idx = 0
                follower = False
        if follower:
            return self._await(bucket, idx)
        self._lead(bucket, linger=self._linger_budget() if hot else 0.0)
        return self._result(bucket, idx)

    def submit_shared(self, key, fn: Callable[[], object]):
        """Single-flight tier: concurrent same-``key`` callers share ONE
        ``fn()`` call and all receive its result. Unlike :meth:`submit`,
        followers may join while the call is already RUNNING — the
        result is *broadcast*, not batched — which is the shape of
        idempotent reads: the object service routes each cold
        ``(address, stripe)`` decode through here, so a zipfian stampede
        on a cold object costs exactly one dispatch
        (docs/object-service.md "Read path").

        Returns ``(result, shared)`` — ``shared`` is True when this
        caller rode another caller's in-flight call. An exception from
        ``fn`` propagates to every member. Flights record the coalesce
        metrics under ``flush_reason="shared"`` (one batch-size
        observation per member, same contract as batched flushes).

        Tracing: ``fn`` runs on the LEADER's thread, so any spans it
        opens land in the leader's request trace — a follower's trace
        would otherwise lose the decode work entirely. The object
        service threads the leader's trace id through the shared result
        so followers can record a ``joined`` span pointing at the
        leader's trace (docs/observability.md "Request tracing")."""
        with self._lock:
            flight = self._flights.get(key)
            if flight is not None:
                flight.members += 1
                follower = True
            else:
                flight = self._flights[key] = _Flight()
                follower = False
        if follower:
            if not flight.done.wait(_FOLLOWER_TIMEOUT_S):
                raise RuntimeError(
                    "shared dispatch never completed (leader lost)"
                )
            if flight.error is not None:
                raise flight.error
            return flight.result, True
        try:
            flight.result = fn()
        # noise-ec: allow(event-on-swallow) — error is re-delivered to every waiter via flight.error
        except BaseException as exc:  # noqa: BLE001 — fan the error out
            flight.error = exc
        finally:
            with self._lock:
                del self._flights[key]
                members = flight.members
            self._batches.add(1)
            self._flush_children["shared"].add(1)
            for _ in range(members):
                self._size_hist.observe(members)
            flight.done.set()
        if flight.error is not None:
            raise flight.error
        return flight.result, False

    def submit_many(self, key, batch_fn: Callable[[list], list],
                    payloads: Sequence) -> list:
        """Explicit batch (the repair engine's group dispatch): joins any
        open bucket for ``key`` and flushes without linger — the batch
        already exists, so admission and batching share the one queue
        with live singleton traffic."""
        payloads = list(payloads)
        if not payloads:
            return []
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is not None and not bucket.closed:
                base = len(bucket.payloads)
                bucket.payloads.extend(payloads)
                follower = True
            else:
                bucket = _Bucket(key, batch_fn)
                bucket.payloads.extend(payloads)
                self._buckets[key] = bucket
                base = 0
                follower = False
        if follower:
            self._await(bucket, base)  # leader flushes; wait for results
            return [self._result(bucket, base + i)
                    for i in range(len(payloads))]
        self._lead(bucket, linger=0.0, reason="bulk")
        return [self._result(bucket, base + i) for i in range(len(payloads))]

    # -------------------------------------------------------------- flush

    def _linger_budget(self) -> float:
        """The bounded latency budget: the base linger, scaled by the
        device-gate queue depth (a deep gate queue means the batch would
        block at admission anyway, so a longer linger costs nothing).
        Background-lane leaders under pressure linger
        ``background_linger_x`` longer still — repair/scrub batches
        YIELD the contended gate to live GETs (collecting bigger
        batches while they wait), the coalescer half of the QoS-lane
        story (the gate's weighted queues are the other half)."""
        if self.linger_seconds <= 0:
            return 0.0
        depth = 0
        try:
            from noise_ec_tpu.ops.dispatch import device_gate

            gate = device_gate()
            depth = gate.in_flight + gate.waiters
        # noise-ec: allow(event-on-swallow) — linger sizing probe — host regime without jax
        except Exception:  # noqa: BLE001 — linger must not require jax
            pass
        budget = max(self.linger_seconds, self.linger_seconds * depth)
        if depth > 0 and current_qos()[0] == "background":
            budget *= self.background_linger_x
            from noise_ec_tpu.obs.events import event

            event("qos.linger", lane="background", depth=depth,
                  budget_ms=round(budget * 1e3, 3))
        return budget

    def _lead(self, bucket: _Bucket, linger: float,
              reason: Optional[str] = None) -> None:
        if linger > 0:
            deadline = time.monotonic() + linger
            while time.monotonic() < deadline:
                with self._lock:
                    if len(bucket.payloads) >= self.max_batch:
                        break
                time.sleep(min(0.0002, linger))
        with self._lock:
            bucket.closed = True
            if self._buckets.get(bucket.key) is bucket:
                del self._buckets[bucket.key]
            size = len(bucket.payloads)
            self._inflight += 1
        if reason is None:
            reason = (
                "full" if size >= self.max_batch
                else ("linger" if linger > 0 else "solo")
            )
        try:
            results = bucket.fn(list(bucket.payloads))
            if len(results) != size:
                raise RuntimeError(
                    f"coalesced batch_fn returned {len(results)} results "
                    f"for {size} payloads"
                )
            bucket.results = list(results)
        # noise-ec: allow(event-on-swallow) — error is re-delivered to every waiter via bucket.error
        except BaseException as exc:  # noqa: BLE001 — fan the error out
            bucket.error = exc
        finally:
            with self._lock:
                self._inflight -= 1
            self._batches.add(1)
            self._flush_children[reason].add(1)
            for _ in range(size):
                self._size_hist.observe(size)
            bucket.done.set()
        if bucket.error is not None:
            raise bucket.error

    def _await(self, bucket: _Bucket, idx: int):
        if not bucket.done.wait(_FOLLOWER_TIMEOUT_S):
            raise RuntimeError(
                "coalesced dispatch never completed (leader lost)"
            )
        return self._result(bucket, idx)

    def _result(self, bucket: _Bucket, idx: int):
        if bucket.error is not None:
            raise bucket.error
        return bucket.results[idx]


# Implicit-coalescing payload cutoff: batching amortizes PER-DISPATCH
# overhead, so it pays exactly while that overhead dominates — always on
# an RPC-fronted accelerator link (~100 ms fixed cost per call), only
# for small payloads on the in-process CPU backend (measured on the
# single-core rig: 8x 1 KiB-stripe requests ran 3x faster batched, 8x
# 64 KiB ran 0.56x — the wide program is compute-bound and the batch
# adds a concat). Requests above the cutoff dispatch directly; explicit
# submit_many batches (the repair engine) are caller-opted and always
# batch.
_cutoff_override: Optional[int] = None


def set_coalesce_cutoff(nbytes: Optional[int]) -> None:
    """Pin the implicit-coalescing payload cutoff (None restores the
    per-backend default; tests use this to force either regime)."""
    global _cutoff_override
    _cutoff_override = nbytes


def coalesce_cutoff_bytes() -> int:
    if _cutoff_override is not None:
        return _cutoff_override
    try:
        import jax

        if jax.default_backend() in ("tpu", "gpu"):
            base = 8 << 20
            # Mesh dispatch tier (parallel/mesh.py): with N chips the
            # batch SHARDS, so per-chip payload is nbytes/N — batching
            # keeps amortizing N× further up the payload scale before a
            # member becomes compute-bound on its own chip.
            from noise_ec_tpu.parallel.mesh import mesh_router

            router = mesh_router()
            if router.enabled:
                base *= router.n_pow2
            return base
    # noise-ec: allow(event-on-swallow) — device-count probe — host regime without jax
    except Exception:  # noqa: BLE001 — no jax, host regime
        pass
    return 128 << 10


_coalescer: Optional[CoalescingDispatcher] = None
_coalescer_lock = threading.Lock()


def coalescer() -> CoalescingDispatcher:
    """The process-wide coalescing dispatcher (lazy singleton)."""
    global _coalescer
    with _coalescer_lock:
        if _coalescer is None:
            _coalescer = CoalescingDispatcher()
        return _coalescer


def configure_coalescer(**kwargs) -> CoalescingDispatcher:
    """Replace the process dispatcher (tests shrink/grow the linger; a
    fresh instance also drops any open buckets). Returns the new one."""
    global _coalescer
    with _coalescer_lock:
        _coalescer = CoalescingDispatcher(**kwargs)
        return _coalescer
