"""Content-addressed stripe store with degraded reads and disk persistence.

A *stripe* is one object's full erasure-coded shard set plus geometry
metadata, addressed by the 16-hex signature prefix that obs tracing and
the plugin's pool keys already use (:func:`obs.trace.trace_key`). The
store is the durability layer the reference lacks: verified receives land
here instead of being dropped after reassembly, and the object stays
readable while up to n-k shards are missing (reconstructed on demand —
the degraded-read path).

Trust model (mirrors the plugin's): shards written by :meth:`put_object`
come from a signature-verified object and are *trusted*. Shards absorbed
from the wire (:meth:`note_shard`, the anti-entropy fill path) are
verified against the trusted remainder when >= k trusted shards exist
(reconstruct-and-compare); otherwise they are held *unverified* until the
repair engine can validate the whole stripe (error-correcting decode,
plus the stored sender signature when available). Degraded reads use
trusted shards only.

Thread safety: one lock guards the stripe table and every stripe
mutation; codec construction happens outside it. Disk writes are atomic
(tmp + rename) so a torn write can never leave a wrong-content shard
under a content-derived name.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

import numpy as np

from noise_ec_tpu.codec.lrc import codec_for_code, parse_code
from noise_ec_tpu.codec.rs import ReedSolomon
from noise_ec_tpu.obs.registry import default_registry
from noise_ec_tpu.obs.trace import trace_key

__all__ = [
    "DegradedReadError",
    "StripeMeta",
    "StripeStore",
    "UnknownStripeError",
]

log = logging.getLogger("noise_ec_tpu.store")

_FIELD_SYM = {"gf256": 1, "gf65536": 2}

# Manifest addresses are content hashes (hex); validated before they
# become file names under <store_dir>/_manifests/.
_MANIFEST_DIR = "_manifests"
_ADDRESS_RE = re.compile(r"^[0-9a-f]{8,128}$")


class UnknownStripeError(KeyError):
    """No stripe under this key."""


class DegradedReadError(RuntimeError):
    """Fewer than k trusted shards survive: the object cannot be served
    locally. The repair engine's anti-entropy fetch is the recovery path."""


@dataclass
class StripeMeta:
    """Geometry + identity metadata for one stripe (persisted as JSON)."""

    file_signature: bytes
    k: int
    n: int
    shard_len: int
    object_len: int
    field: str = "gf256"
    # Codec kind: "rs" (default) or "lrc:<g>" (docs/lrc.md — g local
    # parity groups inside the n-k parity budget). The code travels with
    # the stripe so every reader (degraded read, scrub verify, repair,
    # conversion) rebuilds the SAME generator.
    code: str = "rs"
    # Sender identity captured at put time: lets the repair engine verify
    # an error-corrected restore against the object signature, the same
    # end-to-end anchor the plugin's receive path uses. Optional — a
    # stripe stored outside the plugin path has no sender.
    sender_address: str = ""
    sender_public_key: bytes = b""

    @property
    def key(self) -> str:
        return trace_key(self.file_signature)


@dataclass
class _Stripe:
    meta: StripeMeta
    shards: list  # Optional[bytes] per slot, length n
    unverified: set = field(default_factory=set)  # slot numbers
    # Local arrival time (monotonic): drives the repair engine's
    # anti-entropy ANNOUNCE of recently stored stripes. Stripes loaded
    # from disk stamp load time — after a restart they ARE news to peers
    # that churned while we were down.
    created_at: float = field(default_factory=time.monotonic)
    # Placement-born (docs/placement.md): the entry was CREATED by a
    # targeted placement shard, not a local put or an announced
    # interest. ``note_shard`` absorbs into such stripes ADDITIVELY
    # (returns False so the plugin's pool still sees broadcast
    # traffic) — consuming would starve the reassembly pool of any
    # stripe whose early slots land in this node's failure domain.
    placement: bool = False

    def present(self) -> list[int]:
        return [i for i, s in enumerate(self.shards) if s is not None]

    def trusted(self) -> list[int]:
        return [
            i for i, s in enumerate(self.shards)
            if s is not None and i not in self.unverified
        ]


class _StoreMetrics:
    """Cached registry children for the store metric family (resolved
    once; the scrub/repair loops record per stripe)."""

    _instances: "weakref.WeakSet[StripeStore]" = weakref.WeakSet()

    def __init__(self):
        reg = default_registry()
        self.degraded_reads = reg.counter(
            "noise_ec_store_degraded_reads_total"
        ).labels()
        self.absorbed = reg.counter(
            "noise_ec_store_absorbed_shards_total"
        ).labels()
        self.absorb_rejected = reg.counter(
            "noise_ec_store_absorb_rejected_total"
        ).labels()
        cls = _StoreMetrics
        # Re-registered on every construction (idempotent — the closures
        # read the CLASS WeakSet): the test-isolation registry reset
        # drops callback children, and a once-guard would leave the
        # gauges dead for the rest of the process.
        reg.gauge("noise_ec_store_stripes").set_callback(
            lambda: sum(len(s) for s in list(cls._instances))
        )
        reg.gauge("noise_ec_store_shard_bytes").set_callback(
            lambda: sum(s.shard_bytes for s in list(cls._instances))
        )


class StripeStore:
    """Content-addressed stripe store (see module docstring).

    ``store_dir=None`` keeps stripes in memory only; with a directory,
    every stripe persists as ``<dir>/<key>/meta.json`` + per-shard files
    and :meth:`load` (called from ``__init__``) restores them on startup.
    """

    def __init__(
        self,
        store_dir: Optional[str] = None,
        *,
        backend: str = "numpy",
        max_stripes: int = 65536,
    ):
        self.store_dir = store_dir
        self.backend = backend
        self.max_stripes = max_stripes
        self._lock = threading.Lock()
        self._stripes: dict[str, _Stripe] = {}
        # Object manifests (service/objects.py): content address ->
        # manifest document. The stripe table holds codewords; this
        # table holds the object layer's map from an object to its
        # ordered stripe keys + geometry + size, persisted alongside
        # the stripes so a restart restores the whole object space.
        self._manifests: dict[str, dict] = {}
        # Put listeners: called (key, data, meta) after every successful
        # put_object — the object service absorbs replicated manifests
        # through this hook (a verified receive lands here via the
        # plugin before any listener sees it).
        self._put_listeners: list[Callable] = []
        # Delete listeners: called (key) after a stripe is evicted — the
        # object service's decoded cache drops the RAM copy of a stripe
        # the store no longer backs.
        self._delete_listeners: list[Callable] = []
        self._codecs: dict[tuple[int, int, str, str], ReedSolomon] = {}
        self._codec_lock = threading.Lock()
        self.shard_bytes = 0
        # The repair engine registers itself so note_shard can classify
        # newly fillable stripes and surface remote interest; weakref so
        # a dropped engine cannot pin the store (or vice versa).
        self._engine = lambda: None
        self._metrics = _StoreMetrics()
        _StoreMetrics._instances.add(self)
        if store_dir:
            os.makedirs(store_dir, exist_ok=True)
            self.load()

    # ------------------------------------------------------------- codecs

    def codec(
        self, k: int, n: int, field: str = "gf256", code: str = "rs"
    ) -> ReedSolomon:
        ckey = (k, n, field, code)
        with self._codec_lock:
            rs = self._codecs.get(ckey)
            if rs is not None:
                return rs
        rs = codec_for_code(code, k, n, field=field, backend=self.backend)
        with self._codec_lock:
            return self._codecs.setdefault(ckey, rs)

    def bind_engine(self, engine) -> None:
        self._engine = weakref.ref(engine)

    def add_put_listener(self, fn: Callable) -> None:
        """Register ``fn(key, data, meta)`` to run after every successful
        :meth:`put_object` (outside the store lock; exceptions are logged,
        never raised — a listener must not break the put path)."""
        self._put_listeners.append(fn)

    def add_delete_listener(self, fn: Callable) -> None:
        """Register ``fn(key)`` to run after every successful
        :meth:`evict` (outside the store lock; exceptions are logged,
        never raised — same contract as the put listeners)."""
        self._delete_listeners.append(fn)

    # ------------------------------------------------------------ writes

    def put_object(
        self,
        file_signature: bytes,
        data: bytes,
        k: int,
        n: int,
        *,
        field: str = "gf256",
        code: str = "rs",
        sender_address: str = "",
        sender_public_key: bytes = b"",
    ) -> str:
        """Encode a (verified) object into a full trusted stripe; returns
        the store key. Re-putting the same key replaces the stripe — the
        put path only ever runs on signature-verified bytes, so the
        replacement is at worst identical. ``code`` selects the codec
        kind ("rs" or "lrc:<g>" — the archival tier's geometry)."""
        if not data:
            raise ValueError("cannot store an empty object")
        if not 1 <= k <= n:
            raise ValueError(f"invalid geometry k={k} n={n}")
        parse_code(code)  # reject unknown kinds before any encode
        rs = self.codec(k, n, field, code)
        shards = [
            np.ascontiguousarray(s).view(np.uint8).tobytes()
            for s in rs.encode(rs.split(data))
        ]
        meta = StripeMeta(
            file_signature=bytes(file_signature),
            k=k,
            n=n,
            shard_len=len(shards[0]),
            object_len=len(data),
            field=field,
            code=code,
            sender_address=sender_address,
            sender_public_key=bytes(sender_public_key),
        )
        stripe = _Stripe(meta=meta, shards=list(shards))
        with self._lock:
            if (
                meta.key not in self._stripes
                and len(self._stripes) >= self.max_stripes
            ):
                raise RuntimeError(
                    f"stripe store full ({self.max_stripes} stripes)"
                )
            self._replace_locked(meta.key, stripe)
        self._persist_stripe(stripe)
        for fn in list(self._put_listeners):
            try:
                fn(meta.key, data, meta)
            except Exception as exc:  # noqa: BLE001 — advisory hook only
                log.warning("store put listener failed for %s: %s",
                            meta.key, exc)
        return meta.key

    def write_repaired(
        self, key: str, repaired: dict[int, bytes], *, corrected: bool = False
    ) -> None:
        """Install repaired shard bytes as trusted slots (repair engine
        write-back). ``corrected`` marks overwrites of previously-present
        shards (corruption fixes) as opposed to hole fills."""
        with self._lock:
            stripe = self._stripes.get(key)
            if stripe is None:
                raise UnknownStripeError(key)
            for num, blob in repaired.items():
                if not 0 <= num < stripe.meta.n:
                    raise ValueError(f"shard number {num} out of range")
                if len(blob) != stripe.meta.shard_len:
                    raise ValueError(
                        f"repaired shard {num} length {len(blob)} != "
                        f"{stripe.meta.shard_len}"
                    )
                if stripe.shards[num] is None:
                    self.shard_bytes += len(blob)
                stripe.shards[num] = bytes(blob)
                stripe.unverified.discard(num)
        for num in repaired:
            self._persist_shard(key, num)

    def mark_trusted(self, key: str, numbers: Iterable[int]) -> None:
        """Clear the unverified flag (repair engine: whole-stripe
        validation succeeded for these slots as-is)."""
        with self._lock:
            stripe = self._stripes.get(key)
            if stripe is None:
                raise UnknownStripeError(key)
            for num in numbers:
                stripe.unverified.discard(num)
        self._persist_meta(key)

    def drop_shard(self, key: str, number: int) -> bool:
        """Remove one shard (device loss / test fault injection)."""
        with self._lock:
            stripe = self._stripes.get(key)
            if stripe is None or stripe.shards[number] is None:
                return False
            self.shard_bytes -= len(stripe.shards[number])
            stripe.shards[number] = None
            stripe.unverified.discard(number)
        if self.store_dir:
            try:
                os.unlink(self._shard_path(key, number))
            except OSError:
                pass
        return True

    def corrupt_shard(self, key: str, number: int, mutate: Callable) -> bool:
        """Apply ``mutate(bytes) -> bytes`` to a stored shard in place —
        the test hook the scrub story is exercised through (pairs with
        ``FaultInjector.apply``). Returns False if the shard is absent."""
        with self._lock:
            stripe = self._stripes.get(key)
            if stripe is None or stripe.shards[number] is None:
                return False
            old = stripe.shards[number]
            new = bytes(mutate(old))
            if len(new) != len(old):
                raise ValueError("corruption must preserve shard length")
            stripe.shards[number] = new
        self._persist_shard(key, number)
        return True

    def evict(self, key: str) -> bool:
        with self._lock:
            stripe = self._stripes.pop(key, None)
            if stripe is None:
                return False
            self.shard_bytes -= sum(
                len(s) for s in stripe.shards if s is not None
            )
        if self.store_dir:
            self._rmtree_stripe(key)
        for fn in list(self._delete_listeners):
            try:
                fn(key)
            except Exception as exc:  # noqa: BLE001 — advisory hook only
                log.warning("store delete listener failed for %s: %s",
                            key, exc)
        return True

    def _replace_locked(self, key: str, stripe: _Stripe) -> None:
        old = self._stripes.get(key)
        if old is not None:
            self.shard_bytes -= sum(
                len(s) for s in old.shards if s is not None
            )
        self._stripes[key] = stripe
        self.shard_bytes += sum(
            len(s) for s in stripe.shards if s is not None
        )

    # ------------------------------------------------------------- reads

    def __len__(self) -> int:
        with self._lock:
            return len(self._stripes)

    def recent_keys(
        self,
        window_seconds: float,
        limit: int = 64,
        cursor: Optional[str] = None,
    ) -> tuple[list[str], Optional[str]]:
        """One page of keys of stripes stored within the last
        ``window_seconds``, newest first: ``(keys, next_cursor)``.

        Pass the returned opaque ``next_cursor`` back to continue the
        walk; ``None`` means the window is exhausted. A single-shot
        caller (the announce loop's capped working set) just takes the
        first page — but a LIST-style consumer can now iterate a large
        store page by page instead of forcing one unbounded snapshot.
        A stripe stored *while* paging appears at the front of a fresh
        walk, never in the middle of an in-flight one (the cursor orders
        strictly backward in arrival time)."""
        cutoff = time.monotonic() - window_seconds
        with self._lock:
            fresh = [
                (s.created_at, key)
                for key, s in self._stripes.items()
                if s.created_at >= cutoff
            ]
        fresh.sort(reverse=True)
        if cursor is not None:
            try:
                ts_text, _, ckey = cursor.partition(":")
                pos = (float(ts_text), ckey)
            except ValueError:
                raise ValueError(f"bad recent_keys cursor {cursor!r}")
            fresh = [entry for entry in fresh if entry < pos]
        page = fresh[:limit]
        next_cursor = (
            f"{page[-1][0]!r}:{page[-1][1]}" if len(fresh) > limit else None
        )
        return [key for _, key in page], next_cursor

    def keys(self) -> list[str]:
        with self._lock:
            return list(self._stripes)

    # ---------------------------------------------------------- manifests

    def put_manifest(self, address: str, doc: dict) -> None:
        """Store an object manifest under its content ``address`` (the
        object service's map from one object to its ordered stripe keys
        + geometry + size — docs/object-service.md). Re-putting replaces;
        persisted under ``<store_dir>/_manifests/<address>.json``."""
        if not _ADDRESS_RE.match(address):
            raise ValueError(f"bad manifest address {address!r}")
        with self._lock:
            self._manifests[address] = dict(doc)
        if self.store_dir:
            d = os.path.join(self.store_dir, _MANIFEST_DIR)
            os.makedirs(d, exist_ok=True)
            self._atomic_write(
                os.path.join(d, f"{address}.json"),
                json.dumps(doc).encode(),
            )

    def get_manifest(self, address: str) -> Optional[dict]:
        with self._lock:
            doc = self._manifests.get(address)
            return dict(doc) if doc is not None else None

    def delete_manifest(self, address: str) -> bool:
        with self._lock:
            found = self._manifests.pop(address, None) is not None
        if found and self.store_dir and _ADDRESS_RE.match(address):
            try:
                os.unlink(
                    os.path.join(self.store_dir, _MANIFEST_DIR,
                                 f"{address}.json")
                )
            except OSError:
                pass
        return found

    def manifest_count(self) -> int:
        with self._lock:
            return len(self._manifests)

    def list_manifests(
        self, *, cursor: Optional[str] = None, limit: int = 64
    ) -> tuple[list[tuple[str, dict]], Optional[str]]:
        """One page of ``(address, manifest)`` pairs in address order:
        ``(page, next_cursor)`` — the same cursor contract as
        :meth:`recent_keys` (``None`` = exhausted; the cursor is the last
        address served, iteration resumes strictly after it)."""
        with self._lock:
            addresses = sorted(self._manifests)
            if cursor is not None:
                addresses = [a for a in addresses if a > cursor]
            page = addresses[:limit]
            out = [(a, dict(self._manifests[a])) for a in page]
        next_cursor = page[-1] if len(addresses) > limit else None
        return out, next_cursor

    def meta(self, key: str) -> StripeMeta:
        with self._lock:
            stripe = self._stripes.get(key)
            if stripe is None:
                raise UnknownStripeError(key)
            return stripe.meta

    def status(self, key: str) -> dict:
        """Snapshot of one stripe's health (counts + slot lists)."""
        with self._lock:
            stripe = self._stripes.get(key)
            if stripe is None:
                raise UnknownStripeError(key)
            present = stripe.present()
            trusted = stripe.trusted()
            return {
                "k": stripe.meta.k,
                "n": stripe.meta.n,
                "code": stripe.meta.code,
                "present": present,
                "trusted": trusted,
                "unverified": sorted(stripe.unverified),
                "missing": [
                    i for i in range(stripe.meta.n) if i not in present
                ],
            }

    def snapshot(self, key: str) -> tuple[StripeMeta, list, set]:
        """(meta, shard list copy, unverified copy) under the lock —
        what the scrubber and repair engine work from."""
        with self._lock:
            stripe = self._stripes.get(key)
            if stripe is None:
                raise UnknownStripeError(key)
            return stripe.meta, list(stripe.shards), set(stripe.unverified)

    def snapshot_many(
        self, keys: Iterable[str]
    ) -> dict[str, tuple[StripeMeta, list, set]]:
        """:meth:`snapshot` for a whole key set under ONE lock
        acquisition — the object service's GET path snapshots the
        stripes of a request at once instead of re-taking the store
        lock per stripe. Keys not held are simply absent from the
        result (the caller's per-stripe miss path handles them)."""
        out: dict[str, tuple[StripeMeta, list, set]] = {}
        with self._lock:
            for key in keys:
                stripe = self._stripes.get(key)
                if stripe is not None:
                    out[key] = (
                        stripe.meta, list(stripe.shards),
                        set(stripe.unverified),
                    )
        return out

    def read(self, key: str) -> bytes:
        """Serve the object byte-identically from whatever trusted shards
        survive (the degraded-read API). With the k data shards present
        this is a join; with any k-of-n trusted subset the missing data
        shards are reconstructed on demand through the codec backend.
        Raises :class:`DegradedReadError` below k trusted shards."""
        meta, shards, unverified = self.snapshot(key)
        k = meta.k
        usable = [
            s if (s is not None and i not in unverified) else None
            for i, s in enumerate(shards)
        ]
        if all(usable[i] is not None for i in range(k)):
            blob = b"".join(usable[:k])
            return blob[: meta.object_len]
        trusted = [i for i, s in enumerate(usable) if s is not None]
        if len(trusted) < k:
            raise DegradedReadError(
                f"stripe {key} has {len(trusted)} trusted shards, "
                f"need {k}"
            )
        self._metrics.degraded_reads.add(1)
        rs = self.codec(k, meta.n, meta.field, meta.code)
        full = rs.reconstruct_data(usable)
        return rs.join(full, meta.object_len)

    def classify(self, key: str) -> Optional[str]:
        """Repair-need classification for one stripe:

        - ``None`` — fully present, all trusted (verify is scrub's job);
        - ``"missing"`` — >= k trusted, but holes or unverified slots:
          locally reconstructable from the trusted basis;
        - ``"restore"`` — < k trusted but >= k present including
          unverified: needs the error-correcting whole-stripe decode;
        - ``"fetch"`` — < k present: only peers can help (anti-entropy).
        """
        meta, shards, unverified = self.snapshot(key)
        present = [i for i, s in enumerate(shards) if s is not None]
        trusted = [i for i in present if i not in unverified]
        if len(trusted) == meta.n:
            return None
        if len(trusted) >= meta.k:
            return "missing"
        if len(present) >= meta.k:
            return "restore"
        return "fetch"

    # ----------------------------------------------------- wire absorb

    def note_shard(self, msg) -> bool:
        """Feed one arriving wire shard (a ``host.wire.Shard``) to the
        store — the plugin calls this for every delivery when a store is
        wired in. Two jobs:

        - *absorb*: if the shard names a stripe we hold with that slot
          empty, verify it against >= k trusted shards
          (reconstruct-and-compare) and fill the hole; below k trusted it
          is held unverified for the repair engine's whole-stripe
          validation. This is how anti-entropy responses (and plain
          re-broadcasts) heal local stripes without a decode.
        - *interest*: notify the repair engine that a peer is moving
          shards of a stripe we hold — if we are healthy and the traffic
          is an anti-entropy request, the engine answers with our shards.

        Returns True iff the shard was *consumed* (absorbed, matched a
        stored duplicate, or rejected as inconsistent with the verified
        stripe) — the plugin then skips the pool/decode path: the object
        is already durable here. Placement-born stripes absorb
        ADDITIVELY instead (stored but False — see ``_Stripe.placement``)
        so broadcast stripes still complete through the pool. Never
        raises: a store problem must not break plugin delivery.
        """
        try:
            return self._note_shard(msg, additive=True)
        except Exception as exc:  # noqa: BLE001 — advisory path only
            log.warning("store note_shard failed: %s", exc)
            return False

    def _note_shard(self, msg, *, additive: bool = False) -> bool:
        key = trace_key(msg.file_signature)
        with self._lock:
            stripe = self._stripes.get(key)
            if stripe is None:
                return False
            meta = stripe.meta
            num = int(msg.shard_number)
            if (
                bytes(msg.file_signature) != meta.file_signature
                or int(msg.minimum_needed_shards) != meta.k
                or int(msg.total_shards) != meta.n
                or not 0 <= num < meta.n
                or len(msg.shard_data) != meta.shard_len
                or getattr(msg, "stream_chunk_count", 0)
            ):
                engine = self._engine()
                if engine is not None:
                    engine.on_remote_interest(key)
                return False
            slot_empty = stripe.shards[num] is None
            duplicate = (
                not slot_empty and stripe.shards[num] == bytes(msg.shard_data)
            )
            shards = list(stripe.shards)
            unverified = set(stripe.unverified)
            # additive=True + placement-born: store the shard but report
            # False so the pool path still runs (docstring).
            pass_through = additive and stripe.placement
        engine = self._engine()
        if not slot_empty:
            # A shard we already hold: the interest signal anti-entropy
            # requests ride on. A DIFFERING copy of an occupied slot is
            # not consumed — the normal pool path keeps its evidence (and
            # scrub adjudicates our own copy against parity).
            if engine is not None:
                engine.on_remote_interest(key)
            return duplicate and not pass_through
        blob = bytes(msg.shard_data)
        trusted = [
            i for i, s in enumerate(shards)
            if s is not None and i not in unverified
        ]
        if len(trusted) >= meta.k:
            rs = self.codec(meta.k, meta.n, meta.field, meta.code)
            usable = [
                shards[i] if i in trusted else None for i in range(meta.n)
            ]
            want = rs.reconstruct_some(
                usable, [i == num for i in range(meta.n)]
            )[num]
            if np.ascontiguousarray(want).view(np.uint8).tobytes() != blob:
                # Inconsistent with the verified stripe: drop it here —
                # the stripe already vouches for the object, so the bad
                # copy must not reach the pool either.
                self._metrics.absorb_rejected.add(1)
                return True
            accepted_unverified = False
        else:
            accepted_unverified = True
        with self._lock:
            stripe = self._stripes.get(key)
            if (
                stripe is None
                or stripe.meta is not meta
                or stripe.shards[num] is not None
            ):
                return False
            stripe.shards[num] = blob
            if accepted_unverified:
                stripe.unverified.add(num)
            self.shard_bytes += len(blob)
        self._metrics.absorbed.add(1)
        self._persist_shard(key, num)
        if engine is not None:
            engine.enqueue_auto(key)
        return not pass_through

    def note_placement_shard(self, msg) -> bool:
        """Absorb a TARGETED placement shard (docs/placement.md) — a
        shard the placement ring routed to this node even though no
        local stripe anchors it yet. Unlike :meth:`note_shard`, an
        unknown key CREATES the stripe entry: meta derives from the
        wire geometry (``object_len = k * shard_len`` — the padded
        capacity; the manifest carries the logical size) and the slot
        lands unverified until >= k shards accumulate and the repair
        engine (or a gather's reconstruct-and-compare) vouches for it.
        Known keys delegate to the normal absorb. Advisory like
        ``note_shard``: never raises, True iff the shard was stored or
        rejected against a verified stripe."""
        try:
            with self._lock:
                known = trace_key(msg.file_signature) in self._stripes
            if known:
                return self._note_shard(msg)
            return self._note_placement_shard(msg)
        except Exception as exc:  # noqa: BLE001 — advisory path only
            log.warning("store note_placement_shard failed: %s", exc)
            return False

    def _note_placement_shard(self, msg) -> bool:
        k = int(msg.minimum_needed_shards)
        n = int(msg.total_shards)
        num = int(msg.shard_number)
        blob = bytes(msg.shard_data)
        if (
            not 1 <= k <= n
            or not 0 <= num < n
            or not blob
            or getattr(msg, "stream_chunk_count", 0)
        ):
            return False
        meta = StripeMeta(
            file_signature=bytes(msg.file_signature),
            k=k,
            n=n,
            shard_len=len(blob),
            object_len=k * len(blob),
            field="gf256",
        )
        stripe = _Stripe(
            meta=meta,
            shards=[blob if i == num else None for i in range(n)],
            unverified={num},
            placement=True,
        )
        stored = False
        with self._lock:
            if meta.key in self._stripes:
                # Raced with another arrival: fall through to absorb.
                pass
            elif len(self._stripes) >= self.max_stripes:
                return False
            else:
                self._stripes[meta.key] = stripe
                self.shard_bytes += len(blob)
                self._metrics.absorbed.add(1)
                stored = True
        if not stored:
            return self._note_shard(msg)
        # Persist and enqueue OUTSIDE the lock: both re-enter it
        # (snapshot / classify), and self._lock is not reentrant.
        self._persist_stripe(stripe)
        engine = self._engine()
        if engine is not None:
            engine.enqueue_auto(meta.key)
        return True

    # ------------------------------------------------------- persistence

    def _stripe_dir(self, key: str) -> str:
        return os.path.join(self.store_dir, key)

    def _shard_path(self, key: str, num: int) -> str:
        return os.path.join(self._stripe_dir(key), f"shard.{num:03d}")

    @staticmethod
    def _atomic_write(path: str, blob: bytes) -> None:
        tmp = path + ".part"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)

    def _persist_stripe(self, stripe: _Stripe) -> None:
        if not self.store_dir:
            return
        key = stripe.meta.key
        os.makedirs(self._stripe_dir(key), exist_ok=True)
        self._persist_meta(key)
        with self._lock:
            live = self._stripes.get(key)
            shards = list(live.shards) if live is not None else []
        for num, blob in enumerate(shards):
            if blob is not None:
                self._atomic_write(self._shard_path(key, num), blob)

    def _persist_meta(self, key: str) -> None:
        if not self.store_dir:
            return
        with self._lock:
            stripe = self._stripes.get(key)
            if stripe is None:
                return
            m = stripe.meta
            doc = {
                "file_signature": m.file_signature.hex(),
                "k": m.k,
                "n": m.n,
                "shard_len": m.shard_len,
                "object_len": m.object_len,
                "field": m.field,
                "code": m.code,
                "sender_address": m.sender_address,
                "sender_public_key": m.sender_public_key.hex(),
                "unverified": sorted(stripe.unverified),
                "placement": stripe.placement,
            }
        os.makedirs(self._stripe_dir(key), exist_ok=True)
        self._atomic_write(
            os.path.join(self._stripe_dir(key), "meta.json"),
            json.dumps(doc).encode(),
        )

    def _persist_shard(self, key: str, num: int) -> None:
        if not self.store_dir:
            return
        with self._lock:
            stripe = self._stripes.get(key)
            blob = stripe.shards[num] if stripe is not None else None
        if blob is not None:
            os.makedirs(self._stripe_dir(key), exist_ok=True)
            self._atomic_write(self._shard_path(key, num), blob)
        self._persist_meta(key)

    def _rmtree_stripe(self, key: str) -> None:
        d = self._stripe_dir(key)
        try:
            for name in os.listdir(d):
                os.unlink(os.path.join(d, name))
            os.rmdir(d)
        except OSError:
            pass

    def load(self) -> int:
        """Restore stripes from ``store_dir``; returns the stripe count.
        A shard file whose length disagrees with the metadata is treated
        as missing (the scrubber will flag and repair it)."""
        if not self.store_dir:
            return 0
        loaded = 0
        for key in sorted(os.listdir(self.store_dir)):
            meta_path = os.path.join(self.store_dir, key, "meta.json")
            if not os.path.isfile(meta_path):
                continue
            try:
                with open(meta_path, "rb") as f:
                    doc = json.load(f)
                meta = StripeMeta(
                    file_signature=bytes.fromhex(doc["file_signature"]),
                    k=int(doc["k"]),
                    n=int(doc["n"]),
                    shard_len=int(doc["shard_len"]),
                    object_len=int(doc["object_len"]),
                    field=doc.get("field", "gf256"),
                    code=doc.get("code", "rs"),
                    sender_address=doc.get("sender_address", ""),
                    sender_public_key=bytes.fromhex(
                        doc.get("sender_public_key", "")
                    ),
                )
                parse_code(meta.code)
            except (ValueError, KeyError, json.JSONDecodeError) as exc:
                log.warning("skipping unreadable stripe %s: %s", key, exc)
                continue
            if meta.key != key or not 1 <= meta.k <= meta.n:
                log.warning("skipping inconsistent stripe dir %s", key)
                continue
            shards: list[Optional[bytes]] = [None] * meta.n
            for num in range(meta.n):
                try:
                    with open(self._shard_path(key, num), "rb") as f:
                        blob = f.read()
                except OSError:
                    continue
                if len(blob) == meta.shard_len:
                    shards[num] = blob
            stripe = _Stripe(
                meta=meta,
                shards=shards,
                unverified={
                    int(i) for i in doc.get("unverified", [])
                    if 0 <= int(i) < meta.n
                },
                placement=bool(doc.get("placement", False)),
            )
            with self._lock:
                self._replace_locked(key, stripe)
            loaded += 1
        manifest_dir = os.path.join(self.store_dir, _MANIFEST_DIR)
        if os.path.isdir(manifest_dir):
            for name in sorted(os.listdir(manifest_dir)):
                if not name.endswith(".json"):
                    continue
                address = name[: -len(".json")]
                if not _ADDRESS_RE.match(address):
                    continue
                try:
                    with open(os.path.join(manifest_dir, name), "rb") as f:
                        doc = json.load(f)
                except (OSError, json.JSONDecodeError) as exc:
                    log.warning("skipping unreadable manifest %s: %s",
                                address, exc)
                    continue
                if isinstance(doc, dict):
                    with self._lock:
                        self._manifests[address] = doc
        return loaded

    def close(self) -> None:
        """Flush nothing (writes are synchronous); kept for symmetry with
        the scrubber/engine lifecycle in cli.py."""
