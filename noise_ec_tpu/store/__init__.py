"""Stripe store: keep verified objects as erasure-coded stripes, scrub
them for rot, and repair lazily through batched device reconstructs.

The reference node throws verified objects away after reassembly
(main.go:90-93 logs and deletes the pool); a production erasure-coded
system keeps the stripes, detects corruption in the background, and
repairs at leisure — the scrub/repair role HDFS-EC and Ceph build around
their codecs. Three pieces:

- :class:`StripeStore` (stripe.py) — content-addressed stripe storage
  (keyed by the signature prefix obs tracing already uses), optional disk
  persistence, and the degraded-read API: an object is served
  byte-identically while only k..n-1 shards are present by reconstructing
  on demand.
- :class:`Scrubber` (scrub.py) — walks stripes at a configurable rate and
  runs the parity verify batched through the codec's device dispatch,
  flagging corrupt and missing shards into the repair queue.
- :class:`RepairEngine` (repair.py) — coalesces pending reconstructions
  by geometry into batched device dispatches (``parallel.batch``), writes
  repaired shards back, and falls back to anti-entropy shard fetch from
  peers over the existing SHARD transport opcode when local
  reconstruction is impossible (more than n-k shards lost). LRC stripes
  (codec/lrc.py, docs/lrc.md) heal single losses from ~k/g local group
  members instead of k.
- :class:`ConversionEngine` (convert.py) — the hot→archival policy loop
  (docs/lrc.md): merges cold narrow stripes into wide RS/LRC archival
  generations via device-side re-encode, swapping manifests atomically
  so degraded reads stay byte-identical across the boundary.

Wiring: ``host/plugin.py`` lands verified receives in the store and feeds
arriving shards to :meth:`StripeStore.note_shard`; ``host/cli.py`` exposes
``-store-dir`` / ``-scrub-interval``. See docs/store.md.
"""

from noise_ec_tpu.store.convert import ConversionEngine, ConversionPolicy
from noise_ec_tpu.store.repair import RepairEngine
from noise_ec_tpu.store.scrub import Scrubber
from noise_ec_tpu.store.stripe import (
    DegradedReadError,
    StripeMeta,
    StripeStore,
    UnknownStripeError,
)

__all__ = [
    "ConversionEngine",
    "ConversionPolicy",
    "DegradedReadError",
    "RepairEngine",
    "Scrubber",
    "StripeMeta",
    "StripeStore",
    "UnknownStripeError",
]
