"""Repair engine: coalesce pending stripe repairs into batched device
reconstructs, with anti-entropy peer fetch as the fallback.

The queue holds at most one task per stripe key (re-enqueues upgrade the
kind in place). A drain groups local reconstructions by *repair shape* —
(k, n, field, shard length, trusted-slot pattern) — and runs each group
of same-shape stripes as ONE batched device dispatch
(``parallel.batch.BatchCodec.reconstruct_batch``: the (B, present, S)
stack against one inverted submatrix), which is what turns a 0.03 ms
per-stripe device reconstruct into an always-on background workload
instead of B host round trips.

Task kinds (classified by :meth:`StripeStore.classify`):

- ``missing`` — >= k trusted shards: batched erasure reconstruct, holes
  (and unverified slots) rewritten from the trusted basis.
- ``restore`` — < k trusted but >= k present counting unverified wire
  absorbs: the error-correcting whole-stripe decode (``codec.fec.FEC``,
  Berlekamp-Welch radius), anchored by the stored sender signature when
  available; on success every slot is rewritten/blessed.
- ``fetch`` — < k present: local math cannot help. The engine broadcasts
  the surviving trusted shards over the ordinary SHARD opcode (no new
  wire surface); peers that hold the stripe notice the interest
  (:meth:`StripeStore.note_shard` → :meth:`on_remote_interest`) and
  answer with their shards, which the requester absorbs shard-by-shard.
- ``respond`` — a peer showed interest in a stripe we hold with >= k
  trusted shards: broadcast our trusted shards (rate-limited per key).

Run it either as a background thread (:meth:`start`) that wakes on
enqueue and lingers briefly to let same-shape work coalesce, or drive it
deterministically with :meth:`drain_once` (tests, bench).
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from collections import OrderedDict
from typing import Iterable, Optional

import numpy as np

from noise_ec_tpu.host.wire import Shard
from noise_ec_tpu.obs.events import event
from noise_ec_tpu.obs.registry import default_registry
from noise_ec_tpu.obs.trace import node_attrs, span
from noise_ec_tpu.store.stripe import StripeStore, UnknownStripeError

__all__ = ["RepairEngine"]

log = logging.getLogger("noise_ec_tpu.store")

# Task kinds in escalation order: a re-enqueue may only upgrade towards
# the network fallback, never downgrade a fetch back to local math (the
# classifier re-checks at drain time anyway). "verify_failed" is the
# scrubber's flag for a fully-present stripe whose parity disagrees —
# classify() cannot see it (it only counts slots), so the kind survives
# re-classification below.
_KIND_RANK = {
    "respond": 0, "missing": 1, "verify_failed": 2, "restore": 3, "fetch": 4,
}


class _EngineMetrics:
    _registered = False
    _instances: "weakref.WeakSet[RepairEngine]" = weakref.WeakSet()

    def __init__(self):
        reg = default_registry()
        self.repairs = reg.counter(
            "noise_ec_store_repairs_completed_total"
        ).labels()
        self.failures = reg.counter(
            "noise_ec_store_repair_failures_total"
        ).labels()
        self.batches = reg.counter(
            "noise_ec_store_repair_batches_total"
        ).labels()
        self.batch_stripes = reg.counter(
            "noise_ec_store_repair_batch_stripes_total"
        ).labels()
        self.corrupt_shards = reg.counter(
            "noise_ec_store_corrupt_shards_total"
        ).labels()
        self.requests = reg.counter(
            "noise_ec_store_anti_entropy_requests_total"
        ).labels()
        self.responses = reg.counter(
            "noise_ec_store_anti_entropy_responses_total"
        ).labels()
        # Repair-input accounting by codec kind: the repair-storm
        # bench's repair_fetch_amplification is (lrc reads per heal) /
        # (rs reads per heal) off these counters (docs/lrc.md).
        self.shards_read = {
            code: reg.counter(
                "noise_ec_store_repair_shards_read_total"
            ).labels(code=code)
            for code in ("rs", "lrc")
        }
        self.announces = reg.counter(
            "noise_ec_store_announces_total"
        ).labels()
        cls = _EngineMetrics
        if not cls._registered:
            cls._registered = True
            reg.gauge("noise_ec_store_repair_queue_depth").set_callback(
                lambda: sum(e.queue_depth() for e in list(cls._instances))
            )


class RepairEngine:
    """Batched repair worker over one :class:`StripeStore` (module doc)."""

    def __init__(
        self,
        store: StripeStore,
        network=None,
        *,
        batch_min: int = 2,
        max_batch: Optional[int] = None,
        linger_seconds: float = 0.05,
        fetch_interval_seconds: float = 30.0,
        respond_interval_seconds: float = 30.0,
        announce_interval_seconds: float = 0.0,
        announce_window_seconds: float = 60.0,
        announce_max_stripes: int = 64,
    ):
        self.store = store
        self.network = network
        self.batch_min = batch_min
        if max_batch is None:
            # A drain's group dispatch rides rs.matmul_many through the
            # mesh dispatch tier (parallel/mesh.py): with N chips one
            # batched reconstruct shards N ways, so a repair storm may
            # drain N× wider per dispatch at the same per-chip load.
            max_batch = 64
            try:
                from noise_ec_tpu.parallel.mesh import mesh_router

                router = mesh_router()
                if router.enabled:
                    max_batch = min(64 * router.n_pow2, 512)
            # noise-ec: allow(event-on-swallow) — environment probe: host drain width without jax
            except Exception:  # noqa: BLE001 — no jax, host drain width
                pass
        self.max_batch = max_batch
        self.linger_seconds = linger_seconds
        self.fetch_interval_seconds = fetch_interval_seconds
        self.respond_interval_seconds = respond_interval_seconds
        # Anti-entropy ANNOUNCE (docs/resilience.md): every interval,
        # broadcast ONE trusted shard of each stripe stored within the
        # last ``announce_window_seconds`` (capped). Peers holding the
        # stripe absorb it silently; peers that never received the
        # object open a 1-of-k pool, whose NACK grace timer then pulls
        # the full stripe — the recovery path for SILENT loss (a
        # directional partition drops every shard, so the receiver has
        # nothing to NACK from). 0 disables (the default: announce is a
        # broadcast tax only resilience-minded deployments opt into).
        self.announce_interval_seconds = announce_interval_seconds
        self.announce_window_seconds = announce_window_seconds
        self.announce_max_stripes = announce_max_stripes
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: OrderedDict[str, str] = {}  # key -> kind
        # Stripe keys announced EVERY interval regardless of recency:
        # the object service pins the stripes of namespaces with a
        # replication target (tenant.replicas > 1), so peers that missed
        # (or lost) them keep getting re-offered one shard per interval
        # and NACK-pull the rest (docs/object-service.md).
        self._pinned: set[str] = set()
        # Announce piggybacks: zero-arg callables run after each
        # announce round — the object service's warm-set advert (which
        # peers hold which addresses decoded-warm, service/cache.py)
        # rides the same interval instead of growing its own timer.
        self._announce_hooks: list = []
        self._last_fetch: OrderedDict[str, float] = OrderedDict()
        self._last_respond: OrderedDict[str, float] = OrderedDict()
        self._fecs: dict[tuple[int, int, str], object] = {}
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self.metrics = _EngineMetrics()
        _EngineMetrics._instances.add(self)
        store.bind_engine(self)

    # ------------------------------------------------------------- queue

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def enqueue(self, key: str, kind: str) -> None:
        if kind not in _KIND_RANK:
            raise ValueError(f"unknown repair kind {kind!r}")
        with self._cond:
            prev = self._queue.get(key)
            if prev is None or _KIND_RANK[kind] > _KIND_RANK[prev]:
                self._queue[key] = kind
            self._cond.notify()

    def enqueue_auto(self, key: str) -> None:
        """Classify-and-enqueue (the absorb path calls this after filling
        a hole; a healthy stripe enqueues nothing)."""
        try:
            kind = self.store.classify(key)
        except UnknownStripeError:
            return
        if kind is not None:
            self.enqueue(key, kind)

    def pin_announce(self, keys: "Iterable[str]") -> None:
        """Mark stripe keys as standing announce targets (per-namespace
        replication): :meth:`announce_once` includes them beyond the
        recency window until they are unpinned or evicted."""
        with self._lock:
            self._pinned.update(keys)

    def unpin_announce(self, keys: "Iterable[str]") -> None:
        with self._lock:
            self._pinned.difference_update(keys)

    def pinned_keys(self) -> list[str]:
        with self._lock:
            return sorted(self._pinned)

    def add_announce_hook(self, fn) -> None:
        """Register a zero-arg callable to run after each announce round
        (piggyback surface: the object service broadcasts its warm-set
        advert here — docs/object-service.md "Read path"). Exceptions
        are logged, never raised."""
        self._announce_hooks.append(fn)

    def on_remote_interest(self, key: str) -> None:
        """A peer is moving shards of a stripe we hold (called from the
        plugin receive path via the store — must stay cheap). Rate-limit
        per key, then queue a respond task."""
        now = time.monotonic()
        with self._lock:
            last = self._last_respond.get(key)
            if (
                last is not None
                and now - last < self.respond_interval_seconds
            ):
                return
            self._last_respond[key] = now
            while len(self._last_respond) > 4096:
                self._last_respond.popitem(last=False)
        self.enqueue(key, "respond")

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="noise-ec-repair", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        from noise_ec_tpu.ops.coalesce import qos_lane

        # Repair reconstruct dispatches ride the device gate's
        # background lane: durability work yields to live traffic at a
        # contended gate (the starvation floor guarantees progress).
        with qos_lane("background", tenant="repair"):
            self._run_loop()

    def _run_loop(self) -> None:
        next_announce = (
            time.monotonic() + self.announce_interval_seconds
            if self.announce_interval_seconds > 0 else None
        )
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    if next_announce is None:
                        self._cond.wait()
                    else:
                        remaining = next_announce - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                if self._closed:
                    return
            if (
                next_announce is not None
                and time.monotonic() >= next_announce
            ):
                next_announce = (
                    time.monotonic() + self.announce_interval_seconds
                )
                try:
                    self.announce_once()
                except Exception as exc:  # noqa: BLE001 — keep the worker up
                    log.error("announce failed: %s", exc)
            with self._lock:
                has_work = bool(self._queue)
            if not has_work:
                continue
            # Linger so same-shape repairs arriving in a burst (a scrub
            # cycle, a dying device) coalesce into one batched dispatch.
            if self.linger_seconds > 0:
                time.sleep(self.linger_seconds)
            try:
                self.drain_once()
            except Exception as exc:  # noqa: BLE001 — keep the worker up
                log.error("repair drain failed: %s", exc)

    # -------------------------------------------------------------- drain

    def drain_once(self) -> int:
        """Process everything currently queued; returns the number of
        stripes repaired (fetch/respond count as processed, not
        repaired). Deterministic entry point for tests and bench."""
        with self._lock:
            tasks = list(self._queue.items())[: self.max_batch]
            for key, _ in tasks:
                del self._queue[key]
        if not tasks:
            return 0
        # Re-classify at drain time: absorbs since enqueue may have
        # upgraded a fetch to a local reconstruct (or healed it outright).
        groups: dict[tuple, list[tuple[str, list]]] = {}
        singles: list[tuple[str, str]] = []
        for key, kind in tasks:
            if kind == "respond":
                singles.append((key, "respond"))
                continue
            try:
                now_kind = self.store.classify(key)
            except UnknownStripeError:
                continue
            if now_kind is None:
                # Slot-complete — but a scrub verify_failed flag means the
                # bytes are wrong even though every slot is filled.
                if kind == "verify_failed":
                    singles.append((key, "verify_failed"))
                continue
            if now_kind == "missing":
                meta, shards, unverified = self.store.snapshot(key)
                trusted = tuple(
                    i for i, s in enumerate(shards)
                    if s is not None and i not in unverified
                )
                gkey = (
                    meta.k, meta.n, meta.field, meta.shard_len, trusted,
                    meta.code,
                )
                groups.setdefault(gkey, []).append((key, shards))
            else:
                singles.append((key, now_kind))
        repaired = 0
        for gkey, members in groups.items():
            repaired += self._reconstruct_group(gkey, members)
        for key, kind in singles:
            if kind in ("restore", "verify_failed"):
                repaired += self._restore(key)
            elif kind == "fetch":
                self._fetch(key)
            elif kind == "respond":
                self._respond(key)
        return repaired

    # ------------------------------------------------- local reconstruct

    def _sym_dtype(self, field: str):
        return np.dtype("<u2") if field == "gf65536" else np.dtype(np.uint8)

    def _reconstruct_group(self, gkey: tuple, members: list) -> int:
        """Rebuild every non-trusted slot of a same-shape stripe group.
        B >= batch_min stripes fold into one batched device dispatch;
        smaller groups take the per-stripe codec path. LRC stripes route
        through the codec's tiered ``repair_many``: loss patterns inside
        the group budget heal from ~k/g cell members per stripe (all
        B×|wanted| heals in ONE coalesced all-ones dispatch) instead of
        the full-k basis — the fetch-amplification win docs/lrc.md
        quantifies."""
        k, n, fieldname, shard_len, trusted, code = gkey
        wanted = [i for i in range(n) if i not in trusted]
        if not wanted or len(trusted) < k:
            return 0
        dt = self._sym_dtype(fieldname)
        repaired = 0
        with span("repair", stripes=len(members), k=k, n=n, **node_attrs()):
            from noise_ec_tpu.codec.lrc import LocalReconstructionCode

            rs = self.store.codec(k, n, fieldname, code)
            if isinstance(rs, LocalReconstructionCode):
                plan = rs.repair_plan(trusted, wanted)
                reads = (
                    len({m for basis in plan.values() for m in basis})
                    if plan is not None else k
                )
                rebuilt = rs.repair_many(
                    [shards for _, shards in members], trusted, wanted
                )
                self.metrics.shards_read["lrc"].add(reads * len(members))
                if plan is not None and len(members) >= self.batch_min:
                    self.metrics.batches.add(1)
                    self.metrics.batch_stripes.add(len(members))
            elif len(members) >= self.batch_min:
                # One coalesced dispatch for the whole group: the engine
                # no longer keeps a private batch path — it hands the
                # pre-formed batch to the live-path CoalescingDispatcher
                # (rs.matmul_many -> ops/coalesce.py submit_many), so
                # repair work and live encode/decode traffic share one
                # queue (and the DeviceGate admission behind it), and a
                # concurrent same-shape live request can ride the same
                # device call as a repair storm.
                from noise_ec_tpu.matrix.linalg import reconstruction_matrix

                basis = sorted(trusted)[:k]
                R = reconstruction_matrix(rs.gf, rs.G, basis, wanted)
                self.metrics.shards_read["rs"].add(k * len(members))
                stacks = [
                    np.stack([
                        np.frombuffer(shards[i], dtype=np.uint8).view(dt)
                        for i in basis
                    ])
                    for _, shards in members
                ]
                filled = rs.matmul_many(R, stacks)
                self.metrics.batches.add(1)
                self.metrics.batch_stripes.add(len(members))
                rebuilt = [
                    {
                        i: np.ascontiguousarray(rows[row])
                        .view(np.uint8).tobytes()
                        for row, i in enumerate(wanted)
                    }
                    for rows in filled
                ]
            else:
                self.metrics.shards_read["rs"].add(k * len(members))
                required = [i in wanted for i in range(n)]
                rebuilt = []
                for _, shards in members:
                    usable = [
                        shards[i] if i in trusted else None for i in range(n)
                    ]
                    rows = rs.reconstruct_some(usable, required)
                    rebuilt.append({
                        i: np.ascontiguousarray(rows[i])
                        .view(np.uint8).tobytes()
                        for i in wanted
                    })
            for (key, shards), fixed in zip(members, rebuilt):
                corrected = sum(
                    1 for i in wanted
                    if shards[i] is not None and shards[i] != fixed[i]
                )
                try:
                    self.store.write_repaired(key, fixed)
                except (UnknownStripeError, ValueError) as exc:
                    self.metrics.failures.add(1)
                    log.warning("repair write-back failed for %s: %s",
                                key, exc)
                    continue
                if corrected:
                    self.metrics.corrupt_shards.add(corrected)
                    event("scrub.corrupt", "error", key=key[:16],
                          shards=corrected, source="repair")
                self.metrics.repairs.add(1)
                repaired += 1
        return repaired

    # -------------------------------------------------- restore / verify

    def _fec(self, k: int, n: int, fieldname: str, code: str = "rs"):
        fkey = (k, n, fieldname, code)
        fec = self._fecs.get(fkey)
        if fec is None:
            from noise_ec_tpu.codec.fec import FEC

            # The code kind IS the generator: an LRC stripe restores
            # through FEC over the same "lrc:<g>" matrix (no GRS form,
            # so correction runs the support-enumeration/subset tiers).
            fec = self._fecs[fkey] = FEC(
                k, n, field=fieldname, backend="numpy",
                matrix="cauchy" if code == "rs" else code,
            )
        return fec

    def repair_corrupt(self, key: str) -> bool:
        """Whole-stripe validation + correction: the scrubber sends
        parity-inconsistent stripes here. Error-correcting decode over
        every present shard, sender-signature check when the stripe
        carries one, then re-encode and rewrite whatever disagreed."""
        return self._restore(key) > 0

    def _restore(self, key: str) -> int:
        from noise_ec_tpu.codec.fec import Share

        try:
            meta, shards, unverified = self.store.snapshot(key)
        except UnknownStripeError:
            return 0
        present = [i for i, s in enumerate(shards) if s is not None]
        if len(present) < meta.k:
            self.enqueue(key, "fetch")
            return 0
        fec = self._fec(meta.k, meta.n, meta.field, meta.code)
        with span("repair", key=key, kind="restore", **node_attrs()):
            try:
                data_full = fec.decode(
                    [Share(i, shards[i]) for i in present]
                )
            except Exception as exc:  # noqa: BLE001 — undecodable as-is
                self.metrics.failures.add(1)
                log.warning("restore decode failed for %s: %s", key, exc)
                self.enqueue(key, "fetch")
                return 0
            obj = data_full[: meta.object_len]
            if meta.sender_public_key:
                if not self._signature_ok(meta, obj):
                    self.metrics.failures.add(1)
                    log.warning(
                        "restore of %s decodes but fails the stored "
                        "sender signature; keeping slots unverified", key
                    )
                    self.enqueue(key, "fetch")
                    return 0
            elif len(present) == meta.k and unverified:
                # k mixed trusted/unverified shards, no redundancy and no
                # signature anchor: nothing vouches for the decode.
                self.metrics.failures.add(1)
                self.enqueue(key, "fetch")
                return 0
            rs = self.store.codec(meta.k, meta.n, meta.field, meta.code)
            stride = meta.shard_len // self._sym_dtype(meta.field).itemsize
            D = (
                np.frombuffer(data_full, dtype=np.uint8)
                .view(self._sym_dtype(meta.field))
                .reshape(meta.k, stride)
            )
            truth = [
                np.ascontiguousarray(row).view(np.uint8).tobytes()
                for row in rs.encode(list(D))
            ]
            fixed = {
                i: truth[i]
                for i in range(meta.n)
                if shards[i] != truth[i]
            }
            corrupt = sum(
                1 for i in fixed if shards[i] is not None
            )
            try:
                if fixed:
                    self.store.write_repaired(key, fixed)
                self.store.mark_trusted(key, range(meta.n))
            except (UnknownStripeError, ValueError) as exc:
                self.metrics.failures.add(1)
                log.warning("restore write-back failed for %s: %s", key, exc)
                return 0
            if corrupt:
                self.metrics.corrupt_shards.add(corrupt)
                event("scrub.corrupt", "error", key=key[:16],
                      shards=corrupt, source="restore")
            self.metrics.repairs.add(1)
        return 1

    def _signature_ok(self, meta, obj: bytes) -> bool:
        from noise_ec_tpu.host.crypto import (
            Blake2bPolicy,
            Ed25519Policy,
            PeerID,
            serialize_message,
            verify,
        )

        try:
            return verify(
                Ed25519Policy(),
                Blake2bPolicy(),
                meta.sender_public_key,
                serialize_message(
                    PeerID.create(
                        meta.sender_address, meta.sender_public_key
                    ),
                    obj,
                ),
                meta.file_signature,
            )
        # noise-ec: allow(event-on-swallow) — malformed stored identity — treated as non-origin, nothing to report
        except Exception:  # noqa: BLE001 — malformed stored identity
            return False

    # ------------------------------------------------------ anti-entropy

    def _broadcast_shards(self, meta, shards, numbers) -> int:
        sent = 0
        for i in numbers:
            if shards[i] is None:
                continue
            self.network.broadcast(Shard(
                file_signature=meta.file_signature,
                shard_data=shards[i],
                shard_number=i,
                total_shards=meta.n,
                minimum_needed_shards=meta.k,
            ))
            sent += 1
        return sent

    def _fetch(self, key: str) -> None:
        """Anti-entropy request: re-broadcast our surviving trusted
        shards over the plain SHARD opcode. Peers holding the stripe see
        shards they already have, which is the interest signal their
        engine answers (``respond``); their shards then heal us via the
        absorb path."""
        if self.network is None:
            return
        peers = getattr(self.network, "peers", None)
        if peers is not None and not peers:
            # Nobody to ask yet (startup races peer registration): do NOT
            # burn the per-key rate-limit window on a broadcast to zero
            # peers — the next scrub cycle re-enqueues the fetch and it
            # goes out once a peer registers.
            return
        now = time.monotonic()
        with self._lock:
            last = self._last_fetch.get(key)
            if (
                last is not None
                and now - last < self.fetch_interval_seconds
            ):
                return
            self._last_fetch[key] = now
            while len(self._last_fetch) > 4096:
                self._last_fetch.popitem(last=False)
        try:
            meta, shards, unverified = self.store.snapshot(key)
        except UnknownStripeError:
            return
        trusted = [
            i for i, s in enumerate(shards)
            if s is not None and i not in unverified
        ]
        self._broadcast_shards(meta, shards, trusted)
        self.metrics.requests.add(1)
        log.info(
            "anti-entropy request for stripe %s (%d/%d trusted shards "
            "survive)", key, len(trusted), meta.n,
        )

    def announce_once(self) -> int:
        """Broadcast ONE trusted shard per recently stored stripe (see
        the ``announce_interval_seconds`` doc in ``__init__``). Returns
        the number of stripes announced. Deterministic entry point for
        tests; the background thread calls it on the interval."""
        if self.network is None:
            return 0
        peers = getattr(self.network, "peers", None)
        if peers is not None and not peers:
            return 0  # nobody listening; the next interval retries
        announced = 0
        # Follow the cursor to the END of the recency window: one page
        # per recent_keys call (the per-page cap keeps each store-lock
        # hold bounded), but a store with more than announce_max_stripes
        # fresh stripes announces ALL of them, not just page 1.
        recent: list = []
        cursor = None
        while True:
            page, cursor = self.store.recent_keys(
                self.announce_window_seconds,
                self.announce_max_stripes,
                cursor=cursor,
            )
            recent.extend(page)
            if cursor is None or not page:
                break
        # Pinned keys (namespace replication targets) ride every
        # announce beyond the recency window; dict.fromkeys dedups while
        # keeping the newest-first recents ahead of the standing set.
        for key in dict.fromkeys(list(recent) + self.pinned_keys()):
            try:
                meta, shards, unverified = self.store.snapshot(key)
            except UnknownStripeError:
                continue
            trusted = [
                i for i, s in enumerate(shards)
                if s is not None and i not in unverified
            ]
            if not trusted:
                continue
            self._broadcast_shards(meta, shards, trusted[:1])
            announced += 1
        if announced:
            self.metrics.announces.add(announced)
        for fn in list(self._announce_hooks):
            try:
                fn()
            except Exception as exc:  # noqa: BLE001 — a piggyback must
                # not break the announce loop
                log.warning("announce hook failed: %s", exc)
        return announced

    def _respond(self, key: str) -> None:
        if self.network is None:
            return
        try:
            meta, shards, unverified = self.store.snapshot(key)
        except UnknownStripeError:
            return
        trusted = [
            i for i, s in enumerate(shards)
            if s is not None and i not in unverified
        ]
        if len(trusted) < meta.k:
            return  # we are the one needing help here
        sent = self._broadcast_shards(meta, shards, trusted)
        if sent:
            self.metrics.responses.add(1)
