"""Hot→archival code conversion: merge cold narrow stripes into wide
ones as objects cool (docs/lrc.md).

Hot writes land as narrow RS stripes (fast to encode, cheap to repair
at small k); archival storage wants wide geometries — RS(200, 56) or an
LRC — whose lower overhead the panel kernels made computationally free.
Convertible codes (Maturana & Rashmi) are the theory; this module is the
operational loop:

- :class:`ConversionPolicy` — the per-tenant policy grammar
  (``service/tenants.py`` ``policy`` field, validated at configure
  time): ``archive=lrc:K/G+R`` or ``archive=rs:K+R`` names the archival
  tier and geometry, ``age=SECONDS`` the cold threshold,
  ``stripe_bytes=B`` the archival stripe capacity. Unknown tier names
  and invalid LRC geometries (group count must divide k; >= 1 global
  parity) are rejected with clear ``ValueError``\\ s when the policy is
  parsed, never at conversion time.

- :class:`ConversionEngine` — a background loop (or a deterministic
  :meth:`run_cycle`) that walks the manifest table, picks objects that
  are *cold* (manifest age past the policy threshold AND the address
  not touched in the PR-12 decoded cache within that same threshold —
  recency-bounded, since residency in an idle LRU is not warmth) and
  converts them:

  1. **gather** — source logical bytes come decode-free where a source
     stripe's k data shards are all trusted (a join, no field ops);
     degraded source stripes group by erasure pattern and rebuild
     through ONE batched device dispatch per pattern
     (``reconstruction_matrix`` + ``matmul_many`` — the repair engine's
     shape, riding the same coalescer/DeviceGate/mesh path);
  2. **verify** — the gathered bytes must re-hash to the manifest
     address (the uploader's content anchor), so a conversion can never
     silently launder corruption across tiers;
  3. **re-encode** — the bytes re-chunk at the archival capacity and
     encode through ``StripeStore.put_object`` with the target code
     ("rs" or "lrc:<g>"), one device-dispatched encode per stripe;
     stripe signatures are derived deterministically from (address,
     capacity, code, index), so a crashed conversion re-runs
     idempotently;
  4. **swap** — ONE atomic manifest write (tmp + rename) repoints the
     object at the archival generation. Before the swap every read
     serves the hot generation; after it, the archival one — at no
     instant does the manifest reference an incomplete generation,
     which is the crash-consistency contract the conversion e2e test
     pins (kill before the swap: hot generation intact, re-run
     converts; kill after: archival generation serves, re-run GCs);
  5. **GC** — source stripes no other manifest references are evicted
     (and unpinned from the announce loop); the decoded cache drops the
     address (stripe indexing changed with the capacity).

Scope: conversion is a *local* generation change — the manifest address
(and therefore the object's bytes) is unchanged, so peers holding the
hot generation keep serving it byte-identically; each holder applies
its own tenant policy. Replicating archival stripes across the fleet is
future work (docs/lrc.md).
"""

from __future__ import annotations

import hashlib
import logging
import re
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from noise_ec_tpu.obs.events import event
from noise_ec_tpu.obs.registry import default_registry
from noise_ec_tpu.obs.trace import node_attrs, span
from noise_ec_tpu.store.stripe import StripeStore

__all__ = [
    "ConversionEngine",
    "ConversionPolicy",
    "derive_stripe_sig",
    "finish_prev_stripes_gc",
    "split_qos",
]

log = logging.getLogger("noise_ec_tpu.store")


def derive_stripe_sig(
    namespace: bytes, address: str, code: str, capacity: int, idx: int,
    *, salt: int = 0,
) -> bytes:
    """Deterministic generation-stripe signature: blake2b over
    (namespace, address, code, capacity, index[, salt]). The same
    inputs always reproduce the same key, which is what makes a
    crashed conversion/rebalance re-run idempotent — ``put_object``
    replacement lands on identical keys. ``salt`` (the placement
    epoch for rebalance moves) is omitted from the preimage when 0 so
    conversion signatures are byte-identical to the historical form."""
    return hashlib.blake2b(
        namespace + address.encode() + b"\0"
        + code.encode() + b"\0"
        + capacity.to_bytes(8, "little")
        + idx.to_bytes(8, "little")
        + (salt.to_bytes(8, "little") if salt else b""),
        digest_size=32,
    ).digest()


def finish_prev_stripes_gc(
    store: StripeStore, address: str, doc: dict, *, repair=None
) -> None:
    """Evict source stripes no surviving manifest references (the same
    refcount walk DELETE uses), unpin them from the announce loop, then
    clear the ``prev_stripes`` marker — the idempotent tail of a
    generation swap (conversion or placement rebalance), re-runnable
    after a crash."""
    old_keys = [str(s) for s in doc.get("prev_stripes") or ()]
    new_keys = {str(s) for s in doc.get("stripes") or ()}
    doomed = [k for k in dict.fromkeys(old_keys) if k not in new_keys]
    if doomed:
        refs: set = set()
        cursor = None
        while True:
            page, cursor = store.list_manifests(cursor=cursor, limit=256)
            for _, other in page:
                refs.update(str(s) for s in other.get("stripes") or ())
                ms = other.get("manifest_stripe")
                if ms:
                    refs.add(str(ms))
            if cursor is None:
                break
        doomed = [k for k in doomed if k not in refs]
        for key in doomed:
            store.evict(key)
        if doomed and repair is not None:
            repair.unpin_announce(doomed)
    done = dict(doc)
    done.pop("prev_stripes", None)
    store.put_manifest(address, done)

_FIELD_ORDER = {"gf256": 256, "gf65536": 65536}

# archive=lrc:K/G+R  |  archive=rs:K+R
_GEOMETRY_RE = re.compile(r"^([a-z0-9_]+):(\d+)(?:/(\d+))?\+(\d+)$")

# Tenant-policy QoS tokens (service/tenants.py grammar): ``lane=`` and
# ``weight=`` ride the SAME comma-separated policy string as
# ``archive=``/``age=`` but belong to the device-gate fairness layer
# (ops/dispatch.py), not conversion. The splitter lives here so both
# consumers — tenant configure-time validation and :meth:`policy_for`'s
# archival parse — share one tokenizer without a service<->store import
# cycle (tenants.py already imports this module lazily).
QOS_LANES = ("live", "background")
QOS_WEIGHT_MAX = 1000


def split_qos(text: str) -> tuple[str, int, str]:
    """Split the ``lane=``/``weight=`` QoS tokens out of a tenant policy
    string: ``(lane, weight, archival_rest)``. Raises ``ValueError`` for
    an unknown lane or a weight outside ``[1, QOS_WEIGHT_MAX]`` — the
    same configure-time contract as the archival grammar."""
    lane, weight = "live", 1
    rest: list[str] = []
    for raw in (text or "").split(","):
        tok = raw.strip()
        if not tok:
            continue
        key, _, val = tok.partition("=")
        key, val = key.strip(), val.strip()
        if key == "lane":
            if val not in QOS_LANES:
                raise ValueError(
                    f"unknown QoS lane {val!r} (lanes: "
                    f"{', '.join(QOS_LANES)})"
                )
            lane = val
        elif key == "weight":
            try:
                weight = int(val)
            except ValueError as exc:
                raise ValueError(
                    f"QoS weight {val!r} is not an integer"
                ) from exc
            if not 1 <= weight <= QOS_WEIGHT_MAX:
                raise ValueError(
                    f"QoS weight {weight} outside [1, {QOS_WEIGHT_MAX}]"
                )
        else:
            rest.append(tok)
    return lane, weight, ",".join(rest)


@dataclass(frozen=True)
class ConversionPolicy:
    """One tenant's archival policy (module docstring for the grammar)."""

    tier: str = "lrc"           # "rs" | "lrc"
    k: int = 0
    groups: int = 0             # LRC local groups (0 for rs)
    global_parities: int = 0
    age_seconds: float = 300.0
    stripe_bytes: int = 1 << 20
    field: str = "gf256"

    @property
    def n(self) -> int:
        return self.k + self.groups + self.global_parities

    @property
    def code(self) -> str:
        """The stripe-store code string of the archival tier."""
        return f"lrc:{self.groups}" if self.tier == "lrc" else "rs"

    @property
    def capacity(self) -> int:
        """Logical bytes per archival stripe (k-aligned, like the object
        layer's PUT capacity)."""
        return max(self.k, self.stripe_bytes - self.stripe_bytes % self.k)

    @classmethod
    def parse(cls, text: str) -> "ConversionPolicy":
        """Parse one policy string, e.g. ::

            archive=lrc:20/4+6,age=600,stripe_bytes=4194304

        Raises ``ValueError`` for unknown tiers, malformed geometry
        specs, LRC group counts that do not divide k, or a missing
        global parity — the tenant-configure-time contract."""
        kwargs: dict = {}
        saw_archive = False
        for raw in text.split(","):
            tok = raw.strip()
            if not tok:
                continue
            if "=" not in tok:
                raise ValueError(f"unparseable policy token {tok!r}")
            key, _, val = tok.partition("=")
            key, val = key.strip(), val.strip()
            if key == "archive":
                saw_archive = True
                m = _GEOMETRY_RE.match(val)
                if not m:
                    raise ValueError(
                        f"bad archival geometry {val!r} (want "
                        "'lrc:K/G+R' or 'rs:K+R')"
                    )
                tier, k, g, r = (
                    m.group(1), int(m.group(2)),
                    int(m.group(3)) if m.group(3) else 0, int(m.group(4)),
                )
                if tier not in ("rs", "lrc"):
                    raise ValueError(
                        f"unknown archival tier {tier!r} (known tiers: "
                        "lrc, rs)"
                    )
                if tier == "rs" and g:
                    raise ValueError(
                        f"rs geometry {val!r} takes no group count"
                    )
                if tier == "lrc" and not g:
                    raise ValueError(
                        f"lrc geometry {val!r} needs a group count "
                        "('lrc:K/G+R')"
                    )
                kwargs.update(
                    tier=tier, k=k, groups=g, global_parities=r
                )
            elif key == "age":
                kwargs["age_seconds"] = float(val)
            elif key == "stripe_bytes":
                kwargs["stripe_bytes"] = int(val)
            elif key == "field":
                if val not in _FIELD_ORDER:
                    raise ValueError(f"unknown field {val!r}")
                kwargs["field"] = val
            else:
                raise ValueError(f"unknown policy knob {key!r}")
        if not saw_archive:
            raise ValueError(
                "policy must name an archival tier (archive=lrc:K/G+R "
                "or archive=rs:K+R)"
            )
        pol = cls(**kwargs)
        pol.validate()
        return pol

    def validate(self) -> None:
        if self.k < 1:
            raise ValueError(f"archival k must be >= 1, got {self.k}")
        if self.global_parities < 1:
            raise ValueError(
                f"archival tier needs >= 1 global parity, got "
                f"{self.global_parities}"
            )
        if self.tier == "lrc":
            if self.groups < 1:
                raise ValueError(
                    f"LRC group count must be >= 1, got {self.groups}"
                )
            if self.k % self.groups:
                raise ValueError(
                    f"LRC group count {self.groups} must divide "
                    f"k={self.k}"
                )
        elif self.groups:
            raise ValueError("rs tier takes no local groups")
        if self.n > _FIELD_ORDER[self.field]:
            raise ValueError(
                f"total shards {self.n} exceeds the {self.field} "
                f"field order"
            )
        if self.age_seconds < 0:
            raise ValueError(
                f"age must be >= 0, got {self.age_seconds}"
            )
        if self.stripe_bytes < self.k:
            raise ValueError(
                f"stripe_bytes {self.stripe_bytes} below k={self.k}"
            )


class _ConvertMetrics:
    def __init__(self):
        reg = default_registry()
        self.objects = {
            result: reg.counter(
                "noise_ec_convert_objects_total"
            ).labels(result=result)
            for result in ("converted", "failed")
        }
        self.bytes = reg.counter("noise_ec_convert_bytes_total").labels()
        self.stripes = {
            mode: reg.counter(
                "noise_ec_convert_stripes_total"
            ).labels(mode=mode)
            for mode in ("merge", "reconstruct")
        }
        self.seconds = reg.histogram("noise_ec_convert_seconds").labels()


class ConversionEngine:
    """Background hot→archival converter over one store (module doc).

    ``tenants`` supplies per-namespace policies; ``cache`` (optional)
    supplies the temperature signal and is invalidated on swap;
    ``repair`` (optional, the :class:`RepairEngine`) has GC'd source
    stripes unpinned from its announce loop."""

    def __init__(
        self,
        store: StripeStore,
        tenants,
        *,
        cache=None,
        repair=None,
        interval_seconds: float = 60.0,
        clock: Callable[[], float] = time.time,
    ):
        self.store = store
        self.tenants = tenants
        self.cache = cache
        self.repair = repair
        self.interval_seconds = interval_seconds
        self.clock = clock
        self._policies: dict[str, Optional[ConversionPolicy]] = {}
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # Crash-injection hooks (the conversion e2e test): each runs at
        # its boundary when set; raising simulates dying there.
        self.fault_before_swap: Optional[Callable[[], None]] = None
        self.fault_after_swap: Optional[Callable[[], None]] = None
        self._metrics = _ConvertMetrics()

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="noise-ec-convert", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._closed = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        from noise_ec_tpu.ops.coalesce import qos_lane

        # Conversion decode/re-encode dispatches ride the device gate's
        # background lane (docs/object-service.md "QoS lanes").
        with qos_lane("background", tenant="convert"):
            while not self._closed:
                try:
                    self.run_cycle()
                except Exception as exc:  # noqa: BLE001 — keep converting
                    log.error("conversion cycle failed: %s", exc)
                self._wake.wait(self.interval_seconds)
                self._wake.clear()

    # ------------------------------------------------------------- policy

    def policy_for(self, tenant_name: str) -> Optional[ConversionPolicy]:
        """The tenant's parsed policy, or None (no policy / unknown
        tenant / unparseable — configure-time validation makes the last
        a should-not-happen, logged once)."""
        try:
            tenant = self.tenants.get(tenant_name)
        except KeyError:
            return None
        text = getattr(tenant, "policy", "") or ""
        if not text:
            return None
        if text not in self._policies:
            try:
                # QoS tokens (lane=, weight=) share the policy string but
                # configure the device-gate lanes, not conversion: strip
                # them before the archival parse. A policy that is ONLY
                # QoS tokens has no archival tier.
                archival = split_qos(text)[2]
                self._policies[text] = (
                    ConversionPolicy.parse(archival) if archival else None
                )
            except ValueError as exc:
                log.warning("ignoring bad policy %r: %s", text, exc)
                self._policies[text] = None
        return self._policies[text]

    # -------------------------------------------------------------- cycle

    def run_cycle(self) -> dict:
        """One manifest walk; returns counts for deterministic callers
        (tests, bench): {scanned, converted, failed, young, warm}."""
        stats = {"scanned": 0, "converted": 0, "failed": 0,
                 "young": 0, "warm": 0}
        now = self.clock()
        cursor = None
        while True:
            page, cursor = self.store.list_manifests(
                cursor=cursor, limit=256
            )
            for address, doc in page:
                stats["scanned"] += 1
                pol = self.policy_for(str(doc.get("tenant", "")))
                if pol is None:
                    continue
                if self._at_target(doc, pol):
                    if doc.get("prev_stripes"):
                        # A crash landed between the swap and GC: the
                        # archival generation serves; finish the GC.
                        self._finish_gc(address, doc)
                    continue
                if now - float(doc.get("created", now)) < pol.age_seconds:
                    stats["young"] += 1
                    continue
                if self.cache is not None and self.cache.warm(
                    address, within_seconds=pol.age_seconds
                ):
                    # Temperature: the address was READ within the cold
                    # threshold — converting it would evict the working
                    # set and re-chunk under its readers. Residency
                    # alone is not warmth (an idle LRU never expires),
                    # so the signal is recency-bounded by the policy's
                    # own age. Next cycle.
                    stats["warm"] += 1
                    continue
                if self.convert_object(doc):
                    stats["converted"] += 1
                else:
                    stats["failed"] += 1
            if cursor is None:
                break
        return stats

    @staticmethod
    def _at_target(doc: dict, pol: ConversionPolicy) -> bool:
        return (
            str(doc.get("code", "rs")) == pol.code
            and int(doc.get("k", 0)) == pol.k
            and int(doc.get("n", 0)) == pol.n
            and int(doc.get("stripe_bytes", 0)) == pol.capacity
            and str(doc.get("field", "gf256")) == pol.field
        )

    # ------------------------------------------------------------ convert

    def convert_object(self, doc: dict, pol: Optional[ConversionPolicy] = None) -> bool:
        """Convert one object to its tenant's archival tier (module
        docstring steps); returns True on success. Failures count and
        log, never raise — the loop must survive a sick object."""
        if pol is None:
            pol = self.policy_for(str(doc.get("tenant", "")))
            if pol is None:
                return False
        t0 = time.monotonic()
        address = str(doc["address"])
        try:
            with span("convert", address=address, tier=pol.code,
                      **node_attrs()):
                whole = self._gather(doc)
                if whole is None:
                    raise ValueError("source generation not readable")
                digest = hashlib.blake2b(digest_size=16)
                digest.update(
                    str(doc["tenant"]).encode() + b"\0"
                    + str(doc["name"]).encode() + b"\0"
                )
                digest.update(whole)
                if digest.hexdigest() != address:
                    raise ValueError(
                        "gathered bytes do not hash to the manifest "
                        "address — refusing to convert"
                    )
                new_keys = self._encode_generation(address, whole, pol)
                if self.fault_before_swap is not None:
                    self.fault_before_swap()
                old_keys = [str(s) for s in doc.get("stripes") or ()]
                new_doc = dict(doc)
                new_doc.update(
                    stripes=new_keys,
                    k=pol.k, n=pol.n, field=pol.field,
                    code=pol.code,
                    stripe_bytes=pol.capacity,
                    tier="archive",
                    converted=self.clock(),
                    # Source keys ride the manifest until GC completes,
                    # so a crash in the swap..GC window leaves a marker
                    # the next cycle converges on instead of orphaned
                    # stripes.
                    prev_stripes=old_keys,
                )
                # THE swap: one atomic manifest write. Every read
                # before this line serves the hot generation, every
                # read after it the archival one.
                self.store.put_manifest(address, new_doc)
                event("convert.swap", address=address[:16],
                      tier="archive", stripes=len(old_keys))
                if self.fault_after_swap is not None:
                    self.fault_after_swap()
                if self.cache is not None:
                    # Stripe indexing changed with the capacity; the
                    # address's cached entries map the OLD chunking.
                    self.cache.evict_address(address)
                self._finish_gc(address, new_doc)
        except Exception as exc:  # noqa: BLE001 — counted, not raised
            self._metrics.objects["failed"].add(1)
            log.warning("conversion of %s failed: %s", address, exc)
            return False
        self._metrics.objects["converted"].add(1)
        self._metrics.bytes.add(len(whole))
        self._metrics.seconds.observe(time.monotonic() - t0)
        log.info(
            "converted %s: %d bytes, %d -> %d stripes, %s(%d,%d) -> "
            "%s k=%d n=%d", address, len(whole),
            len(doc.get("stripes") or ()), len(new_keys),
            doc.get("code", "rs"), int(doc.get("k", 0)),
            int(doc.get("n", 0)), pol.code, pol.k, pol.n,
        )
        return True

    # ------------------------------------------------------------- gather

    def _gather(self, doc: dict) -> Optional[bytes]:
        """The object's logical bytes from the source generation:
        decode-free joins where the data shards are intact, batched
        reconstructs (grouped by erasure pattern) otherwise. None when
        any stripe is below k trusted shards locally."""
        keys = [str(s) for s in doc.get("stripes") or ()]
        size = int(doc["size"])
        capacity = int(doc["stripe_bytes"])
        snaps = self.store.snapshot_many(keys)
        parts: list = [None] * len(keys)
        # (pattern gkey) -> [(part index, meta, shards)]
        degraded: dict[tuple, list] = {}
        for idx, key in enumerate(keys):
            snap = snaps.get(key)
            if snap is None:
                return None
            meta, shards, unverified = snap
            trusted = [
                i for i, s in enumerate(shards)
                if s is not None and i not in unverified
            ]
            logical = min(capacity, size - idx * capacity)
            if all(i in trusted for i in range(meta.k)):
                parts[idx] = b"".join(
                    shards[: meta.k]
                )[: meta.object_len][:logical]
                self._metrics.stripes["merge"].add(1)
                continue
            if len(trusted) < meta.k:
                return None
            gkey = (
                meta.k, meta.n, meta.field, meta.shard_len,
                tuple(sorted(trusted)), meta.code,
            )
            degraded.setdefault(gkey, []).append((idx, meta, shards))
        for gkey, members in degraded.items():
            rows_by_member = self._reconstruct_batch(gkey, members)
            for (idx, meta, _), rows in zip(members, rows_by_member):
                logical = min(capacity, size - idx * capacity)
                parts[idx] = rows[: meta.object_len][:logical]
            self._metrics.stripes["reconstruct"].add(len(members))
        return b"".join(parts)

    def _reconstruct_batch(self, gkey: tuple, members: list) -> list:
        """Data bytes for B same-pattern degraded stripes through ONE
        batched dispatch (the repair engine's shape: one inverted
        submatrix, ``matmul_many`` over the member stacks)."""
        from noise_ec_tpu.matrix.linalg import reconstruction_matrix

        k, n, fieldname, shard_len, trusted, code = gkey
        rs = self.store.codec(k, n, fieldname, code)
        dt = np.dtype("<u2") if fieldname == "gf65536" else np.dtype(
            np.uint8
        )
        missing_data = [
            i for i in range(k) if i not in trusted
        ]
        basis = sorted(trusted)[:k]
        R = reconstruction_matrix(rs.gf, rs.G, basis, missing_data)
        stacks = [
            np.stack([
                np.frombuffer(shards[i], dtype=np.uint8).view(dt)
                for i in basis
            ])
            for _, _, shards in members
        ]
        filled = rs.matmul_many(R, stacks)
        out = []
        for (_, meta, shards), rows in zip(members, filled):
            data = [
                shards[i] if i in trusted
                else np.ascontiguousarray(
                    rows[missing_data.index(i)]
                ).view(np.uint8).tobytes()
                for i in range(k)
            ]
            out.append(b"".join(data))
        return out

    # ---------------------------------------------------------- re-encode

    def _encode_generation(
        self, address: str, whole: bytes, pol: ConversionPolicy
    ) -> list:
        """Chunk + encode the archival generation; returns the ordered
        stripe keys. Signatures derive from (address, code, capacity,
        index), so a re-run after a crash re-produces the SAME keys and
        ``put_object`` replacement is byte-identical (idempotence)."""
        capacity = pol.capacity
        keys = []
        for idx in range(0, max(1, -(-len(whole) // capacity))):
            chunk = whole[idx * capacity : (idx + 1) * capacity]
            sig = derive_stripe_sig(
                b"noise-ec-convert\0", address, pol.code, capacity, idx
            )
            keys.append(self.store.put_object(
                sig, chunk, pol.k, pol.n,
                field=pol.field, code=pol.code,
            ))
        return keys

    # ----------------------------------------------------------------- gc

    def _finish_gc(self, address: str, doc: dict) -> None:
        finish_prev_stripes_gc(
            self.store, address, doc, repair=self.repair
        )
