"""Background scrubber: walk stripes, verify parity in device batches,
feed the repair queue.

A scrub cycle snapshots the store's keys and, for every stripe:

- flags missing shards (holes and unverified wire absorbs) straight into
  the repair queue with the classified kind;
- for fully-trusted stripes, runs the parity verify BATCHED: same-shape
  stripes (geometry, field, shard length) are stacked along the stripe
  axis into one ``(k, B*S)`` matrix and checked with a single
  generator-submatrix multiply through the codec's device dispatch
  (``ReedSolomon._mul`` → ``ops/dispatch`` on the device backend) — B
  verifies for the price of one kernel launch. Mismatching stripes are
  flagged ``verify_failed`` for the engine's error-correcting restore.

Findings are counted once per state change (a hole re-seen on the next
cycle does not re-count), so the counters measure rot discovered, not
scan frequency. The walk rate is configurable two ways: the interval
between cycles and an optional stripes/second throttle inside a cycle.

Run as a daemon thread (:meth:`start`) or drive :meth:`run_cycle`
directly (tests, bench).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import numpy as np

from noise_ec_tpu.obs.events import event
from noise_ec_tpu.obs.registry import default_registry
from noise_ec_tpu.obs.trace import node_attrs, span
from noise_ec_tpu.store.stripe import StripeStore, UnknownStripeError

__all__ = ["Scrubber"]

log = logging.getLogger("noise_ec_tpu.store")


class Scrubber:
    """Periodic stripe health walk over one :class:`StripeStore`."""

    def __init__(
        self,
        store: StripeStore,
        engine,
        *,
        interval_seconds: float = 30.0,
        verify_batch: int = 32,
        rate_stripes_per_second: float = 0.0,
    ):
        self.store = store
        self.engine = engine
        self.interval_seconds = interval_seconds
        self.verify_batch = max(1, verify_batch)
        self.rate_stripes_per_second = rate_stripes_per_second
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # key -> (missing tuple, verify_ok) at last flag, so repeated
        # cycles do not re-count unrepaired findings.
        self._seen: dict[str, tuple] = {}
        reg = default_registry()
        self._cycles = reg.counter("noise_ec_store_scrub_cycles_total").labels()
        self._scrubbed = reg.counter(
            "noise_ec_store_scrubbed_stripes_total"
        ).labels()
        self._missing = reg.counter(
            "noise_ec_store_missing_shards_total"
        ).labels()
        self._verify_failures = reg.counter(
            "noise_ec_store_verify_failures_total"
        ).labels()

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="noise-ec-scrub", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._closed = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        from noise_ec_tpu.ops.coalesce import qos_lane

        # The scrub thread's verify dispatches ride the device gate's
        # background lane: they yield to live traffic (up to the gate's
        # starvation floor) instead of racing it for slots.
        with qos_lane("background", tenant="scrub"):
            while not self._closed:
                try:
                    self.run_cycle()
                except Exception as exc:  # noqa: BLE001 — keep scrubbing
                    log.error("scrub cycle failed: %s", exc)
                self._wake.wait(self.interval_seconds)
                self._wake.clear()

    # -------------------------------------------------------------- cycle

    def run_cycle(self) -> dict:
        """One full walk; returns {scrubbed, flagged_missing,
        flagged_corrupt} for callers that drive cycles directly."""
        t0 = time.monotonic()
        keys = self.store.keys()
        stats = {"scrubbed": 0, "flagged_missing": 0, "flagged_corrupt": 0}
        # Same-shape fully-trusted stripes batch into one verify dispatch.
        verify_groups: dict[tuple, list[tuple[str, list]]] = {}
        # Scrub traces are usually anonymous (no message key), so the
        # node identity rides as a span attr — after a fleet-wide merge
        # the background work still attributes to the node that did it.
        with span("scrub", stripes=len(keys), **node_attrs()):
            for key in keys:
                try:
                    meta, shards, unverified = self.store.snapshot(key)
                except UnknownStripeError:
                    continue
                stats["scrubbed"] += 1
                missing = tuple(
                    i for i, s in enumerate(shards)
                    if s is None or i in unverified
                )
                if missing:
                    prev = self._seen.get(key)
                    if prev is None or prev[0] != missing:
                        new = missing if prev is None else tuple(
                            i for i in missing if i not in prev[0]
                        )
                        if new:
                            self._missing.add(len(new))
                        stats["flagged_missing"] += 1
                        self._seen[key] = (missing, True)
                    self.engine.enqueue_auto(key)
                else:
                    gkey = (
                        meta.k, meta.n, meta.field, meta.shard_len,
                        meta.code,
                    )
                    verify_groups.setdefault(gkey, []).append((key, shards))
                self._throttle(t0, stats["scrubbed"])
            for gkey, members in verify_groups.items():
                for lo in range(0, len(members), self.verify_batch):
                    self._verify_batch(gkey, members[lo : lo + self.verify_batch],
                                       stats)
        self._cycles.add(1)
        self._scrubbed.add(stats["scrubbed"])
        # Drop tracking for evicted stripes so _seen stays bounded.
        live = set(keys)
        for key in [k for k in self._seen if k not in live]:
            del self._seen[key]
        return stats

    def _throttle(self, t0: float, processed: int) -> None:
        if self.rate_stripes_per_second <= 0:
            return
        budget = processed / self.rate_stripes_per_second
        elapsed = time.monotonic() - t0
        if budget > elapsed:
            time.sleep(min(budget - elapsed, 1.0))

    def _verify_batch(self, gkey: tuple, members: list, stats: dict) -> None:
        """One batched parity check for B same-shape stripes: stack the
        data shards along the stripe axis and run a single (r, k) x
        (k, B*S) multiply on the store codec's backend (the r rows of an
        LRC generator cover its local AND global parities, so one
        multiply verifies both tiers)."""
        k, n, fieldname, shard_len, code = gkey
        rs = self.store.codec(k, n, fieldname, code)
        if rs.r == 0:
            ok = [True] * len(members)
        else:
            dt = np.dtype("<u2") if fieldname == "gf65536" else np.dtype(
                np.uint8
            )
            S = shard_len // dt.itemsize
            D = np.hstack([
                np.stack([
                    np.frombuffer(shards[i], dtype=np.uint8).view(dt)
                    for i in range(k)
                ])
                for _, shards in members
            ])
            want = np.asarray(rs._mul(rs.G[k:], D))
            ok = []
            for b, (_, shards) in enumerate(members):
                have = np.stack([
                    np.frombuffer(shards[i], dtype=np.uint8).view(dt)
                    for i in range(k, n)
                ])
                ok.append(
                    bool(np.array_equal(want[:, b * S : (b + 1) * S], have))
                )
        for good, (key, _) in zip(ok, members):
            if good:
                self._seen.pop(key, None)
                continue
            prev = self._seen.get(key)
            if prev is None or prev[1]:
                self._verify_failures.add(1)
                stats["flagged_corrupt"] += 1
                self._seen[key] = ((), False)
                event("scrub.corrupt", "error", key=key[:16])
            self.engine.enqueue(key, "verify_failed")
