"""HTTP surface of the object service: PUT / GET / range-GET / DELETE /
LIST mounted onto the stats server's route table.

The API lives alongside ``/metrics`` and ``/healthz`` on the same stdlib
``StatsServer`` (obs/server.py) — :meth:`ObjectAPI.mount` registers the
``/objects`` tree through the server's route registration table, no
dispatch chain edits needed. Endpoints (docs/object-service.md):

- ``PUT /objects/<tenant>/<name>`` — streamed upload (body consumed in
  O(stripe) memory). 201 + manifest summary JSON; 413 on quota, 403 on
  an unknown tenant under closed admission, **503 + Retry-After** when
  admission control sheds (SLO degraded / HBM watermark) — the PUT is
  refused before any stripe is encoded.
- ``GET /objects/<tenant>/<name>`` — the object bytes; honors
  ``Range: bytes=a-b`` / ``bytes=a-`` / ``bytes=-n`` with 206 +
  ``Content-Range`` (416 when unsatisfiable). Served through the tiered
  read path (decoded cache → local join → warm peer → degraded decode,
  docs/object-service.md "Read path"); a stripe below k waits on the
  anti-entropy fetch and 503s if peers cannot heal it in time. ``ETag``
  is the object's content address. A request carrying
  ``X-NoiseEC-Route: direct`` (a warm-peer fetch from another node) is
  served from local tiers only — peer routing never recurses. When the
  node is degraded (SLO/HBM), a GET that cannot be served entirely from
  the warm cache sheds **503 + Retry-After** like a PUT.
- ``DELETE /objects/<tenant>/<name>`` — 204; local delete (see
  service/objects.py on replica semantics).
- ``GET /objects/<tenant>`` — cursored LIST
  (``?cursor=<addr>&limit=<n>``) returning ``{"objects": [...],
  "next_cursor": ...}``.
"""

from __future__ import annotations

import json
import re
import sys
from typing import Optional
from urllib.parse import unquote

from noise_ec_tpu.obs.trace import request as trace_request
from noise_ec_tpu.service.objects import (
    ObjectStore,
    ObjectUnavailableError,
    ShedError,
    UnknownObjectError,
)
from noise_ec_tpu.service.tenants import (
    QuotaExceededError,
    UnknownTenantError,
)

__all__ = ["ObjectAPI"]

_RANGE_RE = re.compile(r"^bytes=(\d*)-(\d*)$")


def _json(status: int, doc: dict, headers: Optional[dict] = None) -> tuple:
    return status, "application/json", (
        json.dumps(doc, indent=1).encode() + b"\n"
    ), (headers or {})


class ObjectAPI:
    """Route handlers over one :class:`ObjectStore` (module docstring)."""

    def __init__(self, objects: ObjectStore):
        self.objects = objects

    def mount(self, server) -> None:
        """Register the /objects tree on a :class:`~noise_ec_tpu.obs.
        server.StatsServer` (or anything with the same ``mount``)."""
        server.mount("GET", "/objects", self._get, prefix=True)
        server.mount("PUT", "/objects/", self._put, prefix=True, stream=True)
        server.mount("DELETE", "/objects/", self._delete, prefix=True)

    # ------------------------------------------------------------ helpers

    @staticmethod
    def _segments(path: str) -> list[str]:
        rest = path[len("/objects"):]
        return [unquote(s) for s in rest.split("/") if s]

    # ------------------------------------------------------------- routes

    def _put(self, req: dict) -> tuple:
        seg = self._segments(req["path"])
        if len(seg) != 2:
            return _json(400, {"error": "expected /objects/<tenant>/<name>"})
        tenant, name = seg
        length = req["length"]
        if length <= 0:
            return _json(400, {"error": "missing or empty body "
                                        "(Content-Length required)"})
        rfile = req["rfile"]

        def chunks():
            remaining = length
            while remaining > 0:
                blk = rfile.read(min(1 << 20, remaining))
                if not blk:
                    return
                remaining -= len(blk)
                yield blk

        try:
            # Adopt a propagated trace id (X-NoiseEC-Trace) so a routed
            # PUT joins the originator's request trace; shed/quota
            # refusals raise through the scope and are kept as error
            # traces by the tail sampler.
            with trace_request(
                "put", trace_id=req["headers"].get("X-NoiseEC-Trace"),
                route="http",
            ):
                doc = self.objects.put_stream(
                    tenant, name, chunks(), length
                )
        except ShedError as exc:
            return _json(
                503,
                {"error": str(exc), "shed": exc.reason},
                {"Retry-After": f"{exc.retry_after:g}"},
            )
        except QuotaExceededError as exc:
            return _json(413, {"error": str(exc), "reason": exc.reason})
        except UnknownTenantError:
            return _json(403, {"error": f"unknown tenant {tenant!r}"})
        except ValueError as exc:
            return _json(400, {"error": str(exc)})
        return _json(201, {
            "address": doc["address"],
            "tenant": doc["tenant"],
            "name": doc["name"],
            "size": doc["size"],
            "stripes": len(doc["stripes"]),
            "k": doc["k"],
            "n": doc["n"],
        }, {"ETag": f'"{doc["address"]}"'})

    def _get(self, req: dict) -> tuple:
        seg = self._segments(req["path"])
        if len(seg) == 1:
            return self._list(req, seg[0])
        if len(seg) != 2:
            return _json(400, {"error": "expected /objects/<tenant>[/<name>]"})
        tenant, name = seg
        try:
            doc = self.objects.resolve(tenant, name)
        except UnknownObjectError:
            return _json(404, {"error": f"no object {tenant}/{name}"})
        size = int(doc["size"])
        start, length, ranged = 0, None, False
        range_header = req["headers"].get("Range")
        if range_header:
            parsed = self._parse_range(range_header, size)
            if parsed is None:
                return _json(
                    416, {"error": f"unsatisfiable range {range_header!r}"},
                    {"Content-Range": f"bytes */{size}"},
                )
            start, length, ranged = parsed
        # A warm-peer fetch from another node: serve local tiers only,
        # so peer routing is a single hop by construction.
        direct = req["headers"].get("X-NoiseEC-Route") == "direct"
        # The request scope must outlive this handler frame (the body
        # streams after we return), so it is entered manually here and
        # closed by finish() — on any error return below, or when the
        # streamed body is exhausted/abandoned. A propagated
        # X-NoiseEC-Trace id (a warm-peer routed fetch) is adopted so
        # the serving node's tier spans merge into the originator's
        # trace; the object layer's own scope joins this one.
        attrs = {"route": "http"}
        if req["headers"].get("X-NoiseEC-Hedge"):
            # This serving leg is one arm of a hedged race on the
            # requesting node — stamped so a fleet-wide trace shows
            # which legs were hedges (and which lost).
            attrs["hedge"] = 1
        rscope = trace_request(
            "get", trace_id=req["headers"].get("X-NoiseEC-Trace"),
            **attrs,
        )
        rscope.__enter__()
        done = [False]

        def finish(exc: Optional[BaseException] = None) -> None:
            if not done[0]:
                done[0] = True
                rscope.__exit__(
                    type(exc) if exc is not None else None, exc, None
                )

        try:
            doc, total, chunks = self.objects.get_range(
                tenant, name, start, length, peer_route=not direct
            )
            # Pull the first chunk EAGERLY: stripe-unavailable is by far
            # the likeliest failure and must surface as a status code,
            # not a broken stream after the 200 went out.
            try:
                first = next(chunks)
            except StopIteration:
                first = b""
        except ShedError as exc:
            finish(exc)
            return _json(
                503,
                {"error": str(exc), "shed": exc.reason},
                {"Retry-After": f"{exc.retry_after:g}"},
            )
        except ObjectUnavailableError as exc:
            finish(exc)
            return _json(503, {"error": str(exc)},
                         {"Retry-After": "2"})
        except ValueError as exc:
            finish(exc)
            return _json(416, {"error": str(exc)},
                         {"Content-Range": f"bytes */{size}"})
        except BaseException as exc:
            finish(exc)
            raise

        def body():
            try:
                yield first
                yield from chunks
            finally:
                finish(sys.exc_info()[1])

        headers = {
            "Content-Length": str(total),
            "Accept-Ranges": "bytes",
            "ETag": f'"{doc["address"]}"',
        }
        status = 200
        if ranged:
            status = 206
            headers["Content-Range"] = (
                f"bytes {start}-{start + total - 1}/{size}"
            )
        return status, "application/octet-stream", body(), headers

    def _list(self, req: dict, tenant: str) -> tuple:
        q = req["query"]
        cursor = q.get("cursor", [None])[0]
        try:
            limit = max(1, min(1024, int(q.get("limit", ["64"])[0])))
        except ValueError:
            return _json(400, {"error": "bad limit"})
        entries, next_cursor = self.objects.list_objects(
            tenant, cursor=cursor, limit=limit
        )
        return _json(200, {
            "tenant": tenant,
            "objects": entries,
            "next_cursor": next_cursor,
        })

    def _delete(self, req: dict) -> tuple:
        seg = self._segments(req["path"])
        if len(seg) != 2:
            return _json(400, {"error": "expected /objects/<tenant>/<name>"})
        tenant, name = seg
        try:
            with trace_request(
                "delete",
                trace_id=req["headers"].get("X-NoiseEC-Trace"),
                route="http",
            ):
                self.objects.delete(tenant, name)
        except UnknownObjectError:
            return _json(404, {"error": f"no object {tenant}/{name}"})
        return 204, "text/plain", b""

    @staticmethod
    def _parse_range(
        header: str, size: int
    ) -> Optional[tuple[int, Optional[int], bool]]:
        """``(start, length, True)`` for a satisfiable single range,
        None otherwise. Suffix ranges (``bytes=-n``) serve the last n
        bytes, RFC 9110 §14.1.2."""
        m = _RANGE_RE.match(header.strip())
        if not m:
            return None
        first, last = m.group(1), m.group(2)
        if first:
            start = int(first)
            if start >= size:
                return None
            if last:
                end = int(last)
                if end < start:
                    return None
                return start, min(end, size - 1) - start + 1, True
            return start, None, True
        if not last:
            return None
        suffix = int(last)
        if suffix <= 0:
            return None
        start = max(0, size - suffix)
        return start, None, True
