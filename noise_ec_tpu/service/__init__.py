"""Erasure-coded object service: the user-facing storage surface.

The ROADMAP's "millions of users" promotion of the stripe store (PR 2):
tenant-scoped PUT / GET / range-GET / DELETE / LIST over objects of
arbitrary size, each chunked into signed erasure-coded stripes that
replicate to peers through the existing plugin broadcast path and read
back degraded from any k-of-n shards — with per-tenant quotas and
SLO/HBM admission control shedding PUTs before the device queue feels
them. Three pieces:

- :class:`ObjectStore` (objects.py) — the object layer: chunking,
  manifests, ranged degraded reads, admission;
- :class:`ObjectAPI` (http.py) — the ``/objects`` HTTP tree, mounted on
  the stats server's route table alongside ``/metrics`` + ``/healthz``;
- :class:`TenantRegistry` (tenants.py) — namespaces, quotas, geometry
  and replication targets.

Wiring: ``host/cli.py`` exposes ``-object-port`` / ``-tenants``.
See docs/object-service.md.
"""

from noise_ec_tpu.service.cache import (
    WARMSET_MAGIC,
    DecodedObjectCache,
    PeerCacheDirectory,
)
from noise_ec_tpu.service.http import ObjectAPI
from noise_ec_tpu.service.objects import (
    MANIFEST_MAGIC,
    ObjectStore,
    ObjectUnavailableError,
    ShedError,
    UnknownObjectError,
)
from noise_ec_tpu.service.tenants import (
    QuotaExceededError,
    Tenant,
    TenantRegistry,
    UnknownTenantError,
)

__all__ = [
    "DecodedObjectCache",
    "MANIFEST_MAGIC",
    "ObjectAPI",
    "ObjectStore",
    "PeerCacheDirectory",
    "WARMSET_MAGIC",
    "ObjectUnavailableError",
    "QuotaExceededError",
    "ShedError",
    "Tenant",
    "TenantRegistry",
    "UnknownObjectError",
    "UnknownTenantError",
]
