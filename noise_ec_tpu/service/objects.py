"""The object layer: chunked erasure-coded puts, manifests, and ranged
degraded reads over the stripe store.

This is the promotion of the PR-2 stripe store into a user-facing
storage surface (docs/object-service.md): an *object* of arbitrary size
is chunked into fixed-capacity stripes, each stripe signed and
erasure-encoded **through the existing plugin send path**
(``ShardPlugin.shard_and_broadcast`` with an explicit per-namespace
geometry) — so every stripe is simultaneously

- stored locally as a trusted stripe (the origin copy, ground truth for
  anti-entropy), and
- broadcast to peers as ordinary signed SHARD traffic, which each peer
  verifies end-to-end and lands in its own store (replication rides the
  transport path that already exists, chaos hardening included).

A *manifest* (content address -> ordered stripe keys + geometry + size
+ tenant/name) is itself broadcast as one more signed object with a
magic prefix; every node's store put-listener recognizes the prefix and
indexes it, so any surviving peer can resolve and serve the object —
the origin node is not special. Reads map a byte range onto the minimal
stripe set and stream decoded bytes, reading degraded (any k of n
trusted shards, ``StripeStore.read``); a stripe below k locally is
enqueued for the repair engine's anti-entropy fetch and the read waits
a bounded time for peers to heal it.

Admission control (the ROADMAP backpressure gap): PUTs are refused
*before any stripe is encoded* when

- the tenant's byte/object quota would be breached
  (:class:`~noise_ec_tpu.service.tenants.QuotaExceededError`), or
- the node is degrading — the wired ``SLOEvaluator`` verdict is
  unhealthy, or device HBM in use crosses the watermark fraction of its
  limit (:class:`ShedError`, surfaced as 503 + ``Retry-After`` by
  service/http.py) — shedding at the door instead of queueing work onto
  a device that is already behind.

Trust and consistency model: manifests arrive only through
signature-verified objects, so indexing one is as trusted as any
delivery; the ``address`` field is the uploader's content hash of the
object (recomputed only on full reads by callers that want it —
stripe-level integrity is already anchored per-stripe by the Ed25519
signature each stripe carries). Re-putting a name replaces it
(last-write-wins per node); DELETE is local — replicas converge by
operator policy, not tombstones (v1 scope, documented).

The GET hot path is TIERED (docs/object-service.md "Read path"): each
stripe of a request is served from the cheapest surviving copy —

1. the local decoded-stripe cache (service/cache.py; content-addressed,
   so invalidation is the address change itself),
2. the local k-data-shard join when every data slot is trusted (a
   memcpy, cheaper than any network hop),
3. a warm peer's ``/objects`` endpoint (the peer advertised the address
   in its warm set; a per-peer breaker degrades a dead cache peer to
   the next tier),
4. the local degraded reconstruct / anti-entropy fetch (the pre-cache
   path, unchanged).

Cache misses ride the PR-8 coalescer's single-flight tier
(``submit_shared``): concurrent readers of one cold (address, stripe)
share ONE fetch, so a zipfian stampede costs one dispatch. Admission:
a degraded node (SLO verdict / HBM watermark) serves its warm cache
but SHEDS reads that would enqueue new decode work — the same 503 +
Retry-After contract PUTs already have.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import re
import threading
import time
import weakref
from typing import Iterable, Iterator, Optional

from noise_ec_tpu.obs.device import hbm_snapshot
from noise_ec_tpu.obs.events import event
from noise_ec_tpu.obs.metrics import percentile_from
from noise_ec_tpu.obs.registry import default_registry
from noise_ec_tpu.obs.trace import (
    current_trace_id,
    default_tracer,
    request,
    span,
    trace_key,
)
from noise_ec_tpu.ops.coalesce import coalescer, qos_lane
from noise_ec_tpu.service.cache import (
    WARMSET_MAGIC,
    DecodedObjectCache,
    PeerCacheDirectory,
    parse_warmset,
    warmset_blob,
)
from noise_ec_tpu.service.tenants import (
    QuotaExceededError,
    TenantRegistry,
    UnknownTenantError,
)
from noise_ec_tpu.store.stripe import (
    DegradedReadError,
    StripeStore,
    UnknownStripeError,
)

__all__ = [
    "MANIFEST_MAGIC",
    "ObjectStore",
    "ObjectUnavailableError",
    "ShedError",
    "UnknownObjectError",
]

log = logging.getLogger("noise_ec_tpu.service")

# Wire/stored prefix of a manifest object; the version rides in the
# magic so a future manifest schema can coexist on the same fleet.
MANIFEST_MAGIC = b"noise-ec-manifest/1\n"

OBJECT_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,127}$")

DEFAULT_STRIPE_BYTES = 1 << 20


class UnknownObjectError(KeyError):
    """No manifest for this tenant/name (or address)."""


class ObjectUnavailableError(RuntimeError):
    """A stripe is below k trusted shards locally and the anti-entropy
    fetch did not heal it within the read's wait budget (or the stripe
    is entirely absent from this node)."""


class ShedError(RuntimeError):
    """PUT admission refused by load-shedding (SLO degraded / HBM
    watermark); ``reason`` is the bounded shed-counter label and
    ``retry_after`` the seconds a client should back off."""

    def __init__(self, reason: str, retry_after: float):
        super().__init__(f"put shed: {reason}")
        self.reason = reason
        self.retry_after = retry_after


# GET serving tiers by cost (docs/object-service.md): a request's route
# label is the most expensive tier any of its stripes touched.
_ROUTE_RANK = {"cache": 0, "local": 1, "peer": 2, "gather": 3, "decode": 4}

# Null request scope for hedge workers running outside any trace.
_NULL_SCOPE = contextlib.nullcontext()


class _HedgeCancelled(Exception):
    """Internal: a hedged fetch attempt observed its cancel flag."""


class _ObjectMetrics:
    """Cached registry children for the noise_ec_object_* family."""

    _instances: "weakref.WeakSet[ObjectStore]" = weakref.WeakSet()

    # Distinct tenant label values recorded before collapsing to
    # "other": a tenant sweep must not explode registry cardinality
    # (mirrors the transport's per-peer bound).
    TENANT_LABEL_CAP = 64

    def __init__(self):
        reg = default_registry()
        self._puts = reg.counter("noise_ec_object_puts_total")
        self._put_bytes = reg.counter("noise_ec_object_put_bytes_total")
        self._deletes = reg.counter("noise_ec_object_deletes_total")
        self._gets = reg.counter("noise_ec_object_gets_total")
        self._rejects = reg.counter("noise_ec_object_rejects_total")
        self._sheds = reg.counter("noise_ec_object_shed_total")
        self._tenant_bytes = reg.gauge("noise_ec_object_tenant_bytes")
        self.get_bytes = reg.counter(
            "noise_ec_object_get_bytes_total"
        ).labels()
        self.put_seconds = reg.histogram(
            "noise_ec_object_put_seconds"
        ).labels()
        self.get_seconds = reg.histogram(
            "noise_ec_object_get_seconds"
        ).labels()
        self.routes = {
            route: reg.counter(
                "noise_ec_object_read_route_total"
            ).labels(route=route)
            for route in ("cache", "local", "peer", "gather", "decode")
        }
        self._op_seconds = reg.histogram("noise_ec_object_op_seconds")
        self._tenant_sheds = reg.counter(
            "noise_ec_object_tenant_shed_total"
        )
        self._op_children: dict[tuple[str, str, str], object] = {}
        self._p95_cache: dict[str, tuple[float, Optional[float]]] = {}
        self._tenant_labels: set[str] = set()
        # Hedged-fetch accounting (docs/object-service.md "Read path"):
        # requests that entered the hedged engine, hedge attempts that
        # won, in-flight losers cancelled, and completions that arrived
        # after a winner was already decided (accounted, never leaked).
        self.hedge_requests = reg.counter(
            "noise_ec_hedge_requests_total"
        ).labels()
        self.hedge_wins = reg.counter("noise_ec_hedge_wins_total").labels()
        self.hedge_cancelled = reg.counter(
            "noise_ec_hedge_cancelled_total"
        ).labels()
        self.hedge_late = reg.counter("noise_ec_hedge_late_total").labels()
        # Per-peer fetch latency: the distribution whose clamped p95
        # arms the hedge trigger for that peer.
        self._peer_seconds = reg.histogram("noise_ec_peer_fetch_seconds")
        self._peer_children: dict[str, object] = {}
        self._peer_p95_cache: dict[str, tuple[float, Optional[float]]] = {}
        self._peer_labels: set[str] = set()
        cls = _ObjectMetrics
        # Re-registered on every construction (idempotent — the closure
        # reads the CLASS WeakSet): the test-isolation registry reset
        # drops callback children, and a once-guard would leave the
        # gauge dead for the rest of the process.
        reg.gauge("noise_ec_object_manifests").set_callback(
            lambda: sum(
                store.manifest_count()
                for store in {
                    id(o.store): o.store for o in list(cls._instances)
                }.values()
            )
        )

    def put(self, tenant: str, nbytes: int) -> None:
        self._puts.labels(tenant=tenant).add(1)
        self._put_bytes.labels(tenant=tenant).add(nbytes)

    def delete(self, tenant: str) -> None:
        self._deletes.labels(tenant=tenant).add(1)

    def get(self, result: str) -> None:
        self._gets.labels(result=result).add(1)

    def reject(self, reason: str) -> None:
        self._rejects.labels(reason=reason).add(1)

    def shed(self, reason: str, tenant: Optional[str] = None) -> None:
        self._sheds.labels(reason=reason).add(1)
        if tenant is not None:
            self._tenant_sheds.labels(
                tenant=self._tenant_label(tenant), reason=reason
            ).add(1)
        event("object.shed", "warn", tenant=tenant, reason=reason)

    def tenant_bytes(self, tenant: str, value: int) -> None:
        self._tenant_bytes.labels(tenant=tenant).set(value)

    def _tenant_label(self, tenant: str) -> str:
        """The tenant label value, collapsed to "other" past the
        cardinality cap (first-come keeps its own series)."""
        if tenant in self._tenant_labels:
            return tenant
        if len(self._tenant_labels) >= self.TENANT_LABEL_CAP:
            return "other"
        self._tenant_labels.add(tenant)
        return tenant

    def op_seconds(self, tenant: str, op: str, route: str,
                   seconds: float, exemplar=None) -> None:
        """Observe one op into the per-tenant attribution histogram
        (children cached — this lands once per request, not per
        stripe). ``exemplar`` is the request scope's deferred trace-id
        resolver: /metrics renders the bucket's ``# {trace_id=...}``
        exemplar from it once the tail sampler has kept the trace."""
        key = (self._tenant_label(tenant), op, route)
        child = self._op_children.get(key)
        if child is None:
            child = self._op_children[key] = self._op_seconds.labels(
                tenant=key[0], op=op, route=route
            )
        child.observe(seconds, exemplar=exemplar)

    # Minimum op-histogram observations before the rolling p95 is
    # trusted as a tail-sampling keep signal; below it every clean
    # trace rides the seeded 1-in-N sample alone.
    P95_MIN_COUNT = 32
    # The tail sampler consults the p95 on EVERY request commit, but
    # the merge below walks (and lock-snapshots) every child of the
    # shared family — per-request cost that scales with tenant/node
    # cardinality (a 50-node lab shares one registry). A threshold a
    # quarter-second stale is indistinguishable for sampling, so the
    # sweep runs at most once per TTL per op.
    P95_CACHE_SECONDS = 0.25

    def op_p95(self, op: str) -> Optional[float]:
        """Rolling per-op p95 merged across every child of the
        op-latency family (all tenants and routes, all ObjectStore
        instances — the family is shared through the registry), or None
        while the histogram is too thin. The tail sampler's
        slower-than-p95 keep rule reads this; results are cached for
        ``P95_CACHE_SECONDS`` (a benign data race: losers recompute)."""
        now = time.monotonic()
        hit = self._p95_cache.get(op)
        if hit is not None and now - hit[0] < self.P95_CACHE_SECONDS:
            return hit[1]
        bounds = None
        counts: Optional[list[float]] = None
        total = 0
        for values, child in self._op_seconds.children():
            if values[1] != op:
                continue
            snap = child.snapshot()
            if bounds is None:
                bounds = snap["bounds"]
                counts = [0.0] * len(snap["counts"])
            for i, c in enumerate(snap["counts"]):
                counts[i] += c
            total += snap["count"]
        if counts is None or total < self.P95_MIN_COUNT:
            p95 = None
        else:
            p95 = percentile_from(bounds, counts, 0.95)
        self._p95_cache[op] = (now, p95)
        return p95

    # Minimum completed fetches from one peer before its p95 arms the
    # hedge trigger (below it the engine uses the ceiling — hedge LATE
    # on an unknown peer rather than double every cold-start fetch).
    HEDGE_MIN_COUNT = 8

    def _peer_label(self, endpoint: str) -> str:
        """Peer label value, collapsed past the cardinality cap (same
        bound as tenants — a churning fleet must not grow the family)."""
        if endpoint in self._peer_labels:
            return endpoint
        if len(self._peer_labels) >= self.TENANT_LABEL_CAP:
            return "other"
        self._peer_labels.add(endpoint)
        return endpoint

    def peer_fetch_seconds(self, endpoint: str, seconds: float) -> None:
        """Observe one COMPLETED fetch from ``endpoint`` (errors and
        cancellations stay out — they would poison the p95 trigger)."""
        label = self._peer_label(endpoint)
        child = self._peer_children.get(label)
        if child is None:
            child = self._peer_children[label] = self._peer_seconds.labels(
                peer=label
            )
        child.observe(seconds)

    def peer_p95(self, endpoint: str) -> Optional[float]:
        """Rolling p95 of completed fetches from ``endpoint``, or None
        while that peer's distribution is thinner than
        ``HEDGE_MIN_COUNT`` (TTL-cached like :meth:`op_p95`)."""
        label = self._peer_label(endpoint)
        now = time.monotonic()
        hit = self._peer_p95_cache.get(label)
        if hit is not None and now - hit[0] < self.P95_CACHE_SECONDS:
            return hit[1]
        child = self._peer_children.get(label)
        p95 = None
        if child is not None:
            snap = child.snapshot()
            if snap["count"] >= self.HEDGE_MIN_COUNT:
                p95 = percentile_from(
                    snap["bounds"], snap["counts"], 0.95
                )
        self._peer_p95_cache[label] = (now, p95)
        return p95


class ObjectStore:
    """Tenant-scoped object API over one :class:`StripeStore` (module
    docstring). The plugin must be wired to the SAME store — verified
    receives (replicated stripes and manifests) land there, and the
    put-listener absorb hook is how this layer learns about them."""

    def __init__(
        self,
        store: StripeStore,
        plugin,
        network,
        *,
        tenants: Optional[TenantRegistry] = None,
        engine=None,
        slo=None,
        stripe_bytes: int = DEFAULT_STRIPE_BYTES,
        k: int = 4,
        n: int = 6,
        hbm_watermark: float = 0.90,
        fetch_timeout_seconds: float = 8.0,
        retry_after_seconds: float = 2.0,
        max_object_bytes: int = 1 << 30,
        cache: Optional[DecodedObjectCache] = None,
        peer_timeout_seconds: float = 2.0,
        hedge_enabled: bool = True,
        hedge_floor_seconds: float = 0.02,
        hedge_ceiling_seconds: float = 1.0,
    ):
        if plugin.store is not store:
            raise ValueError(
                "plugin.store must be the same StripeStore (verified "
                "receives and manifests land there)"
            )
        if not 1 <= k <= n:
            raise ValueError(f"invalid default geometry k={k} n={n}")
        if stripe_bytes < k:
            raise ValueError(f"stripe_bytes {stripe_bytes} below k={k}")
        if not 0 < hedge_floor_seconds <= hedge_ceiling_seconds:
            raise ValueError(
                "hedge clamp must satisfy 0 < floor <= ceiling, got "
                f"{hedge_floor_seconds} / {hedge_ceiling_seconds}"
            )
        self.store = store
        self.plugin = plugin
        self.network = network
        self.tenants = tenants if tenants is not None else TenantRegistry()
        self.engine = engine
        self.slo = slo
        self.stripe_bytes = stripe_bytes
        self.default_k = k
        self.default_n = n
        self.hbm_watermark = hbm_watermark
        self.fetch_timeout_seconds = fetch_timeout_seconds
        self.retry_after_seconds = retry_after_seconds
        self.max_object_bytes = max_object_bytes
        self._lock = threading.Lock()
        self._index: dict[tuple[str, str], str] = {}  # (tenant, name) -> addr
        self._usage: dict[str, list] = {}  # tenant -> [bytes, objects]
        self._known: set[str] = set()  # addresses counted into usage
        # Tiered read path (module docstring): decoded-stripe cache,
        # warm-peer directory, and the advert bookkeeping (one stored
        # advert stripe per peer endpoint — the newest replaces the
        # previous so adverts never accumulate in the store).
        self.cache = cache
        self.peer_timeout_seconds = peer_timeout_seconds
        # Hedged peer fetches (module docstring): with >= 2 allowed warm
        # sources, a straggling primary is raced by the next-ranked peer
        # after that primary's clamped p95, and the loser is CANCELLED.
        self.hedge_enabled = hedge_enabled
        self.hedge_floor_seconds = hedge_floor_seconds
        self.hedge_ceiling_seconds = hedge_ceiling_seconds
        self.directory = PeerCacheDirectory()
        self.advertise_url: Optional[str] = None
        self._advert_stripes: dict[str, str] = {}
        # In-flight GET count: the warm-set advert's load hint, so warm
        # peers route cold-stripe fetches to the LEAST-LOADED holder
        # (docs/object-service.md "Read path").
        self._live_reads = 0
        # PUT write-through stays bounded: objects bigger than this do
        # not pin their whole stripe set into the cache at once.
        self._write_through_cap = (
            cache.max_bytes // 4 if cache is not None else 0
        )
        self._metrics = _ObjectMetrics()
        _ObjectMetrics._instances.add(self)
        # The tail sampler's slower-than-p95 keep rule feeds from the
        # op-latency histograms this layer already records (op_p95
        # reads the SHARED registry family, so any instance's provider
        # sees every instance's observations).
        default_tracer().set_p95_provider(self._metrics.op_p95)
        store.add_put_listener(self._on_store_put)
        store.add_delete_listener(self._on_store_evict)
        self._reindex()

    # --------------------------------------------------------- admission

    def _qos(self, tenant_name: str):
        """The tenant's QoS-lane context for one request: every device
        dispatch and coalesced batch under it queues at the gate in the
        tenant's lane at the tenant's weight (ops/coalesce.py,
        docs/object-service.md "QoS lanes"). Policy problems degrade to
        the live/1 default — QoS must never refuse a request."""
        lane, weight = "live", 1
        try:
            tenant = self.tenants.get(tenant_name)
            lane, weight = tenant.lane, tenant.weight
        # noise-ec: allow(event-on-swallow) — unknown tenant raises from the op body later; pre-count only
        except Exception:  # noqa: BLE001 — unknown tenant raises later
            pass
        return qos_lane(lane, tenant=tenant_name, weight=weight)

    def shed_reason(self) -> Optional[str]:
        """The load-shed signal for PUT admission: ``"slo"`` while the
        wired evaluator's verdict is degraded, ``"hbm"`` when device
        memory in use crosses the watermark fraction of the reported
        limit, ``None`` to admit. Cheap enough per request (one verdict
        sort over a bounded window + one allocator stat read)."""
        if self.slo is not None and not self.slo.verdict()["healthy"]:
            return "slo"
        try:
            hbm = hbm_snapshot()
        # noise-ec: allow(event-on-swallow) — telemetry fast-path — the PUT itself proceeds and raises on real faults
        except Exception:  # noqa: BLE001 — telemetry must not refuse PUTs
            return None
        limit = hbm.get("limit_bytes") or 0
        used = hbm.get("bytes_in_use", hbm.get("live_bytes", 0))
        if limit and used >= self.hbm_watermark * limit:
            return "hbm"
        return None

    def usage(self, tenant: str) -> dict:
        with self._lock:
            used = self._usage.get(tenant, [0, 0])
            return {"bytes": used[0], "objects": used[1]}

    # ----------------------------------------------------- cache routing

    def enable_peer_routing(self, url: str) -> None:
        """Advertise this node's warm addresses and accept warm-peer
        routing. ``url`` is the HTTP endpoint serving this node's
        ``/objects`` tree (the StatsServer the API is mounted on); the
        warm-set advert piggybacks on the repair engine's announce loop
        (``RepairEngine.add_announce_hook``)."""
        self.advertise_url = url.rstrip("/")
        if self.engine is not None and self.cache is not None:
            self.engine.add_announce_hook(self._announce_warm)

    def _announce_warm(self) -> None:
        """Broadcast one warm-set advert (the announce-loop piggyback).
        Rides the ordinary signed-object path, so every peer's store
        put-listener absorbs it exactly like a manifest."""
        if self.cache is None or self.advertise_url is None:
            return
        addresses = self.cache.addresses(limit=256)
        if not addresses:
            return
        with self._lock:
            load = self._live_reads
        blob = warmset_blob(self.advertise_url, addresses, load=load)
        k, n = self.default_k, self.default_n
        blob += b"\n" * ((-len(blob)) % k)
        self.plugin.shard_and_broadcast(self.network, blob, geometry=(k, n))

    def _absorb_warmset(self, key: str, data: bytes) -> None:
        doc = parse_warmset(data)
        if doc is None:
            log.warning("ignoring malformed warm-set advert in stripe %s",
                        key)
            return
        endpoint = doc["endpoint"].rstrip("/")
        prev = self._advert_stripes.get(endpoint)
        self._advert_stripes[endpoint] = key
        if prev is not None and prev != key:
            # One stored advert stripe per peer: adverts refresh every
            # announce interval and would otherwise accumulate forever.
            self.store.evict(prev)
        if endpoint != self.advertise_url:
            self.directory.observe(
                endpoint, doc["addresses"], load=doc.get("load", 0.0)
            )

    def _on_store_evict(self, key: str) -> None:
        """Store delete listener: a stripe evicted out from under an
        address must not keep serving from RAM."""
        if self.cache is not None:
            self.cache.evict_stripe(key)

    # -------------------------------------------------------------- puts

    def put(self, tenant: str, name: str, data: bytes) -> dict:
        """Store one in-memory object; see :meth:`put_stream`."""
        return self.put_stream(tenant, name, iter((data,)), len(data))

    def put_stream(
        self, tenant_name: str, name: str,
        chunks: Iterable[bytes], size: int,
    ) -> dict:
        """Admit, chunk, encode, broadcast and manifest one object of
        ``size`` bytes arriving as a chunk iterator (memory stays
        O(stripe)); returns the manifest document. Admission (quota,
        then shed) runs BEFORE the first chunk is consumed, so a refused
        PUT costs no encode and queues nothing toward the device.

        The whole PUT runs inside a request-scoped trace (joining the
        HTTP layer's when one is active): quota/shed refusals raise
        through the scope and are kept as error traces; each stripe's
        encode+delivery is a ``stripe_put`` child span."""
        with request("put", tenant=tenant_name) as rscope:
            with self._qos(tenant_name):
                return self._put_stream(
                    rscope, tenant_name, name, chunks, size
                )

    def _put_stream(
        self, rscope, tenant_name: str, name: str,
        chunks: Iterable[bytes], size: int,
    ) -> dict:
        t0 = time.monotonic()
        try:
            tenant = self.tenants.get(tenant_name)
        except UnknownTenantError:
            self._metrics.reject("unknown_tenant")
            raise
        if not OBJECT_NAME_RE.match(name):
            raise ValueError(f"bad object name {name!r}")
        if size <= 0:
            raise ValueError("cannot store an empty object")
        if size > self.max_object_bytes:
            raise ValueError(
                f"object of {size} bytes exceeds the "
                f"{self.max_object_bytes}-byte cap"
            )
        with self._lock:
            used_bytes, used_objects = self._usage.get(tenant.name, [0, 0])
        try:
            self.tenants.admit(tenant, used_bytes, used_objects, size)
        except QuotaExceededError as exc:
            self._metrics.reject(exc.reason)
            raise
        reason = self.shed_reason()
        if reason is not None:
            self._metrics.shed(reason, tenant.name)
            raise ShedError(reason, self.retry_after_seconds)

        k = tenant.k or self.default_k
        n = tenant.n or self.default_n
        capacity = max(k, self.stripe_bytes - self.stripe_bytes % k)
        # The address hashes (tenant, name, content) — not content alone:
        # identical bytes under two names must be two objects (their
        # manifests live and die independently) even though their
        # STRIPES still dedup to the same keys (the stripe key is the
        # signature prefix of identical payloads).
        digest = hashlib.blake2b(digest_size=16)
        digest.update(
            tenant.name.encode() + b"\0" + name.encode() + b"\0"
        )
        stripe_keys: list[str] = []
        # Write-through warmth: the PUT just produced decoded-equivalent
        # bytes, so small-enough objects land in the cache on the way in
        # (the address is only known once the whole body hashed, so the
        # logical stripe payloads are held until then — bounded by the
        # write-through cap, O(stripe) memory otherwise).
        warm: Optional[list[tuple[str, bytes]]] = (
            [] if self.cache is not None
            and size <= self._write_through_cap else None
        )
        buf = bytearray()
        total = 0

        def flush(payload: bytes) -> None:
            pad = (-len(payload)) % k
            # Data stripes opt into ring-targeted placement
            # (docs/placement.md: one cohort per owner instead of a
            # full broadcast); the MANIFEST below stays broadcast so
            # every node can index the object.
            with span("stripe_put", stripe=len(stripe_keys)) as sp:
                shards = self.plugin.shard_and_broadcast(
                    self.network, payload + bytes(pad), geometry=(k, n),
                    targeted=True,
                )
                stripe_keys.append(trace_key(shards[0].file_signature))
                sp.set_attr(key=stripe_keys[-1], bytes=len(payload))
            if warm is not None:
                warm.append((stripe_keys[-1], payload))

        for chunk in chunks:
            if not chunk:
                continue
            digest.update(chunk)
            total += len(chunk)
            if total > size:
                raise ValueError(
                    f"body exceeds the declared size of {size} bytes"
                )
            buf += chunk
            while len(buf) >= capacity:
                flush(bytes(buf[:capacity]))
                del buf[:capacity]
        if total != size:
            raise ValueError(
                f"body ended at {total} of the declared {size} bytes"
            )
        if buf:
            flush(bytes(buf))

        doc = {
            "version": 1,
            "address": digest.hexdigest(),
            "tenant": tenant.name,
            "name": name,
            "size": size,
            "stripe_bytes": capacity,
            "k": k,
            "n": n,
            "field": "gf256",
            "stripes": stripe_keys,
            "created": time.time(),
        }
        blob = MANIFEST_MAGIC + json.dumps(doc).encode()
        blob += b"\n" * ((-len(blob)) % k)
        # The broadcast lands the manifest in the local store too, where
        # the put listener (_on_store_put) indexes it — the exact code
        # path every replica runs, so origin and peers converge through
        # one absorb implementation.
        with span("stripe_put", kind="manifest", bytes=len(blob)):
            self.plugin.shard_and_broadcast(
                self.network, blob, geometry=(k, n)
            )
        if warm is not None:
            # After the manifest broadcast: an overwrite-PUT's manifest
            # absorb just evicted the REPLACED address, so the new
            # entries can never be invalidated by their own put.
            for idx, (skey, payload) in enumerate(warm):
                self.cache.put(doc["address"], idx, payload,
                               stripe_key=skey)
        if tenant.replicas > 1 and self.engine is not None:
            with self._lock:
                manifest_stripe = self._manifest_stripe_locked(doc["address"])
            pinned = list(stripe_keys)
            if manifest_stripe:
                pinned.append(manifest_stripe)
            self.engine.pin_announce(pinned)
        self._metrics.put(tenant.name, size)
        elapsed = time.monotonic() - t0
        self._metrics.put_seconds.observe(elapsed)
        self._metrics.op_seconds(
            tenant.name, "put", "encode", elapsed,
            exemplar=rscope.exemplar,
        )
        return self.store.get_manifest(doc["address"]) or doc

    def _manifest_stripe_locked(self, address: str) -> Optional[str]:
        doc = self.store.get_manifest(address)
        return doc.get("manifest_stripe") if doc else None

    # ----------------------------------------------------------- absorb

    def _on_store_put(self, key: str, data: bytes, meta) -> None:
        """Store put listener: recognize manifest objects (local puts
        AND signature-verified replicas arriving through the plugin) and
        index them; recognize warm-set adverts and feed the peer-cache
        directory. Never raises (the store logs and continues)."""
        if data.startswith(WARMSET_MAGIC):
            self._absorb_warmset(key, data)
            return
        if not data.startswith(MANIFEST_MAGIC):
            return
        try:
            doc = json.loads(data[len(MANIFEST_MAGIC):].decode())
            self._validate_manifest(doc)
        except (ValueError, KeyError, UnicodeDecodeError) as exc:
            log.warning("ignoring malformed manifest in stripe %s: %s",
                        key, exc)
            return
        doc["manifest_stripe"] = key
        self.store.put_manifest(doc["address"], doc)
        self._register(doc)

    @staticmethod
    def _validate_manifest(doc: dict) -> None:
        if not isinstance(doc, dict) or doc.get("version") != 1:
            raise ValueError("unsupported manifest version")
        if not re.match(r"^[0-9a-f]{8,128}$", str(doc.get("address", ""))):
            raise ValueError("bad manifest address")
        stripes = doc.get("stripes")
        if (
            not isinstance(stripes, list) or not stripes
            or not all(isinstance(s, str) for s in stripes)
        ):
            raise ValueError("bad manifest stripe list")
        size = doc.get("size")
        capacity = doc.get("stripe_bytes")
        k, n = doc.get("k"), doc.get("n")
        if not (isinstance(size, int) and size > 0):
            raise ValueError("bad manifest size")
        if not (isinstance(capacity, int) and capacity > 0):
            raise ValueError("bad manifest stripe_bytes")
        if len(stripes) != -(-size // capacity):
            raise ValueError("manifest stripe count disagrees with size")
        if not (isinstance(k, int) and isinstance(n, int) and 1 <= k <= n):
            raise ValueError("bad manifest geometry")
        if not isinstance(doc.get("tenant"), str) or not isinstance(
            doc.get("name"), str
        ):
            raise ValueError("bad manifest tenant/name")

    def _register(self, doc: dict) -> None:
        """Index one (validated) manifest; idempotent. A name re-pointed
        at a new address releases the old object locally (last-write-
        wins per node)."""
        addr = doc["address"]
        tenant, name = doc["tenant"], doc["name"]
        replaced: Optional[str] = None
        with self._lock:
            prev = self._index.get((tenant, name))
            if prev == addr and addr in self._known:
                return
            self._index[(tenant, name)] = addr
            if addr not in self._known:
                self._known.add(addr)
                used = self._usage.setdefault(tenant, [0, 0])
                used[0] += int(doc["size"])
                used[1] += 1
                tenant_bytes = used[0]
            else:
                tenant_bytes = self._usage.get(tenant, [0, 0])[0]
            if prev is not None and prev != addr:
                replaced = prev
        self._metrics.tenant_bytes(tenant, tenant_bytes)
        if replaced is not None:
            self._drop_address(replaced)

    def _reindex(self) -> None:
        cursor = None
        while True:
            page, cursor = self.store.list_manifests(cursor=cursor, limit=256)
            for _, doc in page:
                try:
                    self._validate_manifest(doc)
                except (ValueError, KeyError):
                    continue
                self._register(doc)
            if cursor is None:
                break

    # -------------------------------------------------------------- reads

    def resolve(self, tenant: str, name: str) -> dict:
        with self._lock:
            addr = self._index.get((tenant, name))
        if addr is None:
            raise UnknownObjectError(f"{tenant}/{name}")
        doc = self.store.get_manifest(addr)
        if doc is None:
            raise UnknownObjectError(f"{tenant}/{name}")
        return doc

    def get_range(
        self, tenant: str, name: str,
        start: int = 0, length: Optional[int] = None,
        *, shed: bool = True, peer_route: bool = True,
    ) -> tuple[dict, int, Iterator[bytes]]:
        """Resolve and stream one byte range: ``(manifest, range_length,
        chunk iterator)``. The range maps onto the minimal stripe set
        and each stripe is served from the cheapest surviving copy —
        decoded cache, local join, warm peer, degraded decode (module
        docstring; misses are single-flighted so concurrent readers of
        one cold stripe share a fetch). ``shed=False`` bypasses read
        admission (internal verification reads); ``peer_route=False``
        pins the read to local tiers (a peer serving a direct fetch
        must not hop again). The metrics for the read land when the
        iterator is exhausted."""
        try:
            doc = self.resolve(tenant, name)
        except UnknownObjectError:
            # Resolve-time misses raise before the streaming scope below
            # exists; replay through a short request scope so the tail
            # sampler keeps the trace (errors are always kept) — without
            # it the most common GET error class would be invisible.
            with request("get", tenant=tenant, name=name):
                raise
        address = doc["address"]
        size = int(doc["size"])
        capacity = int(doc["stripe_bytes"])
        if start < 0 or start > size:
            raise ValueError(f"range start {start} outside [0, {size}]")
        end = size if length is None else min(size, start + max(0, length))
        total = max(0, end - start)
        i0, i1 = start // capacity, -(-end // capacity)
        # Read admission (the PUT shed contract extended to reads): a
        # degraded node still serves its warm cache — those reads cost
        # RAM only — but refuses to enqueue NEW decode work. The cache
        # coverage check runs first so the hot path never pays the
        # verdict/HBM probe.
        if shed and not self._fully_cached(address, i0, i1):
            reason = self.shed_reason()
            if reason is not None:
                # Shed traces are always kept by the tail sampler: the
                # refusal raises through its own (short) request scope
                # when no outer one is active.
                with request("get", tenant=tenant, name=name):
                    self._metrics.shed(reason, tenant)
                    raise ShedError(reason, self.retry_after_seconds)
        # Per-request read state: served/cached stripe counts for the
        # result label, shared/degraded flags, the most expensive
        # serving tier touched (the per-tenant attribution route label),
        # and the lazily taken one-lock store snapshot of the request's
        # stripe set.
        state: dict = {
            "served": 0, "cached": 0, "degraded": False, "shared": False,
            "route": "cache", "snaps": None,
        }

        def chunks() -> Iterator[bytes]:
            # The request scope opens at first iteration (a built-but-
            # never-consumed iterator must not leak a held trace) and
            # closes when the stream ends — error, shed and abandonment
            # all propagate through it, so the tail sampler sees them.
            with request("get", tenant=tenant, name=name) as rscope, \
                    self._qos(tenant):
                t0 = time.monotonic()
                sent = 0
                result = "ok"
                with self._lock:
                    self._live_reads += 1
                try:
                    for i in range(i0, i1):
                        blob = self._read_stripe_tiered(
                            doc, i, i1, state, peer_route
                        )
                        logical = min(capacity, size - i * capacity)
                        lo = max(0, start - i * capacity)
                        hi = min(logical, end - i * capacity)
                        if lo == 0 and hi == logical == len(blob):
                            piece = blob  # whole-stripe serve: no copy
                        else:
                            piece = bytes(
                                memoryview(blob)[:logical][lo:hi]
                            )
                        sent += len(piece)
                        yield piece
                    if state["shared"]:
                        # The request rode another request's in-flight
                        # fetch; any degraded work was the leader's
                        # (which records it on its own request).
                        result = "coalesced"
                    elif state["degraded"]:
                        result = "degraded"
                    elif (
                        state["served"]
                        and state["cached"] == state["served"]
                    ):
                        result = "hit"
                except ObjectUnavailableError:
                    result = "unavailable"
                    raise
                except Exception:
                    result = "error"
                    raise
                finally:
                    with self._lock:
                        self._live_reads -= 1
                    self._metrics.get(result)
                    self._metrics.get_bytes.add(sent)
                    elapsed = time.monotonic() - t0
                    self._metrics.get_seconds.observe(elapsed)
                    self._metrics.op_seconds(
                        tenant, "get", state["route"], elapsed,
                        exemplar=rscope.exemplar,
                    )

        return doc, total, chunks()

    def read(
        self, tenant: str, name: str,
        *, shed: bool = True, peer_route: bool = True,
    ) -> bytes:
        """Whole-object convenience read (tests, small objects)."""
        _, _, chunks = self.get_range(
            tenant, name, shed=shed, peer_route=peer_route
        )
        return b"".join(chunks)

    def _fully_cached(self, address: str, i0: int, i1: int) -> bool:
        if self.cache is None:
            return False
        return all(self.cache.contains(address, i) for i in range(i0, i1))

    def _cache_store(
        self, address: str, i: int, blob: bytes, stripe_key: str
    ) -> None:
        if self.cache is not None:
            self.cache.put(address, i, blob, stripe_key=stripe_key)

    def _read_stripe_tiered(
        self, doc: dict, i: int, i1: int, state: dict, peer_route: bool
    ) -> bytes:
        """One stripe's logical payload through the tier order. The miss
        path rides the coalescer's single-flight tier keyed by
        (address, stripe index): a concurrent stampede on a cold stripe
        runs ONE fetch and broadcasts the bytes."""
        address = doc["address"]
        state["served"] += 1
        blob = (
            self.cache.get(address, i) if self.cache is not None else None
        )
        if blob is not None:
            state["cached"] += 1
            self._metrics.routes["cache"].add(1)
            return blob

        def fetch() -> tuple[bytes, str, bool, Optional[str]]:
            # The leader's trace id rides the flight result so a
            # coalesced follower can record a ``joined`` span pointing
            # at the trace that did the actual work.
            if self.cache is not None:
                hit = self.cache.peek(address, i)
                if hit is not None:
                    # Landed by another flight between this request's
                    # miss and its flight turn.
                    self._metrics.routes["cache"].add(1)
                    return hit, "cache", False, current_trace_id()
            blob, route, degraded = self._fetch_stripe(
                doc, i, i1, state, peer_route
            )
            return blob, route, degraded, current_trace_id()

        (blob, route, degraded, leader), shared = (
            coalescer().submit_shared(("objget", address, i), fetch)
        )
        if shared:
            with span("joined", stripe=i) as sp:
                if leader is not None:
                    sp.set_attr(leader=leader)
        if route == "cache":
            state["cached"] += 1
        if _ROUTE_RANK.get(route, 3) > _ROUTE_RANK[state["route"]]:
            state["route"] = route
        if shared:
            state["shared"] = True
        if degraded:
            state["degraded"] = True
        return blob

    def _fetch_stripe(
        self, doc: dict, i: int, i1: int, state: dict, peer_route: bool
    ) -> tuple[bytes, str, bool]:
        """The single-flight leader's miss path: local join ("local"
        route) when every data slot is trusted (a memcpy — the cheapest
        surviving copy after RAM), then a warm peer, then the degraded
        decode / anti-entropy tier. Returns ``(logical bytes, route,
        degraded)`` and write-through-populates the cache on every
        success."""
        address = doc["address"]
        key = doc["stripes"][i]
        size = int(doc["size"])
        capacity = int(doc["stripe_bytes"])
        logical = min(capacity, size - i * capacity)
        # ONE store-lock acquisition snapshots the request's remaining
        # stripe set (the per-stripe lock fix): the join fast path and
        # the degraded classification both work from it.
        with span("local_join", stripe=i) as lj:
            if state["snaps"] is None:
                state["snaps"] = self.store.snapshot_many(
                    doc["stripes"][i:i1]
                )
            snap = state["snaps"].get(key)
            if snap is not None:
                meta, shards, unverified = snap
                if all(
                    shards[j] is not None and j not in unverified
                    for j in range(meta.k)
                ):
                    blob = b"".join(
                        shards[: meta.k]
                    )[: meta.object_len][:logical]
                    self._cache_store(address, i, blob, key)
                    self._metrics.routes["local"].add(1)
                    lj.set_attr(outcome="hit", bytes=len(blob))
                    return blob, "local", False
            lj.set_attr(outcome="miss")
        if peer_route:
            blob = self._peer_fetch(doc, i, logical)
            if blob is not None:
                self._cache_store(address, i, blob, key)
                self._metrics.routes["peer"].add(1)
                return blob, "peer", False
        placement = getattr(self.plugin, "placement", None)
        if placement is not None:
            # Targeted placement scattered this stripe across its ring
            # owners, so no single node may hold k shards: gather them
            # (docs/placement.md). A refused or short gather falls
            # through to the decode/anti-entropy tier unchanged.
            padded = placement.gather(
                self.store, self.network, key,
                k=int(doc["k"]), n=int(doc["n"]),
                field=str(doc.get("field", "gf256")),
                code=str(doc.get("code", "rs")),
            )
            if padded is not None:
                blob = bytes(memoryview(padded)[:logical])
                self._cache_store(address, i, blob, key)
                self._metrics.routes["gather"].add(1)
                return blob, "gather", False
        with span("stripe_decode", stripe=i, stripe_key=key) as sd:
            padded, degraded = self._read_stripe(key)
            sd.set_attr(degraded=degraded, bytes=logical)
        blob = (
            padded if len(padded) == logical
            else bytes(memoryview(padded)[:logical])
        )
        self._cache_store(address, i, blob, key)
        self._metrics.routes["decode"].add(1)
        return blob, "decode", degraded

    def _peer_fetch(
        self, doc: dict, i: int, logical: int
    ) -> Optional[bytes]:
        """Fetch one stripe's logical bytes from the warm peers
        advertising the address (directory order: best-ranked first —
        freshest advert, lowest load hint), behind their breakers.
        Returns the bytes or None when no peer could serve.

        With hedging enabled and >= 2 allowed sources the HEDGED engine
        runs (``_peer_fetch_hedged``): the primary is raced by the next
        ranked peer once it straggles past its own clamped p95, the
        first complete response wins, and the losers are cancelled —
        their sockets closed and their threads unwound promptly, with
        every outcome accounted in the noise_ec_hedge_* counters.
        Otherwise the classic sequential ladder runs. Both paths keep
        the ETag contract: the peer must serve the SAME content address,
        so an overwrite landing mid-read can never mix versions."""
        address = doc["address"]
        peers = [
            endpoint
            for endpoint in self.directory.peers_for(address)
            if endpoint != self.advertise_url
            and self.directory.breaker(endpoint).allow()
        ]
        if not peers:
            return None
        if self.hedge_enabled and len(peers) >= 2:
            return self._peer_fetch_hedged(doc, i, logical, peers)
        return self._peer_fetch_serial(doc, i, logical, peers)

    def _peer_request(self, doc: dict, i: int, logical: int, hedged: bool):
        """Build the (urllib Request, address) pair for one stripe
        fetch attempt — shared by the serial and hedged paths."""
        from urllib.parse import quote
        from urllib.request import Request

        capacity = int(doc["stripe_bytes"])
        lo = i * capacity
        path = (
            f"/objects/{quote(doc['tenant'], safe='')}"
            f"/{quote(doc['name'], safe='')}"
        )
        headers = {
            "Range": f"bytes={lo}-{lo + logical - 1}",
            # One hop only: the serving peer reads local tiers.
            "X-NoiseEC-Route": "direct",
        }
        if hedged:
            # The serving peer stamps hedge=1 on its request scope, so
            # fleet-wide traces show which serving legs were races.
            headers["X-NoiseEC-Hedge"] = "1"
        trace_id = current_trace_id()
        if trace_id is not None:
            # Trace context propagation: the serving peer's request
            # scope adopts this id, so the collector merges its
            # local-tier spans into THIS request's fleet-wide trace.
            headers["X-NoiseEC-Trace"] = trace_id
        return lambda endpoint: Request(endpoint + path, headers=headers)

    def _peer_fetch_serial(
        self, doc: dict, i: int, logical: int, peers: list
    ) -> Optional[bytes]:
        """The pre-hedge sequential ladder (hedging disabled, or only
        one allowed source): try each peer in rank order."""
        from urllib.request import urlopen

        address = doc["address"]
        make_req = self._peer_request(doc, i, logical, hedged=False)
        for endpoint in peers:
            breaker = self.directory.breaker(endpoint)
            # One span per peer attempt — outcome + bytes per endpoint
            # is what makes a straggling or dead warm peer visible in
            # the trace's critical path.
            with span("peer_fetch", peer=endpoint, stripe=i) as sp:
                t0 = time.monotonic()
                try:
                    with urlopen(
                        make_req(endpoint),
                        timeout=self.peer_timeout_seconds,
                    ) as resp:
                        etag = (resp.headers.get("ETag") or "").strip('"')
                        if etag != address:
                            raise ValueError(
                                f"peer serves address {etag!r}, "
                                f"wanted {address!r}"
                            )
                        blob = resp.read(logical + 1)
                    if len(blob) != logical:
                        raise ValueError(
                            f"peer served {len(blob)} bytes, "
                            f"wanted {logical}"
                        )
                except Exception as exc:  # noqa: BLE001 — a dead cache
                    # peer degrades to the decode tier, never breaks
                    # the read
                    breaker.record_failure()
                    sp.set_attr(outcome="error", bytes=0)
                    log.debug("warm-peer fetch from %s failed: %s",
                              endpoint, exc)
                    continue
                breaker.record_success()
                self._metrics.peer_fetch_seconds(
                    endpoint, time.monotonic() - t0
                )
                sp.set_attr(outcome="ok", bytes=len(blob))
                return blob
        return None

    def _hedge_delay(self, endpoint: str) -> float:
        """How long the engine lets ``endpoint`` run before launching
        the next ranked source against it: that peer's rolling fetch
        p95, clamped to [floor, ceiling]; an unknown peer (distribution
        below HEDGE_MIN_COUNT) gets the ceiling — hedge late rather
        than double every fetch during warm-up."""
        p95 = self._metrics.peer_p95(endpoint)
        if p95 is None:
            return self.hedge_ceiling_seconds
        return min(
            self.hedge_ceiling_seconds,
            max(self.hedge_floor_seconds, p95),
        )

    def _peer_fetch_hedged(
        self, doc: dict, i: int, logical: int, peers: list
    ) -> Optional[bytes]:
        """The hedged fetch engine (see :meth:`_peer_fetch`). One
        coordinator thread (this one) launches ranked attempts and
        arbitrates; each attempt runs in its own short-lived thread.
        Decisions live under one condition variable:

        - the FIRST complete verified response is the winner; every
          other in-flight attempt is cancelled (its response socket
          closed out from under its read + a cancel flag it polls
          between chunks, so it unwinds within one chunk);
        - an attempt completing after the decision counts as LATE (its
          bytes are dropped but its breaker/latency accounting still
          lands — late responses are accounted, never leaked);
        - if every launched attempt fails fast, the next ranked source
          launches immediately (the sequential ladder's behavior);
        - the whole tier gives up at ``peer_timeout_seconds`` overall,
          cancelling whatever is still in flight, and returns None so
          the read degrades to the gather/decode tiers."""
        from urllib.request import urlopen

        address = doc["address"]
        make_req = self._peer_request(doc, i, logical, hedged=True)
        self._metrics.hedge_requests.add(1)
        cond = threading.Condition()
        state = {"winner": None, "decided": False, "live": 0}
        attempts: list[dict] = []
        trace_id = current_trace_id()

        def conclude(att: dict, outcome: str, blob, elapsed: float) -> str:
            """Land one attempt's result (worker thread). Returns the
            final outcome after arbitration (ok may become late). Only
            plain state mutates under the condition; breaker and metric
            calls land after release (lock-order hygiene)."""
            breaker = self.directory.breaker(att["endpoint"])
            with cond:
                att["live"] = False
                state["live"] -= 1
                if att["cancel"].is_set():
                    # The canceller already counted this attempt; its
                    # partial result is dropped whatever it was.
                    outcome = "cancelled"
                elif outcome == "ok":
                    if state["decided"]:
                        outcome = "late"
                    else:
                        state["winner"] = (att["rank"], blob)
                        state["winner_endpoint"] = att["endpoint"]
                        state["decided"] = True
                cond.notify_all()
            if outcome == "late":
                self._metrics.hedge_late.add(1)
                event("hedge.late", "warn", peer=att["endpoint"],
                      elapsed_ms=round(elapsed * 1e3, 3))
            if outcome in ("ok", "late"):
                breaker.record_success()
                self._metrics.peer_fetch_seconds(att["endpoint"], elapsed)
            elif outcome == "error":
                breaker.record_failure()
            return outcome

        def run(att: dict) -> None:
            endpoint = att["endpoint"]
            t0 = time.monotonic()
            # Joining the caller's request trace from this worker thread
            # re-uses the propagation path peers already take: same
            # trace id, non-owner scope, spans merge into the caller's
            # buffer.
            scope = (
                request("get", trace_id=trace_id)
                if trace_id is not None else _NULL_SCOPE
            )
            with scope:
                with span(
                    "peer_fetch", peer=endpoint, stripe=i,
                    hedge=att["rank"],
                ) as sp:
                    blob = None
                    outcome = "error"
                    try:
                        resp = urlopen(
                            make_req(endpoint),
                            timeout=self.peer_timeout_seconds,
                        )
                        with cond:
                            if att["cancel"].is_set():
                                resp.close()
                                raise _HedgeCancelled()
                            att["resp"] = resp
                        try:
                            etag = (
                                resp.headers.get("ETag") or ""
                            ).strip('"')
                            if etag != address:
                                raise ValueError(
                                    f"peer serves address {etag!r}, "
                                    f"wanted {address!r}"
                                )
                            parts: list[bytes] = []
                            got = 0
                            # Chunked reads so a cancelled attempt
                            # unwinds within one chunk even if the
                            # socket close raced the read.
                            while got < logical + 1:
                                if att["cancel"].is_set():
                                    raise _HedgeCancelled()
                                chunk = resp.read(
                                    min(1 << 16, logical + 1 - got)
                                )
                                if not chunk:
                                    break
                                got += len(chunk)
                                parts.append(chunk)
                        finally:
                            resp.close()
                        blob = b"".join(parts)
                        if len(blob) != logical:
                            raise ValueError(
                                f"peer served {len(blob)} bytes, "
                                f"wanted {logical}"
                            )
                        outcome = "ok"
                    except _HedgeCancelled:
                        outcome = "cancelled"
                    except Exception as exc:  # noqa: BLE001 — a loser
                        # or dead peer degrades, never breaks the read
                        outcome = "error"
                        log.debug(
                            "hedged fetch from %s failed: %s",
                            endpoint, exc,
                        )
                    outcome = conclude(
                        att, outcome, blob, time.monotonic() - t0
                    )
                    sp.set_attr(
                        outcome=outcome,
                        bytes=len(blob) if outcome == "ok" and blob
                        else 0,
                    )

        def launch(rank: int) -> dict:
            """Register + start one attempt. The thread starts OUTSIDE
            the condition (Thread.start() blocks on its own started-
            event; holding the engine lock across that handshake is a
            lock-order edge the lockgraph harness rejects)."""
            att = {
                "endpoint": peers[rank], "rank": rank,
                "cancel": threading.Event(), "resp": None, "live": True,
            }
            with cond:
                attempts.append(att)
                state["live"] += 1
            threading.Thread(
                target=run, args=(att,),
                name="noise-ec-hedge", daemon=True,
            ).start()
            return att

        deadline = time.monotonic() + self.peer_timeout_seconds
        launch(0)
        next_rank = 1
        hedge_at = time.monotonic() + self._hedge_delay(peers[0])
        while True:
            do_launch = False
            with cond:
                if state["winner"] is not None:
                    break
                now = time.monotonic()
                if now >= deadline:
                    break
                if state["live"] == 0 and next_rank >= len(peers):
                    break  # every source failed
                if next_rank < len(peers) and (
                    now >= hedge_at or state["live"] == 0
                ):
                    # Straggling primary (p95 elapsed) or fast failure:
                    # race/promote the next ranked source.
                    do_launch = True
                else:
                    wake = hedge_at if next_rank < len(peers) else deadline
                    cond.wait(max(0.0, min(wake, deadline) - now))
            if do_launch:
                att = launch(next_rank)
                next_rank += 1
                hedge_at = time.monotonic() + self._hedge_delay(
                    att["endpoint"]
                )
        # Decision point: whatever is still in flight loses. Close each
        # loser's response socket out from under its read — the
        # in-flight HTTP fetch aborts NOW, not at its timeout.
        losers: list = []
        with cond:
            state["decided"] = True  # any straggler is late from here
            for att in attempts:
                if att["live"] and not att["cancel"].is_set():
                    att["cancel"].set()
                    losers.append(att.get("resp"))
            winner = state["winner"]
        for resp in losers:
            if resp is not None:
                try:
                    resp.close()
                # noise-ec: allow(event-on-swallow) — loser response close race after hedge cancel; hedge.cancel event follows
                except Exception:  # noqa: BLE001
                    pass
        if losers:
            self._metrics.hedge_cancelled.add(len(losers))
            event("hedge.cancel", losers=len(losers))
        if winner is None:
            return None
        rank, blob = winner
        if rank > 0:
            self._metrics.hedge_wins.add(1)
            event("hedge.win", peer=state.get("winner_endpoint"),
                  rank=rank)
        return blob

    def _read_stripe(self, key: str) -> tuple[bytes, bool]:
        """One stripe's (padded) bytes + whether the read was degraded
        (any of the k data slots untrusted, forcing a reconstruct)."""
        try:
            status = self.store.status(key)
        except UnknownStripeError:
            raise ObjectUnavailableError(
                f"stripe {key} is not held by this node (no metadata to "
                "anchor an anti-entropy fetch)"
            )
        degraded = not all(
            i in status["trusted"] for i in range(status["k"])
        )
        try:
            return self.store.read(key), degraded
        except DegradedReadError:
            pass
        if self.engine is None:
            raise ObjectUnavailableError(
                f"stripe {key} has fewer than k trusted shards and no "
                "repair engine is wired"
            )
        # Below k locally: ask the fleet (PR-2 anti-entropy) and wait a
        # bounded time for absorbs to lift the stripe back over k.
        self.engine.enqueue(key, "fetch")
        deadline = time.monotonic() + self.fetch_timeout_seconds
        while time.monotonic() < deadline:
            if getattr(self.engine, "_thread", None) is None:
                # No background worker: drive the queue ourselves so a
                # test/deterministic deployment still fetches.
                self.engine.drain_once()
            time.sleep(0.05)
            try:
                return self.store.read(key), True
            except DegradedReadError:
                continue
        raise ObjectUnavailableError(
            f"stripe {key}: below k trusted shards and anti-entropy did "
            f"not heal within {self.fetch_timeout_seconds:g}s"
        )

    # -------------------------------------------------------------- list

    def list_objects(
        self, tenant: str, *, cursor: Optional[str] = None, limit: int = 64
    ) -> tuple[list[dict], Optional[str]]:
        """One page of the tenant's objects in address order:
        ``(entries, next_cursor)`` — built on the store's cursored
        manifest walk, so a large namespace never snapshots whole."""
        out: list[dict] = []
        while len(out) < limit:
            page, cursor = self.store.list_manifests(
                cursor=cursor, limit=max(limit, 64)
            )
            for addr, doc in page:
                if doc.get("tenant") != tenant:
                    continue
                out.append({
                    "name": doc.get("name"),
                    "address": addr,
                    "size": doc.get("size"),
                    "created": doc.get("created"),
                })
                if len(out) >= limit:
                    return out, addr
            if cursor is None:
                return out, None
        return out, cursor

    # ------------------------------------------------------------ delete

    def delete(self, tenant: str, name: str) -> None:
        """Drop the manifest, release the quota, and evict stripes no
        other manifest references. Local-only: replicas keep their
        copies (v1 — see module docstring)."""
        with request("delete", tenant=tenant, name=name):
            doc = self.resolve(tenant, name)
            addr = doc["address"]
            with self._lock:
                self._index.pop((tenant, name), None)
            self._drop_address(addr)
            self._metrics.delete(tenant)

    def _drop_address(self, addr: str) -> None:
        # Invalidation-by-address: DELETE and overwrite-PUT both land
        # here (locally AND on every replica through the manifest absorb
        # path), and the cache key IS the address — one eviction call is
        # the whole coherence story.
        if self.cache is not None:
            self.cache.evict_address(addr)
        doc = self.store.get_manifest(addr)
        if doc is None:
            return
        tenant = doc.get("tenant", "")
        self.store.delete_manifest(addr)
        with self._lock:
            if addr in self._known:
                self._known.discard(addr)
                used = self._usage.setdefault(tenant, [0, 0])
                used[0] = max(0, used[0] - int(doc.get("size", 0)))
                used[1] = max(0, used[1] - 1)
                tenant_bytes = used[0]
            else:
                tenant_bytes = self._usage.get(tenant, [0, 0])[0]
        self._metrics.tenant_bytes(tenant, tenant_bytes)
        # Reference-count stripes across the surviving manifests before
        # evicting (identical content shares stripes by construction —
        # the key is the signature prefix of identical bytes).
        refs: set[str] = set()
        cursor = None
        while True:
            page, cursor = self.store.list_manifests(cursor=cursor, limit=256)
            for _, other in page:
                refs.update(other.get("stripes") or ())
                ms = other.get("manifest_stripe")
                if ms:
                    refs.add(ms)
            if cursor is None:
                break
        doomed = [
            key for key in dict.fromkeys(
                list(doc.get("stripes") or ())
                + ([doc["manifest_stripe"]] if doc.get("manifest_stripe")
                   else [])
            )
            if key not in refs
        ]
        for key in doomed:
            self.store.evict(key)
        if doomed and self.engine is not None:
            self.engine.unpin_announce(doomed)
