"""Tiered decoded-object cache and the warm-peer directory.

The GET hot path (docs/object-service.md "Read path") used to decode
every read from shards — the same hot object a thousand times over — so
read throughput was pinned to codec + fetch speed. This module is the
amortizing tier:

- :class:`DecodedObjectCache` — a bounded host-RAM LRU of decoded
  stripe payloads keyed by ``(content address, stripe index)``.
  Per-stripe granularity means range-GETs hit without materializing
  whole objects. The content address is the manifest address (a
  blake2b-128 of ``tenant\\0name\\0content``), so **invalidation is
  free**: an overwrite-PUT mints a new address and the object layer
  simply evicts the old one (:meth:`evict_address`); nothing cached
  under an address can ever be stale. Size is bounded two ways: the
  configured ``max_bytes`` ceiling (LRU eviction, ``reason="lru"``),
  and a **pressure watermark** — while the PR-5 HBM gauges
  (:func:`~noise_ec_tpu.obs.device.hbm_snapshot`) report device memory
  above ``hbm_watermark`` of its limit, the effective ceiling shrinks
  to ``low_fraction * max_bytes`` (``reason="pressure"``), so the host
  cache yields RAM exactly when the node is already memory-stressed.

- :class:`PeerCacheDirectory` — which peers hold which addresses warm.
  Fed by warm-set adverts (:data:`WARMSET_MAGIC` objects piggybacked on
  the repair engine's announce loop — docs/object-service.md), each
  entry maps an HTTP endpoint to its advertised address set with a TTL
  plus a ``load`` hint (the advertiser's in-flight reads), so routing
  picks the LEAST-LOADED warm peer instead of the freshest advert.
  A per-endpoint :class:`~noise_ec_tpu.resilience.breakers.
  CircuitBreaker` guards the routing decision: a dead cache peer opens
  its breaker and the read degrades to the local decode path instead of
  stalling on timeouts.

Metrics: ``noise_ec_object_cache_{hits,misses,evictions,bytes}`` and
(recorded by the object layer) ``noise_ec_object_read_route_total``.
"""

from __future__ import annotations

import json
import threading
import time
import weakref
from collections import OrderedDict
from typing import Callable, Iterable, Optional

from noise_ec_tpu.obs.events import event
from noise_ec_tpu.obs.registry import default_registry
from noise_ec_tpu.obs.trace import current_trace_id, span

__all__ = [
    "DecodedObjectCache",
    "PeerCacheDirectory",
    "WARMSET_MAGIC",
    "parse_warmset",
    "warmset_blob",
]

# Wire/stored prefix of a warm-set advert object; versioned like the
# manifest magic so future advert schemas can coexist on one fleet.
WARMSET_MAGIC = b"noise-ec-warmset/1\n"


def warmset_blob(
    endpoint: str, addresses: Iterable[str], load: float = 0.0
) -> bytes:
    """One warm-set advert payload: which addresses ``endpoint`` can
    serve from its decoded cache, plus the advertiser's ``load`` hint
    (in-flight reads at advert time) so routing can pick the
    LEAST-LOADED warm peer rather than the freshest advert. ``t`` (wall
    time) makes consecutive adverts distinct objects — identical
    payloads would sign to the identical stripe key and peers would
    absorb them as duplicates without refreshing their directory TTL."""
    return WARMSET_MAGIC + json.dumps({
        "version": 1,
        "endpoint": endpoint,
        "addresses": list(addresses),
        "load": float(load),
        "t": time.time(),
    }).encode()


def parse_warmset(data: bytes) -> Optional[dict]:
    """The advert document, or None when malformed (adverts arrive from
    peers; a bad one is dropped, never raised)."""
    if not data.startswith(WARMSET_MAGIC):
        return None
    try:
        doc = json.loads(data[len(WARMSET_MAGIC):].decode().rstrip("\n"))
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(doc, dict) or doc.get("version") != 1:
        return None
    endpoint = doc.get("endpoint")
    addresses = doc.get("addresses")
    if not isinstance(endpoint, str) or not endpoint.startswith("http"):
        return None
    if not isinstance(addresses, list) or not all(
        isinstance(a, str) for a in addresses
    ):
        return None
    # Load hint (PR-12 follow-on): absent in v1 adverts from older
    # peers — coerce to 0.0 so mixed fleets keep routing.
    load = doc.get("load", 0.0)
    if not isinstance(load, (int, float)) or load < 0:
        load = 0.0
    doc["load"] = float(load)
    return doc


class _CacheMetrics:
    _instances: "weakref.WeakSet[DecodedObjectCache]" = weakref.WeakSet()

    def __init__(self):
        reg = default_registry()
        self.hits = reg.counter("noise_ec_object_cache_hits_total").labels()
        self.misses = reg.counter(
            "noise_ec_object_cache_misses_total"
        ).labels()
        self._evictions = reg.counter(
            "noise_ec_object_cache_evictions_total"
        )
        cls = _CacheMetrics
        # Re-registered on every construction (idempotent — the closure
        # reads the CLASS WeakSet): the test-isolation registry reset
        # drops callback children, and a once-guard would leave the
        # gauge dead for the rest of the process.
        reg.gauge("noise_ec_object_cache_bytes").set_callback(
            lambda: sum(c.bytes_used for c in list(cls._instances))
        )

    def evicted(self, reason: str, count: int) -> None:
        if count:
            self._evictions.labels(reason=reason).add(count)


class DecodedObjectCache:
    """Bounded LRU of decoded stripe payloads (module docstring).

    Entries are the *logical* (unpadded) stripe bytes, so a cached
    stripe serves any sub-range by slicing. ``stripe_key`` (the store
    key of the backing stripe) is tracked per entry so a store-level
    eviction invalidates the cached copy through the store's delete
    listener (:meth:`evict_stripe`)."""

    def __init__(
        self,
        max_bytes: int = 256 << 20,
        *,
        low_fraction: float = 0.5,
        hbm_watermark: float = 0.85,
        pressure_interval_seconds: float = 1.0,
    ):
        if max_bytes < 1:
            raise ValueError(f"max_bytes must be >= 1, got {max_bytes}")
        if not 0.0 < low_fraction <= 1.0:
            raise ValueError(f"low_fraction outside (0, 1]: {low_fraction}")
        self.max_bytes = max_bytes
        self.low_fraction = low_fraction
        self.hbm_watermark = hbm_watermark
        self.pressure_interval_seconds = pressure_interval_seconds
        # A single entry may not monopolize the cache: stripes larger
        # than a quarter of the ceiling are served but never cached.
        self.entry_cap = max(1, max_bytes // 4)
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple[str, int], bytes]" = OrderedDict()
        self._by_addr: dict[str, set[int]] = {}
        # Last touch (monotonic) per address: the conversion engine's
        # temperature signal. Residency alone is NOT warmth — an LRU
        # under no pressure never expires, so a write-through entry
        # would otherwise pin its object hot forever.
        self._addr_touched: dict[str, float] = {}
        self._by_stripe: dict[str, tuple[str, int]] = {}
        self._stripe_of: dict[tuple[str, int], str] = {}
        self.bytes_used = 0
        self._pressured = False
        self._last_pressure_check = 0.0
        # Injectable for tests; the default reads the PR-5 device gauges.
        from noise_ec_tpu.obs.device import hbm_snapshot

        self._hbm = hbm_snapshot
        self._metrics = _CacheMetrics()
        _CacheMetrics._instances.add(self)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -------------------------------------------------------------- reads

    def get(self, address: str, idx: int) -> Optional[bytes]:
        """The cached stripe payload (bumping LRU recency) or None;
        records the hit/miss counters — one call per logical lookup.
        Inside a request scope the probe records a ``cache_probe`` span
        (outcome + bytes); outside one — bench warm sweeps, background
        work — the lookup stays span-free."""
        if current_trace_id() is None:
            return self._probe(address, idx)
        with span("cache_probe", stripe=idx) as sp:
            blob = self._probe(address, idx)
            sp.set_attr(
                outcome="hit" if blob is not None else "miss",
                bytes=len(blob) if blob is not None else 0,
            )
            return blob

    def _probe(self, address: str, idx: int) -> Optional[bytes]:
        with self._lock:
            blob = self._entries.get((address, idx))
            if blob is not None:
                self._entries.move_to_end((address, idx))
                self._addr_touched[address] = time.monotonic()
        if blob is None:
            self._metrics.misses.add(1)
        else:
            self._metrics.hits.add(1)
        return blob

    def peek(self, address: str, idx: int) -> Optional[bytes]:
        """Like :meth:`get` but with no recency bump and no counters —
        for re-checks inside an in-flight fetch (the logical request
        already recorded its miss)."""
        with self._lock:
            return self._entries.get((address, idx))

    def contains(self, address: str, idx: int) -> bool:
        with self._lock:
            return (address, idx) in self._entries

    def warm(
        self, address: str, within_seconds: Optional[float] = None
    ) -> bool:
        """True while a stripe of the address sits in the cache AND the
        address was touched within ``within_seconds`` (None = any
        resident entry counts) — the conversion engine's temperature
        signal. Recency matters: an idle LRU never expires entries, so
        residency alone would pin a write-through object hot forever."""
        with self._lock:
            if address not in self._by_addr:
                return False
            if within_seconds is None:
                return True
            touched = self._addr_touched.get(address, 0.0)
            return time.monotonic() - touched <= within_seconds

    def addresses(self, limit: int = 256) -> list[str]:
        """Warm addresses, most recently used first — the node's
        warm-set advert payload."""
        out: list[str] = []
        seen: set[str] = set()
        with self._lock:
            for addr, _ in reversed(self._entries):
                if addr not in seen:
                    seen.add(addr)
                    out.append(addr)
                    if len(out) >= limit:
                        break
        return out

    # ------------------------------------------------------------- writes

    def put(
        self, address: str, idx: int, blob: bytes,
        stripe_key: Optional[str] = None,
    ) -> bool:
        """Insert one decoded stripe payload (write-through from PUT and
        from GET decode results). Returns False when the entry is over
        the per-entry cap and was not cached."""
        blob = bytes(blob)
        if len(blob) > self.entry_cap:
            return False
        limit = self._effective_max()
        with self._lock:
            key = (address, idx)
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes_used -= len(old)
            self._entries[key] = blob
            self.bytes_used += len(blob)
            self._by_addr.setdefault(address, set()).add(idx)
            self._addr_touched[address] = time.monotonic()
            if stripe_key is not None:
                self._by_stripe[stripe_key] = key
                self._stripe_of[key] = stripe_key
            lru = self._shrink_locked(self.max_bytes)
            pressured = self._shrink_locked(limit)
        self._metrics.evicted("lru", lru)
        self._metrics.evicted("pressure", pressured)
        if pressured:
            # The HBM-watermark shrink, not routine LRU turnover: the
            # cache yielding RAM to device pressure is a diagnosis
            # signal (hbm-pressure rule) the eviction counter alone
            # cannot date.
            event("cache.shrink", "warn", evicted=pressured,
                  limit_bytes=limit)
        return True

    def evict_address(self, address: str) -> int:
        """Drop every cached stripe of one content address (DELETE /
        overwrite-PUT invalidation — the address IS the content, so this
        is the whole consistency story). Returns entries dropped."""
        with self._lock:
            idxs = self._by_addr.pop(address, None)
            if not idxs:
                return 0
            count = 0
            for idx in idxs:
                if self._drop_locked((address, idx)):
                    count += 1
        self._metrics.evicted("invalidate", count)
        return count

    def evict_stripe(self, stripe_key: str) -> bool:
        """Drop the entry backed by one store stripe key (the store's
        delete-listener hook: a stripe evicted under an address must not
        keep serving from RAM)."""
        with self._lock:
            key = self._by_stripe.get(stripe_key)
            dropped = key is not None and self._drop_locked(key)
        if dropped:
            self._metrics.evicted("invalidate", 1)
        return dropped

    def clear(self) -> int:
        """Invalidate everything (tests, bench cold-start segments)."""
        with self._lock:
            count = len(self._entries)
            self._entries.clear()
            self._by_addr.clear()
            self._addr_touched.clear()
            self._by_stripe.clear()
            self._stripe_of.clear()
            self.bytes_used = 0
        self._metrics.evicted("invalidate", count)
        return count

    # ----------------------------------------------------------- internal

    def _drop_locked(self, key: tuple[str, int]) -> bool:
        blob = self._entries.pop(key, None)
        if blob is None:
            return False
        self.bytes_used -= len(blob)
        address, idx = key
        idxs = self._by_addr.get(address)
        if idxs is not None:
            idxs.discard(idx)
            if not idxs:
                self._by_addr.pop(address, None)
                self._addr_touched.pop(address, None)
        skey = self._stripe_of.pop(key, None)
        if skey is not None:
            self._by_stripe.pop(skey, None)
        return True

    def _shrink_locked(self, limit: int) -> int:
        count = 0
        while self.bytes_used > limit and self._entries:
            key = next(iter(self._entries))  # LRU head
            self._drop_locked(key)
            count += 1
        return count

    def _effective_max(self) -> int:
        """The live ceiling: ``max_bytes``, shrunk to ``low_fraction``
        of it while device memory sits above the watermark. The gauge
        read is rate-limited — the hot path must not pay a device scan
        per insert."""
        now = time.monotonic()
        with self._lock:
            fresh = (
                now - self._last_pressure_check
                < self.pressure_interval_seconds
            )
            if fresh:
                pressured = self._pressured
        if not fresh:
            pressured = False
            try:
                hbm = self._hbm()
                limit = hbm.get("limit_bytes") or 0
                used = hbm.get("bytes_in_use", hbm.get("live_bytes", 0))
                pressured = bool(limit) and used >= self.hbm_watermark * limit
            # noise-ec: allow(event-on-swallow) — telemetry probe — the put proceeds; cache.shrink fires on the eviction path
            except Exception:  # noqa: BLE001 — telemetry must not break puts
                pressured = False
            with self._lock:
                self._pressured = pressured
                self._last_pressure_check = now
        if pressured:
            return max(1, int(self.max_bytes * self.low_fraction))
        return self.max_bytes


class PeerCacheDirectory:
    """Warm-address directory over peer adverts (module docstring).

    ``observe`` ingests one advert; ``peers_for`` answers "who can serve
    this address from RAM right now" — fresh (within TTL) entries only,
    least-loaded first (the advert's ``load`` hint; freshest advert
    breaks ties). Breakers are per endpoint and owned here so the
    routing layer's failure handling has one home."""

    def __init__(
        self,
        ttl_seconds: float = 90.0,
        max_endpoints: int = 256,
        breaker_factory: Optional[Callable[[], object]] = None,
    ):
        self.ttl_seconds = ttl_seconds
        self.max_endpoints = max_endpoints
        self._lock = threading.Lock()
        # endpoint -> (frozenset(addresses), observed_at, load hint)
        self._peers: "OrderedDict[str, tuple[frozenset, float, float]]" = (
            OrderedDict()
        )
        self._breakers: dict[str, object] = {}
        if breaker_factory is None:
            from noise_ec_tpu.resilience.breakers import CircuitBreaker

            def breaker_factory():
                return CircuitBreaker(
                    failure_threshold=2, reset_timeout=2.0,
                    max_reset_timeout=30.0,
                )
        self._breaker_factory = breaker_factory

    def observe(
        self, endpoint: str, addresses: Iterable[str], load: float = 0.0
    ) -> None:
        now = time.monotonic()
        with self._lock:
            self._peers.pop(endpoint, None)
            self._peers[endpoint] = (
                frozenset(addresses), now, max(0.0, float(load))
            )
            while len(self._peers) > self.max_endpoints:
                stale, _ = self._peers.popitem(last=False)
                self._breakers.pop(stale, None)

    def forget(self, endpoint: str) -> None:
        with self._lock:
            self._peers.pop(endpoint, None)
            self._breakers.pop(endpoint, None)

    def peers_for(self, address: str) -> list[str]:
        """Fresh warm peers for the address, LEAST-LOADED first (the
        PR-12 follow-on: a stampede of cold-stripe fetches used to pile
        onto whichever peer advertised most recently; the load hint
        spreads them). Ties break toward the freshest advert."""
        cutoff = time.monotonic() - self.ttl_seconds
        with self._lock:
            fresh = [
                (load, -t, ep)
                for ep, (addrs, t, load) in self._peers.items()
                if t >= cutoff and address in addrs
            ]
        fresh.sort()
        return [ep for _, _, ep in fresh]

    def load_of(self, endpoint: str) -> Optional[float]:
        """The endpoint's last advertised load hint (None = unknown)."""
        with self._lock:
            entry = self._peers.get(endpoint)
            return entry[2] if entry is not None else None

    def endpoints(self) -> list[str]:
        with self._lock:
            return list(self._peers)

    def breaker(self, endpoint: str):
        with self._lock:
            br = self._breakers.get(endpoint)
            if br is None:
                br = self._breakers[endpoint] = self._breaker_factory()
            return br
