"""Tenant namespaces for the object service: quotas, geometry, and
replication targets.

A *tenant* is one namespace of the object API (``/objects/<tenant>/...``,
service/http.py): its own name space of objects, its own byte/object
quotas enforced at PUT admission, optionally its own erasure geometry
and a replication target (``replicas > 1`` pins the namespace's stripes
into the repair engine's announce loop so peers keep being re-offered
them — docs/object-service.md).

Two admission modes:

- **open** (the default, no config file): unknown tenant names are
  admitted with unlimited quotas — the single-operator dev posture;
- **closed** (``open_admission: false`` in the config, or
  ``TenantRegistry(open_admission=False)``): only configured tenants
  exist; a PUT under any other name is rejected before any work.

Quota semantics: ``max_bytes`` / ``max_objects`` of 0 mean unlimited.
Usage is the sum of *logical object sizes* (manifest ``size``), not the
erasure-expanded shard bytes — the number a user can reason about; the
n/k expansion factor is the operator's to budget. Checks happen at PUT
admission against the declared upload size, so an over-quota PUT is
refused before a single stripe is encoded.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass
from typing import Iterable, Optional

__all__ = [
    "QuotaExceededError",
    "Tenant",
    "TenantRegistry",
    "UnknownTenantError",
]

TENANT_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


class UnknownTenantError(KeyError):
    """Closed admission and the tenant is not configured."""


class QuotaExceededError(RuntimeError):
    """A PUT would push the tenant past its byte or object quota.

    ``reason`` is the bounded label the rejection counter uses
    (``quota_bytes`` | ``quota_objects``)."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


@dataclass(frozen=True)
class Tenant:
    """One namespace's policy (all limits 0 = unlimited / default)."""

    name: str
    max_bytes: int = 0
    max_objects: int = 0
    # Desired copy count across the fleet. 1 = broadcast-once (peers
    # that were up got it); > 1 = the namespace's stripes are pinned
    # into the announce loop so late/partitioned peers converge.
    replicas: int = 1
    # Per-tenant erasure geometry; 0 = the service default.
    k: int = 0
    n: int = 0
    # Tenant policy string (docs/lrc.md archival grammar + the QoS
    # lane/weight grammar, docs/object-service.md "QoS lanes"; empty =
    # never convert, live lane, weight 1): e.g.
    # "archive=lrc:20/4+6,age=600,lane=background,weight=2". Both halves
    # are validated at configure time — an unknown archival tier, an
    # invalid LRC geometry (group count must divide k, >= 1 global
    # parity), an unknown lane or an out-of-range weight all fail HERE
    # with a clear ValueError, not in a background loop.
    policy: str = ""

    @property
    def lane(self) -> str:
        """QoS lane of this tenant's device-gate traffic ("live" |
        "background"; the ``lane=`` policy token, default live)."""
        from noise_ec_tpu.store.convert import split_qos

        return split_qos(self.policy)[0]

    @property
    def weight(self) -> int:
        """Deficit-round-robin weight of this tenant's queue inside its
        lane (the ``weight=`` policy token, default 1)."""
        from noise_ec_tpu.store.convert import split_qos

        return split_qos(self.policy)[1]


class TenantRegistry:
    """The configured tenant set + admission policy (module docstring)."""

    def __init__(
        self,
        tenants: Optional[Iterable[Tenant]] = None,
        *,
        open_admission: bool = True,
    ):
        self.open_admission = open_admission
        self._tenants: dict[str, Tenant] = {}
        for tenant in tenants or ():
            self._tenants[tenant.name] = tenant

    @classmethod
    def from_file(cls, path: str) -> "TenantRegistry":
        """Load a JSON config::

            {"open_admission": false,
             "tenants": {"acme": {"max_bytes": 1073741824,
                                  "max_objects": 10000,
                                  "replicas": 2, "k": 10, "n": 14,
                                  "policy": "archive=lrc:20/4+6,age=600"}}}
        """
        with open(path, "rb") as f:
            doc = json.load(f)
        reg = cls(open_admission=bool(doc.get("open_admission", True)))
        for name, spec in (doc.get("tenants") or {}).items():
            reg.configure(
                name,
                max_bytes=int(spec.get("max_bytes", 0)),
                max_objects=int(spec.get("max_objects", 0)),
                replicas=int(spec.get("replicas", 1)),
                k=int(spec.get("k", 0)),
                n=int(spec.get("n", 0)),
                policy=str(spec.get("policy", "")),
            )
        return reg

    def configure(self, name: str, **kwargs) -> Tenant:
        if not TENANT_NAME_RE.match(name):
            raise ValueError(f"bad tenant name {name!r}")
        tenant = Tenant(name=name, **kwargs)
        if tenant.k or tenant.n:
            if not 1 <= tenant.k <= tenant.n:
                raise ValueError(
                    f"tenant {name!r} geometry k={tenant.k} n={tenant.n} "
                    "is invalid (set both, 1 <= k <= n)"
                )
        if tenant.replicas < 1:
            raise ValueError(f"tenant {name!r} replicas must be >= 1")
        if tenant.policy:
            # Parse-time policy validation (docs/lrc.md archival grammar
            # + the QoS lane/weight grammar): an unknown archival tier,
            # an invalid LRC geometry, an unknown lane or a bad weight
            # must fail the configure call, not a background loop.
            from noise_ec_tpu.store.convert import (
                ConversionPolicy,
                split_qos,
            )

            try:
                archival = split_qos(tenant.policy)[2]
                if archival:
                    ConversionPolicy.parse(archival)
            except ValueError as exc:
                raise ValueError(
                    f"tenant {name!r} policy {tenant.policy!r}: {exc}"
                ) from exc
        self._tenants[name] = tenant
        return tenant

    def get(self, name: str) -> Tenant:
        tenant = self._tenants.get(name)
        if tenant is not None:
            return tenant
        if not TENANT_NAME_RE.match(name):
            raise UnknownTenantError(name)
        if not self.open_admission:
            raise UnknownTenantError(name)
        return Tenant(name=name)

    def names(self) -> list[str]:
        return sorted(self._tenants)

    @staticmethod
    def admit(
        tenant: Tenant, used_bytes: int, used_objects: int, add_bytes: int
    ) -> None:
        """Raise :class:`QuotaExceededError` if adding one ``add_bytes``
        object would breach the tenant's quota."""
        if tenant.max_bytes and used_bytes + add_bytes > tenant.max_bytes:
            raise QuotaExceededError(
                "quota_bytes",
                f"tenant {tenant.name!r}: {used_bytes} + {add_bytes} bytes "
                f"exceeds the {tenant.max_bytes}-byte quota",
            )
        if tenant.max_objects and used_objects + 1 > tenant.max_objects:
            raise QuotaExceededError(
                "quota_objects",
                f"tenant {tenant.name!r}: already at the "
                f"{tenant.max_objects}-object quota",
            )
