"""The bitsliced view of GF(2^m): constants as GF(2) bit-matrices.

Multiplication by a constant c in GF(2^m) is linear over GF(2): writing a field
element x as the bit-vector (x_0 .. x_{m-1}), there is an m x m binary matrix
M_c with bits(c * x) = M_c @ bits(x) (mod 2). Expanding every entry of an
r x k generator matrix G into its M_c block turns the whole RS encode
(parity = G_parity @ data over GF(2^m), reference hot loop main.go:262)
into ONE binary matrix multiply:

    parity_planes (m*r, W) = B (m*r, m*k) @ data_planes (m*k, W)   over GF(2)

where data_planes is the *bitplane* layout: plane (j*m + i) holds bit i of
every symbol of shard j, packed 32 symbol-positions per uint32 word. On the
TPU this binary matmul is pure AND/XOR on 32-bit lanes — no gathers, no
byte-granular multiplies — which is why the Pallas kernels use this layout
(SURVEY.md §7.4 "bitsliced formulation").

This module is the NumPy host-side reference for that machinery; the JAX /
Pallas equivalents in ``noise_ec_tpu.ops`` are tested bit-exactly against it.
"""

from __future__ import annotations

import numpy as np

from noise_ec_tpu.gf.field import GF

WORD_BITS = 32


def constant_bitmatrix(gf: GF, c: int) -> np.ndarray:
    """The m x m GF(2) matrix M_c with bits(c*x) = M_c @ bits(x).

    Column j of M_c is the bit-vector of c * 2^j.
    """
    m = gf.degree
    cols = gf.mul(c, (1 << np.arange(m)).astype(np.int64))  # (m,) values c * 2^j
    out = np.zeros((m, m), dtype=np.uint8)
    for j in range(m):
        v = int(cols[j])
        for i in range(m):
            out[i, j] = (v >> i) & 1
    return out


def expand_generator_bits(gf: GF, G: np.ndarray) -> np.ndarray:
    """Expand an (r, k) GF generator matrix to its (m*r, m*k) GF(2) form."""
    G = np.asarray(G)
    r, k = G.shape
    m = gf.degree
    out = np.zeros((m * r, m * k), dtype=np.uint8)
    for i in range(r):
        for j in range(k):
            out[i * m : (i + 1) * m, j * m : (j + 1) * m] = constant_bitmatrix(
                gf, int(G[i, j])
            )
    return out


def expand_generator_masks(gf: GF, G: np.ndarray) -> np.ndarray:
    """Like :func:`expand_generator_bits` but as uint32 select-masks.

    0xFFFFFFFF where the bit is set, 0 elsewhere — the operand shape the
    AND/XOR kernels consume directly.
    """
    bits = expand_generator_bits(gf, G)
    return (bits.astype(np.uint32) * np.uint32(0xFFFFFFFF)).astype(np.uint32)


_MASKS_CACHE: dict[tuple, np.ndarray] = {}


def expand_generator_masks_cached(gf: GF, G: np.ndarray) -> np.ndarray:
    """Cached :func:`expand_generator_masks` (geometry is runtime-dynamic in
    the reference — main.go:185-191 — so the same matrices recur per
    message). Shared by DeviceCodec and BatchCodec."""
    G = np.ascontiguousarray(np.asarray(G, dtype=gf.dtype))
    key = (gf.degree, G.shape, G.tobytes())
    hit = _MASKS_CACHE.get(key)
    if hit is None:
        hit = expand_generator_masks(gf, G)
        if len(_MASKS_CACHE) > 1024:
            _MASKS_CACHE.clear()
        _MASKS_CACHE[key] = hit
    return hit


# ---------------------------------------------------------------------------
# Bitplane packing


def packed_words(num_symbols: int) -> int:
    return -(-num_symbols // WORD_BITS)


def pack_bitplanes(shards: np.ndarray, gf: GF) -> np.ndarray:
    """(k, S) symbols -> (k*m, W) packed uint32 bitplanes.

    Bit t of word w of plane (j*m + i) is bit i of symbol shards[j, 32w + t].
    Symbol counts not divisible by 32 are zero-padded (unpack slices off).
    """
    shards = np.atleast_2d(np.asarray(shards, dtype=gf.dtype))
    k, S = shards.shape
    m = gf.degree
    W = packed_words(S)
    if W * WORD_BITS != S:
        pad = np.zeros((k, W * WORD_BITS - S), dtype=gf.dtype)
        shards = np.concatenate([shards, pad], axis=1)
    # (k, m, W*32) bit tensor
    bits = (shards[:, None, :].astype(np.uint32) >> np.arange(m, dtype=np.uint32)[None, :, None]) & 1
    bits = bits.reshape(k * m, W, WORD_BITS)
    shifted = bits << np.arange(WORD_BITS, dtype=np.uint32)[None, None, :]
    return np.bitwise_or.reduce(shifted, axis=-1).astype(np.uint32)


def unpack_bitplanes(planes: np.ndarray, num_shards: int, num_symbols: int, gf: GF) -> np.ndarray:
    """(k*m, W) packed uint32 bitplanes -> (k, S) symbols. Inverse of pack."""
    planes = np.asarray(planes, dtype=np.uint32)
    m = gf.degree
    km, W = planes.shape
    if km != num_shards * m:
        raise ValueError(f"plane count {km} != {num_shards} shards x {m} bits")
    bits = (planes[:, :, None] >> np.arange(WORD_BITS, dtype=np.uint32)[None, None, :]) & 1
    bits = bits.reshape(num_shards, m, W * WORD_BITS)[:, :, :num_symbols]
    shifted = bits.astype(np.uint32) << np.arange(m, dtype=np.uint32)[None, :, None]
    return np.bitwise_or.reduce(shifted, axis=1).astype(gf.dtype)


def gf2_matmul_planes(bits: np.ndarray, planes: np.ndarray) -> np.ndarray:
    """Binary matmul: (R, C) 0/1 matrix x (C, W) packed planes -> (R, W).

    NumPy reference for the TPU kernel: out[r] = XOR over {c : bits[r,c]=1}
    of planes[c].
    """
    bits = np.asarray(bits, dtype=np.uint8)
    planes = np.asarray(planes, dtype=np.uint32)
    R, C = bits.shape
    out = np.zeros((R, planes.shape[1]), dtype=np.uint32)
    for c in range(C):
        rows = bits[:, c] != 0
        out[rows] ^= planes[c]
    return out
