"""Finite-field arithmetic for the erasure codec.

``field``     — GF(2^8) / GF(2^16) log/exp-table arithmetic (NumPy, host side).
``bitmatrix`` — the bitsliced view: every GF(2^m) constant is an m x m matrix
                over GF(2), so a generator-matrix multiply becomes a pure
                AND/XOR binary matmul — the formulation the TPU kernels use.
"""

from noise_ec_tpu.gf.field import GF, GF256, GF65536  # noqa: F401
from noise_ec_tpu.gf.bitmatrix import (  # noqa: F401
    constant_bitmatrix,
    expand_generator_bits,
    expand_generator_masks,
    gf2_matmul_planes,
    pack_bitplanes,
    unpack_bitplanes,
)
