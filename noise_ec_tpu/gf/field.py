"""GF(2^m) arithmetic over log/exp tables (host-side reference path).

The reference delegates all field arithmetic to ``vivint/infectious``
(call sites /root/reference/main.go:57-61, 73-77, 248-266). This module is the
framework's own ground-truth implementation: vectorized NumPy arithmetic used
by the golden codec, the generator-matrix builders, and for cross-checking the
bitsliced TPU kernels bit-exactly.

Field choices:

- GF(2^8) with the primitive polynomial x^8+x^4+x^3+x^2+1 (0x11D) — the
  polynomial used by klauspost/reedsolomon (the BASELINE.json comparison bar)
  and by most storage RS codes.
- GF(2^16) with x^16+x^12+x^3+x+1 (0x1100B) for the wide-field variant
  (BASELINE.json config 4).

Both have alpha = 2 as a primitive element (asserted at table-build time).
"""

from __future__ import annotations

import functools

import numpy as np

# Primitive polynomials, full form including the leading x^8 / x^16 term —
# the reduction step (x ^= poly when the overflow bit is set) relies on the
# leading bit to clear the overflow.
POLY_GF256 = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
POLY_GF65536 = 0x1100B  # x^16 + x^12 + x^3 + x + 1


class GF:
    """A binary extension field GF(2^degree) with log/exp tables.

    All element-wise operations accept NumPy arrays (any shape) or Python ints
    and broadcast like NumPy ufuncs. Elements are represented as unsigned
    integers in [0, order).
    """

    def __init__(self, degree: int, poly: int):
        if degree not in (8, 16):
            raise ValueError(f"unsupported field degree {degree}")
        self.degree = degree
        self.poly = poly
        self.order = 1 << degree
        self.dtype = np.uint8 if degree == 8 else np.uint16
        self._build_tables()

    def _build_tables(self) -> None:
        order = self.order
        exp = np.zeros(2 * (order - 1), dtype=np.int32)
        log = np.zeros(order, dtype=np.int32)
        x = 1
        for i in range(order - 1):
            exp[i] = x
            log[x] = i
            x <<= 1
            if x & order:
                x ^= self.poly
        if x != 1:
            raise AssertionError(f"2 is not primitive for poly {self.poly:#x}")
        # Double-length exp table lets mul index log[a]+log[b] without a mod.
        exp[order - 1 :] = exp[: order - 1]
        self.exp = exp
        self.log = log
        self._lut_cache: dict[int, np.ndarray] = {}

    # -- scalar/element-wise ops ------------------------------------------

    def mul(self, a, b):
        """Element-wise GF product, broadcasting."""
        a = np.asarray(a, dtype=np.int32)
        b = np.asarray(b, dtype=np.int32)
        out = self.exp[self.log[a] + self.log[b]]
        out = np.where((a == 0) | (b == 0), 0, out)
        return out.astype(self.dtype)

    def div(self, a, b):
        a = np.asarray(a, dtype=np.int32)
        b = np.asarray(b, dtype=np.int32)
        if np.any(b == 0):
            raise ZeroDivisionError("division by zero in GF")
        out = self.exp[self.log[a] - self.log[b] + (self.order - 1)]
        out = np.where(a == 0, 0, out)
        return out.astype(self.dtype)

    def inv(self, a):
        a = np.asarray(a, dtype=np.int32)
        if np.any(a == 0):
            raise ZeroDivisionError("inverse of zero in GF")
        return self.exp[(self.order - 1) - self.log[a]].astype(self.dtype)

    def pow(self, a, e: int):
        """a ** e with 0**0 == 1 (Vandermonde convention)."""
        a = np.asarray(a, dtype=np.int32)
        e = int(e)
        if e == 0:
            return np.ones_like(a).astype(self.dtype)
        out = self.exp[(self.log[a].astype(np.int64) * e) % (self.order - 1)]
        out = np.where(a == 0, 0, out)
        return out.astype(self.dtype)

    def add(self, a, b):
        """Addition == subtraction == XOR in characteristic 2."""
        return (np.asarray(a, dtype=self.dtype) ^ np.asarray(b, dtype=self.dtype)).astype(
            self.dtype
        )

    # -- linear algebra helpers -------------------------------------------

    def matmul(self, A, B):
        """GF matrix product. A: (r, k), B: (k, c) -> (r, c).

        Vectorized: products via log/exp, accumulation via XOR-reduce.
        """
        A = np.asarray(A, dtype=np.int32)
        B = np.asarray(B, dtype=np.int32)
        prod = self.mul(A[:, :, None], B[None, :, :])  # (r, k, c)
        return np.bitwise_xor.reduce(prod.astype(np.int64), axis=1).astype(self.dtype)

    def _const_lut(self, c: int) -> np.ndarray:
        """Full multiplication table row for constant ``c``: lut[x] = c*x.

        One gather per stripe instead of the generic mul's two log
        lookups + add + exp lookup + zero-mask over int32 temporaries —
        ~6x less memory traffic on the host encode/decode hot loop.
        Cached per constant (256 B for GF(2^8), 128 KiB for GF(2^16)).
        """
        lut = self._lut_cache.get(c)
        if lut is None:
            lut = self.mul(c, np.arange(self.order, dtype=np.int32))
            if len(self._lut_cache) > 512:
                self._lut_cache.clear()
            self._lut_cache[c] = lut
        return lut

    def mul_const(self, c: int, x: np.ndarray) -> np.ndarray:
        """c * x for a scalar constant and an array, via the cached LUT."""
        c = int(c)
        x = np.asarray(x, dtype=self.dtype)
        if c == 0:
            return np.zeros_like(x)
        if c == 1:
            return x
        return self._const_lut(c)[x]

    def matvec_stripes(self, A, D):
        """A @ D where D holds one stripe per row. A: (r, k), D: (k, S) -> (r, S).

        This IS the encode hot loop shape (reference main.go:262): parity
        stripes = generator-parity-rows x data stripes. Per-coefficient
        LUT gathers with in-place XOR accumulation; zero coefficients are
        skipped and unit coefficients degrade to plain XOR (so the
        systematic identity rows and sparse reconstruction matrices cost
        only copies).
        """
        A = np.asarray(A)
        D = np.asarray(D, dtype=self.dtype)
        r, k = A.shape
        out = np.zeros((r, D.shape[1]), dtype=self.dtype)
        for i in range(r):
            acc = None
            for j in range(k):
                c = int(A[i, j])
                if c == 0:
                    continue
                term = self.mul_const(c, D[j])
                if acc is None:
                    # copy=True: term may alias a D row (c == 1).
                    acc = np.array(term, dtype=self.dtype)
                else:
                    np.bitwise_xor(acc, term, out=acc)
            if acc is not None:
                out[i] = acc
        return out


@functools.lru_cache(maxsize=None)
def _field(degree: int, poly: int) -> GF:
    return GF(degree, poly)


def GF256() -> GF:
    return _field(8, POLY_GF256)


def GF65536() -> GF:
    return _field(16, POLY_GF65536)
