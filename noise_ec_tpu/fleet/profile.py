"""The fleet traffic-mix grammar: ``FleetProfile.parse``.

Styled after :meth:`~noise_ec_tpu.resilience.chaos.ChaosProfile.parse`
(comma-separated tokens, one seed reproduces a run): a declarative
description of WHAT a fleet run does — how many peers, what traffic mix
(chat-sized spam / object PUT+GET through the service layer / repair
storms), how fast, under which named chaos profile, with what churn —
while :mod:`noise_ec_tpu.fleet.runner` owns HOW it runs.

Chaos composes by NAME (``chaos=lossy``): the lab's per-link fault model
is the existing :class:`ChaosLink` pipeline, so a named profile is just
a curated ``ChaosProfile.parse`` string. Churn rides the SAME chaos
grammar (the ``churn@`` primitive added to ``ChaosProfile.parse``):
``churn@`` / ``partition@`` / ``reset@`` / ``kill@`` tokens inside a
fleet profile pass through verbatim to the chaos parser rather than
growing a parallel scheduler.

Grammar (docs/fleet.md):

``peers=N``            fleet size (CLI ``-fleet-size`` overrides)
``fanout=F``           per-peer neighbor count (bounded-degree overlay)
``msgs=N``             total traffic submissions across the run
``senders=K``          peers that originate traffic (0 = all)
``drivers=D``          concurrent driver threads (0 = auto)
``rate=R``             per-driver submissions/second pacing (0 = unpaced)
``chat=W``             weight of chat-sized broadcasts in the mix
``object=W``           weight of object PUT/GET through the service layer
``get=W``              weight of hot-read GETs: a zipfian-popular object
                       read back through a random peer's service layer
                       (exercises the decoded-object cache tiers)
``zipf_s=S``           zipf exponent of the GET popularity draw
                       (default 1.1; must be > 1)
``repair=W``           weight of repair-storm ops (drop a stored shard,
                       degraded-read it back through the codec)
``lrc@G``              run the repair-storm mix on the LRC tier
                       (docs/lrc.md): each repair op exercises an
                       LRC(k, G groups, n-k-G globals) stripe, healing
                       single losses from ~k/G local shards; G must
                       divide k and leave >= 1 global parity
``chat_bytes=B``       chat payload size (padded to a multiple of k)
``object_bytes=B``     object payload size
``stripe_bytes=B``     object-service stripe capacity
``k=K`` / ``n=N``      RS geometry for all fleet traffic
``chaos=NAME``         named chaos profile (see :data:`NAMED_CHAOS`)
``churn_peers=C``      peers subject to the churn schedule (0 = ~5%)
``churn@S:I:D[:J]``    passed through to ``ChaosProfile.parse``
``partition@...`` / ``reset@...`` / ``kill@...``  likewise
``domains@D``          partition the fleet's peers into D failure
                       domains ("d0".."d{D-1}", round-robin) and place
                       object stripes through the placement ring
                       (docs/placement.md); D must cover the active
                       geometry (>= n for RS, >= groups + globals for
                       LRC) — rejected at parse time otherwise
``killdomain@T:NAME``  chaos: at T seconds, kill EVERY peer in failure
                       domain NAME at once (the rack-failure drill;
                       requires ``domains@``)
``slow@PEER:MS[:JITTER]``  delay-only chaos on every link touching peer
                       index PEER: MS milliseconds (+ uniform 0..JITTER
                       ms, seeded) on each delivery to or from it, and
                       on every placement gather fetch it serves — the
                       one-straggler scenario the hedged read path is
                       for (docs/fleet.md). Repeatable for several slow
                       peers.
``hedge=0|1``          disable/enable hedged k+Δ gather fan-out on the
                       fleet's object read path (default 1; hedge=0 is
                       the A/B control run)
``noisy=M``            tenant-isolation mix: object/GET traffic splits
                       into a "noisy" tenant submitting M× the "quiet"
                       tenant's share (default 0 = single "fleet"
                       tenant); the report then carries per-tenant GET
                       latency so the QoS-lane isolation bar is
                       checkable
"""

from __future__ import annotations

from dataclasses import dataclass, field

from noise_ec_tpu.resilience.chaos import ChaosProfile

__all__ = ["NAMED_CHAOS", "FleetProfile"]

# Curated, named fault mixes (docs/fleet.md): the acceptance scenarios
# compose "a named chaos profile" instead of ad-hoc token soup, so two
# runs claiming "lossy" mean the same thing.
NAMED_CHAOS: dict[str, str] = {
    "clean": "",
    "lossy": "drop=0.01,corrupt=0.005",
    "flaky": "drop=0.05,corrupt=0.01,duplicate=0.01",
    "storm": "drop=0.08,corrupt=0.02,duplicate=0.02,reorder=0.02",
}

_INT_KEYS = (
    "peers", "fanout", "msgs", "senders", "drivers",
    "chat_bytes", "object_bytes", "stripe_bytes", "k", "n", "churn_peers",
    "hedge",
)
_FLOAT_KEYS = ("chat", "object", "get", "repair", "rate", "zipf_s",
               "noisy")
_CHAOS_PASSTHROUGH = ("churn@", "partition@", "reset@", "kill@")


@dataclass(frozen=True)
class FleetProfile:
    """One declarative fleet run (module docstring for the grammar)."""

    peers: int = 64
    fanout: int = 6
    msgs: int = 200
    senders: int = 0       # 0 = every peer sends
    drivers: int = 0       # 0 = auto (min(4, senders))
    rate: float = 0.0      # per-driver submissions/s; 0 = unpaced
    chat: float = 1.0
    object: float = 0.0
    get: float = 0.0
    zipf_s: float = 1.1
    repair: float = 0.0
    chat_bytes: int = 64
    object_bytes: int = 8192
    stripe_bytes: int = 4096
    # Fleet default geometry carries FOUR parity shards (vs the node
    # default RS(4,6)): Berlekamp–Welch corrects e errors only when
    # m - k >= 2e, so with two parity shards a single link dropping one
    # frame AND corrupting another loses the codeword outright (m=5,
    # e=1 is detect-only) — measured ~1.4e-3 per delivery under the
    # "lossy" profile, an order of magnitude over the 99.9% bar. With
    # n=8 the same codeword survives any (2 drops + 1 corrupt) or
    # (2 corrupt) combination.
    k: int = 4
    n: int = 8
    # LRC local-group count for the repair mix (the ``lrc@G`` token);
    # 0 = repair storms run on plain RS stripes.
    lrc_groups: int = 0
    # Failure domains (the ``domains@D`` token): 0 = no placement ring,
    # broadcast delivery exactly as before. D > 0 partitions peers
    # round-robin into domains "d0".."d{D-1}" and routes object stripes
    # through the placement ring (docs/placement.md).
    domains: int = 0
    # (at_seconds, domain_name) whole-domain kills (``killdomain@``).
    domain_kills: tuple = ()
    # (peer_idx, delay_s, jitter_s) per-peer straggler links (``slow@``,
    # milliseconds in the grammar, seconds here).
    slow_peers: tuple = ()
    # Hedged k+Δ gather fan-out on the object read path (``hedge=0``
    # is the A/B control run with the fan-out disabled).
    hedge: int = 1
    # Noisy-tenant multiplier (``noisy=M``): 0 = single "fleet" tenant;
    # M > 0 splits object/GET traffic into "noisy" (share M/(M+1)) and
    # "quiet" tenants for the QoS-isolation scenario.
    noisy: float = 0.0
    chaos_name: str = "clean"
    churn_peers: int = 0   # 0 = ~5% of the fleet when churn is scheduled
    chaos: ChaosProfile = field(default_factory=ChaosProfile)

    @classmethod
    def parse(cls, text: str) -> "FleetProfile":
        """Parse the CLI grammar (module docstring). Example::

            peers=200,fanout=6,msgs=500,chat=0.8,object=0.2,
            chaos=lossy,churn@2:4:0.5
        """
        kwargs: dict = {}
        chaos_tokens: list[str] = []
        chaos_name = "clean"
        for raw in text.split(","):
            tok = raw.strip()
            if not tok:
                continue
            if tok.startswith(_CHAOS_PASSTHROUGH):
                chaos_tokens.append(tok)
                continue
            if tok.startswith("lrc@"):
                g = int(tok[len("lrc@"):])
                if g < 1:
                    raise ValueError(
                        f"lrc@ group count must be >= 1, got {g}"
                    )
                kwargs["lrc_groups"] = g
                continue
            if tok.startswith("domains@"):
                d = int(tok[len("domains@"):])
                if d < 1:
                    raise ValueError(
                        f"domains@ count must be >= 1, got {d}"
                    )
                kwargs["domains"] = d
                continue
            if tok.startswith("killdomain@"):
                spec = tok[len("killdomain@"):]
                at_text, sep, name = spec.partition(":")
                if not sep or not name:
                    raise ValueError(
                        f"killdomain@ wants T:NAME, got {spec!r}"
                    )
                kills = kwargs.setdefault("domain_kills", [])
                kills.append((float(at_text), name.strip()))
                continue
            if tok.startswith("slow@"):
                parts = tok[len("slow@"):].split(":")
                if len(parts) not in (2, 3):
                    raise ValueError(
                        f"slow@ wants PEER:MS[:JITTER], got {tok!r}"
                    )
                idx = int(parts[0])
                delay_ms = float(parts[1])
                jitter_ms = float(parts[2]) if len(parts) == 3 else 0.0
                if idx < 0:
                    raise ValueError(
                        f"slow@ peer index must be >= 0, got {idx}"
                    )
                if delay_ms < 0 or jitter_ms < 0:
                    raise ValueError(
                        f"slow@ delay/jitter must be >= 0 ms, got {tok!r}"
                    )
                slows = kwargs.setdefault("slow_peers", [])
                slows.append((idx, delay_ms / 1000.0, jitter_ms / 1000.0))
                continue
            if "=" not in tok:
                raise ValueError(f"unparseable fleet token {tok!r}")
            key, _, val = tok.partition("=")
            key = key.strip()
            val = val.strip()
            if key == "chaos":
                if val not in NAMED_CHAOS:
                    raise ValueError(
                        f"unknown chaos profile {val!r}; named profiles: "
                        f"{sorted(NAMED_CHAOS)}"
                    )
                chaos_name = val
            elif key in _INT_KEYS:
                kwargs[key] = int(val)
            elif key in _FLOAT_KEYS:
                kwargs[key] = float(val)
            else:
                raise ValueError(f"unknown fleet knob {key!r}")
        base = NAMED_CHAOS[chaos_name]
        chaos_text = ",".join(
            ([base] if base else []) + chaos_tokens
        )
        chaos = (
            ChaosProfile.parse(chaos_text) if chaos_text else ChaosProfile()
        )
        if "domain_kills" in kwargs:
            kwargs["domain_kills"] = tuple(kwargs["domain_kills"])
        if "slow_peers" in kwargs:
            kwargs["slow_peers"] = tuple(kwargs["slow_peers"])
        prof = cls(chaos_name=chaos_name, chaos=chaos, **kwargs)
        prof.validate()
        return prof

    def validate(self) -> None:
        if self.peers < 2:
            raise ValueError(f"a fleet needs >= 2 peers, got {self.peers}")
        if not 1 <= self.fanout <= self.peers - 1:
            raise ValueError(
                f"fanout {self.fanout} outside [1, peers-1={self.peers - 1}]"
            )
        if min(self.chat, self.object, self.get, self.repair) < 0:
            raise ValueError("traffic weights must be non-negative")
        if self.chat + self.object + self.get + self.repair <= 0:
            raise ValueError("at least one traffic weight must be positive")
        if self.zipf_s <= 1.0:
            raise ValueError(f"zipf_s must be > 1, got {self.zipf_s}")
        if not 1 <= self.k <= self.n <= 256:
            raise ValueError(f"invalid fleet geometry k={self.k} n={self.n}")
        if self.lrc_groups:
            # The same parse-time contract service/tenants.py enforces:
            # groups divide k, and >= 1 global parity remains.
            if self.lrc_groups < 1 or self.k % self.lrc_groups:
                raise ValueError(
                    f"lrc@{self.lrc_groups} must divide k={self.k}"
                )
            if self.n - self.k - self.lrc_groups < 1:
                raise ValueError(
                    f"lrc@{self.lrc_groups} leaves no global parity "
                    f"(k={self.k}, n={self.n})"
                )
        if self.domains:
            # Parse-time geometry cover (the tenant-grammar pattern):
            # the ring places each stripe's shards on DISTINCT domains,
            # so fewer domains than the geometry needs can never place.
            from noise_ec_tpu.placement.ring import required_domains

            code = f"lrc:{self.lrc_groups}" if self.lrc_groups else "rs"
            need = required_domains(self.k, self.n, code)
            if self.domains < need:
                raise ValueError(
                    f"domains@{self.domains} cannot cover the active "
                    f"geometry (k={self.k}, n={self.n}, code={code}: "
                    f"needs >= {need} failure domains)"
                )
            if self.domains > self.peers:
                raise ValueError(
                    f"domains@{self.domains} exceeds peers={self.peers}"
                )
        valid_domains = {f"d{i}" for i in range(self.domains)}
        for at, name in self.domain_kills:
            if not self.domains:
                raise ValueError(
                    "killdomain@ requires a domains@D token"
                )
            if at < 0:
                raise ValueError(
                    f"killdomain@ time must be >= 0, got {at}"
                )
            if name not in valid_domains:
                raise ValueError(
                    f"killdomain@ names unknown domain {name!r} "
                    f"(domains@{self.domains} declares d0..d{self.domains - 1})"
                )
        for idx, delay_s, jitter_s in self.slow_peers:
            if not 0 <= idx < self.peers:
                raise ValueError(
                    f"slow@ peer index {idx} outside [0, peers-1="
                    f"{self.peers - 1}]"
                )
            if delay_s < 0 or jitter_s < 0:
                raise ValueError("slow@ delay/jitter must be >= 0")
        if self.hedge not in (0, 1):
            raise ValueError(f"hedge must be 0 or 1, got {self.hedge}")
        if self.noisy < 0:
            raise ValueError(f"noisy must be >= 0, got {self.noisy}")
        if self.msgs < 1:
            raise ValueError(f"msgs must be >= 1, got {self.msgs}")
        if self.stripe_bytes < self.k:
            raise ValueError(
                f"stripe_bytes {self.stripe_bytes} below k={self.k}"
            )

    def weights(self) -> dict[str, float]:
        """Normalized traffic-mix weights by kind."""
        total = self.chat + self.object + self.get + self.repair
        return {
            "chat": self.chat / total,
            "object": self.object / total,
            "get": self.get / total,
            "repair": self.repair / total,
        }

    def needs_stores(self) -> bool:
        """Object, GET or repair traffic requires per-peer stripe stores
        and the service layer."""
        return self.object > 0 or self.get > 0 or self.repair > 0
