"""The fleet lab: hundreds–thousands of lightweight in-process peers.

A :class:`FleetLab` is the load/chaos harness the ROADMAP's
"fleet-scale scenario harness" item calls for: every peer is a REAL
:class:`~noise_ec_tpu.host.plugin.ShardPlugin` (the full receive state
machine: pool, decode, Berlekamp–Welch repair, Ed25519 verify, stripe
store, SLO evaluator) behind a *network-shaped* shim — no subprocess,
no socket, no event loop per node. What scale costs is concentrated in
three shared structures:

- **cheap identity** — per-peer Ed25519 keys derived from the lab seed
  (``KeyPair.from_seed``), so a thousand identities cost a thousand
  hashes + keygens and the same seed reproduces every signature;
- **bounded-degree overlay** — each peer broadcasts to a fixed, seeded
  ``fanout``-sized neighbor set (real fleets are not full meshes; a
  1000-peer full mesh would be O(P²) deliveries per message);
- **one shared dispatcher** — deliveries ride a
  :class:`~noise_ec_tpu.host.transport._SerialDispatcher` keyed by
  (sender, receiver) link, exactly the TCP transport's per-sender
  ordered dispatch shape, via the BLOCKING ``submit_wait`` entry: a
  full link window makes the producer yield (backpressure), never drop.

Chaos composes per link: every directed edge gets its own seeded
:class:`~noise_ec_tpu.resilience.chaos.ChaosLink` (the proxy's pure
frame pipeline), so drop/corrupt/reorder/partition faults hit the
marshaled wire bytes with the same reproducibility contract as the TCP
chaos proxy. Churn reuses the ``ChaosProfile`` ``churn@`` primitive:
each churned peer expands its own seeded kill/restart schedule
(``churn_windows(stream=peer_index)``).

Admission (fleet-wide load shedding): a sender whose local SLO verdict
is degraded sheds new chat submissions with a Retry-After hint instead
of broadcasting (``noise_ec_fleet_shed_total{reason="slo"}``); object
traffic sheds through the object service's own PR-6 admission path
(:class:`~noise_ec_tpu.service.objects.ShedError`). The scorer counts
shed separately from lost (fleet/score.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
import logging
import struct
import threading
import time
import weakref
from collections import deque
from typing import Optional

import numpy as np

from noise_ec_tpu.fleet.profile import FleetProfile
from noise_ec_tpu.fleet.score import FleetScorer
from noise_ec_tpu.host.crypto import KeyPair, PeerID
from noise_ec_tpu.host.plugin import ShardPlugin
from noise_ec_tpu.host.transport import Ctx, _SerialDispatcher
from noise_ec_tpu.host.wire import Shard, WireError
from noise_ec_tpu.obs.health import SLOEvaluator
from noise_ec_tpu.obs.registry import default_registry
from noise_ec_tpu.obs.trace import default_tracer
from noise_ec_tpu.resilience.chaos import ChaosLink

__all__ = ["FleetLab", "FleetPeer"]

log = logging.getLogger("noise_ec_tpu.fleet")

# Chat payload header: magic + u32 msg_id, then seeded filler. The
# receiver callback matches deliveries back to submissions by it; any
# verified object without the magic (object stripes, manifests) is
# simply not a scored chat message.
_HDR = b"FLT1"
_HDR_LEN = len(_HDR) + 4

# How far ahead churn schedules expand (run horizon; soaks are minutes).
CHURN_HORIZON = 3600.0


class FleetPeer:
    """One lightweight in-process node (module docstring).

    Network-shaped: exposes ``id`` / ``keys`` / ``broadcast`` — the
    slice of the transport surface ``ShardPlugin`` and the object
    service drive — so the production plugin runs unmodified."""

    def __init__(self, lab: "FleetLab", idx: int, keys: KeyPair,
                 profile: FleetProfile):
        self._lab = weakref.ref(lab)
        self.idx = idx
        self.keys = keys
        self.id = PeerID.create(f"fleet://{idx}", keys.public_key)
        self.up = True
        self.kill_times: list[float] = []
        self.neighbors: tuple[int, ...] = ()
        # Tolerant targets: a corrupted-then-BW-repaired message records
        # verify_failed AND ok, so a strict 0.99 success target would
        # shed on every transient corruption; shedding should gate on
        # SUSTAINED degradation (the scorer owns final-delivery truth).
        self.slo = SLOEvaluator(
            window_seconds=15.0, min_events=8,
            success_rate_target=lab.slo_success_target,
            p99_target_seconds=lab.p99_target_seconds,
        )
        self.store = None
        self.objects = None
        # Seeded per-call jitter stream for slow@ placement fetches.
        self._fetch_seq = itertools.count()
        if profile.needs_stores():
            from noise_ec_tpu.store import StripeStore

            self.store = StripeStore(backend="numpy")
        self.plugin = ShardPlugin(
            backend="numpy",
            minimum_needed_shards=profile.k,
            total_shards=profile.n,
            on_message=self._on_message,
            store=self.store,
            slo=self.slo,
        )
        # NACK repair needs a directed transport (send_to) and its
        # broadcast rounds would multiply fleet traffic; parity plus the
        # scorer's explicit loss accounting own the loss story here.
        self.plugin.nack_grace_seconds = 0.0
        if self.store is not None:
            from noise_ec_tpu.service import DecodedObjectCache, ObjectStore

            # Hot-read (get=) traffic exercises the decoded-stripe
            # cache tiers; a modest per-peer ceiling keeps a thousand
            # peers' caches bounded.
            cache = (
                DecodedObjectCache(max_bytes=8 << 20)
                if profile.get > 0 else None
            )
            self.objects = ObjectStore(
                self.store, self.plugin, self,
                stripe_bytes=profile.stripe_bytes,
                k=profile.k, n=profile.n,
                slo=self.slo,
                cache=cache,
                # A below-k stripe with no repair engine cannot heal;
                # fail reads fast instead of stalling the scorer.
                fetch_timeout_seconds=0.2,
                hedge_enabled=bool(profile.hedge),
            )

    # ---- the network surface the plugin drives

    def broadcast(self, msg: Shard) -> None:
        lab = self._lab()
        if lab is not None:
            lab.hub.fan_out(self, msg.marshal())

    # ---- placement surfaces (docs/placement.md): the directed slice a
    # TargetedDelivery probes for. Handles are peer indices; tokens are
    # the ring topology's "fleet://idx" addresses.

    def send_many_to(self, handle, msgs) -> bool:
        lab = self._lab()
        if lab is None:
            return False
        return lab.hub.send_direct(
            self, int(handle), [m.marshal() for m in msgs]
        )

    def placement_directory(self) -> dict:
        lab = self._lab()
        if lab is None:
            return {}
        return {
            f"fleet://{p.idx}": p.idx
            for p in lab.peers
            if p.up and p.idx != self.idx
        }

    def placement_fetch(self, handle, key) -> dict:
        """Owner-slot fetch for the gather read path: a direct snapshot
        of the target peer's store (the lab's stand-in for a directed
        fetch RPC). Raises for a down/storeless peer — the gather
        degrades per-owner. A ``slow@`` endpoint pays its declared link
        delay here too, so the hedged gather actually races the slow
        source (docs/fleet.md)."""
        lab = self._lab()
        if lab is None:
            raise RuntimeError("lab is gone")
        peer = lab.peers[int(handle)]
        if not peer.up or peer.store is None:
            raise RuntimeError(f"peer {handle} is down")
        delay, jitter = lab.slow_edge(self.idx, peer.idx)
        if delay or jitter:
            u = 0.0
            if jitter:
                # Seeded, call-indexed jitter keeps slow fetches
                # reproducible across runs with the same lab seed.
                draw = hashlib.blake2b(
                    struct.pack(
                        "<QIIQ", lab.seed & (2**64 - 1),
                        self.idx, peer.idx, next(self._fetch_seq),
                    ),
                    digest_size=8,
                ).digest()
                u = int.from_bytes(draw, "little") / 2.0**64
            # Same cap as the hub's _deliver: a mis-profiled delay must
            # not wedge a gather worker for seconds.
            time.sleep(min(delay + jitter * u, 0.25))
        _, shards, _ = peer.store.snapshot(key)
        return {i: b for i, b in enumerate(shards) if b is not None}

    def _on_message(self, message: bytes, sender: PeerID) -> None:
        if len(message) < _HDR_LEN or message[:4] != _HDR:
            return  # an object stripe / manifest, not a scored chat
        (msg_id,) = struct.unpack_from("<I", message, 4)
        lab = self._lab()
        if lab is not None:
            lab.scorer.deliver(msg_id, self.idx)


class FleetHub:
    """Link fabric + shared delivery dispatcher (module docstring)."""

    def __init__(self, lab: "FleetLab", workers: int, link_window: int):
        self._lab = weakref.ref(lab)
        self.dispatch = _SerialDispatcher(
            max_workers=workers, max_queue=link_window,
            on_error=lab._record_error,
        )
        self.links: dict[tuple[int, int], ChaosLink] = {}
        self.frame_errors = 0
        self.dropped = 0  # submit_wait timeouts (counted as overflow too)
        # Per-receiver wire sends (pre-chaos): broadcast fan_out and
        # directed send_direct both count, so targeted delivery's
        # peers×→n× cut reads straight off this (bench.py's
        # placement_fanout_ratio).
        self.sends = 0
        self._t0 = time.monotonic()

    def now(self) -> float:
        return time.monotonic() - self._t0

    def fan_out(self, sender: FleetPeer, wire: bytes) -> None:
        """Deliver one marshaled shard to the sender's up-neighbors
        through each link's chaos pipeline. Runs on the driver thread —
        a full link window BLOCKS here (submit_wait), which is the
        transport tier of the backpressure chain."""
        lab = self._lab()
        if lab is None:
            return
        now = self.now()
        for ridx in sender.neighbors:
            receiver = lab.peers[ridx]
            if not receiver.up:
                continue
            self.sends += 1
            link = self.links[(sender.idx, ridx)]
            for buf, delay in link.admit(wire, now):
                if not self.dispatch.submit_wait(
                    struct.pack("<II", sender.idx, ridx),
                    self._deliver, receiver, buf, sender.id, delay,
                ):
                    self.dropped += 1

    def send_direct(self, sender: FleetPeer, ridx: int, wires) -> bool:
        """Directed delivery to ONE peer — the placement layer's
        targeted cohort path and the rebalancer's shard mover. Links to
        non-neighbor targets are created lazily with the SAME seeded
        chaos pipeline (conn_id keeps the (sender, receiver) derivation
        fan_out uses), so targeted traffic faces identical fault odds.
        Returns False when the receiver is down (or the lab is gone)."""
        lab = self._lab()
        if lab is None:
            return False
        receiver = lab.peers[ridx]
        if not receiver.up:
            return False
        link = self.links.get((sender.idx, ridx))
        if link is None:
            conn_id = sender.idx * len(lab.peers) + ridx
            link = self.links.setdefault(
                (sender.idx, ridx),
                ChaosLink(
                    lab.link_chaos(sender.idx, ridx),
                    lab.seed, conn_id, "a2b",
                ),
            )
        now = self.now()
        ok = True
        for wire in wires:
            self.sends += 1
            for buf, delay in link.admit(wire, now):
                if not self.dispatch.submit_wait(
                    struct.pack("<II", sender.idx, ridx),
                    self._deliver, receiver, buf, sender.id, delay,
                ):
                    self.dropped += 1
                    ok = False
        return ok

    def _deliver(self, receiver: FleetPeer, buf: bytes, sender_pid: PeerID,
                 delay: float) -> None:
        if delay > 0:
            # Link delay/bandwidth shaping; capped so a mis-profiled
            # delay cannot wedge a dispatch worker.
            time.sleep(min(delay, 0.25))
        if not receiver.up:
            return  # killed mid-flight; the scorer classifies it churned
        try:
            msg = Shard.unmarshal(buf)
        except WireError:
            self.frame_errors += 1  # corrupt-faulted frame
            return
        lab = self._lab()
        try:
            receiver.plugin.receive(Ctx(msg, sender_pid))
        except Exception as exc:  # noqa: BLE001 — isolate the fabric
            if lab is not None:
                lab._record_error(exc)

    def chaos_stats(self) -> dict:
        agg: dict[str, int] = {}
        for link in self.links.values():
            for key, val in link.stats().items():
                agg[key] = agg.get(key, 0) + val
        agg["frame_errors"] = self.frame_errors
        agg["window_timeouts"] = self.dropped
        return agg


class FleetLab:
    """Build, drive, score and report one fleet run (module docstring).

    Lifecycle: ``start()`` (peers, topology, links, churn schedule) →
    ``run()`` (drive the traffic mix, wait for drain, return the scored
    report) → optionally ``write_report`` / ``write_trace`` →
    ``close()``. ``attach(stats_server)`` mounts ``GET /fleet`` and
    folds the live fleet block into ``/healthz`` details.
    """

    def __init__(
        self,
        profile: FleetProfile,
        *,
        size: Optional[int] = None,
        seed: int = 0,
        p99_target_seconds: float = 2.0,
        slo_success_target: float = 0.85,
        dispatch_workers: int = 4,
        link_window: int = 512,
        shed_retry_after: float = 2.0,
        rebalance_rate_bytes_per_s: float = 4 << 20,
        rebalance_burst_bytes: int = 8 << 20,
    ):
        if size is not None:
            profile = dataclasses.replace(profile, peers=size)
            profile.validate()
        self.profile = profile
        self.seed = seed
        # slow@PEER:MS[:JITTER] → {peer index: (delay_s, jitter_s)};
        # every link touching a slow peer pays the extra delay.
        self._slow = {
            int(idx): (float(d), float(j))
            for idx, d, j in profile.slow_peers
        }
        self.p99_target_seconds = p99_target_seconds
        self.slo_success_target = slo_success_target
        self.dispatch_workers = dispatch_workers
        self.link_window = link_window
        self.shed_retry_after = shed_retry_after
        self.rebalance_rate_bytes_per_s = rebalance_rate_bytes_per_s
        self.rebalance_burst_bytes = rebalance_burst_bytes
        self.peers: list[FleetPeer] = []
        self.hub: Optional[FleetHub] = None
        # Placement ring state (profile ``domains@D``; docs/placement.md):
        # one shared Topology + PlacementRing, a TargetedDelivery per
        # peer plugin, and a Rebalancer per store-carrying peer.
        self.topology = None
        self.ring = None
        self.rebalancers: dict[int, object] = {}
        self.federator = None  # built by build_federator()/attach()
        self.scorer = FleetScorer()
        self.errors: deque = deque(maxlen=256)
        self.error_count = 0
        self.last_report: Optional[dict] = None
        # Put-object ledger for the hot-read (get=) mix: zipfian GETs
        # draw from what the run has already stored.
        self._obj_lock = threading.Lock()
        self._put_objects: list[tuple[str, str, bytes]] = []
        self.get_results = {"ok": 0, "bad": 0, "missing": 0, "shed": 0}
        self._churn_events: list[tuple[float, str, int]] = []
        self._churn_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._started = False
        reg = default_registry()
        self._churn_kill = reg.counter(
            "noise_ec_fleet_churn_events_total"
        ).labels(event="kill")
        self._churn_restart = reg.counter(
            "noise_ec_fleet_churn_events_total"
        ).labels(event="restart")
        ref = weakref.ref(self)
        reg.gauge("noise_ec_fleet_peers").set_callback(
            lambda: _count_peers(ref, up=True), state="up"
        )
        reg.gauge("noise_ec_fleet_peers").set_callback(
            lambda: _count_peers(ref, up=False), state="down"
        )

    def _record_error(self, exc: Exception) -> None:
        self.errors.append(exc)
        self.error_count += 1

    # ---- slow-peer link shaping (profile ``slow@PEER:MS[:JITTER]``)

    def slow_edge(self, a_idx: int, b_idx: int) -> tuple:
        """``(delay_s, jitter_s)`` the profile's ``slow@`` entries add
        to the a↔b link — (0, 0) unless an endpoint is slow; the larger
        delay wins when both are."""
        best = (0.0, 0.0)
        for idx in (a_idx, b_idx):
            entry = self._slow.get(idx)
            if entry is not None and entry[0] >= best[0]:
                best = entry
        return best

    def link_chaos(self, a_idx: int, b_idx: int):
        """The chaos profile for the directed link a→b: the base
        profile, plus any ``slow@`` delay/jitter when either endpoint
        is a declared slow peer. Links between two fast peers share the
        unmodified base profile object."""
        delay, jitter = self.slow_edge(a_idx, b_idx)
        base = self.profile.chaos
        if not delay and not jitter:
            return base
        return dataclasses.replace(
            base, delay=base.delay + delay, jitter=base.jitter + jitter
        )

    # -------------------------------------------------------------- build

    def start(self) -> "FleetLab":
        if self._started:
            return self
        self._started = True
        prof = self.profile
        # Cheap, reproducible identities: one blake2b per peer seeds its
        # Ed25519 keypair.
        for idx in range(prof.peers):
            seed32 = hashlib.blake2b(
                b"noise-ec-fleet\0" + struct.pack("<QI", self.seed & (2**64 - 1), idx),
                digest_size=32,
            ).digest()
            self.peers.append(
                FleetPeer(self, idx, KeyPair.from_seed(seed32), prof)
            )
        # Bounded-degree overlay: each peer draws `fanout` distinct
        # neighbors from one seeded stream.
        topo_rng = np.random.default_rng(
            np.random.SeedSequence([self.seed & 0xFFFFFFFF, 0x70B0])
        )
        for peer in self.peers:
            others = [i for i in range(prof.peers) if i != peer.idx]
            picks = topo_rng.choice(
                len(others), size=prof.fanout, replace=False
            )
            peer.neighbors = tuple(others[int(i)] for i in picks)
        self.hub = FleetHub(
            self, workers=self.dispatch_workers,
            link_window=self.link_window,
        )
        for peer in self.peers:
            for ridx in peer.neighbors:
                conn_id = peer.idx * prof.peers + ridx
                self.hub.links[(peer.idx, ridx)] = ChaosLink(
                    self.link_chaos(peer.idx, ridx),
                    self.seed, conn_id, "a2b",
                )
        if prof.domains:
            self._build_placement()
        if prof.chaos.churns:
            self._schedule_churn()
        if prof.domain_kills:
            events = list(self._churn_events)
            for at, name in prof.domain_kills:
                for token in self.topology.peers_of(name):
                    events.append(
                        (at, "kill", int(token.rsplit("//", 1)[1]))
                    )
            self._churn_events = sorted(events)
        log.info(
            "fleet lab: %d peers, fanout %d, %d links, chaos=%s%s",
            prof.peers, prof.fanout, len(self.hub.links), prof.chaos_name,
            f", churn over {len(set(i for _, _, i in self._churn_events))} "
            "peer(s)" if self._churn_events else "",
        )
        return self

    def _build_placement(self) -> None:
        """Partition the peers round-robin into the profile's failure
        domains ("d0".."d{D-1}": domain j holds peers j, j+D, ...), build
        ONE shared :class:`PlacementRing` (every node must compute the
        same maps), wire a TargetedDelivery per plugin and a Rebalancer
        per store-carrying peer, and register the per-domain
        ``noise_ec_placement_shards`` gauges."""
        from noise_ec_tpu.placement import (
            PlacementRing, TargetedDelivery, Topology,
        )
        from noise_ec_tpu.placement.rebalance import (
            Rebalancer, register_domain_gauges,
        )

        prof = self.profile
        domains = tuple(
            (
                f"d{j}",
                tuple(
                    f"fleet://{i}" for i in range(j, prof.peers, prof.domains)
                ),
            )
            for j in range(prof.domains)
        )
        weights = {tok: 1.0 for _, toks in domains for tok in toks}
        self.topology = Topology(domains=domains, weights=weights)
        self.ring = PlacementRing(self.topology, seed=self.seed)
        for peer in self.peers:
            token = f"fleet://{peer.idx}"
            peer.plugin.placement = TargetedDelivery(
                self.ring, self_token=token,
                hedge=bool(prof.hedge),
            )
            if peer.store is not None:
                self.rebalancers[peer.idx] = Rebalancer(
                    peer.store, self.ring,
                    self_token=token,
                    send=self._rebalance_send(peer),
                    rate_bytes_per_s=self.rebalance_rate_bytes_per_s,
                    burst_bytes=self.rebalance_burst_bytes,
                    self_public_key=peer.keys.public_key,
                )
        ref = weakref.ref(self)
        register_domain_gauges(
            lambda d: _placement_census(ref, d), self.topology.names()
        )

    def _rebalance_send(self, peer: FleetPeer):
        """The rebalancer's directed transport: topology token →
        peer index → the hub's chaos-faithful ``send_direct``."""
        ref = weakref.ref(peer)

        def send(token: str, msgs) -> bool:
            p = ref()
            if p is None or not p.up:
                return False
            return p.send_many_to(int(token.rsplit("//", 1)[1]), msgs)

        return send

    def _schedule_churn(self) -> None:
        prof = self.profile
        count = prof.churn_peers or max(1, prof.peers // 20)
        count = min(count, prof.peers)
        churn_rng = np.random.default_rng(
            np.random.SeedSequence([self.seed & 0xFFFFFFFF, 0xC0C0])
        )
        churned = churn_rng.choice(prof.peers, size=count, replace=False)
        events: list[tuple[float, str, int]] = []
        for idx in sorted(int(i) for i in churned):
            for start, down in prof.chaos.churn_windows(
                self.seed, horizon=CHURN_HORIZON, stream=idx
            ):
                events.append((start, "kill", idx))
                events.append((start + down, "restart", idx))
        self._churn_events = sorted(events)

    def _churn_run(self) -> None:
        hub = self.hub
        for t, event, idx in self._churn_events:
            delay = t - hub.now()
            if delay > 0 and self._stop.wait(delay):
                return
            peer = self.peers[idx]
            if event == "kill":
                peer.up = False
                peer.kill_times.append(time.monotonic())
                self._churn_kill.add(1)
            else:
                peer.up = True
                self._churn_restart.add(1)

    # --------------------------------------------------------------- drive

    def run(self, drain_timeout: float = 60.0) -> dict:
        """Drive the profile's traffic mix to completion, wait for the
        delivery fabric to drain, verify object GETs, and return the
        scored report."""
        if not self._started:
            self.start()
        prof = self.profile
        if self._churn_events and self._churn_thread is None:
            self._churn_thread = threading.Thread(
                target=self._churn_run, name="noise-ec-fleet-churn",
                daemon=True,
            )
            self._churn_thread.start()
        t0 = time.monotonic()
        sender_idxs = list(range(prof.peers))
        if prof.senders:
            sender_idxs = sender_idxs[: prof.senders]
        n_drivers = prof.drivers or min(4, len(sender_idxs))
        # Disjoint sender partitions keep per-link frame order owned by
        # exactly one thread — the chaos reproducibility contract.
        partitions = [sender_idxs[d::n_drivers] for d in range(n_drivers)]
        quotas = [
            prof.msgs // n_drivers + (1 if d < prof.msgs % n_drivers else 0)
            for d in range(n_drivers)
        ]
        threads = [
            threading.Thread(
                target=self._drive, name=f"noise-ec-fleet-drive-{d}",
                args=(partitions[d], quotas[d], d), daemon=True,
            )
            for d in range(n_drivers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self._wait_drained(drain_timeout)
        self._verify_objects()
        duration = time.monotonic() - t0
        report = self.scorer.report(
            {p.idx: list(p.kill_times) for p in self.peers}, duration
        )
        report["peers"] = prof.peers
        report["fanout"] = prof.fanout
        report["chaos_profile"] = prof.chaos_name
        report["chaos"] = self.hub.chaos_stats()
        report["churn"] = {
            "scheduled": len(self._churn_events),
            "kills_applied": sum(len(p.kill_times) for p in self.peers),
        }
        report["errors"] = self.error_count
        report["backpressure_waits"] = _backpressure_waits()
        report["gets"] = dict(self.get_results)
        report["wire_sends"] = self.hub.sends
        reg = default_registry()
        report["hedge"] = {
            key: int(reg.counter(f"noise_ec_hedge_{key}_total")
                     .labels().value)
            for key in ("requests", "wins", "cancelled", "late")
        }
        if self.ring is not None:
            self.scorer.note_placement({
                "domains": self.profile.domains,
                "census": self.placement_census(),
            })
            report["placement"] = dict(self.scorer.placement)
        if self.federator is not None:
            try:
                self.federator.scrape()
                fams = self.federator.merged_families()
                report["fleet_metrics"] = {
                    "targets": len(self.federator.sources),
                    "series": sum(len(f["samples"]) for f in fams),
                }
            except Exception as exc:  # noqa: BLE001 — federation is
                # telemetry; a merge failure must not sink the report
                self._record_error(exc)
        self.last_report = report
        return report

    def _drive(self, senders: list[int], quota: int, stream: int) -> None:
        prof = self.profile
        rng = np.random.default_rng(
            np.random.SeedSequence(
                [self.seed & 0xFFFFFFFF, 0xD21F, stream]
            )
        )
        weights = prof.weights()
        cuts = (
            weights["chat"],
            weights["chat"] + weights["object"],
            weights["chat"] + weights["object"] + weights["get"],
        )
        si = 0
        for _ in range(quota):
            peer = None
            for _ in range(len(senders)):
                cand = self.peers[senders[si % len(senders)]]
                si += 1
                if cand.up:
                    peer = cand
                    break
            if peer is None:
                continue  # every sender in this partition is down
            roll = float(rng.random())
            try:
                if roll < cuts[0] or (peer.objects is None and prof.chat > 0):
                    self.submit_chat(peer, rng)
                elif roll < cuts[1]:
                    self.submit_object(peer, rng)
                elif roll < cuts[2]:
                    self.submit_get(peer, rng)
                else:
                    self.submit_repair(peer, rng)
            except Exception as exc:  # noqa: BLE001 — one bad submission
                # must not end the driver
                self._record_error(exc)
            if prof.rate > 0:
                time.sleep(1.0 / prof.rate)

    # ---- submission kinds (public: tests drive custom patterns)

    def _expected(self, sender: FleetPeer, stores_only: bool = False) -> tuple:
        return tuple(
            r for r in sender.neighbors
            if self.peers[r].up
            and (not stores_only or self.peers[r].objects is not None)
        )

    def submit_chat(self, sender: FleetPeer, rng) -> Optional[int]:
        """One chat-sized broadcast; returns the msg_id or None when
        shed/skipped. Admission: a degraded local SLO verdict sheds the
        submission with a Retry-After hint (scored separately)."""
        if not sender.up:
            return None
        if not sender.slo.verdict()["healthy"]:
            self.scorer.shed(
                "chat", sender.idx, "slo", self.shed_retry_after
            )
            return None
        expected = self._expected(sender)
        msg_id = self.scorer.begin("chat", sender.idx, expected)
        prof = self.profile
        body = _HDR + struct.pack("<I", msg_id)
        fill = max(0, prof.chat_bytes - len(body))
        payload = body + rng.bytes(fill)
        pad = (-len(payload)) % prof.k
        payload += bytes(pad)
        sender.plugin.shard_and_broadcast(
            sender, payload, geometry=(prof.k, prof.n)
        )
        return msg_id

    def submit_object(self, sender: FleetPeer, rng) -> Optional[int]:
        """One object PUT through the service layer; the matching GETs
        are verified from every expected receiver's service after the
        run (fleet/score.py)."""
        if not sender.up or sender.objects is None:
            return None
        from noise_ec_tpu.service.objects import ShedError

        prof = self.profile
        payload = rng.bytes(prof.object_bytes)
        expected = self._expected(sender, stores_only=True)
        name = f"o{sender.idx}-{int(rng.integers(0, 2**31))}"
        tenant = "fleet"
        if prof.noisy > 0:
            # noisy=M tenant mix: M noisy submissions per quiet one, so
            # the noisy share is M/(M+1). The per-tenant op histograms
            # plus the scorer's independent tenant_get samples are what
            # the QoS-isolation scenario reads back (docs/fleet.md).
            share = prof.noisy / (prof.noisy + 1.0)
            tenant = "noisy" if float(rng.random()) < share else "quiet"
        try:
            sender.objects.put(tenant, name, payload)
        except ShedError as exc:
            self.scorer.shed("object", sender.idx, exc.reason,
                             exc.retry_after)
            return None
        msg_id = self.scorer.begin("object", sender.idx, expected)
        digest = hashlib.blake2b(payload, digest_size=16).digest()
        self.scorer.add_object(msg_id, tenant, name, digest)
        with self._obj_lock:
            self._put_objects.append((tenant, name, digest))
        return msg_id

    def submit_get(self, peer: FleetPeer, rng) -> None:
        """One hot-read GET: a zipfian-popular already-put object read
        back through ``peer``'s service layer (the decoded-cache tiers;
        repeated draws of the same hot object hit the peer's cache).
        Not delivery-scored — the run ledger's byte-digest check owns
        read correctness, ``get_results`` reports the outcome mix."""
        if peer.objects is None:
            self.submit_chat(peer, rng)
            return
        with self._obj_lock:
            objs = list(self._put_objects)
        if not objs:
            self.submit_chat(peer, rng)
            return
        from noise_ec_tpu.service.objects import (
            ShedError, UnknownObjectError,
        )

        # Zipf rank (s > 1) over the put ledger: rank 1 = the hottest.
        rank = int(rng.zipf(self.profile.zipf_s))
        tenant, name, digest = objs[(rank - 1) % len(objs)]
        default_registry().counter(
            "noise_ec_fleet_messages_total"
        ).labels(kind="get").add(1)
        t0 = time.monotonic()
        try:
            data = peer.objects.read(tenant, name)
        except ShedError as exc:
            self.get_results["shed"] += 1
            self.scorer.shed("get", peer.idx, exc.reason, exc.retry_after)
        except UnknownObjectError:
            # Manifest never replicated to this peer (bounded-degree
            # overlay): the read failed at resolve, BEFORE the op
            # histogram's timing scope — no scorer sample either, so
            # the two per-tenant views stay aligned.
            self.get_results["missing"] += 1
        except Exception:  # noqa: BLE001 — a below-k/unavailable read;
            # delivery scoring owns loss accounting, not the GET mix.
            # The op histogram DID time this read (its finally runs on
            # the unavailable path), so record the same wall time here —
            # the scorer's per-tenant p99 and the fleet-merged histogram
            # p99 must estimate the same sample set.
            self.scorer.tenant_get(tenant, time.monotonic() - t0)
            self.get_results["missing"] += 1
        else:
            # Scorer-side wall time for the same read the tenant-labeled
            # op histogram observed — the independent per-tenant p99.
            self.scorer.tenant_get(tenant, time.monotonic() - t0)
            ok = hashlib.blake2b(data, digest_size=16).digest() == digest
            self.get_results["ok" if ok else "bad"] += 1

    def submit_repair(self, sender: FleetPeer, rng) -> None:
        """One repair-storm op: drop a shard from a random stored stripe
        and degraded-read it back through the codec (success/failure is
        scored; falls back to chat while the store is still empty).
        With ``lrc@G`` in the profile the op runs on the LRC tier
        instead: a seeded LRC(k, G, n-k-G) stripe loses one data shard
        and the degraded read heals it from its ~k/G-member group cell
        (codec/lrc.py local tier) — the fleet-scale proof that cheap
        repair holds under the chaos profile."""
        if sender.store is None:
            self.submit_chat(sender, rng)
            return
        prof = self.profile
        if prof.lrc_groups:
            key = self._ensure_lrc_stripe(sender, rng)
            try:
                sender.store.drop_shard(
                    key, int(rng.integers(0, prof.k))
                )
                sender.store.read(key)  # local-tier heal
            except Exception as exc:  # noqa: BLE001 — scored, not raised
                self.scorer.repair_result(False)
                self._record_error(exc)
            else:
                self.scorer.repair_result(True)
            return
        keys = sender.store.keys()
        if not keys:
            self.submit_chat(sender, rng)
            return
        key = keys[int(rng.integers(0, len(keys)))]
        try:
            sender.store.drop_shard(
                key, int(rng.integers(0, self.profile.k))
            )
            sender.store.read(key)  # degraded read reconstructs
        except Exception as exc:  # noqa: BLE001 — scored, not raised
            self.scorer.repair_result(False)
            self._record_error(exc)
        else:
            self.scorer.repair_result(True)

    def _ensure_lrc_stripe(self, sender: FleetPeer, rng) -> str:
        """The peer's store-local LRC stripe for the repair mix (lazily
        created, seeded payload). LRC stripes are a STORE tier — the
        wire path stays plain RS — so the repair op puts directly."""
        keys = getattr(sender, "_lrc_keys", None)
        if keys:
            return keys[int(rng.integers(0, len(keys)))]
        prof = self.profile
        gs_bytes = max(prof.k, 512)
        payload = rng.bytes(prof.k * gs_bytes)
        sig = hashlib.blake2b(
            b"noise-ec-fleet-lrc\0" + struct.pack("<I", sender.idx),
            digest_size=32,
        ).digest()
        key = sender.store.put_object(
            sig, payload, prof.k, prof.n,
            code=f"lrc:{prof.lrc_groups}",
        )
        sender._lrc_keys = [key]
        return key

    # ---- placement/rebalance drivers (tests and bench call these)

    def kill_domain(self, name: str) -> int:
        """Kill EVERY peer in failure domain ``name`` at once (the
        ``killdomain@`` drill, callable directly); returns how many
        peers went down. Killed peers count as churned in scoring
        (kill_times), exactly like ``churn@`` kills."""
        if self.topology is None:
            raise RuntimeError("kill_domain needs a domains@ profile")
        downed = 0
        for token in self.topology.peers_of(name):
            peer = self.peers[int(token.rsplit("//", 1)[1])]
            if peer.up:
                peer.up = False
                peer.kill_times.append(time.monotonic())
                self._churn_kill.add(1)
                downed += 1
        return downed

    def restart_domain(self, name: str) -> int:
        """Bring every peer in domain ``name`` back up."""
        if self.topology is None:
            raise RuntimeError("restart_domain needs a domains@ profile")
        restarted = 0
        for token in self.topology.peers_of(name):
            peer = self.peers[int(token.rsplit("//", 1)[1])]
            if not peer.up:
                peer.up = True
                self._churn_restart.add(1)
                restarted += 1
        return restarted

    def placement_census(self) -> dict:
        """``{domain: in-place shard count}`` across the UP peers — the
        numbers the per-domain gauges export and rebalance convergence
        settles (docs/placement.md)."""
        if self.ring is None:
            return {}
        from noise_ec_tpu.placement.rebalance import domain_census

        holdings = [
            (f"fleet://{p.idx}", p.store)
            for p in self.peers
            if p.up and p.store is not None
        ]
        return domain_census(self.ring, holdings)

    def rebalance_cycle(self) -> dict:
        """One rebalance pass across every up store-carrying peer: sync
        each Rebalancer's alive view to the lab's authoritative up set,
        run its cycle, and drain the resulting moves. Returns the
        aggregated cycle stats."""
        agg = {"examined": 0, "moved": 0, "deferred": 0, "dropped": 0}
        alive = {f"fleet://{p.idx}" for p in self.peers if p.up}
        for idx, rb in self.rebalancers.items():
            if not self.peers[idx].up:
                continue
            rb.set_alive(alive)
            stats = rb.run_cycle()
            for key in agg:
                agg[key] += stats.get(key, 0)
        self._wait_drained(10.0)
        return agg

    def rebalance_until_converged(self, max_cycles: int = 8) -> dict:
        """Run rebalance cycles until one completes with nothing moved
        or deferred (converged) or the cycle budget runs out; returns
        the LAST cycle's aggregate plus the cycle count and the total
        bytes every rebalancer has moved."""
        stats: dict = {}
        cycles = 0
        for _ in range(max_cycles):
            stats = self.rebalance_cycle()
            cycles += 1
            if not stats["moved"] and not stats["deferred"]:
                break
        stats["cycles"] = cycles
        stats["bytes_moved"] = sum(
            rb.bytes_moved for rb in self.rebalancers.values()
        )
        self.scorer.note_placement({"rebalance": dict(stats)})
        return stats

    def _wait_drained(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        idle_since = None
        while time.monotonic() < deadline:
            if self.hub.dispatch.queue_depth() == 0:
                if idle_since is None:
                    idle_since = time.monotonic()
                elif time.monotonic() - idle_since > 0.25:
                    return
            else:
                idle_since = None
            time.sleep(0.02)

    def _verify_objects(self) -> None:
        """Post-run GET verification: every expected receiver must serve
        each put object byte-identical through its own service layer."""
        from noise_ec_tpu.service.objects import UnknownObjectError

        with self.scorer._lock:
            objects = dict(self.scorer.objects)
            sent = {m: dict(r) for m, r in self.scorer.sent.items()}
        for msg_id, obj in objects.items():
            rec = sent.get(msg_id)
            if rec is None:
                continue
            for ridx in rec["expected"]:
                receiver = self.peers[ridx]
                if receiver.objects is None:
                    continue
                t0 = time.monotonic()
                try:
                    # shed=False: post-run verification must measure
                    # REPLICATION, not a receiver's late-window load
                    # verdict refusing the read.
                    data = receiver.objects.read(
                        obj["tenant"], obj["name"], shed=False
                    )
                except UnknownObjectError:
                    continue  # not delivered; no op-histogram sample
                except Exception:  # noqa: BLE001 — not delivered, but
                    # the op histogram timed the failed read: mirror it
                    # so scorer and histogram p99 stay comparable.
                    self.scorer.tenant_get(
                        obj["tenant"], time.monotonic() - t0
                    )
                    continue
                # Verification reads land in the tenant-labeled op
                # histogram too; keep the scorer's sample set aligned.
                self.scorer.tenant_get(
                    obj["tenant"], time.monotonic() - t0
                )
                digest = hashlib.blake2b(data, digest_size=16).digest()
                if digest == obj["digest"]:
                    # Latency is not meaningful for a post-run read;
                    # stamp the send time so it lands as 0 and the
                    # report's latency stats skip it.
                    self.scorer.deliver(msg_id, ridx, now=rec["t"])

    # ------------------------------------------------------------ surfaces

    def health_block(self) -> dict:
        """The ``fleet`` block folded into ``/healthz`` details while a
        lab is attached (docs/fleet.md)."""
        snap = self.scorer.snapshot()
        up = sum(1 for p in self.peers if p.up)
        expected = snap["expected_deliveries"]
        return {
            "peers": len(self.peers),
            "up": up,
            "down": len(self.peers) - up,
            "sent": snap["sent"],
            "delivered": snap["delivered"],
            "shed": snap["shed"],
            "delivery_rate": round(
                snap["delivered"] / max(1, expected), 6
            ),
        }

    def build_federator(self):
        """The lab's :class:`~noise_ec_tpu.obs.federate.MetricsFederator`
        over one scrape source per peer (built once; requires
        ``start()``).

        Lab peers share the ONE process registry, so each source serves
        the same exposition document and the merged fleet view
        multiplies every count by the number of reachable peers —
        histogram *quantiles* are scale-invariant under that
        multiplication, so fleet p50/p99 read off ``/fleet/metrics``
        exactly as they would from genuinely separate nodes (the lab
        limitation is counts, not latencies; docs/fleet.md).

        Chaos couples in: each source fails with the profile's ``drop``
        probability from its own seeded stream (``clean`` scrapes never
        fail; ``lossy`` failures are deterministic per seed and bounded
        by the per-target breaker)."""
        if self.federator is not None:
            return self.federator
        if not self._started:
            self.start()
        from noise_ec_tpu.obs.federate import MetricsFederator

        drop = self.profile.chaos.drop
        sources = {
            f"fleet://{peer.idx}": self._scrape_source(peer, drop)
            for peer in self.peers
        }
        self.federator = MetricsFederator(
            sources=sources, registry=default_registry(),
            reset_timeout=0.05,
        )
        return self.federator

    def _scrape_source(self, peer: FleetPeer, drop: float):
        from noise_ec_tpu.obs.export import render_prometheus

        rng = np.random.default_rng(
            np.random.SeedSequence(
                [self.seed & 0xFFFFFFFF, 0xFEDE, peer.idx]
            )
        )
        ref = weakref.ref(peer)

        def source() -> str:
            p = ref()
            if p is None or not p.up:
                raise RuntimeError(f"peer {peer.idx} is down")
            if drop > 0 and float(rng.random()) < drop:
                raise RuntimeError(
                    f"scrape of peer {peer.idx} dropped (chaos)"
                )
            return render_prometheus(default_registry())

        return source

    def attach(self, server) -> None:
        """Mount ``GET /fleet`` (and the federator's ``GET
        /fleet/metrics``) on a StatsServer and fold the live fleet
        block into its ``/healthz`` details."""
        server.mount("GET", "/fleet", self._route_fleet)
        self.build_federator().attach(server)
        prev = server.health_details
        ref = weakref.ref(self)

        def details() -> dict:
            out: dict = {}
            if prev is not None:
                try:
                    out.update(prev())
                except Exception as exc:  # noqa: BLE001 — same contract
                    # as StatsServer: details must never break the probe
                    out["error"] = str(exc)
            lab = ref()
            if lab is not None:
                out["fleet"] = lab.health_block()
            return out

        server.health_details = details

    def _route_fleet(self, req: dict) -> tuple:
        doc = {
            "profile": {
                "peers": self.profile.peers,
                "fanout": self.profile.fanout,
                "chaos": self.profile.chaos_name,
                "mix": self.profile.weights(),
            },
            "live": self.health_block(),
            "report": self.last_report,
        }
        return 200, "application/json", json.dumps(doc, indent=1).encode()

    def write_report(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.last_report or {}, f, indent=1)

    def write_trace(self, path: str) -> dict:
        """Write the fleet-wide merged Perfetto trace: every peer shares
        the process tracer, so one dump IS the merged fleet view (spans
        carry the message trace ids; the single ``node`` track is the
        lab itself)."""
        from noise_ec_tpu.obs.perfetto import write_chrome_trace

        spans = default_tracer().dump()
        label = f"fleet[{len(self.peers)} peers]"
        for s in spans:
            s.setdefault("node", label)
        return write_chrome_trace(path, spans)

    def close(self) -> None:
        self._stop.set()
        if self.federator is not None:
            self.federator.close()
            self.federator = None
        if self._churn_thread is not None:
            self._churn_thread.join(timeout=5)
            self._churn_thread = None
        if self.hub is not None:
            self.hub.dispatch.shutdown(wait=True)


def _placement_census(ref, domain: str) -> float:
    lab = ref()
    if lab is None or lab.ring is None:
        return 0.0
    try:
        return float(lab.placement_census().get(domain, 0))
    except Exception:  # noqa: BLE001 — a scrape must never raise
        return 0.0


def _count_peers(ref, up: bool) -> int:
    lab = ref()
    if lab is None:
        return 0
    return sum(1 for p in lab.peers if p.up == up)


def _backpressure_waits() -> float:
    """Total producer waits across layers (report convenience)."""
    total = 0.0
    fam = default_registry().counter("noise_ec_backpressure_waits_total")
    for _, child in fam.children():
        total += child.value
    return total
