"""Fleet lab: thousand-peer in-process load/chaos harness.

The scale tier of the test story (docs/fleet.md): spin up hundreds to
thousands of lightweight in-process peers — each a real
:class:`~noise_ec_tpu.host.plugin.ShardPlugin` — drive them with a
declarative traffic-mix grammar (:class:`FleetProfile`), compose a
named chaos profile plus churn per link, and score the run honestly
(delivered / shed-with-Retry-After / churned / lost are four different
things). CLI: ``-fleet-profile`` / ``-fleet-size`` / ``-fleet-report``.
"""

from noise_ec_tpu.fleet.profile import NAMED_CHAOS, FleetProfile
from noise_ec_tpu.fleet.runner import FleetLab, FleetPeer
from noise_ec_tpu.fleet.score import FleetScorer

__all__ = [
    "NAMED_CHAOS",
    "FleetLab",
    "FleetPeer",
    "FleetProfile",
    "FleetScorer",
]
