"""Fleet scoring: ground-truth delivery accounting and the run report.

The lab does not trust counters alone — every admitted submission
records its EXPECTED recipient set (the sender's up-neighbors at send
time), every verified delivery is matched back to its submission, and
the report classifies each expected (message, receiver) pair exactly
once:

- **delivered** — the receiver's plugin verified and delivered it (for
  objects: the receiver's object service serves the bytes back
  byte-identical);
- **shed** — never expected at all: admission refused the submission
  with a Retry-After hint BEFORE any encode (the node protecting
  itself is not data loss; scored as its own bucket);
- **churned** — the expected receiver was killed by the churn schedule
  between send and scoring (the schedule, not the stack, removed it;
  excluded from the delivery-rate denominator);
- **lost** — everything else: the stack actually dropped it.

``delivery.rate`` is therefore ``delivered / (expected - churned)`` —
the honest number the acceptance bars gate on (docs/fleet.md, scoring
semantics).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from noise_ec_tpu.obs.registry import default_registry

__all__ = ["FleetScorer"]


def _pct(values: list[float], q: float) -> Optional[float]:
    if not values:
        return None
    vals = sorted(values)
    return vals[min(len(vals) - 1, int(q * len(vals)))]


class FleetScorer:
    """Thread-safe run ledger (module docstring). One per lab run."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next_id = 0
        # msg_id -> {kind, sender, expected, t, delivered: {recv: lat}}
        self.sent: dict[int, dict] = {}
        self.shed_events: list[dict] = []
        # msg_id -> {tenant, name, digest} for post-run GET verification
        self.objects: dict[int, dict] = {}
        # tenant -> wall-clock GET samples: the scorer's own timing of
        # the reads the service-side noise_ec_object_op_seconds
        # histogram also times — the independent check the federation
        # acceptance compares fleet-merged bucket p99s against.
        self.tenant_gets: dict[str, list[float]] = {}
        self.repairs = {"ok": 0, "failed": 0}
        # Placement/rebalance roll-up (fleet runs with a domains@ ring):
        # the lab folds its census + rebalance cycle stats in here so the
        # report carries the convergence story (docs/placement.md).
        self.placement: dict = {}
        reg = default_registry()
        self._m_msgs = reg.counter("noise_ec_fleet_messages_total")
        self._m_msgs_children: dict[str, object] = {}
        self._m_delivered = reg.counter(
            "noise_ec_fleet_deliveries_total"
        ).labels()
        self._m_shed = reg.counter("noise_ec_fleet_shed_total")
        self._m_lost = reg.counter("noise_ec_fleet_lost_total").labels()

    # ------------------------------------------------------------ recording

    def begin(self, kind: str, sender: int, expected: tuple,
              now: Optional[float] = None) -> int:
        """Admit one submission; returns its msg_id (embedded in chat
        payload headers / object names so deliveries match back)."""
        t = time.monotonic() if now is None else now
        with self._lock:
            msg_id = self._next_id
            self._next_id += 1
            self.sent[msg_id] = {
                "kind": kind,
                "sender": sender,
                "expected": tuple(expected),
                "t": t,
                "delivered": {},
            }
        child = self._m_msgs_children.get(kind)
        if child is None:
            child = self._m_msgs_children[kind] = self._m_msgs.labels(
                kind=kind
            )
        child.add(1)
        return msg_id

    def add_object(self, msg_id: int, tenant: str, name: str,
                   digest: bytes) -> None:
        with self._lock:
            self.objects[msg_id] = {
                "tenant": tenant, "name": name, "digest": digest,
            }

    def deliver(self, msg_id: int, receiver: int,
                now: Optional[float] = None) -> None:
        """One verified delivery. ``now=None`` stamps latency from the
        submission time; objects verified post-run pass an explicit
        ``now`` equal to the send time (latency 0 → excluded from the
        latency stats by the report)."""
        t = time.monotonic() if now is None else now
        with self._lock:
            rec = self.sent.get(msg_id)
            if rec is None or receiver in rec["delivered"]:
                return
            rec["delivered"][receiver] = max(0.0, t - rec["t"])
        self._m_delivered.add(1)

    def shed(self, kind: str, sender: int, reason: str,
             retry_after: float) -> None:
        with self._lock:
            self.shed_events.append({
                "kind": kind, "sender": sender, "reason": reason,
                "retry_after": retry_after, "t": time.monotonic(),
            })
        self._m_shed.labels(reason=reason).add(1)

    def tenant_get(self, tenant: str, seconds: float) -> None:
        """One timed GET through a peer's service layer (run-mix reads
        and post-run verification reads both count — the same calls the
        tenant-labeled histogram observes)."""
        with self._lock:
            self.tenant_gets.setdefault(tenant, []).append(seconds)

    def repair_result(self, ok: bool) -> None:
        with self._lock:
            self.repairs["ok" if ok else "failed"] += 1

    def note_placement(self, stats: dict) -> None:
        """Merge placement/rebalance stats into the report's
        ``placement`` block (last write per key wins)."""
        with self._lock:
            self.placement.update(stats)

    # ------------------------------------------------------------- reporting

    def snapshot(self) -> dict:
        """Cheap live totals (the /fleet route and healthz block)."""
        with self._lock:
            expected = sum(len(r["expected"]) for r in self.sent.values())
            delivered = sum(len(r["delivered"]) for r in self.sent.values())
            return {
                "sent": len(self.sent),
                "expected_deliveries": expected,
                "delivered": delivered,
                "shed": len(self.shed_events),
            }

    def report(self, churn_kills: dict[int, list],
               duration: float) -> dict:
        """The scored run report (module docstring for the pair
        classification). ``churn_kills`` maps peer index -> kill times
        (lab epoch = ``time.monotonic`` values)."""
        with self._lock:
            sent = {m: dict(r) for m, r in self.sent.items()}
            shed_events = list(self.shed_events)
            objects = dict(self.objects)
            tenant_gets = {t: list(v) for t, v in self.tenant_gets.items()}
            repairs = dict(self.repairs)
            placement = dict(self.placement)
        expected = delivered = lost = churned = 0
        latencies: list[float] = []
        per_sender: dict[int, list[float]] = {}
        by_kind: dict[str, dict] = {}
        for rec in sent.values():
            kind_stats = by_kind.setdefault(
                rec["kind"], {"sent": 0, "expected": 0, "delivered": 0}
            )
            kind_stats["sent"] += 1
            for receiver in rec["expected"]:
                expected += 1
                kind_stats["expected"] += 1
                lat = rec["delivered"].get(receiver)
                if lat is not None:
                    delivered += 1
                    kind_stats["delivered"] += 1
                    if lat > 0:
                        latencies.append(lat)
                        per_sender.setdefault(rec["sender"], []).append(lat)
                elif any(
                    k >= rec["t"] for k in churn_kills.get(receiver, ())
                ):
                    churned += 1
                else:
                    lost += 1
        if lost:
            self._m_lost.add(lost)
        shed_by_reason: dict[str, int] = {}
        for ev in shed_events:
            shed_by_reason[ev["reason"]] = (
                shed_by_reason.get(ev["reason"], 0) + 1
            )
        denominator = max(1, expected - churned)
        report = {
            "duration_s": round(duration, 3),
            "sent": len(sent),
            "msgs_per_s": round(len(sent) / max(duration, 1e-9), 1),
            "delivery": {
                "expected": expected,
                "delivered": delivered,
                "lost": lost,
                "churned": churned,
                "rate": round(delivered / denominator, 6),
            },
            "shed": {
                "total": len(shed_events),
                "by_reason": shed_by_reason,
                "retry_after_s": (
                    max(ev["retry_after"] for ev in shed_events)
                    if shed_events else None
                ),
            },
            "by_kind": by_kind,
            "objects": {"puts": len(objects)},
            "placement": placement,
            "repair": repairs,
            "latency_ms": {
                "count": len(latencies),
                "p50": _ms(_pct(latencies, 0.50)),
                "p99": _ms(_pct(latencies, 0.99)),
            },
            "per_sender_p99_ms": {
                s: _ms(_pct(lats, 0.99))
                for s, lats in sorted(per_sender.items())
            },
            "tenant_get_p99_ms": {
                t: _ms(_pct(samples, 0.99))
                for t, samples in sorted(tenant_gets.items())
            },
        }
        return report


def _ms(seconds: Optional[float]) -> Optional[float]:
    return None if seconds is None else round(seconds * 1e3, 3)
