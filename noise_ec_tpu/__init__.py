"""noise_ec_tpu — a TPU-native erasure-coding framework.

A brand-new framework with the capabilities of the reference
``da-moon/noise-erasurecode-plugin`` (a Go P2P node that Reed-Solomon-shards
every signed message and broadcasts the shards; see ``/root/reference/main.go``),
re-designed TPU-first:

- The GF(2^8)/GF(2^16) Reed-Solomon hot loops — Cauchy generator-matrix
  multiply for ``Encode()`` (reference call site main.go:262) and
  submatrix-inversion x multiply for ``Reconstruct()`` (main.go:77) — run as
  bitsliced JAX/Pallas kernels over HBM-resident shard batches.
- The host-side plugin runtime (wire format per protobuf/shard.proto:21-27,
  ed25519+blake2b signing per main.go:219-223, shard-reassembly mempool per
  main.go:52-107, broadcast fan-out per main.go:201-210) is implemented
  natively in ``noise_ec_tpu.host``.
- Batched multi-object encode scales over a ``jax.sharding.Mesh`` with parity
  all-gathered over ICI (``noise_ec_tpu.parallel``).
- A C-ABI native shim (``shim/``) exposes the codec under a
  klauspost ``reedsolomon.Encoder``-style C interface for non-Python hosts.

Package layout (SURVEY.md §7.1):

- ``gf``       — finite-field arithmetic + bit-matrix / bit-plane machinery
- ``matrix``   — generator-matrix construction + GF linear algebra
- ``golden``   — slow, obviously-correct NumPy reference codec (ground truth)
- ``ops``      — JAX + Pallas kernels (the TPU compute path)
- ``codec``    — public Encoder APIs (klauspost-style and infectious-style)
- ``parallel`` — mesh/shard_map batching, ICI collectives, streaming
- ``host``     — wire format, identity/signing, mempool, transport, CLI
- ``utils``    — logging, primes, misc
"""

__version__ = "0.1.0"

from noise_ec_tpu.gf.field import GF, GF256, GF65536  # noqa: F401
