"""Native C-ABI codec shim (SURVEY.md §7.1 ``shim/``).

``rs_shim.cpp`` implements the GF(2^8) RS codec behind a C ABI shaped
after klauspost/reedsolomon's Encoder, so a Go host can cgo-link the same
library the Python binding loads. :mod:`noise_ec_tpu.shim.binding` is the
ctypes loader.
"""

from noise_ec_tpu.shim.binding import (
    CppReedSolomon,
    NativeBlake2b,
    native_blake2b,
    build_shim,
    gf16_decode1_fused,
    gf16_matmul_rows,
    gf16_syndrome_rows,
    gf_decode1_fused,
    gf_matmul_rows,
    gf_matmul_stripes,
    gf_scale_rows,
    gf_syndrome_rows,
    shim_available,
)

__all__ = [
    "CppReedSolomon",
    "NativeBlake2b",
    "native_blake2b",
    "build_shim",
    "gf16_decode1_fused",
    "gf16_matmul_rows",
    "gf16_syndrome_rows",
    "gf_decode1_fused",
    "gf_matmul_rows",
    "gf_matmul_stripes",
    "gf_scale_rows",
    "gf_syndrome_rows",
    "shim_available",
]
