"""ctypes binding for the native RS codec shim.

Loads ``librs_shim.so`` (building it with ``make`` on first use) and wraps
the C ABI in the same shard-list surface as
:class:`noise_ec_tpu.codec.rs.ReedSolomon`, so the native backend is a
drop-in for the Python/NumPy path. The same .so is what a Go host would
cgo-link under the ``reedsolomon.Encoder`` interface — the C ABI, not this
module, is the compatibility boundary.

Run ``python -m noise_ec_tpu.shim.binding --selftest`` to build and
cross-check against the golden codec.
"""

from __future__ import annotations

import ctypes
import subprocess
import sys
from pathlib import Path
from typing import Optional, Sequence

import numpy as np

_SHIM_DIR = Path(__file__).resolve().parent
_SO_PATH = _SHIM_DIR / "librs_shim.so"

_MATRIX_KINDS = {"cauchy": 0, "vandermonde": 1}


def build_shim(force: bool = False) -> Path:
    """Build librs_shim.so with make; returns its path."""
    if force or not _SO_PATH.exists():
        subprocess.run(
            ["make", "-C", str(_SHIM_DIR)] + (["-B"] if force else []),
            check=True,
            capture_output=True,
        )
    return _SO_PATH


def shim_available() -> bool:
    """True if the shared library exists or can be built."""
    try:
        build_shim()
        return True
    except (subprocess.CalledProcessError, FileNotFoundError):
        return False


_lib: Optional[ctypes.CDLL] = None


def _reload_fresh(stale: ctypes.CDLL, path) -> ctypes.CDLL:
    """Reopen ``path`` bypassing the dlopen pathname cache.

    glibc dedups dlopen by pathname, so CDLL(path) after a rebuild hands
    back the SAME stale handle. Drop our reference via dlclose first; if
    the handle is pinned (some other refcount), load a temp copy instead.
    """
    try:
        import _ctypes

        _ctypes.dlclose(stale._handle)
        fresh = ctypes.CDLL(str(path))
        if hasattr(fresh, "rs16_decode1_fused"):
            return fresh
    except Exception:  # noqa: BLE001 — fall through to the temp copy
        pass
    import shutil
    import tempfile

    tmp = tempfile.NamedTemporaryFile(
        prefix="librs_shim_", suffix=".so", delete=False
    )
    tmp.close()
    shutil.copyfile(path, tmp.name)
    lib = ctypes.CDLL(tmp.name)
    # The dlopen handle keeps the inode alive on Linux; unlinking now
    # avoids leaking one temp file per stale-shim recovery (r4 advisor).
    try:
        import os

        os.unlink(tmp.name)
    except OSError:
        pass
    return lib


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(str(build_shim()))
        if not hasattr(lib, "rs16_decode1_fused"):
            # Stale prebuilt .so from before the ABI grew (build_shim only
            # runs make when the file is MISSING): rebuild, then reopen
            # past the dlopen pathname cache — otherwise registering the
            # missing symbol below would fail the load and silently
            # disable EVERY native path.
            lib = _reload_fresh(lib, build_shim(force=True))
        lib.rs_encoder_new.restype = ctypes.c_void_p
        lib.rs_encoder_new.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int]
        lib.rs_encoder_free.argtypes = [ctypes.c_void_p]
        lib.rs_encode.restype = ctypes.c_int
        lib.rs_encode.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
        ]
        lib.rs_verify.restype = ctypes.c_int
        lib.rs_verify.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
        ]
        lib.rs_reconstruct.restype = ctypes.c_int
        lib.rs_reconstruct.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
        ]
        lib.rs_shim_version.restype = ctypes.c_char_p
        lib.rs_matmul.restype = ctypes.c_int
        lib.rs_matmul.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
        ]
        lib.rs_scale_rows.restype = ctypes.c_int
        lib.rs_scale_rows.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int, ctypes.c_size_t,
        ]
        lib.rs_matmul_rows.restype = ctypes.c_int
        lib.rs_matmul_rows.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_size_t,
        ]
        lib.rs_syndrome_rows.restype = ctypes.c_int
        lib.rs_syndrome_rows.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
        ]
        lib.rs_decode1_fused.restype = ctypes.c_int
        lib.rs_decode1_fused.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_size_t,
        ]
        u16p = ctypes.POINTER(ctypes.c_uint16)
        lib.rs16_matmul_rows.restype = ctypes.c_int
        lib.rs16_matmul_rows.argtypes = [
            u16p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_size_t,
        ]
        lib.rs16_syndrome_rows.restype = ctypes.c_int
        lib.rs16_syndrome_rows.argtypes = [
            u16p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p), u16p, ctypes.c_size_t,
        ]
        lib.rs16_decode1_fused.restype = ctypes.c_int
        lib.rs16_decode1_fused.argtypes = [
            u16p, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_int, ctypes.c_int,
            u16p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_size_t,
        ]
        lib.b2b_new.restype = ctypes.c_void_p
        lib.b2b_new.argtypes = [ctypes.c_int]
        lib.b2b_update.restype = ctypes.c_int
        lib.b2b_update.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t,
        ]
        lib.b2b_final.restype = ctypes.c_int
        lib.b2b_final.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.b2b_copy.restype = ctypes.c_void_p
        lib.b2b_copy.argtypes = [ctypes.c_void_p]
        lib.b2b_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


def _as_u8_ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


_fast_ok: Optional[bool] = None


def _fast_lib() -> Optional[ctypes.CDLL]:
    """The loaded shim, or None when it cannot be built/loaded (callers
    fall back to NumPy). Resolution is cached."""
    global _fast_ok
    if _fast_ok is None:
        try:
            _load()
            _fast_ok = True
        except Exception:  # noqa: BLE001 — any load failure -> NumPy path
            _fast_ok = False
    return _lib if _fast_ok else None


def gf_matmul_stripes(M: np.ndarray, D: np.ndarray) -> Optional[np.ndarray]:
    """M (r, k) @ D (k, S) over GF(2^8) on the native split-nibble/GFNI
    kernels; None when the shim is unavailable (caller falls back).

    Only uint8 operands (GF(2^8)); the matrix entries must already be
    field elements of the shim's polynomial (0x11D — the same one as
    gf/field.py, asserted by the cross tests in tests/test_shim.py).
    """
    lib = _fast_lib()
    if lib is None:
        return None
    Mb = np.ascontiguousarray(M, dtype=np.uint8)
    Db = np.ascontiguousarray(D, dtype=np.uint8)
    r, k = Mb.shape
    out = np.empty((r, Db.shape[1]), dtype=np.uint8)
    rc = lib.rs_matmul(_as_u8_ptr(Mb), r, k, _as_u8_ptr(Db), _as_u8_ptr(out),
                       Db.shape[1])
    if rc != 0:
        raise RuntimeError(f"rs_matmul failed: {rc}")
    return out


def _row_ptrs(rows: Sequence[np.ndarray]):
    """ctypes void* array over per-row uint8 buffers (no stacking copy).

    Each row must be a C-contiguous 1-D uint8 array; returns (ptr_array,
    keepalive list) — the caller must hold the keepalive until the C call
    returns, because ascontiguousarray may have created temporaries.
    """
    keep = [np.ascontiguousarray(r, dtype=np.uint8) for r in rows]
    arr = (ctypes.c_void_p * len(keep))(*[r.ctypes.data for r in keep])
    return arr, keep


def gf_matmul_rows(
    M: np.ndarray, rows: Sequence[np.ndarray], length: int
) -> Optional[np.ndarray]:
    """M (r, k) @ rows (k separate buffers of ``length`` bytes) -> (r,
    length) uint8, tiled; None when the shim is unavailable."""
    lib = _fast_lib()
    if lib is None:
        return None
    Mb = np.ascontiguousarray(M, dtype=np.uint8)
    r, k = Mb.shape
    out = np.empty((r, length), dtype=np.uint8)
    in_ptrs, in_keep = _row_ptrs(rows)
    out_ptrs, out_keep = _row_ptrs(list(out))
    rc = lib.rs_matmul_rows(_as_u8_ptr(Mb), r, k, in_ptrs, out_ptrs, length)
    del in_keep
    if rc != 0:
        raise RuntimeError(f"rs_matmul_rows failed: {rc}")
    # out rows were written through out_keep views, which alias out's rows
    # only if ascontiguousarray did not copy — rows of a fresh C-order
    # array are contiguous, so they alias by construction.
    del out_keep
    return out


def gf_syndrome_rows(
    A: np.ndarray,
    basis: Sequence[np.ndarray],
    extra: Sequence[np.ndarray],
    length: int,
    want_syndrome: bool = True,
) -> Optional[tuple[Optional[np.ndarray], np.ndarray]]:
    """Fused decode syndrome (see rs_syndrome_rows): returns (s, counts)
    where s (len(extra), length) = A @ basis ^ extra and counts[col] is the
    number of nonzero syndrome rows at that column; s is None when
    ``want_syndrome`` is False. None when the shim is unavailable."""
    lib = _fast_lib()
    if lib is None:
        return None
    Ab = np.ascontiguousarray(A, dtype=np.uint8)
    r2, k = Ab.shape
    if r2 > 255:
        # counts is uint8 in the C ABI; more extra rows would silently
        # wrap the bad-column scan (r4 advisor). Unreachable for deduped
        # GF(2^8) geometries (m <= n <= 256, k >= 1), so NumPy fallback.
        return None
    counts = np.empty(length, dtype=np.uint8)
    b_ptrs, b_keep = _row_ptrs(basis)
    e_ptrs, e_keep = _row_ptrs(extra)
    s = np.empty((r2, length), dtype=np.uint8) if want_syndrome else None
    if s is not None:
        s_ptrs, s_keep = _row_ptrs(list(s))
    else:
        s_ptrs, s_keep = None, None
    rc = lib.rs_syndrome_rows(
        _as_u8_ptr(Ab), r2, k, b_ptrs, e_ptrs, s_ptrs, _as_u8_ptr(counts),
        length,
    )
    del b_keep, e_keep, s_keep
    if rc != 0:
        raise RuntimeError(f"rs_syndrome_rows failed: {rc}")
    return s, counts


def gf_decode1_fused(
    A: np.ndarray,
    basis: Sequence[np.ndarray],
    extra: Sequence[np.ndarray],
    j: int,
    e: int,
    length: int,
) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """Fused single-corrupt-row decode (see rs_decode1_fused): one pass
    computes the syndrome, verifies the single-support hypothesis
    {basis row j} per column, and returns (corrected_row_j, state) with
    state 0 = clean, 1 = corrected, 2 = needs the general path. None when
    the shim is unavailable or the hypothesis cannot be verified (check
    column j identically zero — impossible for MDS checks)."""
    lib = _fast_lib()
    if lib is None:
        return None
    Ab = np.ascontiguousarray(A, dtype=np.uint8)
    r2, k = Ab.shape
    if r2 > 255:
        # Conservative parity with gf_syndrome_rows: the fused kernel is
        # count-free (it thresholds per column without materializing a
        # counter), so r2 > 255 would not wrap anything here — but the
        # syndrome kernel's uint8 per-column counter DOES cap at 255
        # check rows, and the two paths must refuse the same inputs so a
        # decode can't succeed fused yet fail when the probe routes it
        # generically. Reachable via custom generator matrices through
        # syndrome_decode_rows_any; NumPy fallback.
        return None
    out = np.empty(length, dtype=np.uint8)
    state = np.empty(length, dtype=np.uint8)
    b_ptrs, b_keep = _row_ptrs(basis)
    e_ptrs, e_keep = _row_ptrs(extra)
    rc = lib.rs_decode1_fused(
        _as_u8_ptr(Ab), r2, k, b_ptrs, e_ptrs, int(j), int(e),
        _as_u8_ptr(out), _as_u8_ptr(state), length,
    )
    del b_keep, e_keep
    if rc in (-2, -3):
        # -2: check column j identically zero; -3: nnz(A[:, j]) <= e so
        # the count-free shortcut is unsound. Neither occurs for MDS
        # checks; the caller falls back to the generic path.
        return None
    if rc != 0:
        raise RuntimeError(f"rs_decode1_fused failed: {rc}")
    return out, state


def _as_u16_ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))


def _row_ptrs16(rows: Sequence[np.ndarray]):
    """ctypes void* array over per-row uint16 buffers (see _row_ptrs)."""
    keep = [np.ascontiguousarray(r, dtype=np.uint16) for r in rows]
    arr = (ctypes.c_void_p * len(keep))(*[r.ctypes.data for r in keep])
    return arr, keep


def gf16_matmul_rows(
    M: np.ndarray, rows: Sequence[np.ndarray], length: int
) -> Optional[np.ndarray]:
    """GF(2^16) tier of gf_matmul_rows: M (r, k) uint16 @ rows (k uint16
    buffers of ``length`` symbols) -> (r, length) uint16; None when the
    shim is unavailable."""
    lib = _fast_lib()
    if lib is None:
        return None
    Mb = np.ascontiguousarray(M, dtype=np.uint16)
    r, k = Mb.shape
    out = np.empty((r, length), dtype=np.uint16)
    in_ptrs, in_keep = _row_ptrs16(rows)
    out_ptrs, out_keep = _row_ptrs16(list(out))
    rc = lib.rs16_matmul_rows(_as_u16_ptr(Mb), r, k, in_ptrs, out_ptrs, length)
    del in_keep, out_keep
    if rc != 0:
        raise RuntimeError(f"rs16_matmul_rows failed: {rc}")
    return out


def gf16_syndrome_rows(
    A: np.ndarray,
    basis: Sequence[np.ndarray],
    extra: Sequence[np.ndarray],
    length: int,
    want_syndrome: bool = True,
) -> Optional[tuple[Optional[np.ndarray], np.ndarray]]:
    """GF(2^16) tier of gf_syndrome_rows; counts come back uint16 (the
    wide field admits more than 255 extra rows). Lengths in symbols."""
    lib = _fast_lib()
    if lib is None:
        return None
    Ab = np.ascontiguousarray(A, dtype=np.uint16)
    r2, k = Ab.shape
    counts = np.empty(length, dtype=np.uint16)
    b_ptrs, b_keep = _row_ptrs16(basis)
    e_ptrs, e_keep = _row_ptrs16(extra)
    s = np.empty((r2, length), dtype=np.uint16) if want_syndrome else None
    if s is not None:
        s_ptrs, s_keep = _row_ptrs16(list(s))
    else:
        s_ptrs, s_keep = None, None
    rc = lib.rs16_syndrome_rows(
        _as_u16_ptr(Ab), r2, k, b_ptrs, e_ptrs, s_ptrs, _as_u16_ptr(counts),
        length,
    )
    del b_keep, e_keep, s_keep
    if rc != 0:
        raise RuntimeError(f"rs16_syndrome_rows failed: {rc}")
    return s, counts


def gf16_decode1_fused(
    A: np.ndarray,
    basis: Sequence[np.ndarray],
    extra: Sequence[np.ndarray],
    j: int,
    e: int,
    length: int,
) -> Optional[tuple[np.ndarray, np.ndarray]]:
    """GF(2^16) tier of gf_decode1_fused (lengths in symbols; state is
    one byte per column as in the GF(2^8) kernel)."""
    lib = _fast_lib()
    if lib is None:
        return None
    Ab = np.ascontiguousarray(A, dtype=np.uint16)
    r2, k = Ab.shape
    out = np.empty(length, dtype=np.uint16)
    state = np.empty(length, dtype=np.uint8)
    b_ptrs, b_keep = _row_ptrs16(basis)
    e_ptrs, e_keep = _row_ptrs16(extra)
    rc = lib.rs16_decode1_fused(
        _as_u16_ptr(Ab), r2, k, b_ptrs, e_ptrs, int(j), int(e),
        _as_u16_ptr(out), _as_u8_ptr(state), length,
    )
    del b_keep, e_keep
    if rc in (-2, -3):
        # -2: check column j identically zero; -3: nnz(A[:, j]) <= e so
        # the count-free shortcut is unsound. Neither occurs for MDS
        # checks; the caller falls back to the generic path.
        return None
    if rc != 0:
        raise RuntimeError(f"rs16_decode1_fused failed: {rc}")
    return out, state


def gf_scale_rows(consts: np.ndarray, D: np.ndarray) -> Optional[np.ndarray]:
    """Row-wise constant scale over GF(2^8): returns a new (rows, S) array
    with row i = consts[i] * D[i]; None when the shim is unavailable."""
    lib = _fast_lib()
    if lib is None:
        return None
    buf = np.array(D, dtype=np.uint8, copy=True, order="C")
    cb = np.ascontiguousarray(consts, dtype=np.uint8)
    rc = lib.rs_scale_rows(_as_u8_ptr(cb), _as_u8_ptr(buf), buf.shape[0],
                           buf.shape[1])
    if rc != 0:
        raise RuntimeError(f"rs_scale_rows failed: {rc}")
    return buf


class NativeBlake2b:
    """Streaming unkeyed BLAKE2b on the shim (bit-identical to
    hashlib.blake2b — RFC 7693; cross-checked in tests/test_host_crypto).

    Exists because the host node's sign/verify hashes whole objects
    (main.go:82-89, 219-223) and the shim's compression function uses the
    AVX512VL rotate form. Use :func:`native_blake2b` to construct (returns
    None when the shim is unavailable).
    """

    __slots__ = ("_lib", "_ctx", "digest_size")

    def __init__(self, lib, digest_size: int):
        self._lib = lib
        self.digest_size = digest_size
        self._ctx = lib.b2b_new(digest_size)
        if not self._ctx:
            raise ValueError(f"bad digest size {digest_size}")

    def update(self, data) -> None:
        n = len(data)
        if n == 0:
            return
        if isinstance(data, bytes):
            ptr = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p)
        else:
            try:  # writable buffers (bytearray, writable memoryview)
                ptr = ctypes.cast(
                    (ctypes.c_ubyte * n).from_buffer(data), ctypes.c_void_p
                )
            except TypeError:  # read-only non-bytes view: one copy
                data = bytes(data)
                ptr = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p)
                n = len(data)
        rc = self._lib.b2b_update(self._ctx, ptr, n)
        if rc != 0:
            raise RuntimeError(f"b2b_update failed: {rc}")

    def digest(self) -> bytes:
        # Finalize a CLONE: hashlib semantics allow digest() mid-stream,
        # repeated digest(), and update() afterwards; native finalization
        # is destructive.
        dup = self._lib.b2b_copy(self._ctx)
        if not dup:
            raise MemoryError("b2b_copy failed")
        try:
            out = ctypes.create_string_buffer(self.digest_size)
            rc = self._lib.b2b_final(dup, out)
            if rc != 0:
                raise RuntimeError(f"b2b_final failed: {rc}")
            return out.raw
        finally:
            self._lib.b2b_free(dup)

    def __del__(self):
        ctx = getattr(self, "_ctx", None)
        if ctx:
            self._lib.b2b_free(ctx)
            self._ctx = None


def native_blake2b(digest_size: int = 32) -> Optional[NativeBlake2b]:
    """A fresh native streaming BLAKE2b, or None (caller uses hashlib)."""
    lib = _fast_lib()
    if lib is None:
        return None
    return NativeBlake2b(lib, digest_size)


class CppReedSolomon:
    """Native-backend RS codec over contiguous (n, shard_len) buffers."""

    def __init__(self, data_shards: int, parity_shards: int, matrix: str = "cauchy"):
        if matrix not in _MATRIX_KINDS:
            raise ValueError(f"unknown matrix kind {matrix!r}")
        self.k = data_shards
        self.r = parity_shards
        self.n = data_shards + parity_shards
        self._lib = _load()
        self._enc = self._lib.rs_encoder_new(
            data_shards, parity_shards, _MATRIX_KINDS[matrix]
        )
        if not self._enc:
            raise ValueError(
                f"invalid geometry k={data_shards} r={parity_shards} "
                f"(n must be <= 256)"
            )

    def __del__(self):
        enc = getattr(self, "_enc", None)
        if enc:
            self._lib.rs_encoder_free(enc)
            self._enc = None

    @property
    def version(self) -> str:
        return self._lib.rs_shim_version().decode()

    def _buffer(self, shards: Sequence[Optional[np.ndarray]]) -> np.ndarray:
        lens = {s.shape[-1] for s in shards if s is not None}
        if len(lens) != 1:
            raise ValueError("present shards must share one length")
        (ln,) = lens
        buf = np.zeros((self.n, ln), dtype=np.uint8)
        for i, s in enumerate(shards):
            if s is not None:
                buf[i] = s
        return buf

    def encode(self, data_shards: Sequence[np.ndarray]) -> np.ndarray:
        """(k, S) data rows -> full (n, S) codeword (systematic)."""
        if len(data_shards) != self.k:
            raise ValueError(f"expected {self.k} data shards, got {len(data_shards)}")
        buf = self._buffer(list(data_shards) + [None] * self.r)
        rc = self._lib.rs_encode(self._enc, _as_u8_ptr(buf), buf.shape[1])
        if rc != 0:
            raise RuntimeError(f"rs_encode failed: {rc}")
        return buf

    def encode_into(self, codeword: np.ndarray) -> None:
        """Zero-copy encode: fill the parity rows of a contiguous
        C-order (n, S) uint8 buffer in place."""
        if codeword.shape[0] != self.n or codeword.dtype != np.uint8:
            raise ValueError(f"need a C-contiguous ({self.n}, S) uint8 buffer")
        if not codeword.flags.c_contiguous:
            raise ValueError("buffer must be C-contiguous")
        rc = self._lib.rs_encode(self._enc, _as_u8_ptr(codeword), codeword.shape[1])
        if rc != 0:
            raise RuntimeError(f"rs_encode failed: {rc}")

    def verify(self, shards: Sequence[np.ndarray]) -> bool:
        if len(shards) != self.n:
            raise ValueError(f"expected {self.n} shards, got {len(shards)}")
        buf = self._buffer(shards)
        rc = self._lib.rs_verify(self._enc, _as_u8_ptr(buf), buf.shape[1])
        if rc < 0:
            raise RuntimeError(f"rs_verify failed: {rc}")
        return bool(rc)

    def reconstruct(
        self,
        shards: Sequence[Optional[np.ndarray]],
        data_only: bool = False,
    ) -> np.ndarray:
        """Fill ``None`` rows; returns the full (n, S) (or repaired-data)
        buffer. Present rows are trusted (erasure-only — corruption
        detection is the signature layer's job, main.go:82-99)."""
        if len(shards) != self.n:
            raise ValueError(f"expected {self.n} shards, got {len(shards)}")
        present = np.array(
            [0 if s is None else 1 for s in shards], dtype=np.uint8
        )
        if int(present.sum()) < self.k:
            raise ValueError(
                f"need >= {self.k} present shards, have {int(present.sum())}"
            )
        buf = self._buffer(shards)
        rc = self._lib.rs_reconstruct(
            self._enc, _as_u8_ptr(buf), buf.shape[1], _as_u8_ptr(present),
            1 if data_only else 0,
        )
        if rc != 0:
            raise RuntimeError(f"rs_reconstruct failed: {rc}")
        return buf


def _selftest() -> int:
    from noise_ec_tpu.golden.codec import GoldenCodec

    rng = np.random.default_rng(0)
    for k, r in [(4, 2), (10, 4), (17, 3), (50, 20), (1, 1), (2, 0)]:
        for matrix in ("cauchy", "vandermonde"):
            S = 512
            cpp = CppReedSolomon(k, r, matrix=matrix)
            gold = GoldenCodec(k, k + r, matrix=matrix)
            data = rng.integers(0, 256, size=(k, S)).astype(np.uint8)
            cw_cpp = cpp.encode(list(data))
            cw_gold = gold.encode_all(data)
            assert np.array_equal(cw_cpp, cw_gold), (k, r, matrix, "encode")
            assert cpp.verify(list(cw_cpp)), (k, r, matrix, "verify")
            if r:
                bad = cw_cpp.copy()
                bad[k, 0] ^= 1
                assert not cpp.verify(list(bad)), (k, r, matrix, "verify-neg")
                erased = [
                    None if i < min(r, k) else cw_cpp[i] for i in range(k + r)
                ]
                rec = cpp.reconstruct(erased)
                assert np.array_equal(rec, cw_cpp), (k, r, matrix, "reconstruct")
    print("shim selftest OK:", CppReedSolomon(4, 2).version)
    return 0


if __name__ == "__main__":
    if "--selftest" in sys.argv:
        sys.exit(_selftest())
    build_shim(force="--force" in sys.argv)
    print(_SO_PATH)
