// blake2b.cpp: BLAKE2b (RFC 7693) behind the shim's C ABI.
//
// Why it lives in the native shim: the host node signs and verifies every
// object over BLAKE2b-256 (the reference's hash policy, noise/crypto/blake2b
// at /root/reference/main.go:38-41), and on large-object streams the TWO
// whole-object hashes (sender sign + receiver verify) dominate the host
// path — CPython's _blake2 measured ~0.75 GB/s on this image's single
// core. This is a from-the-RFC implementation with an AVX2 compression
// function (the four-lane row formulation: each 256-bit register holds one
// row of the 4x4 state, diagonalization by lane rotation), which roughly
// triples that. Output is bit-identical to hashlib.blake2b by construction
// and cross-checked in tests/test_host_crypto.py.
//
// Unkeyed, sequential BLAKE2b only — exactly the reference's usage
// (digest_size 32; no key, salt, personal, or tree mode).

#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <new>

#if defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

constexpr uint64_t kIV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
    0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

constexpr uint8_t kSigma[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
};

struct B2Ctx {
  uint64_t h[8];
  uint64_t t0, t1;
  uint8_t buf[128];
  size_t buflen;
  int outlen;
};

inline uint64_t load64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;  // x86 is little-endian, matching the spec's word order
}

#if defined(__AVX2__)

#if defined(__AVX512VL__)
// AVX512VL: native 64-bit lane rotates (vprorq) — one uop, shortest
// dependency chain (the G function is chain-bound, not throughput-bound).
inline __m256i ror32v(__m256i x) { return _mm256_ror_epi64(x, 32); }
inline __m256i ror24v(__m256i x) { return _mm256_ror_epi64(x, 24); }
inline __m256i ror16v(__m256i x) { return _mm256_ror_epi64(x, 16); }
inline __m256i ror63v(__m256i x) { return _mm256_ror_epi64(x, 63); }
#else
inline __m256i ror32v(__m256i x) {
  return _mm256_shuffle_epi32(x, _MM_SHUFFLE(2, 3, 0, 1));
}

inline __m256i ror24v(__m256i x) {
  const __m256i m = _mm256_setr_epi8(
      3, 4, 5, 6, 7, 0, 1, 2, 11, 12, 13, 14, 15, 8, 9, 10,
      3, 4, 5, 6, 7, 0, 1, 2, 11, 12, 13, 14, 15, 8, 9, 10);
  return _mm256_shuffle_epi8(x, m);
}

inline __m256i ror16v(__m256i x) {
  const __m256i m = _mm256_setr_epi8(
      2, 3, 4, 5, 6, 7, 0, 1, 10, 11, 12, 13, 14, 15, 8, 9,
      2, 3, 4, 5, 6, 7, 0, 1, 10, 11, 12, 13, 14, 15, 8, 9);
  return _mm256_shuffle_epi8(x, m);
}

inline __m256i ror63v(__m256i x) {
  return _mm256_or_si256(_mm256_add_epi64(x, x), _mm256_srli_epi64(x, 63));
}
#endif

void compress(B2Ctx* S, const uint8_t* block, bool last) {
  uint64_t m[16];
  for (int i = 0; i < 16; ++i) m[i] = load64(block + 8 * i);

  __m256i a = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(S->h));
  __m256i b = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(S->h + 4));
  __m256i c = _mm256_loadu_si256(reinterpret_cast<const __m256i*>(kIV));
  __m256i d = _mm256_xor_si256(
      _mm256_loadu_si256(reinterpret_cast<const __m256i*>(kIV + 4)),
      _mm256_setr_epi64x(static_cast<long long>(S->t0),
                         static_cast<long long>(S->t1),
                         last ? -1LL : 0LL, 0LL));
  const __m256i a0 = a, b0 = b;

  for (int r = 0; r < 12; ++r) {
    const uint8_t* s = kSigma[r];
    // Column step: G over (v0,v4,v8,v12) .. (v3,v7,v11,v15).
    __m256i mx = _mm256_setr_epi64x(
        static_cast<long long>(m[s[0]]), static_cast<long long>(m[s[2]]),
        static_cast<long long>(m[s[4]]), static_cast<long long>(m[s[6]]));
    __m256i my = _mm256_setr_epi64x(
        static_cast<long long>(m[s[1]]), static_cast<long long>(m[s[3]]),
        static_cast<long long>(m[s[5]]), static_cast<long long>(m[s[7]]));
    a = _mm256_add_epi64(a, _mm256_add_epi64(b, mx));
    d = ror32v(_mm256_xor_si256(d, a));
    c = _mm256_add_epi64(c, d);
    b = ror24v(_mm256_xor_si256(b, c));
    a = _mm256_add_epi64(a, _mm256_add_epi64(b, my));
    d = ror16v(_mm256_xor_si256(d, a));
    c = _mm256_add_epi64(c, d);
    b = ror63v(_mm256_xor_si256(b, c));
    // Diagonalize: lanes rotate so columns become the diagonals
    // (v0,v5,v10,v15), (v1,v6,v11,v12), (v2,v7,v8,v13), (v3,v4,v9,v14).
    b = _mm256_permute4x64_epi64(b, 0x39);  // left 1
    c = _mm256_permute4x64_epi64(c, 0x4E);  // left 2
    d = _mm256_permute4x64_epi64(d, 0x93);  // left 3
    mx = _mm256_setr_epi64x(
        static_cast<long long>(m[s[8]]), static_cast<long long>(m[s[10]]),
        static_cast<long long>(m[s[12]]), static_cast<long long>(m[s[14]]));
    my = _mm256_setr_epi64x(
        static_cast<long long>(m[s[9]]), static_cast<long long>(m[s[11]]),
        static_cast<long long>(m[s[13]]), static_cast<long long>(m[s[15]]));
    a = _mm256_add_epi64(a, _mm256_add_epi64(b, mx));
    d = ror32v(_mm256_xor_si256(d, a));
    c = _mm256_add_epi64(c, d);
    b = ror24v(_mm256_xor_si256(b, c));
    a = _mm256_add_epi64(a, _mm256_add_epi64(b, my));
    d = ror16v(_mm256_xor_si256(d, a));
    c = _mm256_add_epi64(c, d);
    b = ror63v(_mm256_xor_si256(b, c));
    // Undiagonalize.
    b = _mm256_permute4x64_epi64(b, 0x93);
    c = _mm256_permute4x64_epi64(c, 0x4E);
    d = _mm256_permute4x64_epi64(d, 0x39);
  }

  a = _mm256_xor_si256(a0, _mm256_xor_si256(a, c));
  b = _mm256_xor_si256(b0, _mm256_xor_si256(b, d));
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(S->h), a);
  _mm256_storeu_si256(reinterpret_cast<__m256i*>(S->h + 4), b);
}

#else  // portable fallback

inline uint64_t rotr64(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }

void compress(B2Ctx* S, const uint8_t* block, bool last) {
  uint64_t m[16], v[16];
  for (int i = 0; i < 16; ++i) m[i] = load64(block + 8 * i);
  for (int i = 0; i < 8; ++i) v[i] = S->h[i];
  for (int i = 0; i < 8; ++i) v[8 + i] = kIV[i];
  v[12] ^= S->t0;
  v[13] ^= S->t1;
  if (last) v[14] = ~v[14];
#define B2G(A, B, C, D, X, Y)            \
  v[A] += v[B] + (X);                    \
  v[D] = rotr64(v[D] ^ v[A], 32);        \
  v[C] += v[D];                          \
  v[B] = rotr64(v[B] ^ v[C], 24);        \
  v[A] += v[B] + (Y);                    \
  v[D] = rotr64(v[D] ^ v[A], 16);        \
  v[C] += v[D];                          \
  v[B] = rotr64(v[B] ^ v[C], 63)
  for (int r = 0; r < 12; ++r) {
    const uint8_t* s = kSigma[r];
    B2G(0, 4, 8, 12, m[s[0]], m[s[1]]);
    B2G(1, 5, 9, 13, m[s[2]], m[s[3]]);
    B2G(2, 6, 10, 14, m[s[4]], m[s[5]]);
    B2G(3, 7, 11, 15, m[s[6]], m[s[7]]);
    B2G(0, 5, 10, 15, m[s[8]], m[s[9]]);
    B2G(1, 6, 11, 12, m[s[10]], m[s[11]]);
    B2G(2, 7, 8, 13, m[s[12]], m[s[13]]);
    B2G(3, 4, 9, 14, m[s[14]], m[s[15]]);
  }
#undef B2G
  for (int i = 0; i < 8; ++i) S->h[i] ^= v[i] ^ v[8 + i];
}

#endif

inline void bump_counter(B2Ctx* S, uint64_t inc) {
  S->t0 += inc;
  if (S->t0 < inc) S->t1 += 1;
}

}  // namespace

extern "C" {

// Unkeyed sequential BLAKE2b context. digest_size in [1, 64]; NULL on a
// bad size or allocation failure.
void* b2b_new(int digest_size) {
  if (digest_size < 1 || digest_size > 64) return nullptr;
  B2Ctx* S = new (std::nothrow) B2Ctx();
  if (!S) return nullptr;
  for (int i = 0; i < 8; ++i) S->h[i] = kIV[i];
  // Parameter block word 0: depth=1, fanout=1, key length 0, digest size.
  S->h[0] ^= 0x01010000ULL ^ static_cast<uint64_t>(digest_size);
  S->t0 = S->t1 = 0;
  S->buflen = 0;
  S->outlen = digest_size;
  return S;
}

int b2b_update(void* ctx, const uint8_t* data, size_t len) {
  B2Ctx* S = static_cast<B2Ctx*>(ctx);
  if (!S || (!data && len)) return -1;
  while (len > 0) {
    if (S->buflen == 128) {
      // More input exists, so the buffered block is not the last one.
      bump_counter(S, 128);
      compress(S, S->buf, false);
      S->buflen = 0;
      // Bulk path: compress directly from the input while more than one
      // block remains (the final block must stay buffered for the
      // last-block flag).
      while (len > 128) {
        bump_counter(S, 128);
        compress(S, data, false);
        data += 128;
        len -= 128;
      }
    }
    size_t take = 128 - S->buflen;
    if (take > len) take = len;
    std::memcpy(S->buf + S->buflen, data, take);
    S->buflen += take;
    data += take;
    len -= take;
  }
  return 0;
}

int b2b_final(void* ctx, uint8_t* out) {
  B2Ctx* S = static_cast<B2Ctx*>(ctx);
  if (!S || !out) return -1;
  bump_counter(S, S->buflen);
  std::memset(S->buf + S->buflen, 0, 128 - S->buflen);
  compress(S, S->buf, true);
  std::memcpy(out, S->h, static_cast<size_t>(S->outlen));
  return 0;
}

void b2b_free(void* ctx) { delete static_cast<B2Ctx*>(ctx); }

// Independent copy of a context (hashlib allows digest() mid-stream and
// further update()s after; finalization is destructive, so the binding
// finalizes a clone). NULL on allocation failure.
void* b2b_copy(const void* ctx) {
  const B2Ctx* src = static_cast<const B2Ctx*>(ctx);
  if (!src) return nullptr;
  B2Ctx* dup = new (std::nothrow) B2Ctx();
  if (!dup) return nullptr;
  *dup = *src;
  return dup;
}

// One-shot convenience for C consumers.
int b2b_hash(const uint8_t* data, size_t len, uint8_t* out, int digest_size) {
  void* S = b2b_new(digest_size);
  if (!S) return -1;
  int rc = b2b_update(S, data, len);
  if (rc == 0) rc = b2b_final(S, out);
  b2b_free(S);
  return rc;
}

}  // extern "C"
