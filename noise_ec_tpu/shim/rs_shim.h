/* rs_shim.h: C ABI of the native GF(2^8) Reed-Solomon erasure codec.
 *
 * The boundary a Go host cgo-links (see example/main.go) exactly where the
 * reference links vivint/infectious (/root/reference/main.go:248-266), and
 * the contract the Python ctypes binding (binding.py) consumes. Shaped
 * after klauspost/reedsolomon's Encoder interface: Encode / Verify /
 * Reconstruct over a contiguous (k + r) x shard_len buffer, data rows
 * first.
 *
 * Bit-compatible with the TPU path: primitive polynomial 0x11D and the
 * same systematic Cauchy / Vandermonde generators as
 * noise_ec_tpu/{gf,matrix} — shards encoded here reconstruct there and
 * vice versa.
 */
#ifndef NOISE_EC_TPU_RS_SHIM_H_
#define NOISE_EC_TPU_RS_SHIM_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* Version / field identification string (static storage, do not free). */
const char* rs_shim_version(void);

/* Create an encoder. matrix_kind: 0 = Cauchy (default), 1 = systematic
 * Vandermonde. Returns NULL on invalid geometry (need k >= 1, r >= 0,
 * k + r <= 256). */
void* rs_encoder_new(int data_shards, int parity_shards, int matrix_kind);

void rs_encoder_free(void* enc);

/* shards: contiguous (k + r) x shard_len buffer, data rows first.
 * Fills the parity rows from the data rows. Returns 0 on success. */
int rs_encode(void* enc, uint8_t* shards, size_t shard_len);

/* Returns 1 when the parity rows match the data rows, 0 on mismatch,
 * < 0 on error. */
int rs_verify(void* enc, const uint8_t* shards, size_t shard_len);

/* present: k + r flags (nonzero = that shard row holds valid bytes).
 * Missing rows of `shards` are overwritten with reconstructed bytes.
 * data_only != 0 restores only the first k rows (ReconstructData).
 * Returns 0 on success, -2 with fewer than k present shards, -3 on a
 * singular submatrix. */
int rs_reconstruct(void* enc, uint8_t* shards, size_t shard_len,
                   const uint8_t* present, int data_only);

/* Generic GF(2^8) product: out (r x len) = M (r x k) @ in (k x len),
 * all contiguous row-major. Returns 0 on success. */
int rs_matmul(const uint8_t* M, int r, int k, const uint8_t* in,
              uint8_t* out, size_t len);

/* In-place per-row scale: buf row i *= consts[i] ((rows x len)
 * contiguous). Returns 0 on success. */
int rs_scale_rows(const uint8_t* consts, uint8_t* buf, int rows, size_t len);

/* rs_matmul over independent row buffers (no stacking copies): out[i] =
 * sum_j M[i][j] * in[j], cache-tiled. Returns 0 on success. */
int rs_matmul_rows(const uint8_t* M, int r, int k, const uint8_t* const* in,
                   uint8_t* const* out, size_t len);

/* Fused decode syndrome: s[i] = (M-product of basis rows) ^ extra[i], and
 * counts[col] = number of nonzero rows of s at that column, one tiled
 * pass. s_out may be NULL (counts only); counts may be NULL (syndrome
 * only). Returns 0 on success. */
int rs_syndrome_rows(const uint8_t* A, int r2, int k,
                     const uint8_t* const* basis, const uint8_t* const* extra,
                     uint8_t* const* s_out, uint8_t* counts, size_t len);

/* GF(2^16) tier (poly 0x1100B), mirroring the three decode hot kernels
 * on uint16 symbols; all lengths are in SYMBOLS, matrices row-major
 * uint16. counts is uint16 per column (the wide field admits more than
 * 255 extra rows). Same return conventions as the GF(2^8) versions. */
int rs16_matmul_rows(const uint16_t* M, int r, int k,
                     const uint16_t* const* in, uint16_t* const* out,
                     size_t len);
int rs16_syndrome_rows(const uint16_t* A, int r2, int k,
                       const uint16_t* const* basis,
                       const uint16_t* const* extra,
                       uint16_t* const* s_out, uint16_t* counts,
                       size_t len);
int rs16_decode1_fused(const uint16_t* A, int r2, int k,
                       const uint16_t* const* basis,
                       const uint16_t* const* extra,
                       int j, int e, uint16_t* out_row, uint8_t* state,
                       size_t len);

/* Fused speculative single-corrupt-row decode: one tiled pass computes
 * the syndrome, verifies the single-support hypothesis {basis row j}
 * column-wise, and writes the corrected row j into out_row. state[col]:
 * 0 = clean (count <= e), 1 = corrected, 2 = unexplained (caller must
 * re-decode those columns generally). Requires 0 <= j < k, e >= 1.
 * Returns 0 on success, -2 when check column j is identically zero. */
int rs_decode1_fused(const uint8_t* A, int r2, int k,
                     const uint8_t* const* basis, const uint8_t* const* extra,
                     int j, int e, uint8_t* out_row, uint8_t* state,
                     size_t len);

#ifdef __cplusplus
} /* extern "C" */
#endif

#endif /* NOISE_EC_TPU_RS_SHIM_H_ */
